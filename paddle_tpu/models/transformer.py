"""Transformer NMT (≙ reference benchmark/fluid/models/machine_translation.py
capability slot + nets.py:332 scaled_dot_product_attention — driver config #4).

The reference era predates a full in-repo Transformer; its attention exists
only as the composite in nets.py. Here the full encoder-decoder is first-class
because it is the TPU flagship: bf16 matmuls on the MXU, static shapes, and
parallelism-friendly structure (qkv/ffn weights laid out for tp sharding, the
sequence dim for sp/ring attention, batch for dp — see
paddle_tpu/parallel/tensor_parallel.py and __graft_entry__.py).
"""

from __future__ import annotations

import numpy as np

from .. import layers
from ..initializer import NormalInitializer
from ..param_attr import ParamAttr


def positional_encoding_table(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float32")
    i = np.arange(d_model)[None, :].astype("float32")
    angle = pos / np.power(10000.0, 2 * (i // 2) / d_model)
    table = np.zeros((max_len, d_model), dtype="float32")
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


def multi_head_attention(q_in, k_in, v_in, d_model, num_heads, dropout=0.0,
                         is_test=False, causal=False, segment_ids=None,
                         name=None):
    """Multi-head attention with explicit head split (≙ nets.py:332 composite
    generalized with masking). All projections are single fused matmuls so
    XLA maps them onto the MXU as large GEMMs; head dim stays last for lane
    alignment.

    segment_ids ([B, T] int32 var): packed-batch masking through the flash
    kernel (tokens attend only within their own segment — the static-shape
    LoD translation). Requires the fused path (attention-weight dropout
    off), which is also the only path that scales to long sequences."""
    b, t_q = q_in.shape[0], q_in.shape[1]
    t_k = k_in.shape[1]
    d_head = d_model // num_heads
    q = layers.fc(q_in, size=d_model, num_flatten_dims=2, bias_attr=False,
                  use_bf16=True, name=name and name + "_q")
    k = layers.fc(k_in, size=d_model, num_flatten_dims=2, bias_attr=False,
                  use_bf16=True, name=name and name + "_k")
    v = layers.fc(v_in, size=d_model, num_flatten_dims=2, bias_attr=False,
                  use_bf16=True, name=name and name + "_v")

    def split_heads(x, t):
        x = layers.reshape(x, shape=[b, t, num_heads, d_head])
        return layers.transpose(x, perm=[0, 2, 1, 3])

    q = split_heads(q, t_q)
    k = split_heads(k, t_k)
    v = split_heads(v, t_k)
    if segment_ids is not None and dropout and not is_test:
        raise NotImplementedError(
            "packed batches (segment_ids) require the fused attention "
            "path; set attention dropout to 0 (residual/ffn dropout is "
            "unaffected)")
    if not dropout or is_test:
        # fused flash-attention op: Pallas kernel on TPU (O(T) memory),
        # XLA composite elsewhere — see ops/pallas_kernels.py
        ctx = layers.fused_attention(q, k, v,
                                     scale=float(d_head) ** -0.5,
                                     causal=causal,
                                     segment_ids=segment_ids)
        if dropout and is_test:
            # downgrade_in_infer: training scaled attention weights by the
            # keep mask; inference must scale by (1-p) to keep the
            # expectation the downstream weights were trained against
            ctx = layers.scale(ctx, scale=1.0 - dropout)
    else:
        # attention-weight dropout needs the explicit weights tensor
        q = layers.scale(q, scale=float(d_head) ** -0.5)
        scores = layers.matmul(q, k, transpose_y=True, use_bf16=True)
        if causal:
            mask_np = np.triu(np.full((t_q, t_k), -1e9, dtype="float32"),
                              k=1)
            mask = layers.assign(mask_np.reshape(1, 1, t_q, t_k))
            scores = layers.elementwise_add(scores, mask)
        weights = layers.softmax(scores)
        weights = layers.dropout(weights, dropout_prob=dropout,
                                 is_test=is_test)
        ctx = layers.matmul(weights, v, use_bf16=True)
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    ctx = layers.reshape(ctx, shape=[b, t_q, d_model])
    return layers.fc(ctx, size=d_model, num_flatten_dims=2, bias_attr=False,
                     use_bf16=True, name=name and name + "_o")


def ffn(x, d_model, d_inner, dropout=0.0, is_test=False, name=None):
    h = layers.fc(x, size=d_inner, num_flatten_dims=2, act="relu",
                  use_bf16=True, name=name and name + "_fc1")
    if dropout:
        h = layers.dropout(h, dropout_prob=dropout, is_test=is_test)
    return layers.fc(h, size=d_model, num_flatten_dims=2, use_bf16=True,
                     name=name and name + "_fc2")


def _add_norm(x, residual, dropout=0.0, is_test=False, name=None):
    """name (when given) pins the LayerNorm parameter names so a decode
    graph built later in the same program shares the trained weights (the
    generation path rebuilds per-step computation from the same names)."""
    if dropout:
        x = layers.dropout(x, dropout_prob=dropout, is_test=is_test)
    kw = {}
    if name:
        kw = {"param_attr": ParamAttr(name=name + ".scale"),
              "bias_attr": ParamAttr(name=name + ".bias")}
    return layers.layer_norm(layers.elementwise_add(x, residual),
                             begin_norm_axis=2, **kw)


def encoder_layer(x, d_model, num_heads, d_inner, dropout, is_test, name):
    attn = multi_head_attention(x, x, x, d_model, num_heads, dropout,
                                is_test, name=name + "_attn")
    x = _add_norm(attn, x, dropout, is_test, name=name + "_ln1")
    f = ffn(x, d_model, d_inner, dropout, is_test, name=name + "_ffn")
    return _add_norm(f, x, dropout, is_test, name=name + "_ln2")


def decoder_layer(x, enc_out, d_model, num_heads, d_inner, dropout, is_test,
                  name):
    self_attn = multi_head_attention(x, x, x, d_model, num_heads, dropout,
                                     is_test, causal=True,
                                     name=name + "_self")
    x = _add_norm(self_attn, x, dropout, is_test, name=name + "_ln1")
    cross = multi_head_attention(x, enc_out, enc_out, d_model, num_heads,
                                 dropout, is_test, name=name + "_cross")
    x = _add_norm(cross, x, dropout, is_test, name=name + "_ln2")
    f = ffn(x, d_model, d_inner, dropout, is_test, name=name + "_ffn")
    return _add_norm(f, x, dropout, is_test, name=name + "_ln3")


def _embed(tokens, vocab_size, d_model, max_len, name, positions=None):
    """positions ([B, T] int32 var): per-token positional-encoding index.
    Packed batches use position-within-segment so a sequence embeds the
    same wherever it lands in the pack; default is the row position."""
    emb = layers.embedding(
        input=tokens, size=[vocab_size, d_model],
        param_attr=ParamAttr(name=name + "_emb",
                             initializer=NormalInitializer(0., d_model ** -0.5)))
    emb = layers.scale(emb, scale=float(d_model) ** 0.5)
    table = positional_encoding_table(max_len, d_model)
    if positions is not None:
        pos = layers.gather(layers.assign(table), positions)
    else:
        pos = layers.assign(table[None, :, :])
    return layers.elementwise_add(emb, pos)


def transformer(src=None, tgt=None, label=None, src_vocab=30000,
                tgt_vocab=30000, max_len=64, d_model=512, d_inner=2048,
                num_heads=8, num_layers=6, dropout=0.1, is_test=False,
                label_smooth=0.1):
    """Transformer-base encoder-decoder; returns (loss, logits).

    src/tgt: [B, T] int64 padded token ids (lod_level=1 data vars with
    companion lengths); label: [B, T] next-token targets.
    """
    if src is None:
        src = layers.data(name="src", shape=[max_len], dtype="int64",
                          lod_level=1)
    if tgt is None:
        tgt = layers.data(name="tgt", shape=[max_len], dtype="int64",
                          lod_level=1)
    if label is None:
        label = layers.data(name="lbl", shape=[max_len], dtype="int64")
    src_len = layers.sequence.get_seqlen(src)
    tgt_len = layers.sequence.get_seqlen(tgt)

    enc = _embed(src, src_vocab, d_model, max_len, "src")
    if dropout:
        enc = layers.dropout(enc, dropout_prob=dropout, is_test=is_test)
    for i in range(num_layers):
        enc = encoder_layer(enc, d_model, num_heads, d_inner, dropout,
                            is_test, f"enc{i}")

    dec = _embed(tgt, tgt_vocab, d_model, max_len, "tgt")
    if dropout:
        dec = layers.dropout(dec, dropout_prob=dropout, is_test=is_test)
    for i in range(num_layers):
        dec = decoder_layer(dec, enc, d_model, num_heads, d_inner, dropout,
                            is_test, f"dec{i}")

    logits = layers.fc(dec, size=tgt_vocab, num_flatten_dims=2,
                       use_bf16=True, name="proj")
    label3 = layers.unsqueeze(label, axes=[2])
    if label_smooth:
        # uniform label smoothing decomposed (identical math, no [B,T,V]
        # one-hot/smoothed-target materialization — those were measured as
        # avoidable HBM traffic on the NMT step):
        #   CE(smooth) = (1-eps)*CE(hard) + eps * mean_V(-log_softmax)
        eps = float(label_smooth)
        ce_hard = layers.softmax_with_cross_entropy(logits, label3)
        lp = layers.log_softmax(logits)
        uniform = layers.scale(
            layers.reduce_mean(lp, dim=[2], keep_dim=True), scale=-1.0)
        token_loss = layers.elementwise_add(
            layers.scale(ce_hard, scale=1.0 - eps),
            layers.scale(uniform, scale=eps))
    else:
        token_loss = layers.softmax_with_cross_entropy(logits, label3)
    mask = layers.sequence_mask(tgt_len, maxlen=max_len)
    mask = layers.unsqueeze(mask, axes=[2])
    masked = layers.elementwise_mul(token_loss, mask)
    loss = layers.reduce_sum(masked) / layers.reduce_sum(mask)
    return loss, logits


def _attend_cached(q, k5, v5, bias, K, num_heads, d_head, dropout=0.0):
    """Per-head attention of a single-position query over a cached K/V:
    q [B,K,H] against k5 / v5 both laid out [B,*,nh,T*,dh] (the * dims
    broadcast over the beam axis; scores read k via transpose_y — free on
    the MXU — so ONE cache layout serves both matmuls and the per-step
    cache write lands on the sublane T axis, not the lane axis), additive
    bias masking invalid keys. When the train graph had attention-weight
    dropout, the context is scaled by (1-p) — the same downgrade_in_infer
    correction the fused multi_head_attention path applies at
    inference."""
    H = num_heads * d_head
    q5 = layers.reshape(q, shape=[0, K, num_heads, 1, d_head])
    scores = layers.matmul(q5, k5, transpose_y=True,
                           alpha=float(d_head) ** -0.5)
    weights = layers.softmax(layers.elementwise_add(scores, bias))
    ctx = layers.reshape(layers.matmul(weights, v5), shape=[0, K, H])
    if dropout:
        ctx = layers.scale(ctx, scale=1.0 - dropout)
    return ctx


def _cached_self_attention(x, states, new_states, cache_id, prefix, K, T,
                           num_heads, d_head, pos, bias, dropout=0.0,
                           slot_axis=None):
    """One cached self-attention block inside a decode scan step: project
    q/k/v from x [B,K,H], write k/v into the PRE-TRANSPOSED caches
    (k and v both [B,K,nh,T,dh]; scores read k via transpose_y) at scalar
    position `pos` via
    `cache_write` (an in-place dynamic_update_slice inside the scan
    carry), attend over the masked cache, output-project. The head-major
    cache layout makes the attention read direct — no per-step transpose
    or one-hot full-cache rewrite, so the per-step HBM cost is one row
    write + one cache read (the decode roofline's structural floor).
    Shared by the LM and encoder-decoder generators; parameter names come
    from `prefix` (matching the train graph's multi_head_attention
    names).

    slot_axis (serving-engine mode): cache rows along this axis belong to
    INDEPENDENT requests at independent positions — `pos` is per-slot and
    the cache_write output is the persistable cache variable itself, so
    the executor round-trips it through donated state instead of a scan
    carry."""
    H = num_heads * d_head
    q = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                  use_bf16=True, name=f"{prefix}_q")
    kn = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                   use_bf16=True, name=f"{prefix}_k")
    vn = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                   use_bf16=True, name=f"{prefix}_v")
    slot_kw = {}
    if slot_axis is not None:
        slot_kw = {"batch_axis": slot_axis}
    kc = layers.cache_write(
        states[f"k{cache_id}"],
        layers.reshape(kn, shape=[0, K, num_heads, 1, d_head]), pos, axis=3,
        out=states[f"k{cache_id}"] if slot_axis is not None else None,
        **slot_kw)
    vc = layers.cache_write(
        states[f"v{cache_id}"],
        layers.reshape(vn, shape=[0, K, num_heads, 1, d_head]), pos, axis=3,
        out=states[f"v{cache_id}"] if slot_axis is not None else None,
        **slot_kw)
    new_states[f"k{cache_id}"], new_states[f"v{cache_id}"] = kc, vc
    ctx = _attend_cached(q, kc, vc, bias, K, num_heads, d_head, dropout)
    return layers.fc(ctx, size=H, num_flatten_dims=2, bias_attr=False,
                     use_bf16=True, name=f"{prefix}_o")


def _gen_embed_step(ids_prev, pos, emb_name, vocab, d_model, pe_table,
                    dropout=0.0):
    """Embed the previous token + positional encoding at `pos` (one-hot
    row-select from the PE table), with the train graph's post-embedding
    dropout corrected to its (1-p) inference scaling."""
    T = pe_table.shape[0]
    onehot_t = layers.one_hot(layers.cast(pos, "int64"), depth=T)
    emb = layers.embedding(layers.unsqueeze(ids_prev, axes=[2]),
                           size=[vocab, d_model],
                           param_attr=ParamAttr(name=emb_name))
    x = layers.scale(emb, scale=float(d_model) ** 0.5)
    x = layers.elementwise_add(
        x, layers.matmul(onehot_t, layers.assign(pe_table)))
    if dropout:
        x = layers.dropout(x, dropout_prob=dropout, is_test=True)
    return x


def _mask_to_bias(mask, axes):
    """0/1 keep-mask -> additive attention bias (-1e9 on masked keys),
    unsqueezed to broadcast against [.., nh, 1, T] score tensors."""
    return layers.unsqueeze(layers.scale(mask, scale=1e9, bias=-1e9),
                            axes=axes)


def _next_pos(pos):
    return layers.elementwise_add(pos,
                                  layers.fill_constant([1], "float32", 1.0))


def _step_mask_bias(pos, arange):
    """Additive bias hiding cache positions beyond the current one."""
    valid = layers.cast(
        layers.less_than(layers.assign(arange), _next_pos(pos)), "float32")
    return _mask_to_bias(valid, axes=[2, 3])


def _init_gen_states(batch_ref, K, T, H, num_layers, num_heads):
    """The decode scan's initial carry: position counter + zeroed
    per-layer PRE-TRANSPOSED head-major KV caches, BOTH [B,K,nh,T,dh]:
    one layout serves the score matmul (via transpose_y) and the context
    matmul, and the per-step `cache_write` updates a [.., 1, dh] slice on
    the SUBLANE T axis (a lane-axis dynamic update would be the slowest
    store path on TPU)."""
    d_head = H // num_heads
    init = {"pos": layers.fill_constant_batch_size_like(
        batch_ref, shape=[-1, K, 1], dtype="float32", value=0.0)}
    for i in range(num_layers):
        for sname in ("k", "v"):
            init[f"{sname}{i}"] = layers.fill_constant_batch_size_like(
                batch_ref, shape=[-1, K, num_heads, T, d_head],
                dtype="float32", value=0.0)
    return init


def transformer_generate(src=None, src_vocab=30000, tgt_vocab=30000,
                         max_src_len=64, max_gen=32, d_model=512,
                         d_inner=2048, num_heads=8, num_layers=6,
                         bos_id=0, eos_id=1, beam_size=4, dropout=0.0):
    """Encoder-decoder generation: encode the source once, then decode
    autoregressively with per-layer SELF-attention KV caches in the scan
    carry; cross-attention keys/values are projected once outside the
    scan and broadcast over the beam axis. Weights shared by name with a
    transformer(...) train graph (enc{i}_*, dec{i}_*, src/tgt_emb, proj)
    built with the same dims — train, then build this in its own program
    and run it in the same scope. Pass the SAME `dropout` the train graph
    used: every dropout site is corrected to its (1-p) inference scaling
    (downgrade_in_infer), exactly as is_test=True does on the train graph.

    Returns (sequences [B, max_gen, K], scores [B, K])."""
    from ..contrib.decoder import BeamSearchDecoder

    if src is None:
        src = layers.data(name="src", shape=[max_src_len], dtype="int64",
                          lod_level=1)
    src_len = layers.sequence.get_seqlen(src)
    K, T, H = beam_size, max_gen, d_model
    Ts = max_src_len
    d_head = d_model // num_heads

    enc = _embed(src, src_vocab, d_model, Ts, "src")
    if dropout:
        enc = layers.dropout(enc, dropout_prob=dropout, is_test=True)
    for i in range(num_layers):
        enc = encoder_layer(enc, d_model, num_heads, d_inner, dropout,
                            True, f"enc{i}")

    # cross K/V once per layer, [B, 1, nh, dh|Ts] views that broadcast
    # over the beam axis inside the scan
    cross_k, cross_v = [], []
    for i in range(num_layers):
        ck = layers.fc(enc, size=H, num_flatten_dims=2, bias_attr=False,
                       use_bf16=True, name=f"dec{i}_cross_k")
        cv = layers.fc(enc, size=H, num_flatten_dims=2, bias_attr=False,
                       use_bf16=True, name=f"dec{i}_cross_v")
        ck = layers.transpose(
            layers.reshape(ck, shape=[0, 1, Ts, num_heads, d_head]),
            perm=[0, 1, 3, 2, 4])                        # [B,1,nh,Ts,dh]
        cv = layers.transpose(
            layers.reshape(cv, shape=[0, 1, Ts, num_heads, d_head]),
            perm=[0, 1, 3, 2, 4])                        # [B,1,nh,Ts,dh]
        cross_k.append(ck)
        cross_v.append(cv)
    src_mask = layers.sequence_mask(src_len, maxlen=Ts)   # [B,Ts]
    src_bias = _mask_to_bias(src_mask, axes=[1, 2, 3])

    decoder = BeamSearchDecoder(beam_size=K, bos_id=bos_id, eos_id=eos_id,
                                max_len=T, name="nmt_gen")
    pe_table = positional_encoding_table(T, d_model).astype("float32")
    arange = np.arange(T, dtype="float32").reshape(1, 1, T)
    init = _init_gen_states(src, K, T, H, num_layers, num_heads)

    def step(states, ids_prev):
        pos = states["pos"]
        x = _gen_embed_step(ids_prev, pos, "tgt_emb", tgt_vocab,
                            d_model, pe_table, dropout)
        self_bias = _step_mask_bias(pos, arange)
        new_states = {"pos": _next_pos(pos)}

        for i in range(num_layers):
            # causal self-attention over the KV cache
            attn = _cached_self_attention(
                x, states, new_states, i, f"dec{i}_self", K, T, num_heads,
                d_head, pos, self_bias, dropout)
            x = _add_norm(attn, x, dropout, True, name=f"dec{i}_ln1")

            # cross-attention over the pre-projected encoder K/V
            cq = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                           use_bf16=True, name=f"dec{i}_cross_q")
            cctx = _attend_cached(cq, cross_k[i], cross_v[i], src_bias,
                                  K, num_heads, d_head, dropout)
            cattn = layers.fc(cctx, size=H, num_flatten_dims=2,
                              bias_attr=False, use_bf16=True,
                              name=f"dec{i}_cross_o")
            x = _add_norm(cattn, x, dropout, True, name=f"dec{i}_ln2")
            f = ffn(x, d_model, d_inner, dropout, True, name=f"dec{i}_ffn")
            x = _add_norm(f, x, dropout, True, name=f"dec{i}_ln3")

        logits = layers.fc(x, size=tgt_vocab, num_flatten_dims=2,
                           use_bf16=True, name="proj")
        return new_states, layers.log_softmax(logits)

    return decoder.decode(src, init, step)


def transformer_lm_generate(prompt=None, vocab=32000, max_gen=32,
                            d_model=512, d_inner=2048, num_heads=8,
                            num_layers=6, bos_id=0, eos_id=-1, beam_size=1,
                            dropout=0.0, packed=False):
    """Autoregressive generation with a per-layer KV cache (capability ≙
    the reference transformer benchmark's fast decoder; the reference
    decodes by re-running the while_op decoder with LoD beam state).

    TPU-first: one StaticRNN (lax.scan) over max_gen positions; the KV
    cache lives in the scan carry PRE-TRANSPOSED head-major
    (k and v both [B,K,nh,T,dh]) and each step writes one row via
    `cache_write` (an in-place dynamic_update_slice in the carry) then
    attends q·K over the masked cache directly — per-step cache cost is
    one row write + one read, the decode roofline's floor. Weights
    are shared BY NAME with a transformer_lm(...) built earlier in the
    same program (l{i}_attn_{q,k,v,o}, l{i}_ln{1,2}, l{i}_ffn_*,
    tok_emb, lm_head) — train first, then build this decode graph and
    run it in the same scope, passing the SAME `dropout` AND the same
    `packed` flag the train graph used (each dropout site is corrected
    to its (1-p) inference scaling, and — mirroring transformer_lm's
    `0.0 if packed else dropout` attention-weight dropout — packed
    training applied NO attention dropout, so packed=True here skips
    the (1-p) attention-context downscale the train graph never had).
    Generation is conditioned on the fed `prompt` ([B, 1] int64): each
    row's first token seeds the decode; `bos_id` is the fallback start
    used only when a caller builds its own decoder. beam_size=1 is
    greedy; >1 is beam search through the shared BeamSearchDecoder.

    Returns (sequences [B, max_gen, K], scores [B, K])."""
    from ..contrib.decoder import BeamSearchDecoder

    if prompt is None:
        prompt = layers.data(name="prompt", shape=[1], dtype="int64")
    K, T, H = beam_size, max_gen, d_model
    d_head = d_model // num_heads
    decoder = BeamSearchDecoder(beam_size=K, bos_id=bos_id, eos_id=eos_id,
                                max_len=T, name="lm_gen")

    pe_table = positional_encoding_table(T, d_model).astype("float32")
    arange = np.arange(T, dtype="float32").reshape(1, 1, T)
    init = _init_gen_states(prompt, K, T, H, num_layers, num_heads)
    attn_dropout = 0.0 if packed else dropout

    def step(states, ids_prev):
        pos = states["pos"]                                      # [B,K,1]
        x = _gen_embed_step(ids_prev, pos, "tok_emb", vocab,
                            d_model, pe_table, dropout)
        bias = _step_mask_bias(pos, arange)
        new_states = {"pos": _next_pos(pos)}
        for i in range(num_layers):
            attn = _cached_self_attention(
                x, states, new_states, i, f"l{i}_attn", K, T, num_heads,
                d_head, pos, bias, attn_dropout)
            x = _add_norm(attn, x, dropout, True, name=f"l{i}_ln1")
            f = ffn(x, d_model, d_inner, dropout, True, name=f"l{i}_ffn")
            x = _add_norm(f, x, dropout, True, name=f"l{i}_ln2")

        logits = layers.fc(x, size=vocab, num_flatten_dims=2, use_bf16=True,
                           name="lm_head")
        return new_states, layers.log_softmax(logits)

    return decoder.decode(prompt, init, step, init_ids=prompt)


def _slot_cache_var(name, shape, dtype="float32"):
    """Persistable zero-initialized cache variable (main + startup blocks,
    the optimizer-accumulator idiom): the serving engine's KV caches live
    in the Scope across ticks and ride the executor's donated read-write
    state path — updated in place on device, never re-staged."""
    from ..framework.program import (default_main_program,
                                     default_startup_program)
    mb = default_main_program().global_block()
    if name in mb.vars:
        return mb.vars[name]
    var = mb.create_var(name=name, shape=list(shape), dtype=dtype,
                        persistable=True)
    var.stop_gradient = True
    sb = default_startup_program().global_block()
    sv = sb.create_var(name=name, shape=list(shape), dtype=dtype,
                       persistable=True)
    sb.append_op("fill_constant", outputs={"Out": [sv.name]},
                 attrs={"shape": list(shape), "value": 0.0, "dtype": dtype})
    return var


def transformer_lm_decode_tick(n_slots, vocab=32000, max_len=64,
                               d_model=512, d_inner=2048, num_heads=8,
                               num_layers=6, dropout=0.0, packed=False,
                               cache_prefix="srv", param_prefix="",
                               emit_logp=False):
    """ONE decode tick over a slot-indexed KV cache — the continuous-
    batching serving engine's compiled step (paddle_tpu/serving_engine.py).

    Where transformer_lm_generate scans max_gen positions with the cache
    in the scan carry (every sequence at the SAME position), this builds a
    single-step program whose state is per-slot: caches are persistable
    [S,1,nh,T,dh] variables written back through the executor's donated
    read-write state, `tick_pos` is PER-SLOT (each slot at its own
    position — one mid-prompt, one 30 tokens into generation), and
    `cache_write(batch_axis=0)` writes each slot's row at its own
    position. One compiled program serves every mixture of request
    phases, which is what lets the scheduler admit a new request into the
    in-flight batch without recompiling or padding to a static batch.

    Inputs (all fed per tick): `tick_tok` [S,1] int64 (the token each
    slot consumes: next prompt token while prefilling, else the slot's
    previously sampled token), `tick_pos` [S,1,1] float32 (the position
    being written). Weights are shared BY NAME with transformer_lm
    (tok_emb, l{i}_attn_*, l{i}_ln*, l{i}_ffn_*, lm_head) — train first
    (or load), then build this in its own program and run it in the same
    scope; pass the SAME dropout/packed the train graph used (inference
    (1-p) corrections applied, as in transformer_lm_generate).

    Returns (next_ids [S,1] int64, cache_names list): argmax of the tick
    logits per slot, and the persistable cache variable names (the engine
    resets nothing on slot reuse — positions > a slot's own pos are
    masked, and prefill overwrites rows 0..P-1 before exposing them).

    param_prefix namespaces EVERY weight name (tok_emb, l{i}_*, lm_head)
    — the speculative DRAFT model is this same builder at param_prefix=
    "draft_" with its own cache_prefix, sharing the engine scope without
    colliding with the target weights (serving/speculative.py). With
    emit_logp=True the tick also returns the full log-softmax logits
    [S,1,V] — the draft-side distribution rejection sampling needs.
    """
    S, T, H = n_slots, max_len, d_model
    d_head = d_model // num_heads
    # STATIC slot dim (no -1 batch): the slot count is the program's shape,
    # and the static form is what lets fuse_decode_attention_pass match the
    # per-tick attention chain against the fixed-shape slot caches
    tok = layers.data(name="tick_tok", shape=[S, 1], dtype="int64",
                      append_batch_size=False)
    pos = layers.data(name="tick_pos", shape=[S, 1, 1], dtype="float32",
                      append_batch_size=False)
    attn_dropout = 0.0 if packed else dropout

    states = {}
    for i in range(num_layers):
        for s in ("k", "v"):
            states[f"{s}{i}"] = _slot_cache_var(
                f"{cache_prefix}_{s}{i}", [S, 1, num_heads, T, d_head])

    pe_table = positional_encoding_table(T, d_model).astype("float32")
    arange = np.arange(T, dtype="float32").reshape(1, 1, T)
    x = _gen_embed_step(tok, pos, f"{param_prefix}tok_emb", vocab, d_model,
                        pe_table, dropout)
    bias = _step_mask_bias(pos, arange)       # per-slot: pos broadcasts
    new_states = {}
    for i in range(num_layers):
        attn = _cached_self_attention(
            x, states, new_states, i, f"{param_prefix}l{i}_attn", 1, T,
            num_heads, d_head, pos, bias, attn_dropout, slot_axis=0)
        x = _add_norm(attn, x, dropout, True, name=f"{param_prefix}l{i}_ln1")
        f = ffn(x, d_model, d_inner, dropout, True,
                name=f"{param_prefix}l{i}_ffn")
        x = _add_norm(f, x, dropout, True, name=f"{param_prefix}l{i}_ln2")
    logits = layers.fc(x, size=vocab, num_flatten_dims=2, use_bf16=True,
                       name=f"{param_prefix}lm_head")
    next_ids = layers.argmax(logits, axis=2)            # [S,1] int64
    cache_names = [v.name for v in states.values()]
    if emit_logp:
        return next_ids, cache_names, layers.log_softmax(logits)
    return next_ids, cache_names


def _attend_cached_multi(q, k5, v5, bias, G, num_heads, d_head, dropout=0.0):
    """`_attend_cached` widened to a G-position query window: q [S,G,H]
    becomes q5 [S,1,nh,G,dh], so the G verify positions ride the query-row
    axis of the SAME matmul→add→softmax→matmul chain —
    fuse_decode_attention_pass matches it for 1 <= G < T and the fused
    kernel reads the cache ONCE for all G positions (the verify-widening
    economics: one cache pass scores γ+1 draft tokens). Returns
    [S, G, H]."""
    H = num_heads * d_head
    q5 = layers.unsqueeze(
        layers.transpose(
            layers.reshape(q, shape=[0, G, num_heads, d_head]),
            perm=[0, 2, 1, 3]),
        axes=[1])                                     # [S,1,nh,G,dh]
    scores = layers.matmul(q5, k5, transpose_y=True,
                           alpha=float(d_head) ** -0.5)
    weights = layers.softmax(layers.elementwise_add(scores, bias))
    ctx5 = layers.matmul(weights, v5)                 # [S,1,nh,G,dh]
    ctx = layers.reshape(
        layers.transpose(ctx5, perm=[0, 1, 3, 2, 4]), shape=[0, G, H])
    if dropout:
        ctx = layers.scale(ctx, scale=1.0 - dropout)
    return ctx


def _spec_window_positions(pos, G):
    """Absolute positions of a verify window: base `pos` [S,1,1] + offsets
    0..G-1 → [S,G,1] (position of each fed token / written cache row)."""
    offs = np.arange(G, dtype="float32").reshape(1, G, 1)
    return layers.elementwise_add(pos, layers.assign(offs))


def _spec_mask_bias(posg, arange):
    """Causal bias for the verify window: query row g (absolute position
    posg[s,g]) attends cache positions t <= posg[s,g] — which includes
    every window row written earlier in the same forward, so the verify
    scores are EXACTLY the scores the plain tick would produce feeding the
    same tokens one at a time. [S,G,1] → [S,1,1,G,T]."""
    valid = layers.cast(
        layers.less_than(layers.assign(arange), _next_pos(posg)), "float32")
    return _mask_to_bias(valid, axes=[1, 2])


def _spec_window_write(cache, new, pos, G, num_heads, d_head):
    """Write a G-row window [S,G,H] into a slot cache [S,1,nh,T,dh] at each
    slot's base position: one `cache_write(batch_axis=0)` whose New spans G
    rows on the T axis (dynamic_update_slice takes any slice length).
    Callers gate rounds on pos+G <= T — dus CLAMPS an overhanging start,
    which would silently relocate the window."""
    row = layers.unsqueeze(
        layers.transpose(
            layers.reshape(new, shape=[0, G, num_heads, d_head]),
            perm=[0, 2, 1, 3]),
        axes=[1])                                     # [S,1,nh,G,dh]
    return layers.cache_write(cache, row, pos, axis=3, batch_axis=0,
                              out=cache)


def transformer_lm_spec_verify_tick(n_slots, gamma, vocab=32000, max_len=64,
                                    d_model=512, d_inner=2048, num_heads=8,
                                    num_layers=6, dropout=0.0, packed=False,
                                    cache_prefix="srv", param_prefix=""):
    """ONE speculative VERIFY forward over the slot-indexed KV cache: score
    G = γ+1 positions per slot — the slot's committed next token followed
    by the draft model's γ proposals (or teacher-forced prompt tokens
    mid-prefill) — through the same fused decode-attention path as
    `transformer_lm_decode_tick`, writing all G KV rows into the SAME
    per-slot caches (shared by `cache_prefix` name with the plain tick's
    program in one scope). The serving engine commits the accepted prefix
    by advancing `fed` and leaves the rejected tail rows stale — masked by
    every later forward's position bias until overwritten, exactly the
    slot-reuse garbage contract the plain tick already lives with.

    Inputs (fed per round): `spec_tok` [S,G] int64, `spec_pos` [S,1,1]
    float32 (base position; rows land at pos..pos+γ — the engine gates
    participation on pos+G <= max_len).

    Returns (ids [S,G] int64, logp [S,G,V], cache_names): per-position
    argmax (greedy acceptance + bonus token) and full log-probs (rejection
    sampling against the draft's distribution)."""
    S, T, H, G = n_slots, max_len, d_model, gamma + 1
    d_head = d_model // num_heads
    tok = layers.data(name="spec_tok", shape=[S, G], dtype="int64",
                      append_batch_size=False)
    pos = layers.data(name="spec_pos", shape=[S, 1, 1], dtype="float32",
                      append_batch_size=False)
    attn_dropout = 0.0 if packed else dropout

    states = {}
    for i in range(num_layers):
        for s in ("k", "v"):
            states[f"{s}{i}"] = _slot_cache_var(
                f"{cache_prefix}_{s}{i}", [S, 1, num_heads, T, d_head])

    pe_table = positional_encoding_table(T, d_model).astype("float32")
    arange = np.arange(T, dtype="float32").reshape(1, 1, T)
    posg = _spec_window_positions(pos, G)             # [S,G,1]
    x = _gen_embed_step(tok, posg, f"{param_prefix}tok_emb", vocab, d_model,
                        pe_table, dropout)
    bias = _spec_mask_bias(posg, arange)              # [S,1,1,G,T]
    for i in range(num_layers):
        prefix = f"{param_prefix}l{i}_attn"
        q = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                      use_bf16=True, name=f"{prefix}_q")
        kn = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                       use_bf16=True, name=f"{prefix}_k")
        vn = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                       use_bf16=True, name=f"{prefix}_v")
        kc = _spec_window_write(states[f"k{i}"], kn, pos, G, num_heads,
                                d_head)
        vc = _spec_window_write(states[f"v{i}"], vn, pos, G, num_heads,
                                d_head)
        ctx = _attend_cached_multi(q, kc, vc, bias, G, num_heads, d_head,
                                   attn_dropout)
        attn = layers.fc(ctx, size=H, num_flatten_dims=2, bias_attr=False,
                         use_bf16=True, name=f"{prefix}_o")
        x = _add_norm(attn, x, dropout, True, name=f"{param_prefix}l{i}_ln1")
        f = ffn(x, d_model, d_inner, dropout, True,
                name=f"{param_prefix}l{i}_ffn")
        x = _add_norm(f, x, dropout, True, name=f"{param_prefix}l{i}_ln2")
    logits = layers.fc(x, size=vocab, num_flatten_dims=2, use_bf16=True,
                       name=f"{param_prefix}lm_head")
    ids = layers.argmax(logits, axis=2)               # [S,G] int64
    logp = layers.log_softmax(logits)                 # [S,G,V]
    cache_names = [v.name for v in states.values()]
    return ids, logp, cache_names


def transformer_lm_paged_decode_tick(n_slots, n_blocks, block_size,
                                     blocks_per_req, vocab=32000,
                                     d_model=512, d_inner=2048, num_heads=8,
                                     num_layers=6, dropout=0.0, packed=False,
                                     cache_prefix="pgd", topk_k=0,
                                     kv_quant=False):
    """ONE decode tick over a PAGED KV cache — the block-table read/write
    variant of `transformer_lm_decode_tick` (serving/kv_pager.py).

    The slot tick owns a full [S,1,nh,max_len,dh] row per slot; here the
    KV state is one device-resident POOL per layer per k/v —
    [n_blocks, nh, block_size, dh] persistable variables — and each slot
    sees the cache through its BLOCK TABLE (`tick_btab` [S, NLB] int64,
    NLB = blocks_per_req): logical block j of slot s lives in physical
    block tick_btab[s, j]. The read path is gather(pool, btab) →
    transpose → reshape, reconstructing the exact [S,1,nh,T,dh] view the
    slot tick attends over (T = NLB*block_size), so the downstream
    q·K/softmax/·V chain is IDENTICAL and fuse_decode_attention_pass
    matches it unchanged. The write path is `paged_cache_write`: slot
    s's new k/v row lands at pool[tick_wblock[s], :, tick_woff[s], :] —
    block-granular, one XLA scatter.

    Physical block 0 is the pool's reserved NULL block: idle slots are
    steered to write there (tok/pos zeroed, btab all-zero) so one
    fixed-shape compiled tick serves any live/idle mix; a live block
    table never maps block 0, and the positional mask hides every view
    position beyond a slot's own `tick_pos`, so null-block garbage is
    never attended. Prefix sharing needs no graph support at all: a
    shared prefix simply means two rows of `tick_btab` carry the SAME
    physical block id — the gather reads the same bytes twice.

    Weights are shared BY NAME with transformer_lm (tok_emb, l{i}_attn_*,
    l{i}_ln*, l{i}_ffn_*, lm_head) — same contract as the slot tick;
    pass the SAME dropout/packed the train graph used.

    Inputs (fed per tick): `tick_tok` [S,1] int64, `tick_pos` [S,1,1]
    float32 (the LOGICAL position being written), `tick_btab` [S,NLB]
    int64, `tick_wblock` [S] int64, `tick_woff` [S] int64.

    Returns (next_ids [S,1] int64, cache_names); with topk_k > 0 also
    the per-slot top-k of the tick's log-probs — (topk_logp [S,1,k],
    topk_ids [S,1,k]) — the host-side scoring surface `paged_beam_search`
    ranks hypotheses with.

    kv_quant=True stores the pools as int8 payloads plus per-row f32
    scale pools ([NB, nh, BS, 1], names `{cache_prefix}_{k,v}{i}_sc`):
    writes quantize on the way in (`paged_cache_write_quant`, symmetric
    amax/127 over each dh row) and the read gathers payload+scales and
    dequantizes with one cast+multiply that XLA fuses into the cache
    read — so the resident pool bytes drop ~4x and the pager hands the
    freed bytes back as extra admitted blocks (the r21 quantized-KV
    kernel path wired into the engine pool storage itself)."""
    S, NB, BS, NLB = n_slots, n_blocks, block_size, blocks_per_req
    T = NLB * BS                      # the per-request logical span
    d_head = d_model // num_heads
    tok = layers.data(name="tick_tok", shape=[S, 1], dtype="int64",
                      append_batch_size=False)
    pos = layers.data(name="tick_pos", shape=[S, 1, 1], dtype="float32",
                      append_batch_size=False)
    btab = layers.data(name="tick_btab", shape=[S, NLB], dtype="int64",
                       append_batch_size=False)
    wblock = layers.data(name="tick_wblock", shape=[S], dtype="int64",
                         append_batch_size=False)
    woff = layers.data(name="tick_woff", shape=[S], dtype="int64",
                       append_batch_size=False)
    attn_dropout = 0.0 if packed else dropout

    pools, scale_pools = _paged_pool_vars(cache_prefix, NB, num_heads, BS,
                                          d_head, num_layers, kv_quant)

    pe_table = positional_encoding_table(T, d_model).astype("float32")
    arange = np.arange(T, dtype="float32").reshape(1, 1, T)
    x = _gen_embed_step(tok, pos, "tok_emb", vocab, d_model, pe_table,
                        dropout)
    bias = _step_mask_bias(pos, arange)       # per-slot: pos broadcasts
    H = d_model
    for i in range(num_layers):
        q = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                      use_bf16=True, name=f"l{i}_attn_q")
        kn = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                       use_bf16=True, name=f"l{i}_attn_k")
        vn = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                       use_bf16=True, name=f"l{i}_attn_v")
        views = []
        for sname, new in (("k", kn), ("v", vn)):
            # write this tick's row into each slot's current block (the
            # pool var round-trips through donated state, as in the
            # slot tick), THEN read the table view from the written pool
            # so the new row is attendable within the same tick
            new3 = layers.reshape(new, shape=[0, num_heads, d_head])
            views.append(_paged_pool_view(
                pools, scale_pools, f"{sname}{i}", new3, wblock, woff,
                btab, num_heads, T, d_head))
        ctx = _attend_cached(q, views[0], views[1], bias, 1, num_heads,
                             d_head, attn_dropout)
        attn = layers.fc(ctx, size=H, num_flatten_dims=2, bias_attr=False,
                         use_bf16=True, name=f"l{i}_attn_o")
        x = _add_norm(attn, x, dropout, True, name=f"l{i}_ln1")
        f = ffn(x, d_model, d_inner, dropout, True, name=f"l{i}_ffn")
        x = _add_norm(f, x, dropout, True, name=f"l{i}_ln2")
    logits = layers.fc(x, size=vocab, num_flatten_dims=2, use_bf16=True,
                       name="lm_head")
    next_ids = layers.argmax(logits, axis=2)            # [S,1] int64
    cache_names = ([v.name for v in pools.values()]
                   + [v.name for v in scale_pools.values()])
    if topk_k:
        logp = layers.log_softmax(logits)
        topk_vals, topk_ids = layers.topk(logp, k=topk_k)
        return next_ids, cache_names, topk_vals, topk_ids
    return next_ids, cache_names


def _paged_pool_vars(cache_prefix, n_blocks, num_heads, block_size, d_head,
                     num_layers, kv_quant):
    """Per-layer k/v pool variables for the paged ticks. kv_quant=False:
    f32 pools, empty scale dict. kv_quant=True: int8 payload pools plus
    f32 per-row scale pools (`{cache_prefix}_{s}{i}_sc`)."""
    pools, scale_pools = {}, {}
    for i in range(num_layers):
        for s in ("k", "v"):
            pools[f"{s}{i}"] = _slot_cache_var(
                f"{cache_prefix}_{s}{i}",
                [n_blocks, num_heads, block_size, d_head],
                dtype="int8" if kv_quant else "float32")
            if kv_quant:
                scale_pools[f"{s}{i}"] = _slot_cache_var(
                    f"{cache_prefix}_{s}{i}_sc",
                    [n_blocks, num_heads, block_size, 1])
    return pools, scale_pools


def _paged_pool_view(pools, scale_pools, key, new3, wblock, woff, btab,
                     num_heads, T, d_head):
    """Write `new3` rows into pool `key` then reconstruct the slot-tick
    cache view [S,1,nh,T,dh] through the block table — dequantizing
    against the gathered scale view when the pool is int8 (scale_pools
    non-empty). Shared by the paged decode tick (one row per slot) and
    the paged verify tick (G rows per slot: wblock/woff [S,G], new3
    [S*G,nh,dh] — `paged_cache_write` flattens the targets)."""
    pool = pools[key]
    if scale_pools:
        spool = scale_pools[key]
        written, wscales = layers.paged_cache_write_quant(
            pool, spool, new3, wblock, woff, out=pool, scales_out=spool)
        g = layers.cast(layers.gather(written, btab), "float32")
        gs = layers.gather(wscales, btab)        # [S,NLB,nh,BS,1]
        g = layers.elementwise_mul(g, gs)        # [S,NLB,nh,BS,dh] f32
    else:
        written = layers.paged_cache_write(pool, new3, wblock, woff,
                                           out=pool)
        g = layers.gather(written, btab)         # [S,NLB,nh,BS,dh]
    g = layers.transpose(g, perm=[0, 2, 1, 3, 4])
    g = layers.reshape(g, shape=[0, num_heads, T, d_head])
    return layers.unsqueeze(g, axes=[1])         # [S,1,nh,T,dh]


def transformer_lm_paged_spec_verify_tick(n_slots, gamma, n_blocks,
                                          block_size, blocks_per_req,
                                          vocab=32000, d_model=512,
                                          d_inner=2048, num_heads=8,
                                          num_layers=6, dropout=0.0,
                                          packed=False, cache_prefix="pgd",
                                          param_prefix="", kv_quant=False):
    """ONE speculative VERIFY forward over the PAGED KV pools — the
    block-table counterpart of `transformer_lm_spec_verify_tick`. Each
    slot scores G = γ+1 positions in one forward; the G new KV rows
    scatter into the slot's CURRENT blocks (`spec_wblock`/`spec_woff`
    [S,G]: per-position physical targets the engine derives from the
    block table at fed..fed+γ), then the table view is gathered back and
    attended with the per-position causal bias. Verify positions occupy
    the slot-tick layout the way beam forks do: rows of rejected
    positions stay in place, masked, until the pager's rollback detaches
    their fully-rejected blocks (`KVPager.rollback`) and later writes
    overwrite the partial boundary block. Idle slots steer every write to
    the reserved null block 0.

    Inputs (fed per round): `spec_tok` [S,G] int64, `spec_pos` [S,1,1]
    float32, `spec_btab` [S,NLB] int64, `spec_wblock` [S,G] int64,
    `spec_woff` [S,G] int64.

    Returns (ids [S,G] int64, logp [S,G,V], cache_names). kv_quant as in
    `transformer_lm_paged_decode_tick` (shares the SAME int8+scale pool
    variables by name)."""
    S, NB, BS, NLB = n_slots, n_blocks, block_size, blocks_per_req
    G = gamma + 1
    T = NLB * BS
    H = d_model
    d_head = d_model // num_heads
    tok = layers.data(name="spec_tok", shape=[S, G], dtype="int64",
                      append_batch_size=False)
    pos = layers.data(name="spec_pos", shape=[S, 1, 1], dtype="float32",
                      append_batch_size=False)
    btab = layers.data(name="spec_btab", shape=[S, NLB], dtype="int64",
                       append_batch_size=False)
    wblock = layers.data(name="spec_wblock", shape=[S, G], dtype="int64",
                         append_batch_size=False)
    woff = layers.data(name="spec_woff", shape=[S, G], dtype="int64",
                       append_batch_size=False)
    attn_dropout = 0.0 if packed else dropout

    pools, scale_pools = _paged_pool_vars(cache_prefix, NB, num_heads, BS,
                                          d_head, num_layers, kv_quant)

    pe_table = positional_encoding_table(T, d_model).astype("float32")
    arange = np.arange(T, dtype="float32").reshape(1, 1, T)
    posg = _spec_window_positions(pos, G)             # [S,G,1]
    x = _gen_embed_step(tok, posg, f"{param_prefix}tok_emb", vocab, d_model,
                        pe_table, dropout)
    bias = _spec_mask_bias(posg, arange)              # [S,1,1,G,T]
    for i in range(num_layers):
        prefix = f"{param_prefix}l{i}_attn"
        q = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                      use_bf16=True, name=f"{prefix}_q")
        kn = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                       use_bf16=True, name=f"{prefix}_k")
        vn = layers.fc(x, size=H, num_flatten_dims=2, bias_attr=False,
                       use_bf16=True, name=f"{prefix}_v")
        views = []
        for sname, new in (("k", kn), ("v", vn)):
            new3 = layers.reshape(new, shape=[S * G, num_heads, d_head])
            views.append(_paged_pool_view(
                pools, scale_pools, f"{sname}{i}", new3, wblock, woff,
                btab, num_heads, T, d_head))
        ctx = _attend_cached_multi(q, views[0], views[1], bias, G,
                                   num_heads, d_head, attn_dropout)
        attn = layers.fc(ctx, size=H, num_flatten_dims=2, bias_attr=False,
                         use_bf16=True, name=f"{prefix}_o")
        x = _add_norm(attn, x, dropout, True, name=f"{param_prefix}l{i}_ln1")
        f = ffn(x, d_model, d_inner, dropout, True,
                name=f"{param_prefix}l{i}_ffn")
        x = _add_norm(f, x, dropout, True, name=f"{param_prefix}l{i}_ln2")
    logits = layers.fc(x, size=vocab, num_flatten_dims=2, use_bf16=True,
                       name=f"{param_prefix}lm_head")
    ids = layers.argmax(logits, axis=2)               # [S,G] int64
    logp = layers.log_softmax(logits)                 # [S,G,V]
    cache_names = ([v.name for v in pools.values()]
                   + [v.name for v in scale_pools.values()])
    return ids, logp, cache_names


def transformer_lm(tokens=None, label=None, vocab=32000, max_len=128,
                   d_model=512, d_inner=2048, num_heads=8, num_layers=6,
                   dropout=0.0, is_test=False, packed=False,
                   mean_loss=False):
    """Decoder-only causal LM — the flagship config used by
    __graft_entry__ (simplest shape that exercises dp/tp/sp sharding).

    packed=True: each batch row holds MULTIPLE sequences back to back,
    described by a `segments` int32 input ([B, max_len]; 0 = padding,
    1..N = sequence index — see data.packing.pack_sequences). Attention is
    segment-masked through the flash kernel and the loss counts only
    non-pad tokens. This is the throughput idiom for ragged corpora: no
    compute wasted on padding (≙ the reference's LoD batches whose whole
    point is padding-free ragged training, lod_tensor.h:58)."""
    if tokens is None:
        tokens = layers.data(name="tokens", shape=[max_len], dtype="int64",
                             lod_level=0 if packed else 1)
    if label is None:
        label = layers.data(name="targets", shape=[max_len], dtype="int64")
    segments = positions = None
    if packed:
        segments = layers.data(name="segments", shape=[max_len],
                               dtype="int32")
        positions = layers.data(name="positions", shape=[max_len],
                                dtype="int32")
    else:
        seqlen = layers.sequence.get_seqlen(tokens)
    x = _embed(tokens, vocab, d_model, max_len, "tok", positions=positions)
    if dropout:
        x = layers.dropout(x, dropout_prob=dropout, is_test=is_test)
    for i in range(num_layers):
        attn = multi_head_attention(x, x, x, d_model, num_heads,
                                    0.0 if packed else dropout,
                                    is_test, causal=True,
                                    segment_ids=segments,
                                    name=f"l{i}_attn")
        x = _add_norm(attn, x, dropout, is_test, name=f"l{i}_ln1")
        f = ffn(x, d_model, d_inner, dropout, is_test, name=f"l{i}_ffn")
        x = _add_norm(f, x, dropout, is_test, name=f"l{i}_ln2")
    logits = layers.fc(x, size=vocab, num_flatten_dims=2, use_bf16=True,
                       name="lm_head")
    label3 = layers.unsqueeze(label, axes=[2])
    token_loss = layers.softmax_with_cross_entropy(logits, label3)
    if packed:
        # a token trains iff it is non-pad AND its successor belongs to
        # the same segment (the last token of each packed sequence has no
        # valid next-token target)
        seg_next = layers.concat([
            layers.slice(segments, axes=[1], starts=[1], ends=[max_len]),
            layers.fill_constant_batch_size_like(segments, [-1, 1],
                                                 "int32", 0)], axis=1)
        nonpad = layers.greater_than(
            segments, layers.fill_constant([1], "int32", 0))
        same = layers.equal(segments, seg_next)
        mask = layers.elementwise_mul(layers.cast(nonpad, "float32"),
                                      layers.cast(same, "float32"))
    else:
        mask = layers.sequence_mask(seqlen, maxlen=max_len)
    mask = layers.unsqueeze(mask, axes=[2])
    masked = layers.elementwise_mul(token_loss, mask)
    if mean_loss:
        # mean over ALL positions instead of the mask-weighted sum/sum
        # quotient — identical for full-length sequences, and the MEAN
        # reduction form the explicit dp gradient pipeline requires
        # (grad_comm averages per-shard gradients; that equals the global
        # gradient only for a batch-mean loss — docs/data_parallel.md)
        loss = layers.mean(masked)
    else:
        loss = layers.reduce_sum(masked) / layers.reduce_sum(mask)
    return loss, logits
