"""SE-ResNeXt (≙ reference tests dist_se_resnext.py /
test_parallel_executor_seresnext.py model family).

TPU-first: NHWC layout, grouped 3x3 convs map to XLA
feature_group_count (one fused conv per block, no per-branch splits),
squeeze-excitation as two tiny MXU matmuls on globally-pooled features.
"""

from __future__ import annotations

from .. import layers
from .resnet import conv_bn_layer


def squeeze_excitation(input, num_channels, reduction_ratio=16,
                       data_format="NHWC", name=None):
    """Global-pool -> bottleneck MLP -> channel gate (the SE block)."""
    pool = layers.pool2d(input, pool_type="avg", global_pooling=True,
                         data_format=data_format)
    pool = layers.reshape(pool, shape=[-1, num_channels])
    squeeze = layers.fc(pool, size=max(num_channels // reduction_ratio, 4),
                        act="relu", name=name and name + "_sq")
    excite = layers.fc(squeeze, size=num_channels, act="sigmoid",
                       name=name and name + "_ex")
    shape = [-1, 1, 1, num_channels] if data_format == "NHWC" \
        else [-1, num_channels, 1, 1]
    gate = layers.reshape(excite, shape=shape)
    return layers.elementwise_mul(input, gate)


def bottleneck_block(input, num_filters, stride, cardinality=32,
                     reduction_ratio=16, is_test=False, data_format="NHWC",
                     use_bf16=False, name=None):
    ch_out = num_filters * 2
    conv1 = conv_bn_layer(input, num_filters, 1, 1, 0, is_test=is_test,
                          data_format=data_format, use_bf16=use_bf16)
    conv2 = layers.conv2d(conv1, num_filters=num_filters, filter_size=3,
                          stride=stride, padding=1, groups=cardinality,
                          act=None, bias_attr=False, data_format=data_format,
                          use_bf16=use_bf16)
    conv2 = layers.batch_norm(conv2, act="relu", is_test=is_test,
                              data_layout=data_format)
    conv3 = conv_bn_layer(conv2, ch_out, 1, 1, 0, act=None, is_test=is_test,
                          data_format=data_format, use_bf16=use_bf16)
    scaled = squeeze_excitation(conv3, ch_out,
                                reduction_ratio=reduction_ratio,
                                data_format=data_format, name=name)
    c_axis = 1 if data_format == "NCHW" else 3
    if input.shape[c_axis] != ch_out or stride != 1:
        short = conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                              is_test=is_test, data_format=data_format,
                              use_bf16=use_bf16)
    else:
        short = input
    return layers.relu(layers.elementwise_add(short, scaled))


_DEPTH = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}


def se_resnext_imagenet(img=None, label=None, depth=50, class_num=1000,
                        cardinality=32, reduction_ratio=16, is_test=False,
                        data_format="NHWC", use_bf16=False):
    """Returns (avg_loss, accuracy, logits); creates img/label data vars if
    not supplied (≙ dist_se_resnext.py SE_ResNeXt.net)."""
    if img is None:
        shape = [3, 224, 224] if data_format == "NCHW" else [224, 224, 3]
        img = layers.data("img", shape=shape)
    if label is None:
        label = layers.data("label", shape=[1], dtype="int64")

    depths = _DEPTH[depth]
    num_filters = [128, 256, 512, 1024]

    conv = conv_bn_layer(img, 64, 7, 2, 3, is_test=is_test,
                         data_format=data_format, use_bf16=use_bf16)
    conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max", data_format=data_format)
    for block, n in enumerate(depths):
        for i in range(n):
            conv = bottleneck_block(
                conv, num_filters[block],
                stride=2 if i == 0 and block != 0 else 1,
                cardinality=cardinality, reduction_ratio=reduction_ratio,
                is_test=is_test, data_format=data_format, use_bf16=use_bf16,
                name=f"se{block}_{i}")
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True,
                         data_format=data_format)
    pool = layers.reshape(pool, shape=[-1, num_filters[-1] * 2])
    drop = layers.dropout(pool, dropout_prob=0.2, is_test=is_test)
    logits = layers.fc(drop, size=class_num, use_bf16=use_bf16)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits
