"""VGG (≙ reference benchmark/fluid/models/vgg.py — the conv_block/
img_conv_group construction)."""

from __future__ import annotations

from .. import layers, nets

_CFG = {
    11: [1, 1, 2, 2, 2],
    13: [2, 2, 2, 2, 2],
    16: [2, 2, 3, 3, 3],
    19: [2, 2, 4, 4, 4],
}


def vgg(img=None, label=None, depth=16, class_num=1000, image_shape=None,
        with_batchnorm=True, is_test=False, fc_size=4096):
    """VGG-{11,13,16,19}. Reference uses img_conv_group stacks of 3x3 convs
    + BN + dropout, then two 4096 fc layers."""
    if img is None:
        img = layers.data(name="img", shape=image_shape or [3, 224, 224])
    if label is None:
        label = layers.data(name="label", shape=[1], dtype="int64")
    counts = _CFG[depth]
    chans = [64, 128, 256, 512, 512]
    tmp = img
    for n, ch in zip(counts, chans):
        tmp = nets.img_conv_group(
            input=tmp, conv_num_filter=[ch] * n, pool_size=2, pool_stride=2,
            conv_filter_size=3, conv_act="relu",
            conv_with_batchnorm=with_batchnorm,
            conv_batchnorm_drop_rate=0.0)
    drop = layers.dropout(tmp, dropout_prob=0.5, is_test=is_test)
    fc1 = layers.fc(drop, size=fc_size, act=None)
    bn = layers.batch_norm(fc1, act="relu", is_test=is_test,
                           data_layout="NHWC")
    drop2 = layers.dropout(bn, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(drop2, size=fc_size, act=None)
    logits = layers.fc(fc2, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits


def vgg16_cifar(img=None, label=None, class_num=10, is_test=False):
    return vgg(img=img, label=label, depth=16, class_num=class_num,
               image_shape=[3, 32, 32], is_test=is_test, fc_size=512)
