"""RNN encoder-decoder machine translation with attention + beam search.

≙ reference benchmark/fluid/models/machine_translation.py and
tests/book/test_machine_translation.py (GRU seq2seq with the attention
decoder built from fc/gru building blocks, trained with CE and decoded with
the beam_search ops). TPU translation: the encoder is one fused dynamic_gru
scan; the attention decoder is a StaticRNN (one lax.scan); beam decode keeps
a static [B, K] beam dim and compiles into a single scan as well, finishing
with gather_tree — no dynamic LoD beam trees.
"""

from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr

__def_cite__ = "reference: benchmark/fluid/models/machine_translation.py:1"


def _gru_cell(x, h_prev, hidden_dim, name):
    """GRU cell from fused fc blocks (≙ the reference decoder's
    fc + gru_unit composition, machine_translation.py seq_to_seq_net).
    x: [..., D], h_prev: [..., H] -> h: [..., H]."""
    nfd = len(x.shape) - 1
    gates = layers.elementwise_add(
        layers.fc(x, size=2 * hidden_dim, num_flatten_dims=nfd,
                  bias_attr=False, name=name + "_xg"),
        layers.fc(h_prev, size=2 * hidden_dim, num_flatten_dims=nfd,
                  name=name + "_hg"))
    gates = layers.sigmoid(gates)
    u = layers.slice(gates, axes=[nfd], starts=[0], ends=[hidden_dim])
    r = layers.slice(gates, axes=[nfd], starts=[hidden_dim],
                     ends=[2 * hidden_dim])
    cand = layers.tanh(layers.elementwise_add(
        layers.fc(x, size=hidden_dim, num_flatten_dims=nfd, bias_attr=False,
                  name=name + "_xc"),
        layers.fc(layers.elementwise_mul(r, h_prev), size=hidden_dim,
                  num_flatten_dims=nfd, name=name + "_hc")))
    one_minus_u = layers.scale(u, scale=-1.0, bias=1.0)
    return layers.elementwise_add(layers.elementwise_mul(u, h_prev),
                                  layers.elementwise_mul(one_minus_u, cand))


def _attention(state, enc_out, src_mask, name):
    """Dot-product attention of decoder state over encoder outputs
    (≙ the reference's simple_attention in book machine_translation).
    state [B, H] (or [B, K, H]), enc_out [B, T, H], src_mask [B, T] 0/1
    (padded source positions muted) -> context like state."""
    if len(state.shape) == 2:
        q = layers.unsqueeze(state, axes=[1])          # [B, 1, H]
    else:
        q = state                                      # [B, K, H]
    scores = layers.matmul(q, enc_out, transpose_y=True)  # [B, *, T]
    neg = layers.scale(src_mask, scale=1e9, bias=-1e9)    # 0 -> -1e9, 1 -> 0
    scores = layers.elementwise_add(scores, layers.unsqueeze(neg, axes=[1]))
    weights = layers.softmax(scores)
    ctx = layers.matmul(weights, enc_out)              # [B, *, H]
    if len(state.shape) == 2:
        ctx = layers.squeeze(ctx, axes=[1])
    return ctx


def encoder(src, src_lens, vocab_size, embed_dim, hidden_dim):
    from ..layers.sequence import tag_sequence
    emb = layers.embedding(src, size=[vocab_size, embed_dim],
                           param_attr=ParamAttr(name="src_emb"))
    proj = layers.fc(emb, size=3 * hidden_dim, num_flatten_dims=2,
                     bias_attr=False, name="enc_proj")
    proj = tag_sequence(proj, src_lens)
    enc = layers.dynamic_gru(proj, size=hidden_dim, name="enc_gru")
    return enc                                          # [B, T, H]


def train_net(src, src_lens, tgt_in, tgt_out, tgt_mask, dict_size=10000,
              embed_dim=64, hidden_dim=128):
    """Teacher-forced training graph. src [B, Ts], tgt_in/tgt_out [B, Tt],
    tgt_mask [B, Tt] float 0/1. Returns (avg_loss, logits)."""
    enc_out = encoder(src, src_lens, dict_size, embed_dim, hidden_dim)
    src_mask = layers.sequence_mask(src_lens, maxlen=src.shape[1])
    dec_init = layers.fc(layers.sequence_last_step(enc_out),
                         size=hidden_dim, act="tanh", name="dec_init")

    tgt_emb = layers.embedding(tgt_in, size=[dict_size, embed_dim],
                               param_attr=ParamAttr(name="tgt_emb"))

    rnn = layers.StaticRNN(name="decoder")
    with rnn.step():
        w = rnn.step_input(tgt_emb)                    # [B, E]
        h_prev = rnn.memory(init=dec_init)             # [B, H]
        ctx = _attention(h_prev, enc_out, src_mask, "att")
        inp = layers.concat([w, ctx], axis=1)
        h = _gru_cell(inp, h_prev, hidden_dim, "dec_gru")
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    dec_hidden = rnn()                                 # [B, Tt, H]

    logits = layers.fc(dec_hidden, size=dict_size, num_flatten_dims=2,
                       name="readout")
    b, t = tgt_out.shape[0], tgt_out.shape[1]
    flat_logits = layers.reshape(logits, shape=[-1, dict_size])
    flat_label = layers.reshape(tgt_out, shape=[-1, 1])
    ce = layers.softmax_with_cross_entropy(flat_logits, flat_label)
    ce = layers.reshape(ce, shape=[b, t])
    masked = layers.elementwise_mul(ce, tgt_mask)
    loss = layers.reduce_sum(masked) / (layers.reduce_sum(tgt_mask) + 1e-6)
    return loss, logits


def infer_net(src, src_lens, dict_size=10000, embed_dim=64, hidden_dim=128,
              beam_size=4, max_len=16, bos_id=0, eos_id=1):
    """Beam-search decode graph reusing the trained parameter names.
    Returns (sequences [B, max_len, K], scores [B, K])."""
    enc_out = encoder(src, src_lens, dict_size, embed_dim, hidden_dim)
    src_mask = layers.sequence_mask(src_lens, maxlen=src.shape[1])
    dec_init = layers.fc(layers.sequence_last_step(enc_out),
                         size=hidden_dim, act="tanh", name="dec_init")

    from ..contrib.decoder import BeamSearchDecoder

    decoder = BeamSearchDecoder(beam_size=beam_size, bos_id=bos_id,
                                eos_id=eos_id, max_len=max_len)

    def step(states, ids_prev):
        h_prev = states["h"]                                        # [B,K,H]
        # ids as [B, K, 1]: with beam_size=1 a bare [B, 1] would be read as
        # an index COLUMN by the embedding convention, squeezing the beam dim
        w = layers.embedding(layers.unsqueeze(ids_prev, axes=[2]),
                             size=[dict_size, embed_dim],
                             param_attr=ParamAttr(name="tgt_emb"))  # [B,K,E]
        ctx = _attention(h_prev, enc_out, src_mask, "att")          # [B,K,H]
        inp = layers.concat([w, ctx], axis=2)
        h = _gru_cell(inp, h_prev, hidden_dim, "dec_gru")           # [B,K,H]
        logits = layers.fc(h, size=dict_size, num_flatten_dims=2,
                           name="readout")
        return {"h": h}, layers.log_softmax(logits)     # [B, K, V]

    return decoder.decode(src, {"h": decoder.expand_to_beams(dec_init)},
                          step)                    # [B, K, ...]
