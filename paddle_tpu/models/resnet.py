"""ResNet (≙ reference benchmark/fluid/models/resnet.py).

TPU-first choices: NHWC data layout (the TPU-native conv layout — XLA tiles
the channel dim onto the lane dimension), bfloat16 matmul/conv inputs with
fp32 accumulation via the layers' use_bf16 path, and batch-stat-free inference
mode through batch_norm(is_test=True).
"""

from __future__ import annotations

from .. import layers


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_test=False, data_format="NHWC", use_bf16=False):
    conv = layers.conv2d(input=input, num_filters=ch_out,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act=None, bias_attr=False,
                         data_format=data_format, use_bf16=use_bf16)
    return layers.batch_norm(input=conv, act=act, is_test=is_test,
                             data_layout=data_format)


def _shortcut(input, ch_out, stride, is_test, data_format, use_bf16):
    c_axis = 1 if data_format == "NCHW" else 3
    ch_in = input.shape[c_axis]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_test=is_test, data_format=data_format,
                             use_bf16=use_bf16)
    return input


def bottleneck_block(input, ch_out, stride, is_test=False,
                     data_format="NHWC", use_bf16=False):
    short = _shortcut(input, ch_out * 4, stride, is_test, data_format,
                      use_bf16)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test,
                          data_format=data_format, use_bf16=use_bf16)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test,
                          data_format=data_format, use_bf16=use_bf16)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test, data_format=data_format,
                          use_bf16=use_bf16)
    return layers.relu(layers.elementwise_add(short, conv3))


def basic_block(input, ch_out, stride, is_test=False, data_format="NHWC",
                use_bf16=False):
    short = _shortcut(input, ch_out, stride, is_test, data_format, use_bf16)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test,
                          data_format=data_format, use_bf16=use_bf16)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test,
                          data_format=data_format, use_bf16=use_bf16)
    return layers.relu(layers.elementwise_add(short, conv2))


_DEPTH = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def resnet_imagenet(img=None, label=None, depth=50, class_num=1000,
                    is_test=False, data_format="NHWC", use_bf16=True):
    """ResNet-{18,34,50,101,152} for 224x224 inputs (driver config #2;
    north-star benchmark model)."""
    if img is None:
        shape = [3, 224, 224] if data_format == "NCHW" else [224, 224, 3]
        img = layers.data(name="img", shape=shape)
    if label is None:
        label = layers.data(name="label", shape=[1], dtype="int64")
    kind, counts = _DEPTH[depth]
    block = bottleneck_block if kind == "bottleneck" else basic_block

    conv1 = conv_bn_layer(img, 64, 7, 2, 3, is_test=is_test,
                          data_format=data_format, use_bf16=use_bf16)
    pool1 = layers.pool2d(conv1, pool_size=3, pool_stride=2, pool_padding=1,
                          pool_type="max", data_format=data_format)
    res = pool1
    for stage, n in enumerate(counts):
        ch = 64 * (2 ** stage)
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            res = block(res, ch, stride, is_test=is_test,
                        data_format=data_format, use_bf16=use_bf16)
    pool2 = layers.pool2d(res, pool_type="avg", global_pooling=True,
                          data_format=data_format)
    logits = layers.fc(pool2, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits


def resnet_cifar10(img=None, label=None, depth=32, class_num=10,
                   is_test=False, data_format="NHWC", use_bf16=False):
    """ResNet for 32x32 cifar inputs (≙ reference benchmark/fluid resnet
    cifar10 flavor; depth = 6n+2)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    if img is None:
        shape = [3, 32, 32] if data_format == "NCHW" else [32, 32, 3]
        img = layers.data(name="img", shape=shape)
    if label is None:
        label = layers.data(name="label", shape=[1], dtype="int64")
    conv1 = conv_bn_layer(img, 16, 3, 1, 1, is_test=is_test,
                          data_format=data_format, use_bf16=use_bf16)
    res = conv1
    for stage, ch in enumerate([16, 32, 64]):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            res = basic_block(res, ch, stride, is_test=is_test,
                              data_format=data_format, use_bf16=use_bf16)
    pool = layers.pool2d(res, pool_type="avg", global_pooling=True,
                         data_format=data_format)
    logits = layers.fc(pool, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits
