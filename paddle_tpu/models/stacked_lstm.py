"""Stacked dynamic-LSTM text model (≙ reference
benchmark/fluid/models/stacked_dynamic_lstm.py — driver config #3).

Sequence inputs are padded [B, T] token ids with a companion length vector
(the LoD translation); the LSTM recurrence is a lax.scan that freezes state
for finished sequences (≙ shrink_rnn_memory semantics).
"""

from __future__ import annotations

from .. import layers


def stacked_lstm_net(data=None, label=None, dict_dim=30000, emb_dim=512,
                     hid_dim=512, stacked_num=3, class_num=2, max_len=100):
    """Sentiment-style classifier: embedding -> [fc + lstm] x N ->
    max-pool(hidden, cell) -> fc softmax (mirrors the reference model)."""
    if data is None:
        data = layers.data(name="words", shape=[max_len], dtype="int64",
                           lod_level=1, append_batch_size=True)
    if label is None:
        label = layers.data(name="label", shape=[1], dtype="int64")
    seqlen = layers.sequence.get_seqlen(data)

    emb = layers.embedding(input=data, size=[dict_dim, emb_dim])
    emb = layers.sequence.tag_sequence(emb, seqlen)

    fc1 = layers.fc(emb, size=hid_dim * 4, num_flatten_dims=2)
    fc1 = layers.sequence.tag_sequence(fc1, seqlen)
    lstm1, cell1 = layers.dynamic_lstm(input=fc1, size=hid_dim * 4)

    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        concat = layers.concat(inputs, axis=-1)
        concat = layers.sequence.tag_sequence(concat, seqlen)
        fc = layers.fc(concat, size=hid_dim * 4, num_flatten_dims=2)
        fc = layers.sequence.tag_sequence(fc, seqlen)
        lstm, cell = layers.dynamic_lstm(input=fc, size=hid_dim * 4)
        inputs = [fc, lstm]

    fc_last = layers.sequence_pool(input=inputs[0], pool_type="max")
    lstm_last = layers.sequence_pool(input=inputs[1], pool_type="max")
    logits = layers.fc([fc_last, lstm_last], size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits


def lstm_language_model(data=None, label=None, vocab_size=10000, emb_dim=200,
                        hid_dim=200, num_layers=2, max_len=35):
    """PTB-style LSTM LM: predict next token at every position. Loss is
    masked mean NLL over valid positions."""
    if data is None:
        data = layers.data(name="tokens", shape=[max_len], dtype="int64",
                           lod_level=1)
    if label is None:
        label = layers.data(name="targets", shape=[max_len], dtype="int64")
    seqlen = layers.sequence.get_seqlen(data)
    emb = layers.embedding(input=data, size=[vocab_size, emb_dim])
    emb = layers.sequence.tag_sequence(emb, seqlen)
    h = emb
    for _ in range(num_layers):
        proj = layers.fc(h, size=hid_dim * 4, num_flatten_dims=2)
        proj = layers.sequence.tag_sequence(proj, seqlen)
        h, _ = layers.dynamic_lstm(input=proj, size=hid_dim * 4)
    logits = layers.fc(h, size=vocab_size, num_flatten_dims=2)
    label3 = layers.unsqueeze(label, axes=[2])
    token_loss = layers.softmax_with_cross_entropy(logits, label3)
    mask = layers.sequence_mask(seqlen, maxlen=max_len)
    mask = layers.unsqueeze(mask, axes=[2])
    masked = layers.elementwise_mul(token_loss, mask)
    loss = layers.reduce_sum(masked) / layers.reduce_sum(mask)
    return loss, logits
