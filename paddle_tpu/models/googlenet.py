"""GoogLeNet / Inception-v1 (≙ reference benchmark legacy googlenet config,
benchmark/README.md:45-52 + IntelOptimizedPaddle.md:49-55 baselines).

TPU-first: NHWC, each inception branch is one fused conv (XLA concatenates
on the lane-aligned channel axis), optional bf16 conv inputs.
"""

from __future__ import annotations

from .. import layers


def _conv(input, ch, k, stride=1, padding=0, data_format="NHWC",
          use_bf16=False):
    return layers.conv2d(input, num_filters=ch, filter_size=k, stride=stride,
                         padding=padding, act="relu",
                         data_format=data_format, use_bf16=use_bf16)


def inception(input, c1, c3r, c3, c5r, c5, proj, data_format="NHWC",
              use_bf16=False):
    """One inception module: 1x1 | 1x1->3x3 | 1x1->5x5 | pool->1x1."""
    kw = dict(data_format=data_format, use_bf16=use_bf16)
    b1 = _conv(input, c1, 1, **kw)
    b2 = _conv(_conv(input, c3r, 1, **kw), c3, 3, padding=1, **kw)
    b3 = _conv(_conv(input, c5r, 1, **kw), c5, 5, padding=2, **kw)
    pool = layers.pool2d(input, pool_size=3, pool_stride=1, pool_padding=1,
                         pool_type="max", data_format=data_format)
    b4 = _conv(pool, proj, 1, **kw)
    c_axis = 1 if data_format == "NCHW" else 3
    return layers.concat([b1, b2, b3, b4], axis=c_axis)


_CFG = [
    # (c1, c3r, c3, c5r, c5, proj), with "pool" markers between stages
    (64, 96, 128, 16, 32, 32),     # 3a
    (128, 128, 192, 32, 96, 64),   # 3b
    "pool",
    (192, 96, 208, 16, 48, 64),    # 4a
    (160, 112, 224, 24, 64, 64),   # 4b
    (128, 128, 256, 24, 64, 64),   # 4c
    (112, 144, 288, 32, 64, 64),   # 4d
    (256, 160, 320, 32, 128, 128),  # 4e
    "pool",
    (256, 160, 320, 32, 128, 128),  # 5a
    (384, 192, 384, 48, 128, 128),  # 5b
]


def googlenet_imagenet(img=None, label=None, class_num=1000, is_test=False,
                       data_format="NHWC", use_bf16=False):
    """Returns (avg_loss, accuracy, logits). Aux classifier heads are
    omitted (modern practice; they only mattered for pre-BN optimization)."""
    if img is None:
        shape = [3, 224, 224] if data_format == "NCHW" else [224, 224, 3]
        img = layers.data("img", shape=shape)
    if label is None:
        label = layers.data("label", shape=[1], dtype="int64")

    kw = dict(data_format=data_format, use_bf16=use_bf16)
    x = _conv(img, 64, 7, stride=2, padding=3, **kw)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max", data_format=data_format)
    x = _conv(x, 64, 1, **kw)
    x = _conv(x, 192, 3, padding=1, **kw)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max", data_format=data_format)
    for cfg in _CFG:
        if cfg == "pool":
            x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                              pool_type="max", data_format=data_format)
        else:
            x = inception(x, *cfg, **kw)
    x = layers.pool2d(x, pool_type="avg", global_pooling=True,
                      data_format=data_format)
    x = layers.reshape(x, shape=[-1, 1024])
    x = layers.dropout(x, dropout_prob=0.4, is_test=is_test)
    logits = layers.fc(x, size=class_num, use_bf16=use_bf16)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits
