"""Model zoo mirroring the reference's benchmark models
(≙ reference benchmark/fluid/models/: mnist, resnet, vgg,
stacked_dynamic_lstm, machine_translation) plus the CTR model family the
pserver/sparse path served (DeepFM — driver config #5).

Each builder appends to the default main/startup programs via the layers API
and returns the loss (and aux outputs), exactly as the reference model files
build programs for fluid_benchmark.py.
"""

from . import (alexnet, deepfm, googlenet,  # noqa: F401
               machine_translation, mnist, ocr_crnn, resnet, se_resnext,
               ssd, stacked_lstm, transformer, vgg)
