"""CRNN-CTC text recognizer (capability ≙ the reference's OCR/CTC stack:
layers warpctc + ctc_align built over conv features and recurrent layers —
reference layers/nn.py warpctc, operators/warpctc_op.cc, ctc_align_op.cc;
the classic conv → BiGRU → CTC recipe its OCR models use).

TPU-first: image columns become the time axis by reshape/transpose (no
LoD), the BiGRU pair is two `dynamic_gru` scans (forward + is_reverse),
and the CTC loss/decoder lower to static-shape XLA dynamic programming."""

from __future__ import annotations

from .. import layers
from ..layers import sequence as seq


def crnn_ctc(img=None, label=None, num_classes=36, image_shape=(1, 32, 128),
             max_label_len=16, hidden=96, is_test=False):
    """conv stack (height -> 1 band) -> columns as sequence -> BiGRU ->
    per-column logits over num_classes+1 (blank last) -> CTC.

    Returns (loss_or_None, logits [B, W', C+1], seqlen [B]) — feed
    `ctc_greedy_decoder(logits, blank, seqlen)` for decoding.
    With is_test=True no loss/label vars are created."""
    if img is None:
        img = layers.data("img", shape=list(image_shape))
    if not is_test and label is None:
        label = layers.data("label", shape=[max_label_len], dtype="int64")

    def block(x, ch, pool_stride):
        x = layers.conv2d(x, num_filters=ch, filter_size=3, padding=1,
                          act="relu")
        return layers.pool2d(x, pool_size=pool_stride,
                             pool_stride=pool_stride)

    # H 32 -> 16 -> 8 -> 4 -> 2; W shrinks only twice (W/4 time steps)
    x = block(img, 32, (2, 2))
    x = block(x, 64, (2, 2))
    x = block(x, 96, (2, 1))
    x = block(x, 96, (2, 1))

    # [B, C, H, W] -> [B, W, C*H]: image columns are the time axis
    b_, c_, h_, w_ = x.shape
    x = layers.transpose(x, perm=[0, 3, 1, 2])
    feat = layers.reshape(x, shape=[-1, w_, c_ * h_])
    seqlen = layers.fill_constant_batch_size_like(
        feat, shape=[-1], dtype="int32", value=w_)
    feat = seq.tag_sequence(feat, seqlen)

    proj_f = seq.tag_sequence(
        layers.fc(feat, size=3 * hidden, num_flatten_dims=2), seqlen)
    proj_b = seq.tag_sequence(
        layers.fc(feat, size=3 * hidden, num_flatten_dims=2), seqlen)
    fwd = seq.dynamic_gru(proj_f, size=hidden)
    bwd = seq.dynamic_gru(proj_b, size=hidden, is_reverse=True)
    rnn = seq.tag_sequence(layers.concat([fwd, bwd], axis=2), seqlen)

    # +1 for the CTC blank, emitted as the LAST class
    logits = layers.fc(rnn, size=num_classes + 1, num_flatten_dims=2)
    logits = seq.tag_sequence(logits, seqlen)

    loss = None
    if not is_test:
        label_len = layers.fill_constant_batch_size_like(
            label, shape=[-1], dtype="int32", value=max_label_len)
        loss = layers.mean(seq.warpctc(logits, label, seqlen, label_len,
                                       blank=num_classes))
    return loss, logits, seqlen
