"""SSD object detector (capability ≙ the reference's SSD stack built from
layers/detection.py: multi_box_head:211, ssd_loss:264, detection_output —
the reference ships the layers and book-style flows rather than a single
canonical model file; this zoo model composes them the same way).

TPU-first: the whole pipeline — prior generation, bipartite matching,
hard-negative mining, smooth-L1/softmax losses, decode + NMS — lowers to
static-shape XLA (matching and NMS are scan+mask, no dynamic shapes), so
train and inference each compile to one program.
"""

from __future__ import annotations

from .. import layers
from ..layers import detection as det


def _conv_block(x, ch, n, name):
    for i in range(n):
        x = layers.conv2d(x, num_filters=ch, filter_size=3, padding=1,
                          act="relu", name=f"{name}_{i}")
    return layers.pool2d(x, pool_size=2, pool_type="max", pool_stride=2)


def ssd_detector(img=None, gt_box=None, gt_label=None, num_classes=21,
                 image_shape=(3, 128, 128), num_gt=8, is_test=False):
    """Compact VGG-style SSD over 3 feature scales.

    Returns (loss_or_None, decode_fn_inputs) where decode_fn_inputs =
    (locs, confs, boxes, variances) feed detection_output for inference.
    With is_test=True no loss/gt vars are created.
    """
    if img is None:
        img = layers.data("img", shape=list(image_shape))
    if not is_test:
        if gt_box is None:
            gt_box = layers.data("gt_box", shape=[num_gt, 4])
        if gt_label is None:
            gt_label = layers.data("gt_label", shape=[num_gt],
                                   dtype="int64")

    # backbone: 128 -> 64 -> 32 (f1) -> 16 (f2) -> 8 (f3)
    x = _conv_block(img, 32, 2, "ssd_c1")
    x = _conv_block(x, 64, 2, "ssd_c2")
    f1 = x                                     # stride 4
    x = _conv_block(f1, 128, 2, "ssd_c3")
    f2 = x                                     # stride 8
    x = _conv_block(f2, 128, 2, "ssd_c4")
    f3 = x                                     # stride 16

    s = float(min(image_shape[1], image_shape[2]))
    locs, confs, boxes, variances = det.multi_box_head(
        [f1, f2, f3], img, num_classes=num_classes,
        min_sizes=[[s * 0.1], [s * 0.25], [s * 0.45]],
        max_sizes=[[s * 0.25], [s * 0.45], [s * 0.75]],
        aspect_ratios=[[1.0, 2.0]] * 3, name="ssd_mbh")

    loss = None
    if not is_test:
        loss = det.ssd_loss(locs, confs, gt_box, gt_label, boxes,
                            overlap_threshold=0.5)
    return loss, (locs, confs, boxes, variances)


def ssd_decode(locs, confs, boxes, variances, score_threshold=0.01,
               keep_top_k=100, nms_threshold=0.45):
    """Inference head: softmax scores + decode + class-wise NMS.
    Returns (out [B, keep_top_k, 6] as [label, score, x1, y1, x2, y2],
    num_detections [B])."""
    probs = layers.softmax(confs)
    scores = layers.transpose(probs, perm=[0, 2, 1])   # [B, C, M]
    return det.detection_output(locs, scores, boxes, variances,
                                score_threshold=score_threshold,
                                keep_top_k=keep_top_k,
                                nms_threshold=nms_threshold)
