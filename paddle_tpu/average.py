"""≙ reference python/paddle/fluid/average.py (WeightedAverage)."""

from __future__ import annotations

import numpy as np

from .core.enforce import InvalidArgumentError, enforce


class WeightedAverage:
    """Running weighted average of scalar-ish metrics
    (≙ reference average.py WeightedAverage: add(value, weight), eval())."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight=1):
        value = np.asarray(value, dtype=np.float64)
        enforce(np.isfinite(value).all(),
                "WeightedAverage.add got non-finite value",
                exc=InvalidArgumentError)
        self.numerator += float(value.mean()) * float(weight)
        self.denominator += float(weight)

    def eval(self):
        enforce(self.denominator > 0,
                "WeightedAverage.eval before any add",
                exc=InvalidArgumentError)
        return self.numerator / self.denominator
