"""Reusable beam-search decoder.

≙ reference python/paddle/fluid/contrib/decoder/beam_search_decoder.py
(TrainingDecoder / BeamSearchDecoder state machine over DynamicRNN and
LoD beam trees). TPU translation: the beam dimension is a FIXED [B, K]
axis, the whole decode compiles into one StaticRNN scan (lax.scan), beam
survival is the beam_search op, recurrent state follows survivors through
a one-hot batched matmul (MXU-friendly), and the hypothesis tree is
unwound by gather_tree at the end — no dynamic LoD trees anywhere.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from .. import layers
from ..core.enforce import InvalidArgumentError, enforce


def gather_beams(x, parent):
    """Reorder beam-major FLOAT state x [B, K, ...] by parent indices
    [B, K]. The one-hot route keeps it a single batched matmul
    (MXU-friendly); trailing dims are flattened around the matmul so any
    state rank works."""
    enforce(str(x.dtype).startswith(("float", "bfloat")),
            f"gather_beams reorders float states (got {x.dtype}); gather "
            f"integer state through the selected-ids path instead",
            exc=InvalidArgumentError)
    k = x.shape[1]
    # ids as [B, K, 1]: a bare [B, 1] (K=1) would be read as an index
    # column by the one_hot convention and squeeze the beam dim away
    onehot = layers.one_hot(layers.unsqueeze(parent, axes=[2]),
                            depth=k)                   # [B, K, K]
    tail = list(x.shape[2:])
    if len(tail) > 1:
        flat = layers.reshape(x, [0, k, -1])           # [B, K, prod(tail)]
        out = layers.matmul(onehot, flat)
        return layers.reshape(out, [0, k] + tail)
    return layers.matmul(onehot, x)


class BeamSearchDecoder:
    """Generic fixed-beam decoder.

    The caller supplies a `step_fn(states, prev_ids) -> (new_states, logp)`
    operating on beam-expanded variables: every state is [B, K, ...], the
    ids are [B, K], and logp must be [B, K, vocab] log-probabilities.
    `decode` drives it max_len steps, keeps the top beam_size hypotheses
    per step (end_id hypotheses are frozen by the beam_search op), and
    returns (sequences [B, max_len, K], scores [B, K]).
    """

    def __init__(self, beam_size: int, bos_id: int, eos_id: int,
                 max_len: int, name: str = "beam_decoder"):
        enforce(beam_size >= 1, "beam_size must be >= 1",
                exc=InvalidArgumentError)
        self.beam_size = beam_size
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.max_len = max_len
        self.name = name

    def expand_to_beams(self, state):
        """[B, H] -> [B, K, H] (replicate an encoder state per beam)."""
        return layers.expand(layers.unsqueeze(state, axes=[1]),
                             expand_times=[1, self.beam_size, 1])

    def decode(self, batch_ref, init_states: Dict[str, object],
               step_fn: Callable, init_ids=None) -> Tuple[object, object]:
        """batch_ref: any variable whose dim 0 is the batch (shapes for the
        id/score/driver tensors derive from it); init_states: name -> [B, K,
        ...] beam-expanded variables (see expand_to_beams); init_ids
        (optional [B, 1] int64 var): per-row FIRST token to condition on —
        every beam starts from it — instead of the constant bos_id."""
        K = self.beam_size
        if init_ids is not None:
            ids0 = layers.expand(init_ids, expand_times=[1, K])
        else:
            ids0 = layers.fill_constant_batch_size_like(
                batch_ref, shape=[-1, K], dtype="int64", value=self.bos_id)
        # beam 0 live, beams 1..K-1 muted so step 1 expands ONE hypothesis
        # instead of K copies of the same bos continuation
        mute = layers.fill_constant_batch_size_like(
            batch_ref, shape=[-1, K], dtype="float32", value=-1e9)
        live0 = layers.fill_constant_batch_size_like(
            batch_ref, shape=[-1, 1], dtype="float32", value=0.0)
        if K > 1:
            scores0 = layers.concat(
                [live0, layers.slice(mute, axes=[1], starts=[1], ends=[K])],
                axis=1)
        else:
            scores0 = live0

        dummy = layers.fill_constant_batch_size_like(
            batch_ref, shape=[-1, self.max_len, 1], dtype="float32",
            value=0.0)

        rnn = layers.StaticRNN(name=self.name)
        with rnn.step():
            rnn.step_input(dummy)                      # drives max_len steps
            mem = {n: rnn.memory(init=v) for n, v in init_states.items()}
            ids_prev = rnn.memory(init=ids0)
            sc_prev = rnn.memory(init=scores0)

            new_states, logp = step_fn(dict(mem), ids_prev)
            enforce(set(new_states) == set(init_states),
                    "step_fn must return the same state names it was given",
                    exc=InvalidArgumentError)
            sel_ids, sel_scores, parent = layers.beam_search(
                ids_prev, sc_prev, logp, beam_size=K, end_id=self.eos_id)
            for n, v in new_states.items():
                # greedy (K=1) has exactly one hypothesis: parent is
                # identically 0 and the beam gather is an identity that
                # would still read+rewrite every state (the KV caches!)
                # once per step — skip it
                rnn.update_memory(mem[n],
                                  v if K == 1 else gather_beams(v, parent))
            rnn.update_memory(ids_prev, sel_ids)
            rnn.update_memory(sc_prev, sel_scores)
            rnn.step_output(sel_ids)
            rnn.step_output(parent)
        ids_seq, parent_seq = rnn()                    # [B, T, K] each
        final_scores = rnn.final_memories()[len(init_states) + 1]
        seqs = layers.beam_search_decode(ids_seq, parent_seq)
        return seqs, final_scores
