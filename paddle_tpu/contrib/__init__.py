"""Contrib utilities (≙ reference python/paddle/fluid/contrib/)."""

from .memory_usage_calc import memory_usage  # noqa: F401
