"""Estimate a program's device-memory footprint before running it.

≙ reference python/paddle/fluid/contrib/memory_usage_calc.py (memory_usage),
which sums var sizes to bracket GPU memory. TPU translation: the estimate
covers parameters + optimizer state (persistent across steps) and the
activation set (live inside one compiled step, before XLA's buffer reuse and
any rematerialization from transpiler.memory_optimize — so it is an upper
bound on activations, exact on state).
"""

from __future__ import annotations

from typing import Optional

from ..framework.program import Program, default_main_program

_DTYPE_BYTES = {"float32": 4, "float64": 8, "int32": 4, "int64": 8,
                "uint8": 1, "int8": 1, "bool": 1, "bfloat16": 2,
                "float16": 2, "int16": 2, "uint32": 4, "uint64": 8}


def _nbytes(var, batch_size: int) -> int:
    if var.shape is None:
        return 0
    numel = 1
    for d in var.shape:
        numel *= batch_size if int(d) == -1 else max(int(d), 1)
    name = var.dtype.name if hasattr(var.dtype, "name") else str(var.dtype)
    return numel * _DTYPE_BYTES.get(name, 4)


def memory_usage(program: Optional[Program] = None, batch_size: int = 1):
    """Returns a dict with byte counts:

    - ``parameters``: trainable + persistable state (params, moments,
      moving stats) — resident for the whole job
    - ``activations``: every non-persistable var the main block produces —
      an upper bound on one step's intermediate footprint (XLA reuses dead
      buffers; memory_optimize remat shrinks this further)
    - ``total`` and human-readable ``summary``
    """
    program = program or default_main_program()
    params = 0
    activations = 0
    seen = set()
    for block in program.blocks:
        for name, var in block.vars.items():
            if name in seen:
                continue
            seen.add(name)
            if getattr(var, "persistable", False):
                params += _nbytes(var, batch_size)
            elif not getattr(var, "is_data", False):
                activations += _nbytes(var, batch_size)
    total = params + activations

    def fmt(n):
        for unit in ("B", "KB", "MB", "GB", "TB"):
            if n < 1024 or unit == "TB":
                return f"{n:.2f} {unit}"
            n /= 1024.0

    return {"parameters": params, "activations": activations,
            "total": total,
            "summary": (f"state {fmt(float(params))}, activations <= "
                        f"{fmt(float(activations))}, total <= "
                        f"{fmt(float(total))} at batch_size={batch_size}")}
