"""Weight-decay regularizers appended as program ops.

≙ reference python/paddle/fluid/regularizer.py (L1DecayRegularizer,
L2DecayRegularizer appended during optimizer.minimize).
"""

from __future__ import annotations

from .core.dtypes import dtype_name
from .layer_helper import LayerHelper


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_tmp_variable(dtype=dtype_name(param.dtype),
                                           shape=param.shape,
                                           stop_gradient=True)
        block.append_op("scale", inputs={"X": [param]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self.coeff, "bias": 0.0,
                               "bias_after_scale": True})
        new_grad = helper.create_tmp_variable(dtype=dtype_name(grad.dtype),
                                              shape=grad.shape,
                                              stop_gradient=True)
        block.append_op("sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [new_grad]})
        return new_grad


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_tmp_variable(dtype=dtype_name(param.dtype),
                                          shape=param.shape,
                                          stop_gradient=True)
        block.append_op("sign", inputs={"X": [param]},
                        outputs={"Out": [sign]})
        decay = helper.create_tmp_variable(dtype=dtype_name(param.dtype),
                                           shape=param.shape,
                                           stop_gradient=True)
        block.append_op("scale", inputs={"X": [sign]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self.coeff, "bias": 0.0,
                               "bias_after_scale": True})
        new_grad = helper.create_tmp_variable(dtype=dtype_name(grad.dtype),
                                              shape=grad.shape,
                                              stop_gradient=True)
        block.append_op("sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [new_grad]})
        return new_grad


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    """≙ reference regularizer.py append_regularization_ops."""
    out = []
    for param, grad in params_grads:
        reg = param.regularizer or regularization
        if reg is None:
            out.append((param, grad))
            continue
        block = grad.block
        out.append((param, reg(param, grad, block)))
    return out
