"""LayerHelper — shared plumbing for layer functions.

≙ reference python/paddle/fluid/layer_helper.py: creates parameters in BOTH
the main program (as Parameter vars) and the startup program (var + init op),
creates temporaries, appends ops, and applies bias/activation.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .core import unique_name
from .core.dtypes import dtype_name
from .core.enforce import InvalidArgumentError, enforce
from .framework.program import (Parameter, Variable, default_main_program,
                                default_startup_program)
from .initializer import (_global_bias_initializer,
                          _global_weight_initializer)
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
        self.name = kwargs.get("name") or unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    # -- parameters -------------------------------------------------------
    def create_parameter(self, attr, shape: Sequence[int], dtype="float32",
                         is_bias: bool = False,
                         default_initializer=None) -> Parameter:
        attr = ParamAttr._to_attr(attr)
        enforce(attr is not None, "parameter attr must not be False here",
                exc=InvalidArgumentError)
        name = attr.name or unique_name.generate(f"{self.name}.w")
        init = attr.initializer or default_initializer or (
            _global_bias_initializer() if is_bias
            else _global_weight_initializer())
        main_block = self.main_program.global_block()
        if name in main_block.vars:
            # shared parameter (attr.name reused) — return existing
            return main_block.vars[name]
        p = main_block.create_parameter(
            name=name, shape=list(shape), dtype=dtype,
            trainable=attr.trainable, regularizer=attr.regularizer,
            gradient_clip=attr.gradient_clip)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        if attr.sharding_spec is not None:
            p.sharding_spec = tuple(attr.sharding_spec)
        # mirror into startup program with its initializer op
        sb = self.startup_program.global_block()
        if name not in sb.vars:
            sv = sb.create_parameter(name=name, shape=list(shape),
                                     dtype=dtype, trainable=attr.trainable)
            init(sv, sb)
        return p

    # -- temporaries ------------------------------------------------------
    def create_tmp_variable(self, dtype="float32", shape=None,
                            stop_gradient: bool = False) -> Variable:
        return self.block.create_var(
            name=unique_name.generate(f"{self.name}.tmp"),
            shape=shape, dtype=dtype, stop_gradient=stop_gradient)

    def create_variable(self, name=None, persistable=False, dtype="float32",
                        shape=None) -> Variable:
        return self.block.create_var(name=name, shape=shape, dtype=dtype,
                                     persistable=persistable)

    def create_global_variable(self, name=None, persistable=True,
                               dtype="float32", shape=None,
                               stop_gradient=True) -> Variable:
        return self.main_program.global_block().create_var(
            name=name or unique_name.generate(f"{self.name}.global"),
            shape=shape, dtype=dtype, persistable=persistable,
            stop_gradient=stop_gradient)

    def append_op(self, **kwargs):
        return self.block.append_op(
            kwargs["type"], kwargs.get("inputs"), kwargs.get("outputs"),
            kwargs.get("attrs"))

    # -- bias / activation (≙ LayerHelper.append_bias_op/append_activation) --
    def append_bias_op(self, input_var: Variable, dim_start: int = 1,
                       dim_end: Optional[int] = None,
                       use_bf16: bool = False) -> Variable:
        bias_attr = ParamAttr._to_attr(self.kwargs.get("bias_attr"))
        if bias_attr is None:
            return input_var
        size = input_var.shape[dim_start:dim_end]
        b = self.create_parameter(bias_attr, shape=list(size),
                                  dtype=dtype_name(input_var.dtype),
                                  is_bias=True)
        out = self.create_tmp_variable(dtype=dtype_name(input_var.dtype),
                                       shape=input_var.shape)
        # use_bf16: the add casts the fp32 bias down to the activation dtype
        # instead of promoting the whole tensor back to fp32
        self.append_op(type="elementwise_add",
                       inputs={"X": [input_var], "Y": [b]},
                       outputs={"Out": [out]},
                       attrs={"axis": dim_start, "use_bf16": use_bf16})
        return out

    def append_activation(self, input_var: Variable) -> Variable:
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        out = self.create_tmp_variable(dtype=dtype_name(input_var.dtype),
                                       shape=input_var.shape)
        self.append_op(type=act, inputs={"X": [input_var]},
                       outputs={"Out": [out]}, attrs={})
        return out
