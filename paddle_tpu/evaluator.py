"""≙ reference python/paddle/fluid/evaluator.py — the deprecated Evaluator
aliases the reference kept for compatibility; real implementations live in
paddle_tpu.metrics."""

from .metrics import (Accuracy, Auc, ChunkEvaluator,  # noqa: F401
                      DetectionMAP, EditDistance, Precision, Recall)

__all__ = ["Accuracy", "Auc", "ChunkEvaluator", "DetectionMAP",
           "EditDistance", "Precision", "Recall"]
