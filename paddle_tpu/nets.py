"""Composite networks built from layers (≙ reference python/paddle/fluid/nets.py).

Each composite appends ops to the default program via the layers API, exactly
as the reference composes them (nets.py:simple_img_conv_pool, img_conv_group,
sequence_conv_pool, glu, scaled_dot_product_attention at nets.py:332).
"""

from __future__ import annotations

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    """conv2d + pool2d (≙ reference nets.py simple_img_conv_pool)."""
    conv_out = layers.conv2d(input=input, num_filters=num_filters,
                             filter_size=filter_size, stride=conv_stride,
                             padding=conv_padding, dilation=conv_dilation,
                             groups=conv_groups, param_attr=param_attr,
                             bias_attr=bias_attr, act=act)
    return layers.pool2d(input=conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride,
                         pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Stack of convs (+ optional BN/dropout) followed by a pool — the VGG
    building block (≙ reference nets.py img_conv_group)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _to_list(obj):
        if isinstance(obj, (list, tuple)):
            assert len(obj) == len(conv_num_filter)
            return list(obj)
        return [obj] * len(conv_num_filter)

    conv_padding = _to_list(conv_padding)
    conv_filter_size = _to_list(conv_filter_size)
    param_attr = _to_list(param_attr)
    conv_batchnorm_drop_rate = _to_list(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm:
            local_conv_act = None
        tmp = layers.conv2d(input=tmp, num_filters=conv_num_filter[i],
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i], param_attr=param_attr[i],
                            act=local_conv_act)
        if conv_with_batchnorm:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    """sequence_conv + sequence_pool (≙ reference nets.py sequence_conv_pool)."""
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    """Gated linear unit: split in half along dim, a * sigmoid(b)
    (≙ reference nets.py glu)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0, is_test=False):
    """Multi-head scaled dot-product attention over [B, T, C] tensors
    (≙ reference nets.py:332). Returns [B, Tq, C_v].

    TPU note: this is the composite form; the fused flash/ring variants live
    in paddle_tpu.ops (flash_attention) and paddle_tpu.parallel
    (ring_attention) — this one exists for API parity and as the XLA-fusable
    baseline.
    """
    if queries.shape[-1] % num_heads != 0:
        raise ValueError("hidden size must divide num_heads")

    def _split_heads(x):
        if num_heads == 1:
            return x
        b, t, c = x.shape
        x = layers.reshape(x, shape=[b if b and b > 0 else -1, t, num_heads,
                                     c // num_heads])
        return layers.transpose(x, perm=[0, 2, 1, 3])

    def _merge_heads(x):
        if num_heads == 1:
            return x
        b, h, t, d = x.shape
        x = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(x, shape=[b if b and b > 0 else -1, t, h * d])

    q = _split_heads(queries)
    k = _split_heads(keys)
    v = _split_heads(values)
    key_dim = float(int(queries.shape[-1]) // num_heads)
    scaled_q = layers.scale(q, scale=key_dim ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 is_test=is_test)
    ctx = layers.matmul(weights, v)
    return _merge_heads(ctx)
