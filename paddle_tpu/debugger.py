"""Program inspection: pretty printing + graphviz dumps.

≙ reference python/paddle/fluid/debugger.py (pprint_program_codes :275,
draw_block_graphviz) and net_drawer.py / the ir graph_viz_pass.
"""

from __future__ import annotations

import os
from typing import Optional

from .framework.program import Block, Program


def _var_brief(block: Block, name: str) -> str:
    if block.has_var(name):
        v = block.var(name)
        shape = list(v.shape) if v.shape is not None else "?"
        tag = "P" if getattr(v, "is_parameter", False) or \
            v.__class__.__name__ == "Parameter" else \
            ("s" if v.persistable else "t")
        return f"{name}[{tag}:{v.dtype}:{shape}]"
    return name


def pprint_block_codes(block: Block, show_backward: bool = True) -> str:
    """Render a block as pseudo-code, one op per line."""
    lines = []
    for i, op in enumerate(block.ops):
        outs = ", ".join(_var_brief(block, n) for n in op.output_names())
        ins = ", ".join(_var_brief(block, n) for n in op.input_names())
        attrs = {k: v for k, v in op.attrs.items()
                 if not k.startswith("_") and not callable(v)}
        attr_s = ""
        if attrs:
            short = ", ".join(f"{k}={v!r}" for k, v in sorted(attrs.items())
                              if not hasattr(v, "ops"))[:120]
            if short:
                attr_s = f"  # {short}"
        lines.append(f"  {i:>4}: {outs} = {op.type}({ins}){attr_s}")
    return "\n".join(lines)


def pprint_program_codes(program: Program) -> str:
    """≙ debugger.pprint_program_codes — dump every block."""
    parts = []
    for bi, block in enumerate(program.blocks):
        parts.append(f"block {bi} {{")
        parts.append(pprint_block_codes(block))
        parts.append("}")
    return "\n".join(parts)


def draw_block_graphviz(block: Block, path: str,
                        highlights: Optional[set] = None) -> str:
    """Write a graphviz .dot file of the block's op/var dataflow
    (≙ debugger.draw_block_graphviz / graph_viz_pass)."""
    highlights = highlights or set()
    lines = ["digraph G {", '  rankdir="TB";',
             '  node [fontsize=10];']
    seen_vars = set()

    def var_node(name):
        nid = f"var_{name}".replace(".", "_").replace("@", "_")
        if name not in seen_vars:
            seen_vars.add(name)
            color = ', style=filled, fillcolor="#ffcccc"' \
                if name in highlights else ""
            shape = "ellipse"
            if block.has_var(name) and block.var(name).persistable:
                shape = "box3d"
            lines.append(
                f'  {nid} [label="{_var_brief(block, name)}", '
                f'shape={shape}{color}];')
        return nid

    for i, op in enumerate(block.ops):
        onid = f"op_{i}"
        lines.append(f'  {onid} [label="{op.type}", shape=box, '
                     f'style=filled, fillcolor="#ccccff"];')
        for n in op.input_names():
            lines.append(f"  {var_node(n)} -> {onid};")
        for n in op.output_names():
            lines.append(f"  {onid} -> {var_node(n)};")
    lines.append("}")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


def dump_hlo(program: Program, feed_shapes: dict, path: Optional[str] = None,
             fetch_list=None) -> str:
    """Lower the program's global block to StableHLO text — the compiled-IR
    dump the reference never had (its nearest analogue is the ProgramDesc
    protobuf dump). Useful for verifying fusion / sharding decisions."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .framework.lowering import LowerCtx, build_plan, run_plan

    block = program.global_block()
    plan = build_plan(block)
    fetch_names = [getattr(f, "name", f) for f in (fetch_list or [])]
    if not fetch_names:
        produced = [n for op in block.ops for n in op.output_names()]
        fetch_names = produced[-1:]
    feed_names = sorted(feed_shapes)

    # zero-fill every block-declared var the program reads but doesn't feed
    # (parameters etc.) so lowering sees fully-defined inputs
    read = set()
    produced = set()
    for op in block.ops:
        read |= set(op.input_names())
        produced |= set(op.output_names())
    implicit = sorted(n for n in read - produced - set(feed_names)
                      if block.has_var(n) and block.var(n).shape is not None
                      and -1 not in block.var(n).shape)

    def fn(*feed_vals):
        env = dict(zip(feed_names, feed_vals))
        for n in implicit:
            v = block.var(n)
            env[n] = jnp.zeros(tuple(v.shape), dtype=v.dtype)
        ctx = LowerCtx(rng_key=jax.random.PRNGKey(0))
        run_plan(plan, env, block, ctx)
        return tuple(env[n] for n in fetch_names)

    args = [jnp.zeros(s, dtype=np.float32) if not isinstance(s, tuple) or
            len(s) != 2 or not isinstance(s[1], str)
            else jnp.zeros(s[0], dtype=s[1]) for s in
            (feed_shapes[n] for n in feed_names)]
    text = jax.jit(fn).lower(*args).as_text()
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
