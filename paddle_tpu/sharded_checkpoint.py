"""Sharded (per-process) checkpointing: tensorstore-style save/restore.

Each process writes ONLY its addressable shards — a native tensor-store
container (`shard-<p>.pts`) plus a JSON manifest (`manifest-<p>.json`)
mapping each variable chunk to its global-offset slice. Restore re-shards
onto whatever mesh is current: a host reads just the chunks intersecting
its addressable slices, so state that does not fit one host (ZeRO-1
optimizer shards, expert/embedding partitions) round-trips without ever
being gathered.

Capability translation (SURVEY §5 checkpoint row: "jittable sharded
checkpoint (tensorstore-style)"): the reference checkpoints pserver-side
state per shard by construction (reference
paddle/fluid/operators/listen_and_serv_op.cc checkpoint handler;
python/paddle/fluid/trainer.py:641 _save_checkpoint with per-trainer and
per-pserver artifacts); on TPU the sharding lives on the arrays
themselves, so the per-process slice map comes from
`jax.Array.addressable_shards`.

Layout of a checkpoint directory:
    shard-0.pts      chunks owned by process 0 (native container)
    manifest-0.json  {var: {shape, dtype, chunks: [{start, shape, file,
                      key}]}}
    shard-1.pts, manifest-1.json, ...

A chunk is recorded once per distinct slice (replica_id == 0 dedupe), so
replicated axes do not bloat the checkpoint.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.enforce import InvalidArgumentError, NotFoundError, enforce

MANIFEST_PREFIX = "manifest-"
SHARD_PREFIX = "shard-"


def _slice_starts(index, shape) -> List[int]:
    """Normalize a Shard.index (tuple of slices) to absolute start offsets."""
    starts = []
    for sl, dim in zip(index, shape):
        start, _, step = sl.indices(dim)
        enforce(step == 1, "strided shards are not supported",
                exc=InvalidArgumentError)
        starts.append(int(start))
    # scalar / rank-0 arrays have an empty index
    return starts


def collect_chunks(arrays: Dict[str, object],
                   process_index: Optional[int] = None,
                   world_size: Optional[int] = None,
                   only_devices=None):
    """The device→host phase of a sharded save, split out so an async
    snapshot (parallel/elastic.py) can copy state off-device at a step
    boundary and hand the file writes to a background thread. Returns
    (chunks, manifest, pid): `chunks` maps chunk key → host numpy array,
    `manifest` is the per-process manifest dict referencing them."""
    import jax
    import jax.numpy as jnp

    pid = jax.process_index() if process_index is None else int(process_index)
    world = jax.process_count() if world_size is None else int(world_size)
    chunks: Dict[str, np.ndarray] = {}
    manifest: Dict[str, dict] = {"__meta__": {"world_size": world}}
    shard_file = f"{SHARD_PREFIX}{pid}.pts"
    for name, arr in arrays.items():
        if not hasattr(arr, "addressable_shards"):
            # host array: keep its exact numpy dtype (jnp.asarray would
            # silently narrow int64/float64 under default jax config)
            data = np.asarray(arr)
            key = name + "@" + ",".join("0" for _ in data.shape)
            chunks[key] = data
            manifest[name] = {
                "shape": list(data.shape), "dtype": str(data.dtype),
                "chunks": [{"start": [0] * data.ndim,
                            "shape": list(data.shape),
                            "file": shard_file, "key": key}]}
            continue
        arr = jnp.asarray(arr)
        entry = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                 "chunks": []}
        for sh in arr.addressable_shards:
            if sh.replica_id != 0:
                continue  # one writer per distinct slice
            if only_devices is not None and sh.device not in only_devices:
                continue
            starts = _slice_starts(sh.index, arr.shape)
            data = np.asarray(sh.data)
            key = name + "@" + ",".join(map(str, starts))
            chunks[key] = data
            entry["chunks"].append({"start": starts,
                                    "shape": list(data.shape),
                                    "file": shard_file, "key": key})
        if entry["chunks"]:
            manifest[name] = entry
    return chunks, manifest, pid


def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def truncate_payload_at(dirname: str, offset: int,
                        exclude: Sequence[str] = ()) -> bool:
    """Make `dirname` look exactly as if a sequential writer died
    `offset` bytes into its payload: truncate the file holding that
    offset, delete everything after it (deterministic name order;
    `.tmp` files and `exclude` names are not payload). Returns False
    when the offset lies beyond the payload (nothing touched) — the ONE
    copy of the crash-offset accounting both fault-injection paths
    (elastic crash_mid_save, process_world crash_rank stage) share."""
    names = sorted(n for n in os.listdir(dirname)
                   if n not in exclude and not n.endswith(".tmp"))
    cum = 0
    for i, n in enumerate(names):
        sz = os.path.getsize(os.path.join(dirname, n))
        if offset < cum + sz:
            with open(os.path.join(dirname, n), "r+b") as f:
                f.truncate(offset - cum)
            for later in names[i + 1:]:
                os.unlink(os.path.join(dirname, later))
            return True
        cum += sz
    return False


def write_chunks(dirname: str, chunks: Dict[str, np.ndarray],
                 manifest: Dict[str, dict], pid: int,
                 fsync: bool = False) -> str:
    """The file-write phase of a sharded save (see collect_chunks).
    fsync=True forces shard container and manifest to stable storage
    before returning — the elastic commit protocol requires it (the
    COMMIT marker must never become visible before the data it names).
    Returns the manifest path."""
    from .data.tensor_store import save_tensors
    os.makedirs(dirname, exist_ok=True)
    shard_file = f"{SHARD_PREFIX}{pid}.pts"
    # write-then-replace: re-saving into an existing checkpoint dir must not
    # clobber the shard container the still-valid old manifest points to if
    # we crash mid-write (the manifest swap below is only atomic if the data
    # it references is too)
    spath = os.path.join(dirname, shard_file)
    save_tensors(spath + ".tmp", chunks)
    os.replace(spath + ".tmp", spath)
    mpath = os.path.join(dirname, f"{MANIFEST_PREFIX}{pid}.json")
    tmp = mpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, mpath)  # atomic: a crash never clobbers a good manifest
    if fsync:
        _fsync_file(spath)
        _fsync_file(dirname)
    return mpath


def save_sharded(dirname: str, arrays: Dict[str, object],
                 process_index: Optional[int] = None,
                 world_size: Optional[int] = None,
                 only_devices=None, fsync: bool = False) -> str:
    """Write this process's addressable shards of `arrays` to dirname.

    world_size (default jax.process_count()) is recorded in the manifest;
    the reader refuses a directory whose manifest count does not match it,
    so a re-save from a SMALLER world over an old checkpoint directory
    errors instead of silently stitching stale shard files in.

    only_devices: restrict to shards living on these devices — used by
    single-process tests to emulate the per-host split of a multi-host
    save (in a real multi-host world addressable_shards IS that split).
    Returns the manifest path.
    """
    chunks, manifest, pid = collect_chunks(
        arrays, process_index=process_index, world_size=world_size,
        only_devices=only_devices)
    return write_chunks(dirname, chunks, manifest, pid, fsync=fsync)


class ShardedCheckpoint:
    """Reader over all manifests of a checkpoint directory. Chunk data is
    loaded lazily per (file, key) and cached, so restoring a slice touches
    only the containers that hold intersecting chunks."""

    def __init__(self, dirname: str):
        self.dirname = dirname
        paths = sorted(glob.glob(
            os.path.join(dirname, MANIFEST_PREFIX + "*.json")))
        if not paths:
            raise NotFoundError(f"no sharded checkpoint under {dirname!r} "
                                f"(no {MANIFEST_PREFIX}*.json)")
        self.vars: Dict[str, dict] = {}
        world_sizes = set()
        for p in paths:
            try:
                with open(p) as f:
                    m = json.load(f)
            except (json.JSONDecodeError, UnicodeDecodeError) as e:
                # a raw json error names neither the checkpoint nor the
                # hazard — say what is actually wrong (an interrupted save
                # left a truncated/corrupt manifest behind)
                raise InvalidArgumentError(
                    f"checkpoint dir {dirname!r}: manifest "
                    f"{os.path.basename(p)!r} is truncated or corrupt "
                    f"({e}) — an interrupted save? Restore from a "
                    f"committed snapshot instead") from e
            meta = m.pop("__meta__", None)
            if meta is not None:
                world_sizes.add(int(meta.get("world_size", len(paths))))
            for name, entry in m.items():
                known = self.vars.get(name)
                if known is None:
                    self.vars[name] = {"shape": entry["shape"],
                                       "dtype": entry["dtype"],
                                       "chunks": list(entry["chunks"])}
                else:
                    enforce(known["shape"] == entry["shape"] and
                            known["dtype"] == entry["dtype"],
                            f"manifests disagree on {name!r}",
                            exc=InvalidArgumentError)
                    known["chunks"].extend(entry["chunks"])
        if world_sizes:
            enforce(len(world_sizes) == 1 and
                    world_sizes == {len(paths)},
                    f"checkpoint dir {dirname!r} holds {len(paths)} "
                    f"manifest(s) but the save recorded world_size"
                    f"={sorted(world_sizes)} — stale files from an earlier "
                    f"save with a different process count? Save into a "
                    f"fresh directory.", exc=InvalidArgumentError)
        self._cache: Dict[tuple, np.ndarray] = {}
        # every shard container a manifest references must exist: a clear
        # error up front beats a per-chunk IO error mid-restore
        missing = sorted({e["file"] for v in self.vars.values()
                          for e in v["chunks"]
                          if not os.path.exists(
                              os.path.join(dirname, e["file"]))})
        enforce(not missing,
                f"checkpoint dir {dirname!r} is missing shard container(s) "
                f"{missing} referenced by its manifest(s) — a partially "
                f"written or partially deleted save. Restore from a "
                f"committed snapshot instead", exc=InvalidArgumentError)

    def names(self) -> List[str]:
        return sorted(self.vars)

    def _chunk(self, c) -> np.ndarray:
        key = (c["file"], c["key"])
        if key not in self._cache:
            from .data.tensor_store import load_tensors
            try:
                got = load_tensors(os.path.join(self.dirname, c["file"]),
                                   [c["key"]])
            except (IOError, OSError, KeyError, ValueError) as e:
                raise InvalidArgumentError(
                    f"checkpoint dir {self.dirname!r}: shard container "
                    f"{c['file']!r} is truncated or corrupt reading chunk "
                    f"{c['key']!r} ({e}) — an interrupted save? Restore "
                    f"from a committed snapshot instead") from e
            self._cache[key] = got[c["key"]]
        return self._cache[key]

    def read_slice(self, name: str, index) -> np.ndarray:
        """Assemble the sub-array `index` (tuple of slices in global
        coordinates) of var `name` from every intersecting chunk."""
        if name not in self.vars:
            raise NotFoundError(f"{name!r} not in checkpoint")
        entry = self.vars[name]
        shape = entry["shape"]
        import ml_dtypes  # registers bfloat16 with numpy
        del ml_dtypes
        dtype = np.dtype(entry["dtype"]) if entry["dtype"] != "bfloat16" \
            else np.dtype("bfloat16")
        starts, stops = [], []
        for sl, dim in zip(index, shape):
            a, b, step = sl.indices(dim)
            enforce(step == 1, "strided restore not supported",
                    exc=InvalidArgumentError)
            starts.append(a)
            stops.append(b)
        out = np.empty([b - a for a, b in zip(starts, stops)], dtype)
        filled = np.zeros(out.shape, bool) if entry["chunks"] else None
        for c in entry["chunks"]:
            c_start = c["start"] or [0] * len(shape)
            c_stop = [s + d for s, d in zip(c_start, c["shape"])]
            inter_a = [max(a, ca) for a, ca in zip(starts, c_start)]
            inter_b = [min(b, cb) for b, cb in zip(stops, c_stop)]
            if any(a >= b for a, b in zip(inter_a, inter_b)) and out.ndim:
                continue
            dst = tuple(slice(a - o, b - o)
                        for a, b, o in zip(inter_a, inter_b, starts))
            src = tuple(slice(a - o, b - o)
                        for a, b, o in zip(inter_a, inter_b, c_start))
            if out.ndim == 0:
                out[...] = np.asarray(self._chunk(c)).reshape(())
            else:
                out[dst] = self._chunk(c)[src]
            if filled is not None:
                filled[dst] = True
        if filled is not None and not filled.all():
            raise NotFoundError(
                f"checkpoint chunks do not cover the requested slice of "
                f"{name!r} (a shard file from another process is missing?)")
        return out

    def read(self, name: str) -> np.ndarray:
        entry = self.vars[name]
        return self.read_slice(
            name, tuple(slice(0, d) for d in entry["shape"]))


def restore_array(ckpt: ShardedCheckpoint, name: str, sharding=None):
    """Materialize var `name` from the checkpoint.

    sharding=None: full host (numpy) array in the exact saved dtype — not
    run through jnp.asarray, which would narrow int64/float64 under the
    default jax config. With a jax Sharding: build the
    (possibly distributed) array via make_array_from_callback — each
    process reads ONLY the chunks its addressable slices intersect, which
    is what lets a restore re-shard onto a different mesh/device count
    without any host ever holding the full state."""
    import jax

    entry = ckpt.vars.get(name)
    if entry is None:
        raise NotFoundError(f"{name!r} not in checkpoint")
    if sharding is None:
        return ckpt.read(name)
    shape = tuple(entry["shape"])
    return jax.make_array_from_callback(
        shape, sharding, lambda idx: ckpt.read_slice(name, idx))


def restore_sharded(dirname: str, shardings: Optional[Dict] = None,
                    names: Optional[Sequence[str]] = None) -> Dict[str, object]:
    """Restore {name: array} for `names` (default: everything saved).
    shardings maps name -> jax Sharding (missing/None -> host array)."""
    ckpt = ShardedCheckpoint(dirname)
    shardings = shardings or {}
    out = {}
    for name in (names if names is not None else ckpt.names()):
        out[name] = restore_array(ckpt, name, shardings.get(name))
    return out
