"""Inference API.

≙ reference python/paddle/fluid/inferencer.py (Inferencer:113 area) and the
C++ predictor interface (api/paddle_inference_api.h PaddlePredictor,
api/api_impl.cc:126 NativePaddlePredictor::Run). The TPU predictor wraps a
loaded inference program + scope in an Executor whose compiled step is
cached — repeated `infer` calls with same shapes hit the XLA executable
cache, which is the analogue of the reference cloning one Executor per
predictor thread.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from . import io as pio
from .core.enforce import InvalidArgumentError, enforce
from .framework.executor import Executor
from .framework.program import Program
from .framework.scope import Scope


class Predictor:
    """Load-and-run predictor (≙ NativePaddlePredictor)."""

    def __init__(self, model_dir: str, place=None,
                 scope: Optional[Scope] = None):
        self.scope = scope or Scope()
        self.executor = Executor(place)
        self.program, self.feed_names, self.fetch_names = \
            pio.load_inference_model(model_dir, executor=self.executor,
                                     scope=self.scope)

    def run(self, feed: Dict[str, Any],
            fetch_names: Optional[Sequence[str]] = None,
            return_numpy: bool = True) -> List[Any]:
        missing = set(self.feed_names) - set(feed)
        extra = {k for k in feed
                 if k not in self.feed_names and
                 not k.endswith("@SEQLEN")}
        enforce(not missing, f"missing feeds: {sorted(missing)}",
                exc=InvalidArgumentError)
        enforce(not extra, f"unexpected feeds: {sorted(extra)}",
                exc=InvalidArgumentError)
        return self.executor.run(program=self.program, feed=feed,
                                 fetch_list=list(fetch_names or
                                                 self.fetch_names),
                                 scope=self.scope,
                                 return_numpy=return_numpy)

    def clone(self) -> "Predictor":
        """≙ PaddlePredictor::Clone — share weights (scope), fresh executor
        caches for another thread/stream of requests."""
        p = object.__new__(Predictor)
        p.scope = self.scope
        p.executor = Executor(self.executor.place)
        p.program = self.program
        p.feed_names = list(self.feed_names)
        p.fetch_names = list(self.fetch_names)
        return p

    @staticmethod
    def from_exported(model_dir: str) -> "ExportedPredictor":
        """Cold-load the jax.export artifact written by
        save_inference_model(..., export=True). The returned predictor runs
        with no program, no op registry, and no tracer — the serving path
        (≙ the reference's C++ predictor loading a ProgramDesc+params dir,
        api_impl.cc:126; here the deployable unit is serialized StableHLO
        executable by any PJRT runtime)."""
        return ExportedPredictor(model_dir)


class ExportedPredictor:
    """Serve a serialized StableHLO inference function (see
    io.export_inference_model). Parameters travel inside the artifact."""

    def __init__(self, model_dir: str):
        self._exported, self.feed_names, self.fetch_names = \
            pio.load_exported_model(model_dir)

    def run(self, feed: Dict[str, Any],
            fetch_names: Optional[Sequence[str]] = None,
            return_numpy: bool = True) -> List[Any]:
        # same error contract as Predictor.run
        missing = set(self.feed_names) - set(feed)
        extra = set(feed) - set(self.feed_names)
        enforce(not missing, f"missing feeds: {sorted(missing)}",
                exc=InvalidArgumentError)
        enforce(not extra, f"unexpected feeds: {sorted(extra)}",
                exc=InvalidArgumentError)
        if fetch_names is not None:
            unknown = set(fetch_names) - set(self.fetch_names)
            enforce(not unknown,
                    f"unknown fetch names {sorted(unknown)}; exported "
                    f"fetches are {self.fetch_names}",
                    exc=InvalidArgumentError)
        outs = self._exported.call(*(feed[n] for n in self.feed_names))
        if fetch_names is not None:
            index = {n: i for i, n in enumerate(self.fetch_names)}
            outs = [outs[index[n]] for n in fetch_names]
        if return_numpy:
            import numpy as np
            return [np.asarray(o) for o in outs]
        return list(outs)


class Inferencer:
    """≙ fluid.Inferencer — high-level wrapper over Predictor."""

    def __init__(self, param_path: str, place=None,
                 scope: Optional[Scope] = None):
        self._predictor = Predictor(param_path, place=place, scope=scope)

    @property
    def program(self) -> Program:
        return self._predictor.program

    def infer(self, inputs: Dict[str, Any], return_numpy: bool = True):
        return self._predictor.run(inputs, return_numpy=return_numpy)
