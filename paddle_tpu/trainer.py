"""High-level event-driven training loop with checkpoint/resume.

≙ reference python/paddle/fluid/trainer.py: Trainer (:169) with
Begin/EndEpoch + Begin/EndStep events (:40-99), CheckpointConfig (:100),
serial-numbered checkpoint dirs with retention (_scroll_delete :1168),
trainer-args persistence, `_SUCCESS` markers (:1190), and resume-on-init
(load_checkpoint :741). The reference's pserver/dist-transpile branch maps to
the SPMD ParallelExecutor path here (parallel strategies compile into the
step; no separate server processes on TPU).
"""

from __future__ import annotations

import json
import os
import shutil
from time import perf_counter as _perf_counter
from typing import Callable, List, Optional, Sequence

from . import io as _io
from . import optimizer as _optimizer_mod
from .core.enforce import InvalidArgumentError, enforce
from .data.feeder import DataFeeder
from .framework.executor import Executor
from .framework.program import (Program, Variable, program_guard)
from .framework.scope import Scope


class BeginEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id: int, step_id: int):
        self.epoch = epoch_id
        self.step = step_id
        #: set True by a handler to get metrics fetched this step
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id: int, step_id: int, metrics: list):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """≙ trainer.CheckpointConfig (reference trainer.py:100).

    elastic=True routes through the atomic elastic runtime
    (parallel/elastic.py): two-phase-committed snapshots carrying the
    COMPLETE training state (params, sharded optimizer accumulators,
    error-feedback residuals, RNG seed counters, parallel config), with
    deterministic resume and dp-world resize on restore — the
    preemption-safe mode (docs/fault_tolerance.md). async_save
    additionally moves the file writes off the step critical path (only
    the device→host copy runs at the step boundary)."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 max_num_checkpoints: int = 3,
                 epoch_interval: int = 1,
                 step_interval: int = 10,
                 sharded: bool = False,
                 elastic: bool = False,
                 async_save: bool = False):
        self.checkpoint_dir = checkpoint_dir or \
            os.path.join(os.getcwd(), "checkpoint")
        enforce(epoch_interval >= 1 and step_interval >= 1,
                "checkpoint intervals must be >= 1",
                exc=InvalidArgumentError)
        enforce(not (async_save and not elastic),
                "async_save requires elastic=True (only the elastic "
                "runtime has the background commit protocol)",
                exc=InvalidArgumentError)
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = epoch_interval
        self.step_interval = step_interval
        # sharded=True: per-process shard files via sharded_checkpoint —
        # the at-scale mode (ZeRO-1/EP state never gathered to one host)
        self.sharded = sharded
        self.elastic = elastic
        self.async_save = async_save
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial: Optional[int] = None


CHECKPOINT_PREFIX = "checkpoint"
TRAINER_ARGS_FILE = "trainer_args.json"
SUCCESS_MARKER = "_SUCCESS"

_train_metrics = None


def training_metrics():
    """The trainer-side operational series, registered (idempotently)
    into `observability.metrics.default_registry()` — one /metrics
    scrape sees training throughput next to the `ptpu_ckpt_*`
    checkpoint counters and the engine's serving series."""
    global _train_metrics
    if _train_metrics is None:
        from .observability import metrics as m
        r = m.default_registry()
        _train_metrics = {
            "steps": m.get_or_create(
                r, "counter", "ptpu_train_steps_total",
                "Training steps executed by Trainer.train."),
            "epochs": m.get_or_create(
                r, "counter", "ptpu_train_epochs_total",
                "Training epochs completed by Trainer.train."),
            "step_seconds": m.get_or_create(
                r, "histogram", "ptpu_train_step_seconds",
                "Wall time of one training step (feed + dispatch + "
                "fetch).",
                buckets=(1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1,
                         0.25, 0.5, 1.0, 2.5, 5.0, 10.0)),
        }
    return _train_metrics


def _serial_dir(root: str, serial: int) -> str:
    return os.path.join(root, f"{CHECKPOINT_PREFIX}_{serial}")


def _list_serials(root: str) -> List[int]:
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if not name.startswith(CHECKPOINT_PREFIX + "_"):
            continue
        suffix = name[len(CHECKPOINT_PREFIX) + 1:]
        if suffix.isdigit() and os.path.exists(
                os.path.join(root, name, SUCCESS_MARKER)):
            out.append(int(suffix))
    return sorted(out)


def get_latest_checkpoint_serial(root: str) -> int:
    """Latest *complete* (marker present) checkpoint serial, or -1."""
    serials = _list_serials(root)
    return serials[-1] if serials else -1


def _global_barrier(tag: str):
    """No-op in a single-process world; in a jax.distributed world, block
    until every process reaches the same tag (the multi-phase commit
    protocol below depends on it)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def save_checkpoint(executor: Executor, checkpoint_dir: str,
                    main_program: Program,
                    trainer_args: Optional[dict] = None,
                    max_num_checkpoints: int = 3,
                    scope: Optional[Scope] = None,
                    sharded: bool = False,
                    serial: Optional[int] = None) -> int:
    """Write persistables + trainer args into the next serial dir; commit via
    the `_SUCCESS` marker only after all state hit disk (crash-safe: readers
    ignore marker-less dirs); then scroll-delete old serials
    (≙ trainer.save_checkpoint :641 + _scroll_delete :1168).

    sharded=True routes through sharded_checkpoint: each process writes
    only its addressable shards. Multi-process commit protocol (all
    phases separated by a global barrier so the marker really means
    "complete"): the chief clears leftovers from a preempted attempt ->
    everyone writes shards -> the CHIEF ALONE writes trainer args +
    _SUCCESS. Every process must call save_checkpoint at the same point
    in the program; `serial` may be passed explicitly (all processes
    agree trivially since the barrier orders them; by default each reads
    the same directory state after the barrier)."""
    import jax
    chief = jax.process_index() == 0
    multi = jax.process_count() > 1 and sharded
    if multi:
        # order every process behind the same view of the directory
        _global_barrier("ptpu_ckpt_enter")
    if serial is None:
        serial = get_latest_checkpoint_serial(checkpoint_dir) + 1
    cur = _serial_dir(checkpoint_dir, serial)
    if (chief or not multi) and os.path.isdir(cur):
        shutil.rmtree(cur)  # incomplete leftovers from a preempted run
    os.makedirs(cur, exist_ok=True)
    if multi:
        _global_barrier("ptpu_ckpt_cleaned")   # nobody writes into leftovers
    _io.save_persistables(executor, cur, main_program=main_program,
                          scope=scope, sharded=sharded)
    if multi:
        _global_barrier("ptpu_ckpt_written")   # all shards are on disk
    if chief or not multi:
        if trainer_args is not None:
            with open(os.path.join(cur, TRAINER_ARGS_FILE), "w") as f:
                json.dump(trainer_args, f)
        with open(os.path.join(cur, SUCCESS_MARKER), "w") as f:
            f.write("")
        # retention: keep the most recent max_num_checkpoints, and never
        # the serial just written (an explicit low `serial` override must
        # not delete its own checkpoint)
        serials = [s for s in _list_serials(checkpoint_dir) if s != serial]
        for old in serials[:-(max_num_checkpoints - 1) or None]:
            shutil.rmtree(_serial_dir(checkpoint_dir, old),
                          ignore_errors=True)
    if multi:
        # nobody returns until the marker exists — otherwise a fast
        # non-chief process could enter the NEXT save, read a stale
        # directory state, and compute a different serial (split-brain
        # checkpoint dirs)
        _global_barrier("ptpu_ckpt_committed")
    return serial


def load_checkpoint(executor: Executor, checkpoint_dir: str,
                    main_program: Program,
                    serial: Optional[int] = None,
                    scope: Optional[Scope] = None,
                    sharded: bool = False,
                    shardings=None) -> Optional[dict]:
    """Restore persistables from the given (default: latest complete)
    serial; returns the saved trainer args or None if no checkpoint.
    sharded/shardings: restore a sharded checkpoint, re-sharding onto the
    current mesh (see io.load_persistables)."""
    if serial is None:
        serial = get_latest_checkpoint_serial(checkpoint_dir)
    if serial < 0:
        return None
    cur = _serial_dir(checkpoint_dir, serial)
    _io.load_persistables(executor, cur, main_program=main_program,
                          scope=scope, sharded=sharded,
                          shardings=shardings)
    args_path = os.path.join(cur, TRAINER_ARGS_FILE)
    if os.path.exists(args_path):
        with open(args_path) as f:
            return json.load(f)
    return {}


class Trainer:
    """≙ fluid.Trainer (reference trainer.py:169).

    train_func: () -> loss Variable (or [loss, metric, ...]); builds the
    forward program when called under our program guard.
    optimizer_func: () -> Optimizer.
    """

    def __init__(self, train_func: Callable,
                 optimizer_func: Callable[[], "_optimizer_mod.Optimizer"],
                 place=None,
                 parallel: bool = False,
                 checkpoint_config: Optional[CheckpointConfig] = None,
                 mesh=None):
        self.checkpoint_cfg = checkpoint_config
        self.place = place
        self.parallel = parallel
        self.mesh = mesh
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        self.stop_flag = False

        with program_guard(self.train_program, self.startup_program):
            outs = train_func()
            if isinstance(outs, (list, tuple)):
                self.loss = outs[0]
                self.metrics = list(outs)
            else:
                self.loss = outs
                self.metrics = [outs]
            # forward-only clone BEFORE optimizer ops are appended, so
            # test() cannot touch parameters (≙ main.clone(for_test=True))
            self.test_program = self.train_program.clone(for_test=True)
            opt = optimizer_func()
            enforce(isinstance(opt, _optimizer_mod.Optimizer),
                    "optimizer_func must return an Optimizer",
                    exc=InvalidArgumentError)
            opt.minimize(self.loss)

        self.exe = Executor(place)
        self.exe.run(self.startup_program, scope=self.scope)
        self._pe = None
        if parallel:
            from .parallel import DeviceMesh, ParallelExecutor
            mesh = mesh or DeviceMesh.default_data_parallel()
            self._pe = ParallelExecutor(loss_name=self.loss.name, mesh=mesh,
                                        main_program=self.train_program,
                                        scope=self.scope)

        if self.checkpoint_cfg and self.checkpoint_cfg.elastic:
            from .parallel import elastic as _elastic
            snap = _elastic.latest_snapshot(
                self.checkpoint_cfg.checkpoint_dir)
            if snap is not None:
                meta = _elastic.restore_train_state(
                    snap, program=self.train_program, scope=self.scope,
                    executor=self._train_executor())
                extra = meta.get("extra", {})
                self.checkpoint_cfg.epoch_id = int(extra.get("epoch_id", 0))
                self.checkpoint_cfg.step_id = int(extra.get("step_id", 0))
        elif self.checkpoint_cfg:
            args = load_checkpoint(self.exe,
                                   self.checkpoint_cfg.checkpoint_dir,
                                   self.train_program, scope=self.scope,
                                   sharded=self.checkpoint_cfg.sharded)
            if args:
                self.checkpoint_cfg.epoch_id = int(args.get("epoch_id", 0))
                self.checkpoint_cfg.step_id = int(args.get("step_id", 0))
                self.checkpoint_cfg.load_serial = \
                    get_latest_checkpoint_serial(
                        self.checkpoint_cfg.checkpoint_dir)

    def _train_executor(self):
        """The executor whose run counter drives the training seed
        stream — what the elastic snapshot must record/restore."""
        return self._pe if self._pe is not None else self.exe

    def stop(self):
        """Ask train() to exit after the current step (callable from the
        event handler — ≙ trainer.stop)."""
        self.stop_flag = True

    def train(self, num_epochs: int, event_handler: Callable,
              reader: Callable, feed_order: Sequence[str]):
        """Saved trainer args are the NEXT work item (resume_epoch,
        resume_step): a resumed run skips everything already trained —
        including the whole run when it had completed."""
        from .observability import memory as _memory
        from .parallel import elastic as _elastic
        # materialize the ptpu_memory_*/ptpu_mfu families up front: a
        # scrape or crash dossier taken before the first step must see
        # them (the executor stamps the values per run)
        _memory.memory_metrics()
        feeder = DataFeeder(feed_list=[
            self.train_program.global_block().var(n) for n in feed_order])
        start_epoch = (self.checkpoint_cfg.epoch_id
                       if self.checkpoint_cfg else 0)
        skip_steps = (self.checkpoint_cfg.step_id
                      if self.checkpoint_cfg else 0)
        elastic = bool(self.checkpoint_cfg and self.checkpoint_cfg.elastic)
        for epoch_id in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch_id))
            for step_id, batch in enumerate(reader()):
                if epoch_id == start_epoch and step_id < skip_steps:
                    continue  # already trained before preemption
                if self.stop_flag:
                    if self.checkpoint_cfg:
                        self._save_checkpoint(epoch_id, step_id)
                    if elastic:
                        # the stop-checkpoint may be async: it must
                        # commit before train() returns, or a prompt
                        # process exit kills the writer mid-write
                        _elastic.wait_for_pending()
                    return
                if elastic:
                    # PTPU_FAULT_INJECT=crash_at_step preemption point —
                    # BEFORE the step, so the snapshot interval decides
                    # how much work a preemption replays
                    _elastic.maybe_crash_at_step(
                        self._train_executor()._run_counter)
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                fetch = [m.name for m in self.metrics] \
                    if begin.fetch_metrics else []
                feed = feeder.feed(batch)
                t_step = _perf_counter()
                if self._pe is not None:
                    metrics = self._pe.run(feed=feed, fetch_list=fetch)
                else:
                    metrics = self.exe.run(self.train_program, feed=feed,
                                           fetch_list=fetch,
                                           scope=self.scope)
                tm = training_metrics()
                tm["steps"].inc()
                tm["step_seconds"].observe(_perf_counter() - t_step)
                event_handler(EndStepEvent(epoch_id, step_id, metrics))
                if (self.checkpoint_cfg and
                        (step_id + 1) % self.checkpoint_cfg.step_interval
                        == 0):
                    self._save_checkpoint(epoch_id, step_id + 1)
            event_handler(EndEpochEvent(epoch_id))
            training_metrics()["epochs"].inc()
            if (self.checkpoint_cfg and
                    (epoch_id + 1) % self.checkpoint_cfg.epoch_interval == 0):
                self._save_checkpoint(epoch_id + 1, 0)
        if self.checkpoint_cfg:
            self._save_checkpoint(num_epochs, 0)
        if elastic:
            # no writer thread may still hold dirty state at exit
            _elastic.wait_for_pending()

    def test(self, reader: Callable, feed_order: Sequence[str]):
        """Average the metric values over the reader, on the forward-only
        test program (no backward/optimize ops — parameters are not
        touched)."""
        feeder = DataFeeder(feed_list=[
            self.test_program.global_block().var(n) for n in feed_order])
        import numpy as np
        totals = None
        count = 0
        for batch in reader():
            feed = feeder.feed(batch)
            vals = self.exe.run(self.test_program, feed=feed,
                                fetch_list=[m.name for m in self.metrics],
                                scope=self.scope)
            vals = [np.mean(np.asarray(v)) for v in vals]
            totals = vals if totals is None else \
                [t + v for t, v in zip(totals, vals)]
            count += 1
        enforce(count > 0, "test reader yielded no batches",
                exc=InvalidArgumentError)
        return [t / count for t in totals]

    def save_params(self, param_path: str):
        _io.save_params(self.exe, param_path,
                        main_program=self.train_program, scope=self.scope)

    def save_inference_model(self, param_path: str,
                             feeded_var_names: Sequence[str],
                             target_vars: Sequence[Variable]):
        _io.save_inference_model(param_path, feeded_var_names, target_vars,
                                 executor=self.exe,
                                 main_program=self.train_program,
                                 scope=self.scope)

    def _save_checkpoint(self, resume_epoch: int, resume_step: int):
        if self.checkpoint_cfg.elastic:
            from .parallel import elastic as _elastic
            exe = self._train_executor()
            _elastic.save_train_state(
                self.checkpoint_cfg.checkpoint_dir,
                program=self.train_program, scope=self.scope, executor=exe,
                step=exe._run_counter,
                extra_meta={"epoch_id": resume_epoch,
                            "step_id": resume_step},
                max_snapshots=self.checkpoint_cfg.max_num_checkpoints,
                block=not self.checkpoint_cfg.async_save)
            return
        save_checkpoint(
            self.exe, self.checkpoint_cfg.checkpoint_dir, self.train_program,
            trainer_args={"epoch_id": resume_epoch, "step_id": resume_step},
            max_num_checkpoints=self.checkpoint_cfg.max_num_checkpoints,
            scope=self.scope, sharded=self.checkpoint_cfg.sharded)


class SupervisorExhaustedError(RuntimeError):
    """The Supervisor's restart budget ran out without a clean exit —
    the terminal crash-loop signal (raise_on_exhaust=True)."""

    def __init__(self, message: str, exit_code: int,
                 exit_codes: Sequence[int]):
        super().__init__(message)
        self.exit_code = exit_code
        self.exit_codes = list(exit_codes)


class Supervisor:
    """Retry/backoff supervisor for preemptible training processes.

    The process-level half of elastic recovery (≙ the reference's
    pserver/trainer restart story, checkpoint-mediated here): run the
    training command as a child, and when it dies of a crash/preemption
    (SIGKILL, OOM, nonzero exit), relaunch it after an exponential
    backoff — the restarted run resumes from the latest COMMITTED
    elastic snapshot (CheckpointConfig(elastic=True) or
    parallel.elastic.restore_train_state in the child). A clean exit 0
    ends supervision.

        Supervisor([sys.executable, "train.py"], max_restarts=20).run()

    Hardening knobs:

    - the restart budget is a HARD cap: when it runs out, run() logs a
      clear terminal crash-loop error and returns the last exit code —
      or raises SupervisorExhaustedError with raise_on_exhaust=True — so
      a persistently broken child fails loudly instead of looping under
      ever-longer backoffs;
    - `backoff_jitter` decorrelates a gang of supervisors restarting
      after a shared failure (thundering herd): each delay is scaled by
      a uniform factor in [1-j, 1+j];
    - `healthy_run_s` resets the backoff to its base after a child that
      ran at least that long: a crash every few hours is a preemption
      pattern and deserves fast restarts, not the accumulated backoff of
      a morning's crash loop.

    world_size > 1 supervises a GANG of rank processes: the same argv is
    launched once per rank with PTPU_WORLD_RANK/PTPU_WORLD_SIZE in the
    env; any rank dying kills the rest of the gang (SIGTERM, then wait)
    and the whole world restarts together — the restart granularity the
    chief-commits barrier assumes (a half-restarted world would dead-ack
    the barrier). Structure-pinned for hardware; in this container the
    gang members cannot form a jax process world (jaxlib 0.4.x), so
    multi-rank children run the simulated ProcessWorld internally.

    `dossier_dir` arms the flight recorder across restarts
    (observability/flight_recorder.py): children inherit
    PTPU_DOSSIER_DIR (their barrier phase beacons and crash dossiers
    land there) plus PTPU_SUPERVISOR_RESTARTS (surfaced on /healthz),
    and after every incarnation that DIES the supervisor folds the
    beacons + dossiers into `post_mortem-<k>.json` — which rank died,
    in which barrier phase, with the per-rank straggler timeline —
    before restarting the gang. Paths collect in `self.post_mortems`.

    Fault injection (PTPU_FAULT_INJECT, parallel/elastic.py +
    parallel/process_world.py) makes the crash side testable:
    tests/test_elastic.py and tools/recovery_smoke.py supervise children
    that SIGKILL themselves mid-run, mid-save, and mid-barrier.
    """

    def __init__(self, argv: Sequence[str],
                 max_restarts: int = 10,
                 backoff_s: float = 1.0,
                 backoff_factor: float = 2.0,
                 max_backoff_s: float = 60.0,
                 backoff_jitter: float = 0.0,
                 healthy_run_s: Optional[float] = None,
                 world_size: int = 1,
                 raise_on_exhaust: bool = False,
                 env: Optional[dict] = None,
                 dossier_dir: Optional[str] = None,
                 sleep_fn: Optional[Callable[[float], None]] = None,
                 rng=None):
        enforce(len(argv) >= 1, "Supervisor needs a command",
                exc=InvalidArgumentError)
        enforce(max_restarts >= 0 and backoff_s >= 0
                and backoff_factor >= 1.0,
                "Supervisor: max_restarts >= 0, backoff_s >= 0, "
                "backoff_factor >= 1 required", exc=InvalidArgumentError)
        enforce(0.0 <= backoff_jitter < 1.0,
                "Supervisor: backoff_jitter must be in [0, 1)",
                exc=InvalidArgumentError)
        enforce(world_size >= 1, "Supervisor: world_size must be >= 1",
                exc=InvalidArgumentError)
        enforce(healthy_run_s is None or healthy_run_s > 0,
                "Supervisor: healthy_run_s must be positive",
                exc=InvalidArgumentError)
        self.argv = list(argv)
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.backoff_factor = backoff_factor
        self.max_backoff_s = max_backoff_s
        self.backoff_jitter = backoff_jitter
        self.healthy_run_s = healthy_run_s
        self.world_size = world_size
        self.raise_on_exhaust = raise_on_exhaust
        self.env = env
        self.dossier_dir = dossier_dir
        if dossier_dir:
            os.makedirs(dossier_dir, exist_ok=True)
        self._sleep = sleep_fn or __import__("time").sleep
        self._rng = rng or __import__("random").Random()
        #: restarts performed by the last run()
        self.restarts = 0
        #: True when the last run() ended by exhausting the budget
        self.exhausted = False
        #: exit codes observed, in order (negative = killed by signal);
        #: for a gang, the FIRST nonzero code of each incarnation
        self.exit_codes: List[int] = []
        #: post_mortem-<k>.json paths written by the last run()
        self.post_mortems: List[str] = []

    def _child_env(self, rank: Optional[int] = None) -> dict:
        env = dict(self.env if self.env is not None else os.environ)
        if self.dossier_dir:
            env["PTPU_DOSSIER_DIR"] = self.dossier_dir
        env["PTPU_SUPERVISOR_RESTARTS"] = str(self.restarts)
        if rank is not None:
            env["PTPU_WORLD_RANK"] = str(rank)
            env["PTPU_WORLD_SIZE"] = str(self.world_size)
        return env

    def _write_post_mortem(self):
        """After an incarnation died: fold the dossier dir's beacons +
        dossiers into post_mortem-<incarnation>.json, then ARCHIVE them
        into an incarnation-<k>/ subdir — the next incarnation's
        beacons start from a clean top level, so a stale crash marker
        from a previous death can never win the next post-mortem's
        verdict (and the fold stays bounded on long-running jobs). The
        children are already dead here, so no writer holds the moved
        files open. Best-effort — a post-mortem failure must never
        break supervision itself."""
        if not self.dossier_dir:
            return
        from .core import flags
        from .observability import flight_recorder as _fr
        try:
            k = len(self.exit_codes)
            path = _fr.write_post_mortem(
                self.dossier_dir, incarnation=k,
                extra={"exit_code": self.exit_codes[-1],
                       "restarts": self.restarts,
                       "argv": self.argv})
            self.post_mortems.append(path)
            archive = os.path.join(self.dossier_dir, f"incarnation-{k}")
            os.makedirs(archive, exist_ok=True)
            for name in os.listdir(self.dossier_dir):
                if name.startswith((_fr.BEACON_PREFIX,
                                    _fr.DOSSIER_PREFIX)):
                    os.replace(os.path.join(self.dossier_dir, name),
                               os.path.join(archive, name))
            flags.vlog(0, "Supervisor: post-mortem %s", path)
        except Exception as e:  # noqa: BLE001 - best effort
            flags.vlog(0, "Supervisor: post-mortem failed: %s: %s",
                       type(e).__name__, e)

    def _launch_gang(self):
        """One incarnation: world_size children with rank identities in
        env. Returns the incarnation's exit code: 0 iff every rank
        exited 0; otherwise the first failing rank's code, after the
        rest of the gang was terminated (the barrier protocol assumes
        whole-world restarts)."""
        import subprocess
        if self.world_size == 1:
            return subprocess.run(self.argv,
                                  env=self._child_env()).returncode
        procs = []
        for r in range(self.world_size):
            procs.append(subprocess.Popen(self.argv,
                                          env=self._child_env(r)))
        import time as _time
        rc = 0
        kill_deadline = None
        live = set(range(self.world_size))
        while live:
            for r in sorted(live):
                code = procs[r].poll()
                if code is None:
                    continue
                live.discard(r)
                if code != 0 and rc == 0:
                    rc = code
                    # gang semantics: one death restarts the world
                    for r2 in sorted(live):
                        procs[r2].terminate()
                    kill_deadline = _time.monotonic() + 10.0
            if live and kill_deadline is not None \
                    and _time.monotonic() >= kill_deadline:
                # a rank ignoring SIGTERM (wedged in native code) must
                # not hang the supervisor — escalate to SIGKILL; the
                # barrier protocol is kill-safe by construction
                for r2 in sorted(live):
                    procs[r2].kill()
                kill_deadline = float("inf")
            if live:
                _time.sleep(0.05)
        if rc != 0:
            for p in procs:
                p.wait()
        return rc

    def run(self) -> int:
        """Supervise until the world exits 0 or the restart budget is
        spent. Returns the final exit code (0 on success; the child's
        last code — negative for a signal death — when the budget ran
        out; raises SupervisorExhaustedError instead when
        raise_on_exhaust=True)."""
        import time as _time

        from .core import flags
        self.restarts = 0
        self.exhausted = False
        self.exit_codes = []
        self.post_mortems = []
        delay = self.backoff_s
        while True:
            t0 = _time.monotonic()
            rc = self._launch_gang()
            ran_s = _time.monotonic() - t0
            self.exit_codes.append(rc)
            if rc == 0:
                return 0
            # the incarnation died: synthesize its post-mortem from the
            # flight-recorder beacons/dossiers BEFORE restarting (a
            # restarted gang appends new beacon lines)
            self._write_post_mortem()
            if self.restarts >= self.max_restarts:
                self.exhausted = True
                msg = (f"Supervisor: restart budget ({self.max_restarts})"
                       f" exhausted — the child is crash-looping, not "
                       f"being preempted (exit codes {self.exit_codes});"
                       f" last exit code {rc}. Fix the persistent "
                       f"failure; restarting further would only mask it")
                flags.vlog(0, "%s", msg)
                if self.raise_on_exhaust:
                    raise SupervisorExhaustedError(msg, rc,
                                                   self.exit_codes)
                return rc
            if (self.healthy_run_s is not None
                    and ran_s >= self.healthy_run_s):
                # a long healthy run before this death: preemption
                # pattern, not a crash loop — restart fast again
                delay = self.backoff_s
            flags.vlog(0, "Supervisor: child exited %d (%s) after %.1fs; "
                       "restart %d/%d after %.1fs backoff", rc,
                       "signal" if rc < 0 else "error", ran_s,
                       self.restarts + 1, self.max_restarts, delay)
            jitter = 1.0
            if self.backoff_jitter:
                jitter += self._rng.uniform(-self.backoff_jitter,
                                            self.backoff_jitter)
            if delay > 0:
                self._sleep(delay * jitter)
            delay = min(delay * self.backoff_factor, self.max_backoff_s)
            self.restarts += 1
