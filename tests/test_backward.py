"""Autodiff machinery tests: multiple losses, calc_gradient, clipping.

≙ reference tests/unittests/test_calc_gradient.py + backward coverage.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers

import pytest

pytestmark = pytest.mark.quick  # run_ci.sh quick smoke tier


def test_two_losses_shared_trunk(rng):
    """Two vjp_regions whose forward segments share the earliest op must both
    execute (regression: build_plan used to key regions by min index)."""
    x = layers.data(name="x", shape=[4])
    trunk = layers.fc(x, size=8, act="relu")
    head1 = layers.fc(trunk, size=1)
    head2 = layers.fc(trunk, size=1)
    loss1 = layers.mean(head1)
    loss2 = layers.mean(head2)
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss1)
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss2)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    l1, l2 = exe.run(feed={"x": rng.rand(8, 4).astype(np.float32)},
                     fetch_list=[loss1, loss2])
    assert np.isfinite(l1) and np.isfinite(l2)


def test_calc_gradient(rng):
    x = layers.data(name="x", shape=[3], stop_gradient=False)
    y = layers.fc(x, size=1, bias_attr=False)
    grads = pt.calc_gradient(y, x)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = rng.rand(5, 3).astype(np.float32)
    gx, = exe.run(feed={"x": xv}, fetch_list=[grads[0]])
    # d(sum(xW))/dx = broadcast of W^T
    w_name = pt.default_main_program().all_parameters()[0].name
    w = np.asarray(pt.global_scope().get(w_name))
    np.testing.assert_allclose(gx, np.tile(w.T, (5, 1)), rtol=1e-5)


def test_gradient_clip_by_global_norm(rng):
    x = layers.data(name="x", shape=[4])
    h = layers.fc(x, size=16, act="relu")
    y = layers.fc(h, size=1)
    loss = layers.mean(y)
    pt.clip.set_gradient_clip(pt.clip.GradientClipByGlobalNorm(0.5))
    opt = pt.optimizer.SGD(learning_rate=1.0)
    opt.minimize(loss)
    # shared scale subgraph: sqrt op appears exactly once
    ops = [op.type for op in pt.default_main_program().global_block().ops]
    assert ops.count("sqrt") == 1
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    lo, = exe.run(feed={"x": rng.rand(8, 4).astype(np.float32)},
                  fetch_list=[loss])
    assert np.isfinite(lo)


def test_regularizer_appends_ops(rng):
    x = layers.data(name="x", shape=[4])
    y = layers.fc(x, size=2)
    loss = layers.mean(y)
    opt = pt.optimizer.SGD(learning_rate=0.1,
                           regularization=pt.regularizer.L2Decay(0.01))
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    lo, = exe.run(feed={"x": rng.rand(4, 4).astype(np.float32)},
                  fetch_list=[loss])
    assert np.isfinite(lo)


def test_stop_gradient_blocks_flow(rng):
    """A stop_gradient var cuts the path: grads wrt params behind it are 0."""
    x = layers.data(name="x", shape=[4])
    h = layers.fc(x, size=4, bias_attr=False)
    h.stop_gradient = True  # cut here
    y = layers.fc(h, size=1, bias_attr=False)
    loss = layers.mean(y)
    params = pt.default_main_program().all_parameters()
    pgs = pt.append_backward(loss, parameter_list=[p.name for p in params])
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    fetches = [g for _, g in pgs]
    outs = exe.run(feed={"x": rng.rand(4, 4).astype(np.float32)},
                   fetch_list=fetches)
    by_name = {g.name: o for (_, g), o in zip(pgs, outs)}
    first_w = params[0].name + "@GRAD"
    np.testing.assert_allclose(by_name[first_w], 0.0, atol=1e-7)
