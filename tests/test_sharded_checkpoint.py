"""Sharded checkpoint: per-process shard files + manifest, re-shard restore.

≙ SURVEY §5 checkpoint translation ("jittable sharded checkpoint,
tensorstore-style"); reference per-shard pserver checkpoints
(trainer.py:641, listen_and_serv_op.cc checkpoint handler). VERDICT r2 #5.

The 8-device CPU mesh stands in for a pod slice; the multi-host split is
emulated with save_sharded(only_devices=...) — in a real multi-host world
`addressable_shards` IS that split, same code path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.sharded_checkpoint import (ShardedCheckpoint, restore_array,
                                           restore_sharded, save_sharded)


def _mesh(shape, names):
    devs = np.array(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


class TestSaveRestoreRoundTrip:
    def test_plain_arrays_round_trip(self, tmp_path):
        rng = np.random.RandomState(0)
        arrays = {
            "w": rng.randn(16, 8).astype(np.float32),
            "b": rng.randn(8).astype(np.float32),
            "step": np.asarray(7, np.int64),
        }
        save_sharded(str(tmp_path), {k: jnp.asarray(v)
                                     for k, v in arrays.items()})
        back = restore_sharded(str(tmp_path))
        assert sorted(back) == ["b", "step", "w"]
        for k in arrays:
            np.testing.assert_array_equal(np.asarray(back[k]), arrays[k])

    def test_bf16_round_trip(self, tmp_path):
        x = jnp.linspace(0, 1, 64, dtype=jnp.bfloat16).reshape(8, 8)
        save_sharded(str(tmp_path), {"xb": x})
        back = restore_sharded(str(tmp_path))["xb"]
        assert str(back.dtype) == "bfloat16"
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))

    def test_sharded_array_dedupes_replicas(self, tmp_path):
        """dp-replicated tp-sharded array: only ONE copy of each distinct
        slice is written (replica_id == 0), not one per device."""
        mesh = _mesh((4, 2), ("dp", "tp"))
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        xs = jax.device_put(x, NamedSharding(mesh, P(None, "tp")))
        save_sharded(str(tmp_path), {"x": xs})
        ckpt = ShardedCheckpoint(str(tmp_path))
        assert len(ckpt.vars["x"]["chunks"]) == 2  # tp=2 slices, dp deduped
        np.testing.assert_array_equal(ckpt.read("x"), np.asarray(x))


class TestMultiProcessEmulation:
    def test_two_process_split_and_restore(self, tmp_path):
        """Each 'process' writes only its half of a dp-sharded array; the
        reader stitches both manifests; a missing shard file is detected."""
        mesh = _mesh((8,), ("dp",))
        rng = np.random.RandomState(1)
        w = rng.randn(16, 4).astype(np.float32)
        acc = rng.randn(16, 4).astype(np.float32)  # ZeRO-1-style accumulator
        ws = jax.device_put(jnp.asarray(w), NamedSharding(mesh, P("dp")))
        accs = jax.device_put(jnp.asarray(acc), NamedSharding(mesh, P("dp")))
        devs = jax.devices()
        save_sharded(str(tmp_path), {"w": ws, "acc": accs},
                     process_index=0, world_size=2,
                     only_devices=set(devs[:4]))
        save_sharded(str(tmp_path), {"w": ws, "acc": accs},
                     process_index=1, world_size=2,
                     only_devices=set(devs[4:]))

        ckpt = ShardedCheckpoint(str(tmp_path))
        assert len(ckpt.vars["w"]["chunks"]) == 8
        np.testing.assert_array_equal(ckpt.read("w"), w)
        np.testing.assert_array_equal(ckpt.read("acc"), acc)

    def test_stale_manifest_world_mismatch_rejected(self, tmp_path):
        """Regression: re-saving from a smaller world over an old
        checkpoint dir must error, not silently stitch stale shards."""
        mesh = _mesh((8,), ("dp",))
        w = jnp.arange(32, dtype=jnp.float32).reshape(16, 2)
        ws = jax.device_put(w, NamedSharding(mesh, P("dp")))
        devs = jax.devices()
        save_sharded(str(tmp_path), {"w": ws}, process_index=0,
                     world_size=2, only_devices=set(devs[:4]))
        save_sharded(str(tmp_path), {"w": ws}, process_index=1,
                     world_size=2, only_devices=set(devs[4:]))
        # later: a 1-process world re-saves into the same directory
        save_sharded(str(tmp_path), {"w": ws}, process_index=0,
                     world_size=1)
        with pytest.raises(Exception) as ei:
            ShardedCheckpoint(str(tmp_path))
        assert "stale" in str(ei.value) or "world_size" in str(ei.value)

    def test_int64_scalar_dtype_preserved(self, tmp_path):
        """Regression: host int64 values (global step counters) must not
        be narrowed to int32 by a jnp round-trip on save or restore."""
        big = np.asarray(5_000_000_000, np.int64)
        save_sharded(str(tmp_path), {"global_step": big})
        back = restore_sharded(str(tmp_path))["global_step"]
        assert back.dtype == np.int64
        assert int(back) == 5_000_000_000

    def test_missing_shard_detected(self, tmp_path):
        mesh = _mesh((8,), ("dp",))
        w = jnp.arange(32, dtype=jnp.float32).reshape(16, 2)
        ws = jax.device_put(w, NamedSharding(mesh, P("dp")))
        devs = jax.devices()
        save_sharded(str(tmp_path), {"w": ws}, process_index=0,
                     only_devices=set(devs[:4]))
        ckpt = ShardedCheckpoint(str(tmp_path))
        with pytest.raises(Exception) as ei:
            ckpt.read("w")
        assert "cover" in str(ei.value)


class TestReshardRestore:
    def test_restore_onto_different_mesh_shape(self, tmp_path):
        """Save sharded over dp=8, restore sharded over (dp=2, tp=2) on a
        4-device mesh — the elastic world-resize story."""
        rng = np.random.RandomState(2)
        w = rng.randn(16, 8).astype(np.float32)
        mesh8 = _mesh((8,), ("dp",))
        ws = jax.device_put(jnp.asarray(w),
                            NamedSharding(mesh8, P("dp", None)))
        save_sharded(str(tmp_path), {"w": ws})

        mesh4 = _mesh((2, 2), ("dp", "tp"))
        target = NamedSharding(mesh4, P("dp", "tp"))
        ckpt = ShardedCheckpoint(str(tmp_path))
        restored = restore_array(ckpt, "w", target)
        assert restored.sharding == target
        np.testing.assert_array_equal(np.asarray(restored), w)

    def test_restore_slice_crosses_chunk_boundaries(self, tmp_path):
        """A target shard spanning several saved chunks assembles from all
        of them (save dp=8 -> restore dp=2: each restored shard covers 4
        saved chunks)."""
        mesh8 = _mesh((8,), ("dp",))
        w = np.arange(64, dtype=np.float32).reshape(16, 4)
        ws = jax.device_put(jnp.asarray(w), NamedSharding(mesh8, P("dp")))
        save_sharded(str(tmp_path), {"w": ws})
        ckpt = ShardedCheckpoint(str(tmp_path))
        got = ckpt.read_slice("w", (slice(2, 14), slice(0, 4)))
        np.testing.assert_array_equal(got, w[2:14])


class TestCorruptCheckpointRejection:
    """Partially written checkpoints surface CLEAR enforce errors naming
    the directory and the damaged piece — never a raw JSON/IO error
    (the elastic commit protocol's reject-side, docs/fault_tolerance.md)."""

    def _saved(self, tmp_path):
        save_sharded(str(tmp_path),
                     {"w": jnp.arange(32, dtype=jnp.float32).reshape(8, 4)})
        return str(tmp_path)

    def test_truncated_manifest_names_file(self, tmp_path):
        import os
        d = self._saved(tmp_path)
        mpath = os.path.join(d, "manifest-0.json")
        with open(mpath, "r+") as f:
            f.truncate(os.path.getsize(mpath) // 2)
        with pytest.raises(Exception) as ei:
            ShardedCheckpoint(d)
        msg = str(ei.value)
        assert "manifest-0.json" in msg and "truncated" in msg
        assert d in msg

    def test_missing_shard_container_named_up_front(self, tmp_path):
        import os
        d = self._saved(tmp_path)
        os.unlink(os.path.join(d, "shard-0.pts"))
        with pytest.raises(Exception) as ei:
            ShardedCheckpoint(d)
        msg = str(ei.value)
        assert "shard-0.pts" in msg and "missing" in msg

    def test_truncated_shard_container_clear_error(self, tmp_path):
        import os
        d = self._saved(tmp_path)
        spath = os.path.join(d, "shard-0.pts")
        with open(spath, "r+b") as f:
            f.truncate(os.path.getsize(spath) // 2)
        ckpt = ShardedCheckpoint(d)
        with pytest.raises(Exception) as ei:
            ckpt.read("w")
        msg = str(ei.value)
        assert "shard-0.pts" in msg
        assert "truncated or corrupt" in msg


class TestIoIntegration:
    def test_save_load_persistables_sharded(self, tmp_path):
        """io.save_persistables(sharded=True) end to end through a real
        trained program, restore into a fresh scope, same fetch values."""
        from paddle_tpu import layers
        x = layers.data(name="x", shape=[4])
        y = layers.fc(x, size=3)
        loss = layers.reduce_mean(y)
        pt.optimizer.MomentumOptimizer(learning_rate=0.1,
                                       momentum=0.9).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"x": np.ones((2, 4), np.float32)}
        for _ in range(3):
            exe.run(feed=feed, fetch_list=[loss])

        saved = pt.io.save_persistables(dirname=str(tmp_path), sharded=True)
        assert any(n.endswith(".w_0") or "fc" in n for n in saved)
        names = list(saved)
        vals = {n: np.asarray(pt.global_scope().get(n)) for n in names}
        # every run IS a train step (minimize appended): the reference is
        # the step-4 loss from the saved state, taken AFTER saving
        ref = exe.run(feed=feed, fetch_list=[loss])[0]

        # wipe and restore (momentum accumulators included -> the next
        # step reproduces exactly)
        pt.reset_global_scope()
        # scope is empty now; program still exists
        pt.io.load_persistables(dirname=str(tmp_path), sharded=True,
                                scope=pt.global_scope())
        for n in names:
            np.testing.assert_array_equal(
                np.asarray(pt.global_scope().get(n)), vals[n])
        exe2 = pt.Executor()
        got = exe2.run(feed=feed, fetch_list=[loss])[0]
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_trainer_checkpoint_sharded_round_trip(self, tmp_path):
        """trainer.save_checkpoint/load_checkpoint(sharded=True): serial
        dirs + _SUCCESS markers + trainer args compose with the sharded
        container."""
        from paddle_tpu.trainer import load_checkpoint, save_checkpoint
        from paddle_tpu import layers
        x = layers.data(name="x", shape=[4])
        layers.fc(x, size=2, name="tsfc")
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        serial = save_checkpoint(exe, str(tmp_path),
                                 pt.default_main_program(),
                                 trainer_args={"step": 11}, sharded=True)
        assert serial == 0
        w = np.asarray(pt.global_scope().get("tsfc.w_0"))
        pt.reset_global_scope()
        args = load_checkpoint(exe, str(tmp_path),
                               pt.default_main_program(), sharded=True)
        assert args == {"step": 11}
        np.testing.assert_array_equal(
            np.asarray(pt.global_scope().get("tsfc.w_0")), w)

    def test_explicit_low_serial_not_deleted_by_retention(self, tmp_path):
        """Regression: save_checkpoint(serial=0) with newer serials present
        must not scroll-delete the checkpoint it just wrote."""
        from paddle_tpu.trainer import load_checkpoint, save_checkpoint
        from paddle_tpu import layers
        x = layers.data(name="x", shape=[4])
        layers.fc(x, size=2, name="rlfc")
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        prog = pt.default_main_program()
        for expected in (0, 1, 2, 3):
            s = save_checkpoint(exe, str(tmp_path), prog,
                                trainer_args={"s": expected},
                                max_num_checkpoints=3, sharded=True)
            assert s == expected
        # overwrite serial 0 explicitly — it must survive its own save
        save_checkpoint(exe, str(tmp_path), prog, trainer_args={"s": 99},
                        max_num_checkpoints=3, sharded=True, serial=0)
        args = load_checkpoint(exe, str(tmp_path), prog, serial=0,
                               sharded=True)
        assert args == {"s": 99}

    def test_load_persistables_sharded_with_shardings(self, tmp_path):
        from paddle_tpu import layers
        x = layers.data(name="x", shape=[8])
        y = layers.fc(x, size=8, name="shfc")
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        pt.io.save_persistables(dirname=str(tmp_path), sharded=True)
        w_name = [n for n in pt.global_scope().local_var_names()
                  if "shfc" in n and "w" in n][0]
        ref = np.asarray(pt.global_scope().get(w_name))

        mesh = _mesh((4,), ("tp",))
        sh = NamedSharding(mesh, P(None, "tp"))
        pt.reset_global_scope()
        pt.io.load_persistables(dirname=str(tmp_path), sharded=True,
                                shardings={w_name: sh})
        got = pt.global_scope().get(w_name)
        assert got.sharding == sh
        np.testing.assert_array_equal(np.asarray(got), ref)


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-x", "-q"]))
