"""CTC / CRF / chunk-eval op tests against numpy dynamic-programming
references (≙ reference test_warpctc_op.py, test_linear_chain_crf_op.py,
test_crf_decoding_op.py, test_chunk_eval_op.py, test_ctc_align.py)."""

import numpy as np
import pytest
from scipy.special import logsumexp as np_lse

from op_test import check_grad, check_output, run_op


# ---------------------------------------------------------------------------
# numpy references
# ---------------------------------------------------------------------------

def np_ctc_loss(logits, labels, logit_lens, label_lens, blank=0):
    """Log-space CTC forward algorithm, one sequence at a time."""
    B = logits.shape[0]
    out = np.zeros((B, 1), dtype=np.float64)
    for b in range(B):
        T, L = int(logit_lens[b]), int(label_lens[b])
        lp = logits[b, :T].astype(np.float64)
        lp = lp - np_lse(lp, axis=1, keepdims=True)
        lab = labels[b, :L]
        ext = [blank]
        for tok in lab:
            ext += [int(tok), blank]
        S = len(ext)
        alpha = np.full((T, S), -np.inf)
        alpha[0, 0] = lp[0, ext[0]]
        if S > 1:
            alpha[0, 1] = lp[0, ext[1]]
        for t in range(1, T):
            for s in range(S):
                cands = [alpha[t - 1, s]]
                if s >= 1:
                    cands.append(alpha[t - 1, s - 1])
                if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                    cands.append(alpha[t - 1, s - 2])
                alpha[t, s] = np_lse(cands) + lp[t, ext[s]]
        ll = np_lse([alpha[T - 1, S - 1],
                     alpha[T - 1, S - 2]] if S > 1 else [alpha[T - 1, 0]])
        out[b, 0] = -ll
    return out


def np_crf_nll(emission, transition, label, length):
    start_w, end_w, trans = transition[0], transition[1], transition[2:]
    B = emission.shape[0]
    out = np.zeros((B, 1))
    for b in range(B):
        n = int(length[b])
        e = emission[b, :n].astype(np.float64)
        lab = label[b, :n]
        alpha = start_w + e[0]
        for t in range(1, n):
            alpha = np_lse(alpha[:, None] + trans, axis=0) + e[t]
        logz = np_lse(alpha + end_w)
        score = start_w[lab[0]] + e[np.arange(n), lab].sum() + end_w[lab[-1]]
        for t in range(1, n):
            score += trans[lab[t - 1], lab[t]]
        out[b, 0] = logz - score
    return out


def np_viterbi(emission, transition, length):
    start_w, end_w, trans = transition[0], transition[1], transition[2:]
    B, T, D = emission.shape
    paths = np.zeros((B, T), dtype=np.int64)
    for b in range(B):
        n = int(length[b])
        e = emission[b, :n].astype(np.float64)
        v = start_w + e[0]
        bp = np.zeros((n, D), dtype=int)
        for t in range(1, n):
            scores = v[:, None] + trans
            bp[t] = np.argmax(scores, axis=0)
            v = scores.max(axis=0) + e[t]
        tag = int(np.argmax(v + end_w))
        seq = [tag]
        for t in range(n - 1, 0, -1):
            tag = bp[t][tag]
            seq.append(tag)
        paths[b, :n] = seq[::-1]
    return paths


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------

class TestWarpCTC:
    def test_forward_matches_numpy_dp(self, rng):
        B, T, C, L = 4, 9, 6, 3
        logits = rng.randn(B, T, C).astype("float32")
        labels = rng.randint(1, C, (B, L)).astype("int64")
        logit_lens = np.array([9, 7, 9, 5], dtype="int64")
        label_lens = np.array([3, 2, 1, 3], dtype="int64")
        exp = np_ctc_loss(logits, labels, logit_lens, label_lens)
        check_output("warpctc",
                     {"Logits": logits, "Label": labels,
                      "LogitsLength": logit_lens, "LabelLength": label_lens},
                     {"Loss": exp.astype("float32")}, atol=1e-3, rtol=1e-3)

    def test_norm_by_times(self, rng):
        B, T, C, L = 2, 6, 5, 2
        logits = rng.randn(B, T, C).astype("float32")
        labels = rng.randint(1, C, (B, L)).astype("int64")
        ll = np.array([6, 4], dtype="int64")
        tl = np.array([2, 2], dtype="int64")
        base = run_op("warpctc", {"Logits": logits, "Label": labels,
                                  "LogitsLength": ll, "LabelLength": tl})
        norm = run_op("warpctc", {"Logits": logits, "Label": labels,
                                  "LogitsLength": ll, "LabelLength": tl},
                      attrs={"norm_by_times": True})
        np.testing.assert_allclose(
            norm["Loss"][0][:, 0], base["Loss"][0][:, 0] / ll, rtol=1e-5)

    def test_grad_vs_numeric(self, rng):
        B, T, C, L = 2, 5, 4, 2
        logits = rng.randn(B, T, C).astype("float32")
        labels = rng.randint(1, C, (B, L)).astype("int64")
        check_grad("warpctc",
                   {"Logits": logits, "Label": labels,
                    "LogitsLength": np.array([5, 4], dtype="int64"),
                    "LabelLength": np.array([2, 1], dtype="int64")},
                   grad_slots=["Logits"], out_slot="Loss",
                   atol=5e-2, rtol=5e-2)

    def test_perfect_logits_near_zero_loss(self):
        # logits massively favoring the exact label path -> loss ~ 0
        T, C = 5, 4
        labels = np.array([[1, 2, 3]], dtype="int64")
        path = [1, 2, 3, 0, 0]  # label then blanks
        logits = np.full((1, T, C), -20.0, dtype="float32")
        for t, k in enumerate(path):
            logits[0, t, k] = 20.0
        out = run_op("warpctc", {"Logits": logits, "Label": labels,
                                 "LogitsLength": np.array([5], dtype="int64"),
                                 "LabelLength": np.array([3], dtype="int64")})
        assert out["Loss"][0][0, 0] < 1e-3


class TestCTCAlign:
    def test_merge_and_strip(self):
        x = np.array([[0, 1, 1, 0, 2, 2, 0, 3],
                      [1, 1, 2, 0, 0, 3, 3, 1]], dtype="int32")
        lens = np.array([8, 6], dtype="int64")
        out = run_op("ctc_align", {"Input": x, "InputLength": lens},
                     attrs={"blank": 0})
        got, glen = out["Output"][0], out["OutputLength"][0]
        np.testing.assert_array_equal(got[0, :3], [1, 2, 3])
        assert glen[0, 0] == 3
        np.testing.assert_array_equal(got[1, :3], [1, 2, 3])
        assert glen[1, 0] == 3

    def test_greedy_decoder_layer(self, rng):
        import paddle_tpu as pt
        from paddle_tpu import layers
        probs = layers.data("probs", shape=[7, 5], dtype="float32")
        plen = layers.data("plen", shape=[], dtype="int64")
        dec, dec_len = layers.ctc_greedy_decoder(probs, blank=0,
                                                 input_length=plen)
        exe = pt.Executor()
        p = rng.rand(2, 7, 5).astype("float32")
        lens = np.array([7, 5], dtype="int64")
        got, glen = exe.run(feed={"probs": p, "plen": lens},
                            fetch_list=[dec, dec_len])
        # reference: argmax -> merge repeats -> drop blanks
        for b in range(2):
            best = p[b, :lens[b]].argmax(-1)
            ref = [t for i, t in enumerate(best)
                   if t != 0 and (i == 0 or t != best[i - 1])]
            np.testing.assert_array_equal(got[b, :len(ref)], ref)
            assert glen[b, 0] == len(ref)


# ---------------------------------------------------------------------------
# CRF
# ---------------------------------------------------------------------------

class TestLinearChainCRF:
    def test_nll_matches_numpy(self, rng):
        B, T, D = 3, 6, 4
        emission = rng.randn(B, T, D).astype("float32")
        transition = (rng.randn(D + 2, D) * 0.5).astype("float32")
        label = rng.randint(0, D, (B, T)).astype("int64")
        length = np.array([6, 4, 2], dtype="int64")
        exp = np_crf_nll(emission, transition, label, length)
        check_output("linear_chain_crf",
                     {"Emission": emission, "Transition": transition,
                      "Label": label, "Length": length},
                     {"LogLikelihood": exp.astype("float32")},
                     atol=1e-4, rtol=1e-4)

    def test_grads(self, rng):
        B, T, D = 2, 4, 3
        emission = rng.randn(B, T, D).astype("float32")
        transition = (rng.randn(D + 2, D) * 0.5).astype("float32")
        label = rng.randint(0, D, (B, T)).astype("int64")
        length = np.array([4, 3], dtype="int64")
        check_grad("linear_chain_crf",
                   {"Emission": emission, "Transition": transition,
                    "Label": label, "Length": length},
                   grad_slots=["Emission", "Transition"],
                   out_slot="LogLikelihood", atol=5e-2, rtol=5e-2)

    def test_nll_nonnegative(self, rng):
        B, T, D = 4, 5, 6
        out = run_op("linear_chain_crf",
                     {"Emission": rng.randn(B, T, D).astype("float32"),
                      "Transition": rng.randn(D + 2, D).astype("float32"),
                      "Label": rng.randint(0, D, (B, T)).astype("int64"),
                      "Length": np.array([5, 5, 3, 1], dtype="int64")})
        assert (out["LogLikelihood"][0] >= -1e-4).all()


class TestCRFDecoding:
    def test_viterbi_matches_numpy(self, rng):
        B, T, D = 3, 7, 4
        emission = rng.randn(B, T, D).astype("float32")
        transition = (rng.randn(D + 2, D) * 0.5).astype("float32")
        length = np.array([7, 5, 3], dtype="int64")
        exp = np_viterbi(emission, transition, length)
        out = run_op("crf_decoding",
                     {"Emission": emission, "Transition": transition,
                      "Length": length})
        np.testing.assert_array_equal(out["ViterbiPath"][0], exp)

    def test_viterbi_beats_random_paths(self, rng):
        # decoded path must score >= any random path under the CRF score
        B, T, D = 1, 6, 5
        emission = rng.randn(B, T, D).astype("float32")
        transition = (rng.randn(D + 2, D) * 0.3).astype("float32")
        length = np.array([6], dtype="int64")
        path = run_op("crf_decoding",
                      {"Emission": emission, "Transition": transition,
                       "Length": length})["ViterbiPath"][0][0]

        def score(p):
            s = transition[0, p[0]] + transition[1, p[-1]]
            s += emission[0, np.arange(T), p].sum()
            s += sum(transition[2 + p[t - 1], p[t]] for t in range(1, T))
            return s

        best = score(path)
        for _ in range(50):
            assert best >= score(rng.randint(0, D, T)) - 1e-4

    def test_label_mode_marks_correct_positions(self, rng):
        B, T, D = 2, 5, 3
        emission = rng.randn(B, T, D).astype("float32")
        transition = (rng.randn(D + 2, D) * 0.5).astype("float32")
        length = np.array([5, 3], dtype="int64")
        path = run_op("crf_decoding",
                      {"Emission": emission, "Transition": transition,
                       "Length": length})["ViterbiPath"][0]
        out = run_op("crf_decoding",
                     {"Emission": emission, "Transition": transition,
                      "Length": length, "Label": path.astype("int64")})
        ok = out["ViterbiPath"][0]
        for b in range(B):
            np.testing.assert_array_equal(ok[b, :length[b]], 1)
            np.testing.assert_array_equal(ok[b, length[b]:], 0)


# ---------------------------------------------------------------------------
# chunk_eval
# ---------------------------------------------------------------------------

class TestChunkEval:
    def _run(self, inf, lab, length, scheme, nct, excluded=None):
        return run_op("chunk_eval",
                      {"Inference": np.asarray(inf, dtype="int64"),
                       "Label": np.asarray(lab, dtype="int64"),
                       "Length": np.asarray(length, dtype="int64")},
                      attrs={"chunk_scheme": scheme,
                             "num_chunk_types": nct,
                             "excluded_chunk_types": excluded or []})

    def test_iob_exact_match(self):
        # IOB, 2 chunk types: tags B0=0 I0=1 B1=2 I1=3 O=4
        seq = [[0, 1, 4, 2, 3, 3]]
        out = self._run(seq, seq, [6], "IOB", 2)
        assert out["NumInferChunks"][0][0] == 2
        assert out["NumLabelChunks"][0][0] == 2
        assert out["NumCorrectChunks"][0][0] == 2
        assert out["F1-Score"][0][0] == pytest.approx(1.0)

    def test_iob_partial_match(self):
        # infer: chunk [0,1] type0, chunk [3] type1
        # label: chunk [0,1] type0, chunk [4,5] type1
        inf = [[0, 1, 4, 2, 4, 4]]
        lab = [[0, 1, 4, 4, 2, 3]]
        out = self._run(inf, lab, [6], "IOB", 2)
        assert out["NumInferChunks"][0][0] == 2
        assert out["NumLabelChunks"][0][0] == 2
        assert out["NumCorrectChunks"][0][0] == 1
        assert out["Precision"][0][0] == pytest.approx(0.5)
        assert out["Recall"][0][0] == pytest.approx(0.5)

    def test_boundary_mismatch_not_correct(self):
        # same start, different end -> not a correct chunk
        inf = [[0, 1, 1, 4]]
        lab = [[0, 1, 4, 4]]
        out = self._run(inf, lab, [4], "IOB", 1)
        assert out["NumCorrectChunks"][0][0] == 0

    def test_plain_scheme(self):
        # plain, 3 types: every non-O token is its own single-token chunk
        # (reference chunk_eval_op.h: plain sets tag_single=0)
        inf = [[0, 0, 1, 3, 2]]   # O tag = 3
        lab = [[0, 0, 1, 3, 1]]
        out = self._run(inf, lab, [5], "plain", 3)
        assert out["NumInferChunks"][0][0] == 4
        assert out["NumLabelChunks"][0][0] == 4
        assert out["NumCorrectChunks"][0][0] == 3

    def test_iobes_single(self):
        # IOBES 1 type: B=0 I=1 E=2 S=3 O=4
        inf = [[3, 4, 0, 1, 2]]
        lab = [[3, 4, 0, 1, 2]]
        out = self._run(inf, lab, [5], "IOBES", 1)
        assert out["NumInferChunks"][0][0] == 2
        assert out["NumCorrectChunks"][0][0] == 2

    def test_excluded_types(self):
        inf = [[0, 1, 4, 2, 3, 3]]
        out = self._run(inf, inf, [6], "IOB", 2, excluded=[1])
        assert out["NumInferChunks"][0][0] == 1
        assert out["NumCorrectChunks"][0][0] == 1

    def test_length_masks_tail(self):
        seq = [[0, 1, 0, 1, 0, 1]]
        out = self._run(seq, seq, [2], "IOB", 1)
        assert out["NumInferChunks"][0][0] == 1  # only [0,1] inside length


# ---------------------------------------------------------------------------
# end-to-end: CRF trains through the layer API
# ---------------------------------------------------------------------------

def test_crf_layer_trains(rng):
    import paddle_tpu as pt
    from paddle_tpu import layers

    B, T, D, V = 8, 6, 4, 20
    words = layers.data("words", shape=[T], dtype="int64")
    label = layers.data("label", shape=[T], dtype="int64")
    length = layers.data("length", shape=[], dtype="int64")
    emb = layers.embedding(words, size=[V, 16])
    emission = layers.fc(emb, size=D, num_flatten_dims=2)
    crf_cost = layers.linear_chain_crf(emission, label, length,
                                       param_attr=pt.ParamAttr(name="crfw"))
    avg = layers.mean(crf_cost)
    pt.optimizer.SGDOptimizer(learning_rate=0.05).minimize(avg)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    w = rng.randint(0, V, (B, T)).astype("int64")
    lab = (w % D).astype("int64")  # learnable mapping
    lens = np.full((B,), T, dtype="int64")
    feed = {"words": w, "label": lab, "length": lens}
    first = exe.run(feed=feed, fetch_list=[avg])[0]
    for _ in range(25):
        last = exe.run(feed=feed, fetch_list=[avg])[0]
    assert last < first * 0.8
