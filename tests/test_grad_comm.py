"""Unit tests for the gradient-comm collective primitives (fast tier).

Numpy parity of the block-scale compress/decompress round trip, the
reduce_scatter divisibility contract at the API boundary, and the shared
wire-byte accounting model. The executor-level pipeline suite (HLO census,
loss parity, error-feedback state) lives in tests/test_zero_comm.py.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.parallel import collective as C
from paddle_tpu.parallel.mesh import DeviceMesh, shard_map

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from probe_common import collective_census, collective_wire_bytes  # noqa: E402


def _np_quantize_blocks(flat, block):
    """Independent numpy reimplementation of collective.quantize_blocks."""
    xb = flat.reshape(-1, block)
    amax = np.max(np.abs(xb), axis=1, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(xb / scale), -127, 127).astype(np.int8)
    return q, scale


class TestBlockQuantization:
    def test_roundtrip_matches_numpy(self, rng):
        flat = (rng.randn(4 * 256) * 3).astype(np.float32)
        q, s = C.quantize_blocks(jnp.asarray(flat), block=256)
        qn, sn = _np_quantize_blocks(flat, 256)
        np.testing.assert_array_equal(np.asarray(q), qn)
        np.testing.assert_allclose(np.asarray(s), sn, rtol=1e-7)
        deq = np.asarray(C.dequantize_blocks(q, s))
        np.testing.assert_allclose(deq, (qn.astype(np.float32) * sn).ravel(),
                                   rtol=1e-7)

    def test_roundtrip_error_bound(self, rng):
        flat = (rng.randn(8 * 128) * 10).astype(np.float32)
        q, s = C.quantize_blocks(jnp.asarray(flat), block=128)
        deq = np.asarray(C.dequantize_blocks(q, s))
        # symmetric round-to-nearest: per-value error <= scale/2
        bound = np.repeat(np.asarray(s).ravel(), 128) / 2 + 1e-7
        assert np.all(np.abs(deq - flat) <= bound)

    def test_zero_blocks_exact(self):
        flat = jnp.zeros((512,), jnp.float32)
        q, s = C.quantize_blocks(flat, block=256)
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.asarray(s) == 1.0)
        np.testing.assert_array_equal(np.asarray(C.dequantize_blocks(q, s)),
                                      np.zeros(512, np.float32))

    def test_residual_is_exact_complement(self, rng):
        # flat == dequant(compress(flat)) + residual, in the exact padded
        # chunk layout the wire transfer uses
        flat = (rng.randn(8 * 100) * 2).astype(np.float32)   # chunks of 100
        res = np.asarray(C.quantization_residual_flat(
            jnp.asarray(flat), 8, wire_dtype="int8", block=64))
        xb = flat.reshape(8, 100)
        xp = np.pad(xb, ((0, 0), (0, 28)))                    # cpad 128
        qn, sn = _np_quantize_blocks(xp.reshape(-1), 64)
        deq = (qn.astype(np.float32) * sn).reshape(8, 128)[:, :100]
        np.testing.assert_allclose(res, flat - deq.reshape(-1),
                                   rtol=1e-6, atol=1e-7)

    def test_bf16_compress(self, rng):
        flat = (rng.randn(256)).astype(np.float32)
        res = np.asarray(C.quantization_residual_flat(
            jnp.asarray(flat), 8, wire_dtype="bf16"))
        np.testing.assert_allclose(
            res, flat - flat.astype(jnp.bfloat16).astype(np.float32),
            rtol=1e-6, atol=1e-7)


class TestReduceScatterBoundary:
    """Satellite: reduce_scatter for dims not divisible by the axis size
    used to surface a shape error from deep inside psum_scatter; now the
    API boundary raises a clear enforce error."""

    def _mesh(self):
        return DeviceMesh(jax.devices(), {"dp": 8})

    def test_divisible_ok(self):
        mesh = self._mesh()
        f = shard_map(lambda x: C.reduce_scatter(x, "dp"),
                      mesh=mesh.jax_mesh, in_specs=(P(),),
                      out_specs=P("dp"), check_vma=False)
        out = jax.jit(f)(jnp.ones((16, 4), jnp.float32))
        # every shard contributed identical ones: each owned slice sums to 8
        np.testing.assert_array_equal(np.asarray(out),
                                      np.full((16, 4), 8.0, np.float32))

    def test_non_divisible_raises_clear_error(self):
        mesh = self._mesh()
        f = shard_map(lambda x: C.reduce_scatter(x, "dp"),
                      mesh=mesh.jax_mesh, in_specs=(P(),),
                      out_specs=P("dp"), check_vma=False)
        with pytest.raises(InvalidArgumentError, match="not divisible"):
            jax.jit(f)(jnp.ones((10, 4), jnp.float32))

    def test_bad_dim_raises(self):
        mesh = self._mesh()
        f = shard_map(lambda x: C.reduce_scatter(x, "dp", scatter_dim=3),
                      mesh=mesh.jax_mesh, in_specs=(P(),),
                      out_specs=P("dp"), check_vma=False)
        with pytest.raises(InvalidArgumentError, match="out of range"):
            jax.jit(f)(jnp.ones((16, 4), jnp.float32))


class TestCollectiveCensusParsing:
    def test_tuple_shape_with_tpu_layout(self):
        # TPU HLO prints tiled layouts with parens INSIDE the tuple shape
        # — the census must not silently drop such instructions (that
        # would make no-gradient-all-reduce asserts pass vacuously)
        hlo = ("  %ar = (f32[128,256]{1,0:T(8,128)}, f32[64]{0:T(256)}) "
               "all-reduce(f32[128,256]{1,0:T(8,128)} %a, f32[64]{0} %b), "
               "replica_groups={{0,1}}\n"
               "  %a2a = (s8[8,256]{1,0:T(8,128)(4,1)}) "
               "all-to-all(s8[8,256]{1,0} %q), replica_groups={{0,1}}\n")
        census = collective_census(hlo)
        assert sum(b for b, _ in census["all-reduce"]) == 128 * 256 * 4 + 256
        assert sum(b for b, _ in census["all-to-all"]) == 8 * 256

    def test_async_pairs_counted_once(self):
        hlo = ("  %s = f32[64]{0} all-reduce-start(f32[64]{0} %x)\n"
               "  %d = f32[64]{0} all-reduce-done(f32[64]{0} %s)\n")
        assert len(collective_census(hlo)["all-reduce"]) == 1


class TestMeanLossGate:
    def test_sum_reduced_loss_rejected(self, rng):
        """The explicit pipeline averages per-shard gradients — only exact
        for a batch-MEAN loss. A sum-reduced loss must be rejected, not
        silently trained at 1/dp gradient scale."""
        from paddle_tpu import layers
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.strategy import (BuildStrategy,
                                                  ReduceStrategy)
        x = layers.data("x", shape=[16])
        label = layers.data("label", shape=[1], dtype="int64")
        loss = layers.reduce_sum(layers.softmax_with_cross_entropy(
            layers.fc(x, size=4), label))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        bst = BuildStrategy()
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
        exe = ParallelExecutor(loss_name=loss.name,
                               mesh=DeviceMesh(jax.devices(), {"dp": 8}),
                               build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        with pytest.raises(InvalidArgumentError, match="MEAN-reduced"):
            exe.run(feed={"x": np.zeros((16, 16), np.float32),
                          "label": np.zeros((16, 1), np.int64)},
                    fetch_list=[loss])


class TestWireByteModel:
    def test_allreduce_equals_rs_plus_ag(self):
        # the ring identity the reduce-scatter mode exploits: an all-reduce
        # costs exactly its reduce-scatter + all-gather decomposition
        n, dev = 1 << 20, 8
        ar = collective_wire_bytes("all-reduce", n, dev)
        rs = collective_wire_bytes("reduce-scatter", n // dev, dev)
        ag = collective_wire_bytes("all-gather", n, dev)
        assert ar == rs + ag

    def test_compressed_ratio(self):
        # int8 + one f32 scale per 256 values: 3.94x fewer bytes than f32
        assert 1 / C.compressed_size_ratio("int8", 256) > 3.9
        assert C.compressed_size_ratio("bf16") == 0.5
