"""LR decay schedules computed in-graph match numpy references.

≙ reference tests/unittests/test_learning_rate_scheduler.py (each decay fn
vs a python reference over successive steps).
"""

import math

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _run_schedule(lr_var, steps):
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    out = []
    for _ in range(steps):
        (v,) = exe.run(pt.default_main_program(), feed={},
                       fetch_list=[lr_var])
        out.append(float(np.asarray(v).reshape(())))
    return out


def test_exponential_decay():
    lr = layers.exponential_decay(0.1, decay_steps=10, decay_rate=0.5)
    got = _run_schedule(lr, 5)
    want = [0.1 * 0.5 ** (s / 10.0) for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_exponential_decay_staircase():
    lr = layers.exponential_decay(0.1, decay_steps=3, decay_rate=0.5,
                                  staircase=True)
    got = _run_schedule(lr, 7)
    want = [0.1 * 0.5 ** (s // 3) for s in range(7)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_natural_exp_decay():
    lr = layers.natural_exp_decay(0.1, decay_steps=10, decay_rate=0.5)
    got = _run_schedule(lr, 5)
    want = [0.1 * math.exp(-0.5 * s / 10.0) for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_inverse_time_decay():
    lr = layers.inverse_time_decay(0.1, decay_steps=10, decay_rate=0.5)
    got = _run_schedule(lr, 5)
    want = [0.1 / (1.0 + 0.5 * s / 10.0) for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_noam_decay():
    d_model, warmup = 64, 4
    lr = layers.noam_decay(d_model, warmup)
    got = _run_schedule(lr, 8)
    want = [d_model ** -0.5 * min(s ** -0.5, s * warmup ** -1.5)
            for s in range(1, 9)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("cycle", [False, True])
def test_polynomial_decay(cycle):
    lr = layers.polynomial_decay(0.1, decay_steps=5, end_learning_rate=0.01,
                                 power=2.0, cycle=cycle)
    got = _run_schedule(lr, 12)
    want = []
    for s in range(12):
        if cycle:
            div = max(1.0, math.ceil(s / 5.0))
            steps = 5.0 * div
            frac = s / steps
        else:
            frac = min(float(s), 5.0) / 5.0
        want.append((0.1 - 0.01) * (1 - frac) ** 2.0 + 0.01)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_piecewise_decay():
    lr = layers.piecewise_decay(boundaries=[3, 6], values=[0.1, 0.05, 0.01])
    got = _run_schedule(lr, 9)
    want = [0.1 if s < 3 else (0.05 if s < 6 else 0.01) for s in range(9)]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_cosine_decay():
    lr = layers.cosine_decay(0.1, step_each_epoch=2, epochs=4)
    got = _run_schedule(lr, 8)
    want = [0.1 * 0.5 * (math.cos((s // 2) * math.pi / 4) + 1)
            for s in range(8)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_scheduler_drives_optimizer():
    """A scheduler var feeds Optimizer(learning_rate=Variable) and the
    effective step size shrinks accordingly."""
    x = layers.data(name="x", shape=[4])
    y = layers.data(name="y", shape=[1])
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    lr = layers.exponential_decay(0.1, decay_steps=1, decay_rate=0.5)
    opt = pt.optimizer.SGDOptimizer(learning_rate=lr)
    opt.minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 4).astype(np.float32),
            "y": rng.rand(8, 1).astype(np.float32)}
    vals = []
    for _ in range(3):
        (v,) = exe.run(pt.default_main_program(), feed=feed,
                       fetch_list=[lr])
        vals.append(float(np.asarray(v).reshape(())))
    np.testing.assert_allclose(vals, [0.1, 0.05, 0.025], rtol=1e-5)
