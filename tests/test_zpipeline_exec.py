"""Executor-level pipeline-parallel suite: fixed-seed parity, dp x pp
composition (incl. the r08 ReduceScatter pipeline), HLO boundary census,
and the kill switch.

(Named test_zpipeline_* so the heavyweight compiles in this file sort
after the whole suite — the same discipline as tests/test_zero_comm.py;
the fast unit half lives in tests/test_pipeline_parallel.py.)
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from probe_common import collective_census  # noqa: E402

from test_pipeline_parallel import (_baseline, _build_conv,  # noqa: E402
                                    _build_mlp, _compiled_hlo, _conv_feed,
                                    _mlp_feed, _pipeline_run)


# ---------------------------------------------------------------------------
# fixed-seed parity vs the single-device baseline
# ---------------------------------------------------------------------------

class TestPipelineParity:
    @pytest.mark.quick
    def test_mlp_parity_both_schedules(self):
        feeds = [_mlp_feed(i) for i in range(3)]
        base = _baseline(_build_mlp, feeds)
        for sched in ("gpipe", "1f1b"):
            got, _, _ = _pipeline_run(_build_mlp, feeds, {"pp": 2}, 2, 4,
                                      sched)
            np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)

    def test_conv_parity(self):
        feeds = [_conv_feed(i) for i in range(3)]
        base = _baseline(_build_conv, feeds)
        for sched in ("gpipe", "1f1b"):
            got, _, _ = _pipeline_run(_build_conv, feeds, {"pp": 2}, 2, 4,
                                      sched)
            np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)

    def test_four_stage_parity(self):
        feeds = [_mlp_feed(i) for i in range(2)]
        base = _baseline(lambda: _build_mlp(depth=6), feeds)
        got, _, _ = _pipeline_run(lambda: _build_mlp(depth=6), feeds,
                                  {"pp": 4}, 4, 8, "1f1b")
        np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)


class TestDpPpComposition:
    def test_dp2_pp2_parity_allreduce_and_reduce_scatter(self):
        """dp=2 x pp=2 train step == single device, including the r08
        explicit reduce-scatter gradient pipeline under pipeline mode."""
        feeds = [_mlp_feed(i) for i in range(3)]
        base = _baseline(_build_mlp, feeds)
        for rs in (ReduceStrategy.AllReduce, ReduceStrategy.ReduceScatter):
            got, exe, _ = _pipeline_run(_build_mlp, feeds,
                                        {"dp": 2, "pp": 2}, 2, 4, "1f1b",
                                        reduce_strategy=rs)
            np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)
        # ReduceScatter under pipeline keeps its structural contract: the
        # explicit dp pipeline engaged (reduce-scatter present on the wire)
        census = collective_census(_compiled_hlo(exe, feeds[-1]))
        assert "reduce-scatter" in census, census.keys()

    def test_run_steps_scan_fused_window(self):
        feeds = [_mlp_feed(i) for i in range(3)]
        base = _baseline(_build_mlp, feeds)
        pt.reset_default_programs()
        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            loss = _build_mlp()
        bst = BuildStrategy(pipeline_stages=2, num_microbatches=4)
        mesh = DeviceMesh(jax.devices()[:4], {"dp": 2, "pp": 2})
        exe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                               build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        out = exe.run_steps(feeds, fetch_list=[loss])
        np.testing.assert_allclose(np.asarray(out[0]).ravel(), base,
                                   rtol=0, atol=1e-5)


# ---------------------------------------------------------------------------
# HLO census: boundary comm structure of the compiled step
# ---------------------------------------------------------------------------

class TestHLOCensus:
    def test_one_boundary_permute_pair_per_tick(self):
        """The scan body carries exactly ONE boundary-activation shift and
        ONE boundary-gradient shift per tick — two collective-permute
        instructions in the whole compiled step, no matter how many
        microbatches run through it."""
        feeds = [_mlp_feed(0)]
        for m in (2, 8):
            got, exe, _ = _pipeline_run(_build_mlp, feeds, {"pp": 2}, 2, m,
                                        "1f1b")
            census = collective_census(_compiled_hlo(exe, feeds[0]))
            assert len(census.get("collective-permute", [])) == 2, {
                k: len(v) for k, v in census.items()}


class TestKillSwitch:
    def _exe(self, loss, stages=2, m=4):
        bst = BuildStrategy(pipeline_stages=stages, num_microbatches=m)
        mesh = DeviceMesh(jax.devices()[:stages], {"pp": stages})
        return ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                build_strategy=bst)

    def test_kill_switch_runs_unpartitioned_spmd(self):
        feeds = [_mlp_feed(i) for i in range(2)]
        base = _baseline(_build_mlp, feeds)
        pt.reset_default_programs()
        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            loss = _build_mlp()
        exe = self._exe(loss)
        pt.Executor().run(pt.default_startup_program())
        old = flags.get_flag("pipeline")
        try:
            flags.set_flag("pipeline", False)
            got = [float(exe.run(feed=f, fetch_list=[loss])[0])
                   for f in feeds]
            np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)
            # no pipeline region compiled: the plain SPMD path ran
            prog = exe._prepare_program(pt.default_main_program(),
                                        pt.global_scope())
            assert not getattr(prog, "_pp_applied", False)
        finally:
            flags.set_flag("pipeline", old)


