"""Unit tests for the static sharding-propagation subsystem
(framework/sharding.py): per-op propagation rules, conflict diagnostics
with op provenance, the tp_shard_pass rewrite structure, the analyzer
integration (mutation tests), and the manual-mode gate branches in
ParallelExecutor.

The executor-level half (fixed-seed parity on tp2 / dp2xtp2 / dp2xpp2xtp2
meshes, HLO census, kill switch) lives in tests/test_ztp_exec.py — same
split as test_pipeline_parallel.py vs test_zpipeline_exec.py.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.framework import analysis, sharding
from paddle_tpu.framework.passes import get_pass
from paddle_tpu.framework.sharding import (TP_AXIS, ProgramAnalysisError,
                                           propagate_sharding,
                                           tp_analytic_wire_bytes,
                                           tp_component, tp_local_shape)
from paddle_tpu.param_attr import ParamAttr


# ---------------------------------------------------------------------------
# helpers: tiny hand-built programs
# ---------------------------------------------------------------------------


def _col_row_mlp(d_in=8, d_h=8, col=True, row=True, nclass=4):
    """The Megatron pair: column-parallel fc1 -> row-parallel fc2."""
    x = layers.data("x", shape=[d_in])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=d_h, act="relu", name="fc1",
                  param_attr=ParamAttr(
                      name="fc1.w",
                      sharding_spec=(None, TP_AXIS) if col else None),
                  bias_attr=ParamAttr(
                      name="fc1.b",
                      sharding_spec=(TP_AXIS,) if col else None))
    h = layers.fc(h, size=nclass, name="fc2",
                  param_attr=ParamAttr(
                      name="fc2.w",
                      sharding_spec=(TP_AXIS, None) if row else None))
    loss = layers.mean(layers.softmax_with_cross_entropy(h, label))
    pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return loss


def _tp_transformer(vocab=64, d_model=32, heads=4):
    from paddle_tpu.models import transformer
    from paddle_tpu.parallel import annotate_tp
    loss, _ = transformer.transformer_lm(
        vocab=vocab, max_len=8, d_model=d_model, d_inner=2 * d_model,
        num_heads=heads, num_layers=2, mean_loss=True)
    pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return loss, annotate_tp()


# ---------------------------------------------------------------------------
# spec helpers
# ---------------------------------------------------------------------------


class TestSpecHelpers:
    def test_tp_component(self):
        assert tp_component(None) is None
        assert tp_component((None, None)) is None
        assert tp_component((None, "tp")) == (None, "tp")
        # general specs naming other axes / axis tuples reduce to tp-only
        assert tp_component(("dp", "tp")) == (None, "tp")
        assert tp_component((("tp", "dp"), None)) == ("tp", None)
        assert tp_component(("dp", None)) is None

    def test_tp_local_shape(self):
        assert tp_local_shape((8, 6), (None, "tp"), 2) == (8, 3)
        assert tp_local_shape((8, 6), ("tp", None), 2) == (4, 6)
        assert tp_local_shape((8, 6), None, 2) == (8, 6)
        assert tp_local_shape((-1, 6), ("tp", "tp"), 2) == (-1, 3)


# ---------------------------------------------------------------------------
# propagation: the Megatron column -> row recipe
# ---------------------------------------------------------------------------


class TestPropagation:
    def test_column_row_pair_propagates_clean(self):
        _col_row_mlp()
        res = propagate_sharding(pt.default_main_program(), tp_size=2)
        assert not res.errors, [str(d) for d in res.errors]
        sharded = res.sharded_vars()
        assert sharded["fc1.w"] == (None, "tp")
        assert sharded["fc2.w"] == ("tp", None)
        # the activation between them is feature-sharded; the row output
        # (pre-psum) is replicated in the propagated env
        assert any(s == (None, "tp") for n, s in sharded.items()
                   if n.startswith("fc1"))
        # exactly one partial-sum output (the row-parallel matmul), one
        # ident (column input), zero splits (x arrives sharded from fc1)
        kinds = {"psums": 0, "idents": 0, "splits": 0, "gathers": 0}
        for a in res.actions:
            for k in kinds:
                kinds[k] += len(getattr(a, k))
        assert kinds["psums"] == 1
        assert kinds["idents"] >= 1
        assert kinds["splits"] == 0

    def test_row_alone_splits_input(self):
        _col_row_mlp(col=False, row=True)
        res = propagate_sharding(pt.default_main_program(), tp_size=2)
        assert not res.errors, [str(d) for d in res.errors]
        # replicated activation into a row-parallel weight: local slice
        assert sum(len(a.splits) for a in res.actions) == 1
        assert sum(len(a.psums) for a in res.actions) == 1

    def test_accumulators_inherit_param_sharding(self):
        x = layers.data("x", shape=[8])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=8, name="cfc",
                      param_attr=ParamAttr(name="cfc.w",
                                           sharding_spec=(None, TP_AXIS)),
                      bias_attr=False)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=4), label))
        pt.optimizer.Adam(learning_rate=0.1).minimize(loss)
        res = propagate_sharding(pt.default_main_program(), tp_size=2)
        assert not res.errors, [str(d) for d in res.errors]
        acc = [n for n, s in res.sharded_vars().items()
               if "moment" in n and s == (None, "tp")]
        assert len(acc) == 2, res.sharded_vars()

    def test_divisibility_diagnostic(self):
        x = layers.data("x", shape=[8])
        layers.fc(x, size=6, name="odd",
                  param_attr=ParamAttr(name="odd.w",
                                       sharding_spec=(None, TP_AXIS)),
                  bias_attr=False)
        res = propagate_sharding(pt.default_main_program(), tp_size=4)
        assert any(d.code == "shard-divisibility" for d in res.diagnostics)
        # size-agnostic verification skips the check
        res2 = propagate_sharding(pt.default_main_program(), tp_size=None)
        assert not [d for d in res2.diagnostics
                    if d.code == "shard-divisibility"]

    def test_ruleless_op_falls_back_to_gather_with_warning(self):
        x = layers.data("x", shape=[8])
        h = layers.fc(x, size=8, name="gfc",
                      param_attr=ParamAttr(name="gfc.w",
                                           sharding_spec=(None, TP_AXIS)),
                      bias_attr=False)
        layers.topk(h, k=2)  # top_k has no sharding rule
        res = propagate_sharding(pt.default_main_program(), tp_size=2)
        assert not res.errors
        warns = [d for d in res.diagnostics if d.code == "shard-reshard"]
        assert warns and "all-gathered" in warns[0].message
        assert sum(len(a.gathers) for a in res.actions) >= 1

    def test_size1_x_broadcast_into_sharded_y_gets_ident(self):
        """A replicated size-1 X dim broadcasting into a tp-sharded Y dim
        is the mirror of the bias case: X's backward cotangent sums over
        the sharded dim, so X must be tp_ident-wrapped too."""
        x = layers.data("x", shape=[8])
        h = layers.fc(x, size=8, name="xb",
                      param_attr=ParamAttr(name="xb.w",
                                           sharding_spec=(None, TP_AXIS)),
                      bias_attr=False)
        g = layers.reduce_sum(x, dim=[1], keep_dim=True)  # [B, 1]
        layers.elementwise_mul(g, h)
        res = propagate_sharding(pt.default_main_program(), tp_size=2)
        assert not res.errors, [str(d) for d in res.errors]
        block = pt.default_main_program().global_block()
        idents = [(block.ops[a.op_idx].type, slot)
                  for a in res.actions for slot, _ in a.idents]
        assert ("elementwise_mul", "X") in idents, idents

    def test_transformer_annotation_propagates_clean(self):
        loss, ann = _tp_transformer()
        assert len(ann) >= 10
        res = propagate_sharding(pt.default_main_program(), tp_size=2)
        assert not res.errors, [str(d) for d in res.errors]
        sharded = res.sharded_vars()
        # head-sharded attention rides through the reshape/transpose pair
        assert any(s and len(s) == 4 and s[1] == "tp"
                   for s in sharded.values()), "no head-sharded 4d value"


# ---------------------------------------------------------------------------
# conflicts: provenance-carrying diagnostics
# ---------------------------------------------------------------------------


class TestConflicts:
    def _diag_codes(self, tp=2):
        res = propagate_sharding(pt.default_main_program(), tp_size=tp)
        return res

    def test_weight_sharded_both_dims(self):
        x = layers.data("x", shape=[8])
        layers.fc(x, size=8, name="bad",
                  param_attr=ParamAttr(name="bad.w",
                                       sharding_spec=(TP_AXIS, TP_AXIS)),
                  bias_attr=False)
        res = self._diag_codes()
        errs = [d for d in res.errors if d.code == "shard-conflict"]
        assert errs and "BOTH" in errs[0].message
        # provenance: block/op#/op.type, the analyzer's op_loc format
        assert "block 0 op#" in errs[0].loc and "'mul'" in errs[0].loc

    def test_sharded_bias_on_replicated_activation(self):
        """The classic annotation bug: a tp-sharded bias added to a
        replicated activation (no column-parallel weight upstream)."""
        x = layers.data("x", shape=[8])
        layers.fc(x, size=8, name="bb",
                  param_attr=ParamAttr(name="bb.w"),
                  bias_attr=ParamAttr(name="bb.b",
                                      sharding_spec=(TP_AXIS,)))
        res = self._diag_codes()
        errs = [d for d in res.errors if d.code == "shard-conflict"]
        assert errs, [str(d) for d in res.diagnostics]
        assert "elementwise_add" in errs[0].loc

    def test_spec_arity_mismatch(self):
        x = layers.data("x", shape=[8])
        layers.fc(x, size=8, name="ar",
                  param_attr=ParamAttr(name="ar.w",
                                       sharding_spec=(TP_AXIS,)),
                  bias_attr=False)
        res = self._diag_codes()
        assert any(d.code == "shard-spec-arity" for d in res.diagnostics)

    def test_analyzer_folds_in_sharding_diagnostics(self):
        """analyze_program surfaces a propagation conflict as a
        provenance-carrying diagnostic (the acceptance-bar mutation test:
        corrupt a clean annotation, assert the specific diagnostic)."""
        loss, ann = _tp_transformer()
        prog = pt.default_main_program()
        diags = analysis.analyze_program(prog, tp_size=2)
        assert not [d for d in diags if d.severity == "error"
                    and d.code.startswith("shard")]
        # mutation: lie about the lm-head bias — shard a rank-1 bias that
        # adds to the (replicated, post-psum) logits
        prog.global_block().var("lm_head.w_1").sharding_spec = (TP_AXIS,)
        diags = analysis.analyze_program(prog, tp_size=2)
        errs = [d for d in diags if d.severity == "error"
                and d.code == "shard-conflict"]
        assert errs, "mutated annotation produced no conflict"
        assert any("block 0 op#" in d.loc for d in errs), \
            [str(d) for d in errs]

    def test_control_flow_consuming_sharded_value_conflicts(self):
        x = layers.data("x", shape=[8])
        h = layers.fc(x, size=8, name="cf",
                      param_attr=ParamAttr(name="cf.w",
                                           sharding_spec=(None, TP_AXIS)),
                      bias_attr=False)
        cond = layers.fill_constant([1], "bool", True)
        layers.cond(cond, lambda: layers.scale(h, scale=2.0),
                    lambda: h)
        res = self._diag_codes()
        assert any("control-flow" in d.message for d in res.errors)


# ---------------------------------------------------------------------------
# tp_shard_pass: rewrite structure
# ---------------------------------------------------------------------------


class TestTpShardPass:
    def test_splices_collectives_and_marks_vars(self):
        _col_row_mlp()
        prog = pt.default_main_program()
        out = get_pass("tp_shard_pass", tp=2)(prog)
        assert out is not prog and out._tp_applied and out._tp_size == 2
        ops = [op.type for op in out.global_block().ops]
        assert "tp_allreduce" in ops and "tp_ident" in ops
        # the partial-sum output was renamed and restored
        ar = next(op for op in out.global_block().ops
                  if op.type == "tp_allreduce")
        assert ar.inputs["X"][0].endswith("@TPPART")
        # sharded vars (params AND their grads) carry tp_spec
        b = out.global_block()
        assert b.var("fc1.w").tp_spec == (None, "tp")
        assert b.var("fc2.w").tp_spec == ("tp", None)
        assert b.var("fc2.w@GRAD").tp_spec == ("tp", None)
        # source program untouched
        assert not any(op.type.startswith("tp_")
                       for op in prog.global_block().ops)

    def test_idempotent_and_noop_without_annotations(self):
        _col_row_mlp(col=False, row=False)
        prog = pt.default_main_program()
        assert get_pass("tp_shard_pass", tp=2)(prog) is prog
        _ = None
        pt.reset_default_programs()
        with pt.core.unique_name.guard():
            _col_row_mlp()
        prog = pt.default_main_program()
        out = get_pass("tp_shard_pass", tp=2)(prog)
        assert get_pass("tp_shard_pass", tp=2)(out) is out

    def test_conflict_raises_with_provenance(self):
        x = layers.data("x", shape=[8])
        layers.fc(x, size=8, name="bad2",
                  param_attr=ParamAttr(name="bad2.w",
                                       sharding_spec=(TP_AXIS, TP_AXIS)),
                  bias_attr=False)
        with pytest.raises(ProgramAnalysisError) as ei:
            get_pass("tp_shard_pass", tp=2)(pt.default_main_program())
        assert "block 0 op#" in str(ei.value)

    def test_pass_sanitizer_clean_on_transformer(self):
        """PTPU_VERIFY_PASSES=1 (conftest) runs verify-before/after around
        every pass apply; a sanitizer violation would raise here. Also
        assert the rewritten program re-analyzes clean at tp-local shapes."""
        assert flags.get_flag("verify_passes")
        _tp_transformer()
        out = get_pass("tp_shard_pass", tp=2)(pt.default_main_program())
        diags = analysis.analyze_program(out, tp_size=2)
        errs = [d for d in diags if d.severity == "error"]
        assert not errs, [str(d) for d in errs]

    def test_vocab_lookup_rewritten(self):
        _tp_transformer()
        out = get_pass("tp_shard_pass", tp=2)(pt.default_main_program())
        ops = [op.type for op in out.global_block().ops]
        assert "tp_vocab_lookup" in ops
        op = next(o for o in out.global_block().ops
                  if o.type == "tp_vocab_lookup")
        assert op.attrs["parts"] == 2 and op.attrs["vocab"] == 64

    def test_reshape_attrs_localized(self):
        """Head-split reshape targets divide by tp (the [B,T,D@tp] ->
        [B,T,nh/tp,dh] case)."""
        _tp_transformer(d_model=32, heads=4)
        out = get_pass("tp_shard_pass", tp=2)(pt.default_main_program())
        head_splits = [op for op in out.global_block().ops
                       if op.type == "reshape"
                       and len(op.attrs.get("shape", ())) == 4]
        assert head_splits
        for op in head_splits:
            assert op.attrs["shape"][2] == 2  # 4 heads / tp2

    def test_analytic_wire_bytes(self):
        _col_row_mlp()
        prog = pt.default_main_program()
        assert tp_analytic_wire_bytes(prog, 2) is None  # not rewritten
        out = get_pass("tp_shard_pass", tp=2)(prog)
        w = tp_analytic_wire_bytes(out, 2, nominal_batch=8)
        assert w["tp_op_counts"]["tp_allreduce"] == 1
        assert w["tp_op_counts"]["tp_ident"] >= 1
        # fwd psum of the [8, 4] row output: ring all-reduce 2n(tp-1)/tp
        assert w["tp_allreduce_wire_bytes"] >= int(2 * 8 * 4 * 4 * 0.5)
        assert w["tp_wire_bytes"] == (w["tp_allreduce_wire_bytes"]
                                      + w["tp_allgather_wire_bytes"])


# ---------------------------------------------------------------------------
# the manual-mode gate: one test per branch (satellite #1)
# ---------------------------------------------------------------------------


class TestManualModeGate:
    def _exe(self, mesh_axes, **bst_kw):
        import jax
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.mesh import DeviceMesh
        from paddle_tpu.parallel.strategy import BuildStrategy, \
            ReduceStrategy
        n = int(np.prod(list(mesh_axes.values())))
        bst = BuildStrategy(**bst_kw)
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
        mesh = DeviceMesh(jax.devices()[:n], mesh_axes)
        return ParallelExecutor(mesh=mesh, build_strategy=bst)

    def test_sp_feed_splitting_rejected_with_or_without_tp(self):
        _col_row_mlp()
        exe = self._exe({"dp": 2, "sp": 2}, enable_sequence_parallel=True)
        with pytest.raises(InvalidArgumentError, match="WHOLE"):
            exe._prepare_program(pt.default_main_program(),
                                 pt.global_scope())

    def test_non_tp_axis_sharded_param_rejected(self):
        x = layers.data("x", shape=[8])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=8, name="dpw",
                      param_attr=ParamAttr(name="dpw.w",
                                           sharding_spec=(None, "sp")),
                      bias_attr=False)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=4), label))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = self._exe({"dp": 2, "sp": 2})
        with pytest.raises(InvalidArgumentError,
                           match=r"sharded over mesh\s+axes \['sp'\]"):
            exe._prepare_program(pt.default_main_program(),
                                 pt.global_scope())

    def test_kill_switch_branch_names_the_flag(self):
        _col_row_mlp()
        exe = self._exe({"dp": 2, "tp": 2})
        old = flags.get_flag("tp_shard")
        try:
            flags.set_flag("tp_shard", False)
            with pytest.raises(InvalidArgumentError,
                               match="PTPU_TP_SHARD"):
                exe._prepare_program(pt.default_main_program(),
                                     pt.global_scope())
        finally:
            flags.set_flag("tp_shard", old)

    def test_tp_sharded_param_now_passes_the_gate(self):
        """The r11 lift: the exact configuration the old blanket gate
        rejected — tp-sharded params + explicit dp pipeline — prepares
        cleanly (the tp_shard_pass rewrite runs first)."""
        _col_row_mlp()
        exe = self._exe({"dp": 2, "tp": 2})
        prog = exe._prepare_program(pt.default_main_program(),
                                    pt.global_scope())
        assert prog._tp_applied and prog._dp_comm_applied
        ops = [op.type for op in prog.global_block().ops]
        assert "tp_allreduce" in ops and "dp_grad_comm" in ops

    def test_annotation_on_tp_less_mesh_composes(self):
        """A tp annotation resolved on a mesh WITHOUT a tp axis is
        replicated and rides the manual modes untouched (no rewrite)."""
        _col_row_mlp()
        exe = self._exe({"dp": 2})
        prog = exe._prepare_program(pt.default_main_program(),
                                    pt.global_scope())
        assert not getattr(prog, "_tp_applied", False)
        assert prog._dp_comm_applied
