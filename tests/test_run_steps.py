"""Executor.run_steps: K train steps fused into one XLA execution via
lax.scan — the in-graph training loop (≙ the reference's py_reader-driven
executor loop, layers/io.py:474, where the device consumes batches without
a per-step Python round-trip).

Parity pin: the scan-fused loop must produce the SAME loss trajectory and
the SAME final parameters as K sequential Executor.run calls.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

pytestmark = pytest.mark.quick  # run_ci.sh quick smoke tier


def _build_net():
    x = layers.data("x", shape=[6])
    y = layers.data("y", shape=[1])
    h = layers.fc(x, size=8, act="relu", name="rs_fc1")
    pred = layers.fc(h, size=1, name="rs_fc2")
    loss = layers.reduce_mean(layers.square(pred - y))
    pt.optimizer.MomentumOptimizer(learning_rate=0.05,
                                   momentum=0.9).minimize(loss)
    return loss


def _feeds(k=6):
    r = np.random.RandomState(7)
    W = r.randn(6, 1).astype("float32")
    out = []
    for i in range(k):
        rb = np.random.RandomState(100 + i)
        xb = rb.rand(8, 6).astype("float32")
        out.append({"x": xb, "y": (xb @ W).astype("float32")})
    return out


class TestRunSteps:
    def test_matches_sequential_run(self):
        feeds = _feeds()
        loss = _build_net()
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        seq = [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
        seq_w = np.asarray(pt.global_scope().get("rs_fc1.w_0"))

        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            pass
        exe2 = pt.Executor()
        exe2.run(pt.default_startup_program())
        fused = exe2.run_steps(feeds, fetch_list=[loss])[0]
        assert fused.shape == (len(feeds),)
        np.testing.assert_allclose(fused, seq, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pt.global_scope().get("rs_fc1.w_0")), seq_w,
            rtol=1e-5)

    def test_state_continues_across_calls(self):
        feeds = _feeds(8)
        loss = _build_net()
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        first = exe.run_steps(feeds[:4], fetch_list=[loss])[0]
        second = exe.run_steps(feeds[4:], fetch_list=[loss])[0]
        # training really progressed across the two fused calls
        assert second[-1] < first[0]

    def test_mismatched_signatures_rejected(self):
        loss = _build_net()
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feeds = _feeds(2)
        feeds[1]["x"] = feeds[1]["x"][:4]  # different batch size
        with pytest.raises(Exception) as ei:
            exe.run_steps(feeds, fetch_list=[loss])
        assert "signature" in str(ei.value)

    def test_staged_uint8_feeds(self):
        img = layers.data(name="img", shape=[4, 4, 3],
                          staging_dtype="uint8")
        label = layers.data(name="label", shape=[1], dtype="int64")
        flat = layers.reshape(img, shape=[-1, 48])
        logits = layers.fc(flat, size=3)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        rng = np.random.RandomState(0)
        feeds = [{"img": rng.randint(0, 256, (8, 4, 4, 3)).astype(np.uint8),
                  "label": rng.randint(0, 3, (8, 1)).astype(np.int64)}
                 for _ in range(5)]
        # same batch each step so the loss must fall monotonically-ish
        feeds = [feeds[0]] * 5
        curve = exe.run_steps(feeds, fetch_list=[loss])[0]
        assert curve[-1] < curve[0]


class TestParallelExecutorRunSteps:
    def test_pe_run_steps_matches_pe_sequential(self):
        import jax
        from paddle_tpu.parallel import DeviceMesh, ParallelExecutor
        feeds = _feeds(6)
        loss = _build_net()
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        pe = ParallelExecutor(loss_name=loss.name,
                              mesh=DeviceMesh(jax.devices()))
        seq = [float(pe.run(feed=f, fetch_list=[loss.name])[0])
               for f in feeds]
        w_seq = np.asarray(pt.global_scope().get("rs_fc1.w_0"))

        pt.reset_global_scope()
        exe2 = pt.Executor()
        exe2.run(pt.default_startup_program())
        pe2 = ParallelExecutor(loss_name=loss.name,
                               mesh=DeviceMesh(jax.devices()))
        fused = pe2.run_steps(feeds, fetch_list=[loss.name])[0]
        np.testing.assert_allclose(fused, seq, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pt.global_scope().get("rs_fc1.w_0")), w_seq,
            rtol=1e-5)
        # the fused-loop state really lives sharded/replicated on the mesh
        w = pt.global_scope().get("rs_fc1.w_0")
        assert len(w.sharding.device_set) == len(jax.devices())

    def test_pe_run_steps_rejects_indivisible_batch(self):
        import jax
        from paddle_tpu.parallel import DeviceMesh, ParallelExecutor
        loss = _build_net()
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        pe = ParallelExecutor(loss_name=loss.name,
                              mesh=DeviceMesh(jax.devices()))
        bad = [{"x": np.ones((7, 6), np.float32),
                "y": np.ones((7, 1), np.float32)}]
        with pytest.raises(Exception) as ei:
            pe.run_steps(bad, fetch_list=[loss.name])
        assert "divisible" in str(ei.value)


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-x", "-q"]))
