"""Tests: r17 memory + utilization observability.

- the HLO liveness-walk temp fallback (costs.hlo_liveness_temp_bytes)
  on a hand-computed module;
- watermark channels + the ptpu_memory_*/ptpu_mfu gauges + the `memory`
  trace channel's Chrome COUNTER rendering and its trace_merge lane;
- costs.memory_categories per-device predictions vs hand-computed bytes;
- the LEDGER ACCOUNTING IDENTITY (check_memory_identity) on a builder
  sweep across parallel configs — per-category bytes EXACT, the category
  walk re-deriving XLA's argument figure, unattributed residual bounded
  (the full r17 cell matrix incl. pp/tp/ef is committed by
  tools/bench_mem.py as BENCH_MEM_r17.json);
- one mutation test per identity discipline: an inflated predicted
  category is caught BY NAME in the residual buckets;
- the tracing overhead budget (<= 3% on / <= 0.5% off) re-asserted with
  the memory channel recording.
"""

import json

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.framework import costs
from paddle_tpu.observability import memory as obs_memory
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import tracing
from paddle_tpu.observability.ledger import CostLedger


@pytest.fixture(autouse=True)
def _fresh_watermarks():
    obs_memory.reset_watermarks()
    yield
    obs_memory.reset_watermarks()


# ---------------------------------------------------------------------------
# HLO liveness walk (the documented temp fallback)
# ---------------------------------------------------------------------------


_HLO_SAMPLE = """\
HloModule jit_f, is_scheduled=true

%region_0.8 (Arg_0.9: f32[], Arg_1.10: f32[]) -> f32[] {
  %Arg_0.9 = f32[] parameter(0)
  %Arg_1.10 = f32[] parameter(1)
  ROOT %add.11 = f32[] add(f32[] %Arg_0.9, f32[] %Arg_1.10)
}

ENTRY %main.13 (Arg_0.1: f32[32,64], Arg_1.2: f32[64,64]) -> f32[] {
  %Arg_0.1 = f32[32,64]{1,0} parameter(0)
  %Arg_1.2 = f32[64,64]{1,0} parameter(1)
  %dot.4 = f32[32,64]{1,0} dot(f32[32,64]{1,0} %Arg_0.1, f32[64,64]{1,0} %Arg_1.2)
  %tanh.5 = f32[32,64]{1,0} tanh(f32[32,64]{1,0} %dot.4)
  %dot.7 = f32[32,64]{1,0} dot(f32[32,64]{1,0} %tanh.5, f32[64,64]{1,0} %Arg_1.2)
  ROOT %reduce.12 = f32[] reduce(f32[32,64]{1,0} %dot.7, f32[] %dot.7), dimensions={0,1}, to_apply=%region_0.8
}
"""


class TestHloLivenessWalk:
    def test_hand_computed_peak(self):
        # live sets: {dot.4}=8192 -> {dot.4,tanh.5}=16384 (tanh consumes
        # dot.4 at its own index) -> {tanh.5,dot.7}=16384 -> root
        # (excluded: output buffer). Parameters excluded (argument
        # buffers).
        assert costs.hlo_liveness_temp_bytes(_HLO_SAMPLE) == 16384

    def test_called_computation_adds_its_peak(self):
        hlo = _HLO_SAMPLE.replace(
            "ROOT %add.11 = f32[] add(f32[] %Arg_0.9, f32[] %Arg_1.10)",
            "%big.1 = f32[128]{0} broadcast(f32[] %Arg_0.9)\n"
            "  %big.2 = f32[128]{0} negate(f32[128]{0} %big.1)\n"
            "  ROOT %add.11 = f32[] add(f32[] %Arg_0.9, f32[] %Arg_1.10)")
        # region now holds 2x512 transient bytes, charged at the reduce
        # callsite where entry liveness is 8192 (dot.7 live, tanh.5
        # freed after dot.7's index... dot.7 is consumed by the root) —
        # peak moves only if callsite + callee exceeds 16384; here
        # 8192 + 1024 < 16384, so the peak is unchanged — and the
        # callee's contribution is still exercised via a module whose
        # entry is small:
        assert costs.hlo_liveness_temp_bytes(hlo) == 16384
        small = (
            "ENTRY %m (p0: f32[4]) -> f32[4] {\n"
            "  %p0 = f32[4]{0} parameter(0)\n"
            "  %a = f32[4]{0} negate(f32[4]{0} %p0), to_apply=%region_1\n"
            "  ROOT %r = f32[4]{0} negate(f32[4]{0} %a)\n"
            "}\n"
            "%region_1 (q0: f32[]) -> f32[] {\n"
            "  %q0 = f32[] parameter(0)\n"
            "  %w = f32[256]{0} broadcast(f32[] %q0)\n"
            "  ROOT %s = f32[] negate(f32[] %q0)\n"
            "}\n")
        # a=16 live + callee peak 1024 = 1040
        assert costs.hlo_liveness_temp_bytes(small) == 1040

    def test_empty_or_unparseable_is_zero(self):
        assert costs.hlo_liveness_temp_bytes("") == 0
        assert costs.hlo_liveness_temp_bytes("not hlo at all") == 0

    def test_real_compiled_module_close_to_xla_temp(self):
        """On a module where the CPU backend DOES report temps, the walk
        must land at-or-above the reported figure (it cannot see buffer
        reuse, never below by more than fusion slack) — pinned loosely:
        within [1x, 3x]."""
        import jax
        import jax.numpy as jnp

        def f(x, w):
            return (jnp.tanh(x @ w) @ w.T).sum()

        c = jax.jit(f).lower(jnp.ones((32, 64)),
                             jnp.ones((64, 64))).compile()
        reported = c.memory_analysis().temp_size_in_bytes
        if reported == 0:
            pytest.skip("backend reports no temp for this module")
        walked = costs.hlo_liveness_temp_bytes(c.as_text())
        assert reported <= walked <= 3 * reported, (reported, walked)


# ---------------------------------------------------------------------------
# watermarks, gauges, counter channel
# ---------------------------------------------------------------------------


class TestWatermarks:
    def test_unknown_channel_rejected(self):
        from paddle_tpu.core.enforce import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="memory channel"):
            obs_memory.update_watermark("not_a_channel", 1)

    def test_current_and_peak_ratchet(self):
        obs_memory.update_watermark("kv_cache_bytes", 100)
        obs_memory.update_watermark("kv_cache_bytes", 40)
        board = obs_memory.watermark_board()
        assert board["kv_cache_bytes"]["current"] == 40
        assert board["kv_cache_bytes"]["peak"] == 100
        obs_memory.reset_watermarks()
        assert obs_memory.watermark_board()["kv_cache_bytes"]["peak"] == 0

    def test_gauges_live_in_default_registry(self):
        obs_memory.update_watermark("host_staging_bytes", 7)
        obs_memory.note_mfu(1e12, 0.1)   # 1e13 flops/s over 197e12 peak
        text = obs_metrics.default_registry().expose()
        assert "ptpu_memory_host_staging_bytes 7" in text
        assert ('ptpu_memory_watermark_bytes'
                '{channel="host_staging_bytes"} 7') in text
        mfu_line = [ln for ln in text.splitlines()
                    if ln.startswith("ptpu_mfu ")][0]
        assert abs(float(mfu_line.split()[-1])
                   - 1e12 / 0.1 / costs.V5E_PEAK_TFLOPS) < 1e-12

    def test_counter_samples_render_as_chrome_counter_events(self,
                                                             tmp_path):
        tracing.clear()
        obs_memory.update_watermark("device_state_bytes", 1234)
        path = str(tmp_path / "trace.json")
        tracing.export_chrome_trace(path)
        events = json.load(open(path))["traceEvents"]
        cs = [e for e in events if e.get("ph") == "C"]
        assert cs, events
        ev = [e for e in cs
              if e["name"] == "memory/device_state_bytes"][0]
        assert ev["args"]["value"] == 1234.0
        assert "dur" not in ev

    def test_record_counter_disabled_returns_none(self):
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            assert tracing.record_counter("memory/x", 1) is None
        finally:
            flags.set_flag("trace", old)

    def test_counter_kind_is_closed(self):
        assert "memory" in tracing.SPAN_KINDS

    def test_trace_merge_gives_memory_its_own_lane(self, tmp_path):
        import sys
        import os
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools"))
        import trace_merge
        tracing.clear()
        with tracing.rank_scope("w", 1, 2):
            obs_memory.update_watermark("kv_cache_bytes", 5)
        src = str(tmp_path / "rank.json")
        tracing.export_chrome_trace(src)
        doc = trace_merge.merge([src], align_span="")
        meta = {(e["pid"], e["tid"]): e["args"]["name"]
                for e in doc["traceEvents"]
                if e.get("ph") == "M" and e["name"] == "thread_name"}
        counter = [e for e in doc["traceEvents"]
                   if e.get("ph") == "C"][0]
        assert counter["pid"] == 1                      # rank lane
        assert meta[(1, counter["tid"])] == "memory"    # named lane


# ---------------------------------------------------------------------------
# predicted categories
# ---------------------------------------------------------------------------


def _build_mnist(rng, batch=16):
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    feed = {"x": rng.rand(batch, 64).astype("float32"),
            "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
    return loss, feed


class TestMemoryCategories:
    def test_hand_computed_plain(self, rng):
        _build_mnist(rng)
        cats = costs.memory_categories(pt.default_main_program(),
                                       dp=1, nominal_batch=16)
        # params: 64x32 + 32 + 32x10 + 10 = 2410 f32 = 9640 bytes;
        # momentum keeps one velocity per param; feeds: x 16x64x4 +
        # label 16x1x4 (int64 CANONICALIZES to int32 on device)
        assert cats["params"] == 9640
        assert cats["optimizer_state"] == 9640
        assert cats["feeds"] == 16 * 64 * 4 + 16 * 4
        assert cats["ef_residual"] == 0
        assert cats["seed"] == 4
        assert cats["transient_peak"] > 0

    def test_dp_splits_batch_led_feeds_only(self, rng):
        _build_mnist(rng)
        c1 = costs.memory_categories(pt.default_main_program(),
                                     dp=1, nominal_batch=16)
        c2 = costs.memory_categories(pt.default_main_program(),
                                     dp=2, nominal_batch=16)
        assert c2["feeds"] == c1["feeds"] // 2
        assert c2["params"] == c1["params"]   # replicated: not split


# ---------------------------------------------------------------------------
# the accounting identity (builder sweep + mutations)
# ---------------------------------------------------------------------------


def _run_cell(rng, mode, batch=16):
    """One (mnist, mode) identity cell; returns (ledger row, census)."""
    import jax
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    loss, feed = _build_mnist(rng, batch)
    if mode == "plain":
        exe = pt.Executor()
        pt.Executor().run(pt.default_startup_program())
        exe.run(feed=feed, fetch_list=[loss])
        predicted = costs.predict(pt.default_main_program(), dp=1,
                                  nominal_batch=batch)
        dp = 1
    else:
        bst = BuildStrategy()
        if mode == "dp2":
            bst.reduce_strategy = ReduceStrategy.ReduceScatter
            mesh = DeviceMesh(jax.devices()[:2], {"dp": 2})
            dp = 2
        elif mode == "pp2":
            bst.pipeline_stages = 2
            bst.num_microbatches = 4
            bst.pipeline_schedule = "1f1b"
            mesh = DeviceMesh(jax.devices()[:2], {"pp": 2})
            dp = 1
        exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                               mesh=mesh)
        pt.Executor().run(pt.default_startup_program())
        exe.run(feed=feed, fetch_list=[loss])
        predicted = exe.cost_report(nominal_batch=batch)
    census = exe.memory_census(feed=feed)
    row = CostLedger("t").row(f"mnist_{mode}", dp=dp)
    row.set_prediction(predicted)
    row.set_memory_census(census)
    return row, census


class TestMemoryLedgerIdentity:
    """The per-builder identity sweep. The full r17 matrix — incl.
    dp2xpp2, tp2, and the quantized+error-feedback cell — is committed
    by tools/bench_mem.py (BENCH_MEM_r17.json); this sweep keeps the
    tier-1 cells cheap."""

    @pytest.mark.parametrize("mode", ["plain", "dp2", "pp2"])
    def test_identity_holds_mnist(self, rng, mode):
        row, census = _run_cell(rng, mode)
        rec = row.check_memory_identity()
        assert row.ok, [c for c in row.checks if not c["ok"]]
        # every category check was EXACT and the walk re-derived XLA's
        # own argument figure
        whats = {c["what"] for c in row.checks}
        assert {"memory_params", "memory_optimizer_state",
                "memory_feeds", "memory_args_balance",
                "memory_residual_bound"} <= whats
        assert rec["measured_total"] == (rec["attributed_total"]
                                         + sum(v for k, v in
                                               rec["buckets"].items()
                                               if k.startswith(
                                                   "unattributed:")))

    def test_identity_holds_transformer_dp2(self, rng):
        import jax
        from paddle_tpu.models import transformer
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.mesh import DeviceMesh
        from paddle_tpu.parallel.strategy import (BuildStrategy,
                                                  ReduceStrategy)
        loss, _ = transformer.transformer_lm(
            vocab=32, max_len=8, d_model=16, d_inner=32, num_heads=2,
            num_layers=1, dropout=0.0, mean_loss=True)
        pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
        feed = {"tokens": rng.randint(0, 32, (8, 8)).astype("int64"),
                "tokens@SEQLEN": np.full((8,), 8, "int32"),
                "targets": rng.randint(0, 32, (8, 8)).astype("int64")}
        bst = BuildStrategy()
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
        exe = ParallelExecutor(
            loss_name=loss.name, build_strategy=bst,
            mesh=DeviceMesh(jax.devices()[:2], {"dp": 2}))
        pt.Executor().run(pt.default_startup_program())
        exe.run(feed=feed, fetch_list=[loss])
        row = CostLedger("t").row("transformer_dp2", dp=2)
        row.set_prediction(exe.cost_report(nominal_batch=8))
        row.set_memory_census(exe.memory_census(feed=feed))
        rec = row.check_memory_identity()
        # exact across the board — the @SEQLEN sidecar rides a declared
        # data var, so even the sequence-length feed bytes reconcile
        assert row.ok, [c for c in row.checks if not c["ok"]]
        assert rec["ok"], rec

    def test_mutation_inflated_category_is_named(self, rng):
        """ISSUE 13 satellite: inflate ONE predicted category and the
        identity must fail naming exactly that category's residual."""
        row, _ = _run_cell(rng, "plain")
        row.predicted["memory"]["per_device"]["params"] *= 2
        rec = row.check_memory_identity()
        params_check = [c for c in row.checks
                        if c["what"] == "memory_params"][0]
        assert not params_check["ok"]
        assert "unrealized:params" in rec["buckets"]
        others = [c for c in row.checks
                  if c["what"].startswith("memory_")
                  and c["what"] not in ("memory_params",)]
        assert all(c["ok"] for c in others), others

    def test_mutation_missing_measured_category_breaks_args_balance(
            self, rng):
        """Zeroing a measured category breaks the cross-measurement
        check (the walk no longer re-derives XLA's argument bytes) —
        a category the census silently dropped cannot pass."""
        row, census = _run_cell(rng, "plain")
        drop = census["state"]["categories"]["optimizer_state"]
        census["state"]["categories"]["optimizer_state"] = 0.0
        census["state"]["categories"]["state_total"] -= drop
        row.check_memory_identity()
        bal = [c for c in row.checks
               if c["what"] == "memory_args_balance"][0]
        assert not bal["ok"], bal

    def test_requires_both_sides(self):
        from paddle_tpu.core.enforce import InvalidArgumentError
        row = CostLedger("t").row("empty")
        with pytest.raises(InvalidArgumentError, match="memory census"):
            row.check_memory_identity()


# ---------------------------------------------------------------------------
# overhead budget with the memory channel on
# ---------------------------------------------------------------------------


def _counter_overhead_s(n=2000):
    """Measured per-sample cost of one watermark update (the memory
    channel's whole per-step hot path) in the CURRENT trace state."""
    import time
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            obs_memory.update_watermark("device_state_bytes", 1.0)
        dt = (time.perf_counter() - t0) / n
        best = dt if best is None else min(best, dt)
    return best


class TestOverheadBudgetWithMemoryChannel:
    """ISSUE 13 satellite: the r12 budget (<= 3% of step time enabled,
    <= 0.5% disabled) re-asserted with the memory channel recording —
    spans AND the per-step watermark/MFU samples."""

    def _step_time_and_spans(self, rng):
        import time
        from paddle_tpu.models import mnist
        loss, acc = mnist.mlp()[:2]
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"img": rng.rand(8, 784).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}
        exe.run(feed=feed, fetch_list=[loss])   # compile
        m = tracing.mark()
        t0 = time.perf_counter()
        for _ in range(5):
            exe.run(feed=feed, fetch_list=[loss])
        step_s = (time.perf_counter() - t0) / 5
        window = tracing.spans_since(m)
        spans_per_step = len(window) / 5
        counters_per_step = len([s for s in window
                                 if s.kind == "memory"]) / 5
        return step_s, spans_per_step, counters_per_step

    def test_budget_holds_with_memory_channel(self, rng):
        step_s, spans_per_step, counters_per_step = \
            self._step_time_and_spans(rng)
        # the executor's per-run sampling IS live (device_state + mfu)
        assert counters_per_step >= 2, counters_per_step
        span_cost = tracing.span_overhead_s()
        ctr_cost = _counter_overhead_s()
        frac_on = (span_cost * spans_per_step
                   + ctr_cost * counters_per_step) / step_s
        assert frac_on <= 0.03, (frac_on, span_cost, ctr_cost, step_s)
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            span_off = tracing.span_overhead_s()
            ctr_off = _counter_overhead_s()
        finally:
            flags.set_flag("trace", old)
        frac_off = (span_off * spans_per_step
                    + ctr_off * counters_per_step) / step_s
        assert frac_off <= 0.005, (frac_off, span_off, ctr_off, step_s)


# ---------------------------------------------------------------------------
# healthz / dossier boards
# ---------------------------------------------------------------------------


class TestMemoryBoards:
    def test_dossier_embeds_memory_board(self, tmp_path):
        from paddle_tpu.observability import flight_recorder as fr
        obs_memory.update_watermark("kv_cache_bytes", 42)
        fr.configure(str(tmp_path))
        try:
            path = fr.dump_dossier("test")
            doc = json.load(open(path))
            # flat — the SAME shape /healthz embeds, one vocabulary
            wm = doc["memory"]
            assert wm["kv_cache_bytes"]["current"] == 42
            assert "mfu" in wm
        finally:
            fr.reset()

    def test_engine_seeds_kv_watermark(self):
        from paddle_tpu.serving_engine import ContinuousBatchingEngine
        eng = ContinuousBatchingEngine(n_slots=2, vocab=16, max_len=8,
                                       d_model=8, d_inner=16,
                                       num_heads=2, num_layers=1)
        board = obs_memory.watermark_board()
        assert board["kv_cache_bytes"]["current"] == \
            eng._kv_cache_bytes() > 0
