"""Transpiler layer tests.

≙ reference tests: test_memory_optimization_transpiler.py,
test_inference_transpiler (BN-fold numerics), test_dist_transpiler.py
(transpiled program structure asserted without running servers).
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.transpiler import (DistributeTranspiler, HashName,
                                   InferenceTranspiler, QuantizeTranspiler,
                                   RoundRobin, memory_optimize, release_memory,
                                   slice_variable)


def _mlp():
    img = layers.data("img", shape=[16], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(img, size=32, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label))
    return img, label, logits, loss


class TestMemoryOptimize:
    def test_remat_same_loss_and_grads(self, rng):
        img, label, logits, loss = _mlp()
        opt = pt.optimizer.SGDOptimizer(learning_rate=0.0)  # lr=0: no drift
        opt.minimize(loss)

        feed = {"img": rng.rand(8, 16).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}

        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        base = exe.run(feed=feed, fetch_list=[loss])[0]

        memory_optimize(pt.default_main_program(), level=1)
        opt_loss = exe.run(feed=feed, fetch_list=[loss])[0]
        np.testing.assert_allclose(base, opt_loss, rtol=1e-5)

    def test_level0_policy_set(self):
        _, _, _, loss = _mlp()
        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        prog = memory_optimize(pt.default_main_program(), level=0)
        regions = [op for op in prog.global_block().ops
                   if op.type == "vjp_region"]
        assert regions and all(op.attrs["remat"] for op in regions)
        assert regions[0].attrs["remat_policy"] == \
            "dots_with_no_batch_dims_saveable"
        assert "live_out" in regions[0].attrs

    def test_release_memory_keeps_fetchable_loss(self, rng):
        img, label, logits, loss = _mlp()
        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        release_memory(pt.default_main_program())
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"img": rng.rand(8, 16).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}
        out = exe.run(feed=feed, fetch_list=[loss])[0]
        assert np.isfinite(out).all()

    def test_bad_level_raises(self):
        with pytest.raises(ValueError):
            memory_optimize(pt.default_main_program(), level=7)

    def test_fetch_of_narrowed_intermediate_still_works(self, rng):
        # liveness can't see fetch lists — the executor must keep a fetched
        # forward var alive even after live-out narrowing dropped it
        img, label, logits, loss = _mlp()
        hidden = logits.block.ops[0]  # first op's output is an intermediate
        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        memory_optimize(pt.default_main_program(), level=1)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"img": rng.rand(8, 16).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}
        mid_name = hidden.output_names()[0]
        vals = exe.run(feed=feed, fetch_list=[loss, mid_name])
        assert np.isfinite(vals[0]).all()
        assert np.asarray(vals[1]).size > 0


class TestInferenceTranspiler:
    def test_conv_bn_fold_matches(self, rng):
        img = layers.data("img", shape=[3, 8, 8], dtype="float32")
        conv = layers.conv2d(img, num_filters=4, filter_size=3,
                             bias_attr=False)
        out = layers.batch_norm(conv, is_test=True)
        prog = pt.default_main_program().clone(for_test=True)

        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        scope = pt.global_scope()
        # non-trivial BN stats
        bn_ops = [op for op in prog.global_block().ops
                  if op.type == "batch_norm"]
        assert len(bn_ops) == 1
        bn = bn_ops[0]
        scope.set_var(bn.inputs["Mean"][0],
                      rng.rand(4).astype("float32"))
        scope.set_var(bn.inputs["Variance"][0],
                      (rng.rand(4) + 0.5).astype("float32"))
        scope.set_var(bn.inputs["Scale"][0],
                      (rng.rand(4) + 0.5).astype("float32"))
        scope.set_var(bn.inputs["Bias"][0], rng.rand(4).astype("float32"))

        feed = {"img": rng.rand(2, 3, 8, 8).astype("float32")}
        base = exe.run(prog, feed=feed, fetch_list=[out])[0]

        InferenceTranspiler().transpile(prog, scope=scope)
        types = [op.type for op in prog.global_block().ops]
        assert "batch_norm" not in types
        fused = exe.run(prog, feed=feed, fetch_list=[out])[0]
        np.testing.assert_allclose(base, fused, atol=1e-4, rtol=1e-4)

    def test_conv_bias_bn_fold_matches(self, rng):
        img = layers.data("img", shape=[3, 8, 8], dtype="float32")
        conv = layers.conv2d(img, num_filters=4, filter_size=3)  # with bias
        out = layers.batch_norm(conv, is_test=True)
        prog = pt.default_main_program().clone(for_test=True)

        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        scope = pt.global_scope()
        bn = [op for op in prog.global_block().ops
              if op.type == "batch_norm"][0]
        scope.set_var(bn.inputs["Mean"][0], rng.rand(4).astype("float32"))
        scope.set_var(bn.inputs["Variance"][0],
                      (rng.rand(4) + 0.5).astype("float32"))

        feed = {"img": rng.rand(2, 3, 8, 8).astype("float32")}
        base = exe.run(prog, feed=feed, fetch_list=[out])[0]
        InferenceTranspiler().transpile(prog, scope=scope)
        assert "batch_norm" not in [o.type for o in prog.global_block().ops]
        fused = exe.run(prog, feed=feed, fetch_list=[out])[0]
        np.testing.assert_allclose(base, fused, atol=1e-4, rtol=1e-4)


class TestQuantizeTranspiler:
    def test_qat_inserts_fake_quant_and_runs(self, rng):
        img, label, logits, loss_pre = None, None, None, None
        img = layers.data("img", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, size=32, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))

        t = QuantizeTranspiler(weight_bits=8, activation_bits=8)
        t.training_transpile(pt.default_main_program())
        types = [op.type for op in pt.default_main_program().global_block().ops]
        assert types.count("fake_quantize_abs_max") >= 4  # 2 acts + 2 weights

        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"img": rng.rand(8, 16).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}
        l0 = exe.run(feed=feed, fetch_list=[loss])[0]
        for _ in range(5):
            l1 = exe.run(feed=feed, fetch_list=[loss])[0]
        assert np.isfinite(l1).all() and l1 < l0  # QAT still trains

    def test_moving_average_scale_state_advances(self, rng):
        img = layers.data("img", shape=[16], dtype="float32")
        h = layers.fc(img, size=8)
        loss = layers.mean(h)
        QuantizeTranspiler(
            activation_quantize_type="moving_average_abs_max"
        ).training_transpile(pt.default_main_program())
        pt.optimizer.SGDOptimizer(learning_rate=0.0).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        scope = pt.global_scope()
        sname = "img.quant_scale"
        assert scope.has_var(sname)
        s0 = float(np.asarray(scope.get(sname)))
        feed = {"img": rng.rand(4, 16).astype("float32")}
        exe.run(feed=feed, fetch_list=[loss])
        s1 = float(np.asarray(scope.get(sname)))
        exe.run(feed=feed, fetch_list=[loss])
        s2 = float(np.asarray(scope.get(sname)))
        assert s1 != s0 and s2 != s1  # the moving average actually moves

    def test_transpile_after_minimize_raises(self):
        img = layers.data("img", shape=[8], dtype="float32")
        h = layers.fc(img, size=4)
        loss = layers.mean(h)
        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        with pytest.raises(RuntimeError):
            QuantizeTranspiler().training_transpile(pt.default_main_program())

    def test_freeze_rounds_weights(self, rng):
        img = layers.data("img", shape=[8], dtype="float32")
        out = layers.fc(img, size=4)
        QuantizeTranspiler().training_transpile(pt.default_main_program())
        prog = pt.default_main_program()
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        scope = pt.global_scope()
        wname = [op.inputs["Y"][0].replace(".quantized", "")
                 for op in prog.global_block().ops if op.type == "mul"][0]
        before = np.asarray(scope.get(wname)).copy()
        QuantizeTranspiler().freeze_program(prog, scope=scope)
        after = np.asarray(scope.get(wname))
        # weights now lie exactly on the int8 grid
        s = np.abs(before).max()
        grid = (np.round(before * 127 / s) * s / 127)
        np.testing.assert_allclose(after, grid, atol=1e-6)


class TestDistTranspiler:
    def test_slice_variable_balanced(self):
        img = layers.data("img", shape=[8], dtype="float32")
        w = pt.default_main_program().global_block().create_parameter(
            name="w_big", shape=[1000, 64], dtype="float32")
        blocks = slice_variable([w], slice_count=4, min_block_size=1024)[0]
        assert len(blocks) == 4
        assert sum(b.size for b in blocks) == 1000 * 64
        # row-aligned shards
        assert all(b.size % 64 == 0 for b in blocks[:-1])

    def test_transpile_structure(self, rng):
        img = layers.data("img", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, size=64, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

        eps = "ps0:6174,ps1:6174"
        t = DistributeTranspiler()
        t.transpile(trainer_id=0, program=pt.default_main_program(),
                    pservers=eps, trainers=2)
        plan = t.get_shard_plan()
        # every trainable param fully covered by shards
        for p in pt.default_main_program().all_parameters():
            if not p.trainable:
                continue
            total = sum(vb.size for vb, _ in plan.by_var[p.name])
            numel = int(np.prod(p.shape))
            assert total == numel

        # pserver programs contain sgd ops on shards (≙ test_dist_transpiler)
        seen_sgd = 0
        for ep in eps.split(","):
            psprog = t.get_pserver_program(ep)
            ops = psprog.global_block().ops
            seen_sgd += sum(op.type == "sgd" for op in ops)
            startup = t.get_startup_program(ep, psprog)
            exe = pt.Executor()
            scope = pt.Scope()
            exe.run(startup, scope=scope)
        assert seen_sgd >= 2  # at least weight shards carry optimizers

    def test_pserver_program_runs_shard_update(self, rng):
        img = layers.data("img", shape=[16], dtype="float32")
        h = layers.fc(img, size=8, bias_attr=False)
        loss = layers.mean(h)
        pt.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
        t = DistributeTranspiler()
        t.transpile(0, pt.default_main_program(), pservers="a:1", trainers=1)
        psprog = t.get_pserver_program("a:1")
        sgd = [op for op in psprog.global_block().ops if op.type == "sgd"]
        assert sgd
        pname = sgd[0].inputs["Param"][0]
        gname = sgd[0].inputs["Grad"][0]
        lr = sgd[0].inputs["LearningRate"][0]
        size = psprog.global_block().vars[pname].shape[0]

        scope = pt.Scope()
        exe = pt.Executor()
        exe.run(t.get_startup_program("a:1", psprog), scope=scope)
        scope.set_var(lr, np.asarray(0.5, dtype="float32"))
        g = rng.rand(size).astype("float32")
        exe.run(psprog, feed={gname: g}, fetch_list=[pname], scope=scope)
        updated = np.asarray(scope.get(pname))
        np.testing.assert_allclose(updated, -0.5 * g, atol=1e-6)

    def test_dispatchers(self):
        rr = RoundRobin(["a", "b"])
        assert rr.dispatch([1, 2, 3]) == ["a", "b", "a"]
        hn = HashName(["a", "b", "c"])
        d1 = hn.dispatch(["w1", "w2", "w1"])
        assert d1[0] == d1[2]  # stable by name


class TestPassFrameworkAndAnalyzer:
    def test_registry_and_unknown_pass(self):
        from paddle_tpu import get_pass, registered_passes
        from paddle_tpu.core.enforce import NotFoundError
        assert {"prune_pass", "bn_fold_pass", "quant_freeze_pass",
                "memory_optimize_pass",
                "graph_viz_pass"} <= set(registered_passes())
        with pytest.raises(NotFoundError):
            get_pass("nope_pass")

    def test_analyzer_pipeline_serving_prep(self, rng, tmp_path):
        """prune -> BN fold -> viz over a trained conv program; outputs
        unchanged (≙ analyzer running its pass pipeline before serving)."""
        import paddle_tpu as pt
        from paddle_tpu import Analyzer, layers

        img = layers.data("img", shape=[3, 8, 8])
        c = layers.conv2d(img, num_filters=4, filter_size=3, bias_attr=False)
        out = layers.batch_norm(c, is_test=True)
        aux = layers.reduce_sum(out)  # prune target excludes this
        prog = pt.default_main_program().clone(for_test=True)

        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        scope = pt.global_scope()
        bn = [op for op in prog.global_block().ops
              if op.type == "batch_norm"][0]
        scope.set_var(bn.inputs["Mean"][0], rng.rand(4).astype("float32"))
        scope.set_var(bn.inputs["Variance"][0],
                      (rng.rand(4) + 0.5).astype("float32"))
        feed = {"img": rng.rand(2, 3, 8, 8).astype("float32")}
        base = exe.run(prog, feed=feed, fetch_list=[out])[0]

        dot = str(tmp_path / "g.dot")
        analyzed = Analyzer(
            passes=["bn_fold_pass", "graph_viz_pass"],
            graph_viz_pass={"path": dot}).run(prog, scope, targets=[out])
        types = [op.type for op in analyzed.global_block().ops]
        assert "batch_norm" not in types
        assert "reduce_sum" not in types   # pruned away
        got = exe.run(analyzed, feed=feed, fetch_list=[out])[0]
        np.testing.assert_allclose(got, base, atol=1e-4, rtol=1e-4)
        assert "digraph" in open(dot).read()
