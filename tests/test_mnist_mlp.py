"""End-to-end slice: MNIST-style MLP trains and loss decreases.

≙ reference tests/book/test_recognize_digits.py (train briefly, check loss
drops) — the SURVEY §7 stage-3 "one model" milestone.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers

import pytest

pytestmark = pytest.mark.quick  # run_ci.sh quick smoke tier


def _synthetic_mnist(rng, n=512):
    x = rng.rand(n, 784).astype(np.float32)
    # learnable structure: label depends on input
    w = rng.rand(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int64).reshape(-1, 1)
    return x, y


def build_mlp():
    img = layers.data(name="img", shape=[784])
    label = layers.data(name="label", shape=[1], dtype="int64")
    h = layers.fc(img, size=128, act="relu")
    h = layers.fc(h, size=64, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.softmax_with_cross_entropy(logits, label)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(logits, label)
    return avg_loss, acc


def test_mnist_mlp_trains(rng):
    avg_loss, acc = build_mlp()
    opt = pt.optimizer.SGD(learning_rate=0.1)
    opt.minimize(avg_loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    x, y = _synthetic_mnist(rng)
    losses = []
    for step in range(30):
        lo, ac = exe.run(pt.default_main_program(),
                         feed={"img": x[:64], "label": y[:64]},
                         fetch_list=[avg_loss, acc])
        losses.append(float(lo))
    assert losses[-1] < losses[0] * 0.9, f"loss did not drop: {losses}"


def test_mnist_adam_trains(rng):
    avg_loss, acc = build_mlp()
    opt = pt.optimizer.Adam(learning_rate=0.01)
    opt.minimize(avg_loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x, y = _synthetic_mnist(rng)
    first = None
    last = None
    for step in range(30):
        lo, = exe.run(feed={"img": x[:64], "label": y[:64]},
                      fetch_list=[avg_loss])
        first = first if first is not None else float(lo)
        last = float(lo)
    assert last < first * 0.7, f"adam loss did not drop: {first} -> {last}"


def test_executor_caches_compilation(rng):
    avg_loss, _ = build_mlp()
    pt.optimizer.SGD(learning_rate=0.1).minimize(avg_loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x, y = _synthetic_mnist(rng, n=128)
    exe.run(feed={"img": x[:64], "label": y[:64]}, fetch_list=[avg_loss])
    assert len(exe._cache) == 2  # startup + train step
    exe.run(feed={"img": x[64:128], "label": y[64:128]},
            fetch_list=[avg_loss])
    assert len(exe._cache) == 2  # same signature -> cache hit
