"""Native C++ serving entry (ptpu_predict) — VERDICT r2 #6.

Builds native/ptpu_predict (TF C API + XlaCallModule/XLA:CPU), exports a
book-style conv model with save_inference_model(export=True), runs the C++
binary on a .npy input, and pins its logits against
Predictor.from_exported — the same-artifact, no-Python serving parity the
reference proves with its inference/tests/book C++ tests
(≙ paddle/fluid/inference/api/api_impl.cc:126, tests/book/).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native")


@pytest.fixture(scope="session")
def ptpu_predict_bin():
    binpath = os.path.join(NATIVE_DIR, "ptpu_predict")
    src = os.path.join(NATIVE_DIR, "ptpu_predict.cc")
    if (not os.path.exists(binpath)
            or os.path.getmtime(binpath) < os.path.getmtime(src)):
        r = subprocess.run(["sh", "build.sh", "predict"], cwd=NATIVE_DIR,
                           capture_output=True, text=True, timeout=600)
        if r.returncode != 0 or not os.path.exists(binpath):
            pytest.skip(f"cannot build ptpu_predict: {r.stderr[-800:]}")
    return binpath


def _export_model(tmp_path):
    img = layers.data(name="img", shape=[8, 8, 1])
    conv = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                         data_format="NHWC")
    pool = layers.pool2d(conv, pool_size=2, pool_type="max", pool_stride=2,
                         data_format="NHWC")
    flat = layers.reshape(pool, shape=[-1, 4 * 4 * 4])
    logits = layers.fc(flat, size=10, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["img"], [logits], executor=exe,
                               export=True, native=True)
    return d, logits


class TestNativePredict:
    def test_native_artifact_files_written(self, tmp_path):
        d, _ = _export_model(tmp_path)
        assert os.path.exists(os.path.join(d, "__exported_native__.stablehlo"))
        meta = open(os.path.join(d, "__exported_native__.meta")).read()
        assert meta.splitlines()[0].startswith("version ")
        assert "in img float32 -1 8 8 1" in meta
        assert "nout 1" in meta

    def test_cpp_logits_match_python_predictor(self, tmp_path,
                                               ptpu_predict_bin):
        d, logits = _export_model(tmp_path)
        rng = np.random.RandomState(0)
        x = rng.rand(3, 8, 8, 1).astype(np.float32)

        from paddle_tpu.inferencer import Predictor
        ref = Predictor.from_exported(d).run({"img": x})[0]

        np.save(tmp_path / "img.npy", x)
        out_dir = tmp_path / "native_out"
        out_dir.mkdir()
        r = subprocess.run(
            [ptpu_predict_bin, d, str(tmp_path / "img.npy"),
             "--out", str(out_dir)],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-1500:]
        got = np.load(out_dir / "out0.npy")
        assert got.shape == ref.shape
        np.testing.assert_allclose(got, np.asarray(ref), atol=1e-5,
                                   rtol=1e-5)

    def test_cpp_serves_other_batch_size(self, tmp_path, ptpu_predict_bin):
        """The symbolic batch dim survives into the native artifact: one
        export serves any batch."""
        d, _ = _export_model(tmp_path)
        x = np.random.RandomState(1).rand(7, 8, 8, 1).astype(np.float32)
        np.save(tmp_path / "img7.npy", x)
        r = subprocess.run(
            [ptpu_predict_bin, d, str(tmp_path / "img7.npy"),
             "--out", str(tmp_path)],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-1500:]
        got = np.load(tmp_path / "out0.npy")
        assert got.shape == (7, 10)
        np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-4)

    def test_cpp_rejects_wrong_dtype(self, tmp_path, ptpu_predict_bin):
        d, _ = _export_model(tmp_path)
        x = np.zeros((2, 8, 8, 1), np.int32)
        np.save(tmp_path / "bad.npy", x)
        r = subprocess.run(
            [ptpu_predict_bin, d, str(tmp_path / "bad.npy")],
            capture_output=True, text=True, timeout=300)
        assert r.returncode != 0
        assert "dtype" in r.stderr


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-x", "-q"]))
