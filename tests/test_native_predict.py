"""Native C++ serving entry (ptpu_predict) — VERDICT r2 #6.

Builds native/ptpu_predict (TF C API + XlaCallModule/XLA:CPU), exports a
book-style conv model with save_inference_model(export=True), runs the C++
binary on a .npy input, and pins its logits against
Predictor.from_exported — the same-artifact, no-Python serving parity the
reference proves with its inference/tests/book C++ tests
(≙ paddle/fluid/inference/api/api_impl.cc:126, tests/book/).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native")


@pytest.fixture(scope="session")
def ptpu_predict_bin():
    binpath = os.path.join(NATIVE_DIR, "ptpu_predict")
    src = os.path.join(NATIVE_DIR, "ptpu_predict.cc")
    if (not os.path.exists(binpath)
            or os.path.getmtime(binpath) < os.path.getmtime(src)):
        r = subprocess.run(["sh", "build.sh", "predict"], cwd=NATIVE_DIR,
                           capture_output=True, text=True, timeout=600)
        if r.returncode != 0 or not os.path.exists(binpath):
            pytest.skip(f"cannot build ptpu_predict: {r.stderr[-800:]}")
    return binpath


def _export_model(tmp_path):
    img = layers.data(name="img", shape=[8, 8, 1])
    conv = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                         data_format="NHWC")
    pool = layers.pool2d(conv, pool_size=2, pool_type="max", pool_stride=2,
                         data_format="NHWC")
    flat = layers.reshape(pool, shape=[-1, 4 * 4 * 4])
    logits = layers.fc(flat, size=10, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["img"], [logits], executor=exe,
                               export=True, native=True)
    return d, logits


class TestNativePredict:
    def test_native_artifact_files_written(self, tmp_path):
        d, _ = _export_model(tmp_path)
        assert os.path.exists(os.path.join(d, "__exported_native__.stablehlo"))
        meta = open(os.path.join(d, "__exported_native__.meta")).read()
        assert meta.splitlines()[0].startswith("version ")
        assert "in img float32 -1 8 8 1" in meta
        assert "nout 1" in meta

    def test_cpp_logits_match_python_predictor(self, tmp_path,
                                               ptpu_predict_bin):
        d, logits = _export_model(tmp_path)
        rng = np.random.RandomState(0)
        x = rng.rand(3, 8, 8, 1).astype(np.float32)

        from paddle_tpu.inferencer import Predictor
        ref = Predictor.from_exported(d).run({"img": x})[0]

        np.save(tmp_path / "img.npy", x)
        out_dir = tmp_path / "native_out"
        out_dir.mkdir()
        r = subprocess.run(
            [ptpu_predict_bin, d, str(tmp_path / "img.npy"),
             "--out", str(out_dir)],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-1500:]
        got = np.load(out_dir / "out0.npy")
        assert got.shape == ref.shape
        np.testing.assert_allclose(got, np.asarray(ref), atol=1e-5,
                                   rtol=1e-5)

    def test_cpp_serves_other_batch_size(self, tmp_path, ptpu_predict_bin):
        """The symbolic batch dim survives into the native artifact: one
        export serves any batch."""
        d, _ = _export_model(tmp_path)
        x = np.random.RandomState(1).rand(7, 8, 8, 1).astype(np.float32)
        np.save(tmp_path / "img7.npy", x)
        r = subprocess.run(
            [ptpu_predict_bin, d, str(tmp_path / "img7.npy"),
             "--out", str(tmp_path)],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-1500:]
        got = np.load(tmp_path / "out0.npy")
        assert got.shape == (7, 10)
        np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-4)

    def test_cpp_rejects_wrong_dtype(self, tmp_path, ptpu_predict_bin):
        d, _ = _export_model(tmp_path)
        x = np.zeros((2, 8, 8, 1), np.int32)
        np.save(tmp_path / "bad.npy", x)
        r = subprocess.run(
            [ptpu_predict_bin, d, str(tmp_path / "bad.npy")],
            capture_output=True, text=True, timeout=300)
        assert r.returncode != 0
        assert "dtype" in r.stderr


class TestNativeGenerate:
    def test_cpp_runs_exported_beam_generation(self, tmp_path,
                                               ptpu_predict_bin):
        """The KV-cache beam-search decode graph exports like any other
        program (control-flow sub-blocks and all) and runs from the pure
        C++ entry: compiled GENERATION served with no Python in the
        process."""
        from paddle_tpu.core import unique_name
        from paddle_tpu.models import transformer

        with unique_name.guard():
            seqs, scores = transformer.transformer_lm_generate(
                vocab=50, max_gen=6, d_model=32, d_inner=64, num_heads=4,
                num_layers=2, bos_id=1, beam_size=2)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        prompt = np.full((3, 1), 1, "int64")
        ref = np.asarray(exe.run(feed={"prompt": prompt},
                                 fetch_list=[seqs])[0])

        d = str(tmp_path / "genmodel")
        pt.io.save_inference_model(d, ["prompt"], [seqs], executor=exe,
                                   export=True, native=True)
        # jax canonicalizes int64 to int32 (x64 off), so the artifact's
        # input signature — which the C++ entry enforces strictly — is i4
        np.save(tmp_path / "prompt.npy", prompt.astype(np.int32))
        r = subprocess.run(
            [ptpu_predict_bin, d, str(tmp_path / "prompt.npy"),
             "--out", str(tmp_path)],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-1500:]
        got = np.load(tmp_path / "out0.npy")
        assert got.shape == ref.shape
        np.testing.assert_array_equal(got, ref)


class TestNativeGenerateNMT:
    def test_cpp_runs_encoder_decoder_generation(self, tmp_path,
                                                 ptpu_predict_bin):
        """The encoder-decoder generator exports with BOTH its feeds —
        the src tokens and the int32 @SEQLEN companion — and the C++
        entry reproduces the Python beam decode exactly."""
        from paddle_tpu.core import unique_name
        from paddle_tpu.models import transformer

        with unique_name.guard():
            seqs, scores = transformer.transformer_generate(
                src_vocab=40, tgt_vocab=40, max_src_len=6, max_gen=5,
                d_model=32, d_inner=64, num_heads=4, num_layers=2,
                bos_id=0, eos_id=-1, beam_size=2)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        rng = np.random.RandomState(4)
        src = rng.randint(1, 40, (2, 6)).astype("int64")
        lens = np.full((2,), 6, "int32")
        ref = np.asarray(exe.run(
            feed={"src": src, "src@SEQLEN": lens}, fetch_list=[seqs])[0])

        d = str(tmp_path / "nmtgen")
        pt.io.save_inference_model(d, ["src", "src@SEQLEN"], [seqs],
                                   executor=exe, export=True, native=True)
        np.save(tmp_path / "src.npy", src.astype(np.int32))
        np.save(tmp_path / "lens.npy", lens)
        r = subprocess.run(
            [ptpu_predict_bin, d, str(tmp_path / "src.npy"),
             str(tmp_path / "lens.npy"), "--out", str(tmp_path)],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-1500:]
        got = np.load(tmp_path / "out0.npy")
        np.testing.assert_array_equal(got, ref)


@pytest.fixture()
def cpp_server(tmp_path, ptpu_predict_bin):
    """A ptpu_predict --serve process over a freshly exported model; yields
    (host, port, reference_predictor_output_fn)."""
    d, _ = _export_model(tmp_path)
    proc = subprocess.Popen([ptpu_predict_bin, d, "--serve", "0"],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    try:
        line = proc.stdout.readline()
        if not line.startswith("LISTENING "):
            # kill BEFORE reading stderr: .read() on a live process blocks
            # until EOF and would wedge the whole test session
            proc.kill()
            proc.wait(timeout=30)
            pytest.fail(f"server failed to start: {line!r}\n"
                        f"{proc.stderr.read()[-800:]}")
        port = int(line.split()[1])
        yield d, "127.0.0.1", port
    finally:
        proc.kill()
        proc.wait(timeout=30)


class TestNativeServe:
    """Server mode of the C++ entry: the same TCP protocol as
    paddle_tpu.serving.PredictorServer, served from a pure-C++ process with
    a private TFE context per connection (≙ reference api_impl.cc:126
    long-lived NativePaddlePredictor, :170 Clone-per-thread)."""

    def test_served_logits_match_python(self, cpp_server):
        d, host, port = cpp_server
        from paddle_tpu.inferencer import Predictor
        from paddle_tpu.serving import PredictorClient

        x = np.random.RandomState(0).rand(3, 8, 8, 1).astype(np.float32)
        ref = np.asarray(Predictor.from_exported(d).run({"img": x})[0])
        with PredictorClient(host, port) as c:
            got = c.infer({"img": x})[0]
        np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)

    def test_pipelined_and_concurrent_connections(self, cpp_server):
        d, host, port = cpp_server
        from paddle_tpu.serving import PredictorClient

        rng = np.random.RandomState(1)
        xs = [rng.rand(2, 8, 8, 1).astype(np.float32) for _ in range(6)]
        with PredictorClient(host, port) as c1, \
                PredictorClient(host, port) as c2:
            # pipeline 6 requests on c1 before reading any response; c2
            # interleaves blocking RPCs on its own connection (own context)
            for x in xs:
                c1.send({"img": x})
            other = c2.infer({"img": xs[0]})[0]
            outs = [c1.recv()[0] for _ in xs]
        # responses in request order (softmax rows sum to 1, batch matches)
        for x, o in zip(xs, outs):
            assert o.shape == (2, 10)
            np.testing.assert_allclose(o.sum(axis=1), 1.0, rtol=1e-4)
        np.testing.assert_allclose(other, outs[0], atol=1e-6)

    def test_per_request_error_keeps_connection(self, cpp_server):
        d, host, port = cpp_server
        from paddle_tpu.serving import PredictorClient

        x = np.random.RandomState(2).rand(2, 8, 8, 1).astype(np.float32)
        with PredictorClient(host, port) as c:
            with pytest.raises(RuntimeError, match="dtype"):
                c.infer({"img": x.astype(np.float64)})
            with pytest.raises(RuntimeError, match="missing feed"):
                c.infer({"wrong_name": x})
            out = c.infer({"img": x})[0]  # connection survived both errors
            assert out.shape == (2, 10)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-x", "-q"]))
