"""Speculative decoding through the serving stack (ISSUE r22 tentpole).

Covers the speculative contract end to end:
- greedy token identity: a speculative engine is token-identical to its
  target-only twin on shared weights, across {slot, paged} engines and
  {f32, int8, int4} draft precisions (the acceptance rule — drafted
  token == target argmax, mismatch replaced by the target's own output —
  makes this structural, not statistical);
- rejection sampling preserves the target distribution at a fixed seed
  (Leviathan et al.'s lemma, checked empirically against an adversarial
  draft distribution);
- paged rollback keeps the pool honest: used + free == n_blocks - 1 and
  refcounts reconcile after EVERY round, with zero leaked blocks across
  100 evict/reuse cycles;
- the verify window forward is bit-identical to sequential plain ticks
  (the fused G-wide decode-attention chain vs γ+1 single-position
  ticks), for both f32 and int8 KV pools — paged_cache_write_quant's op
  coverage;
- draft weights land in the `params_draft` census category and the
  measured bytes reconcile against a hand sum of the resident payloads;
- sub-phase accounting: spec_draft/spec_verify ride
  `phases(subphases=True)` without disturbing the 4-phase partition.
"""

import numpy as np
import pytest

from paddle_tpu.framework.scope import Scope
from paddle_tpu.serving import (ContinuousBatchingEngine, PagedKVEngine,
                                SpecConfig, rejection_sample)

pytestmark = pytest.mark.quick

_DIMS = dict(vocab=80, max_len=32, d_model=32, d_inner=64, num_heads=4,
             num_layers=2)


def _drive(eng, n_requests=5, max_new=10, seed=0):
    rng = np.random.RandomState(seed)
    reqs = []
    for _ in range(n_requests):
        p = rng.randint(1, _DIMS["vocab"], size=rng.randint(2, 8)).tolist()
        reqs.append(eng.submit(p, max_new=max_new))
    eng.run_until_idle(max_ticks=4000)
    return [r.tokens for r in reqs]


# ---------------------------------------------------------------------------
# greedy token identity: {slot, paged} x {f32, int8, int4}
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("draft", ["f32", "int8", "int4"])
def test_greedy_identity_slot(draft):
    scope = Scope()
    base = ContinuousBatchingEngine(n_slots=3, scope=scope, **_DIMS)
    want = _drive(base)
    spec = ContinuousBatchingEngine(
        n_slots=3, scope=scope,
        speculative=SpecConfig(gamma=4, draft=draft), **_DIMS)
    got = _drive(spec)
    assert got == want
    s = spec.spec.stats()
    assert s["rounds"] > 0 and s["draft_proposed"] > 0
    # every round advances every live slot at least one position but
    # runs ONE target forward: strictly fewer target forwards than
    # emitted tokens + prefill positions
    assert spec.target_forwards < base.target_forwards
    assert spec.tokens_out / spec.target_forwards > 1.0


@pytest.mark.parametrize("draft", ["f32", "int8", "int4"])
def test_greedy_identity_paged(draft):
    scope = Scope()
    base = PagedKVEngine(n_slots=3, scope=scope, block_size=8, **_DIMS)
    want = _drive(base)
    spec = PagedKVEngine(
        n_slots=3, scope=scope, block_size=8,
        speculative=SpecConfig(gamma=4, draft=draft), **_DIMS)
    got = _drive(spec)
    assert got == want
    spec.pager.pool.check()
    pool = spec.pager.pool
    assert pool.n_used + pool.n_free == pool.n_blocks - 1


def test_greedy_identity_paged_kv_quant():
    """int8 KV pools under speculation: identical to the plain engine
    over the SAME int8 pools (kv_quant changes the target's numerics, so
    the twin must be kv_quant too)."""
    scope = Scope()
    base = PagedKVEngine(n_slots=3, scope=scope, block_size=8,
                         kv_quant=True, **_DIMS)
    want = _drive(base)
    spec = PagedKVEngine(
        n_slots=3, scope=scope, block_size=8, kv_quant=True,
        speculative=SpecConfig(gamma=3, draft="int8"), **_DIMS)
    got = _drive(spec)
    assert got == want
    spec.pager.pool.check()


def test_greedy_identity_paged_quant_target():
    """Weight-quantized target (r21) under speculation: the verify
    program must ride the SAME resident @qparam/@qscale payloads as the
    main tick (quantize pass twin-reuse), so spec decode is
    token-identical to the plain quant engine. Regression: the verify
    startup must NOT reinstall random f32 weights over names the target
    quantize pass erased (that re-quantized garbage onto the shared
    payloads)."""
    seed_scope = Scope()
    seed = PagedKVEngine(n_slots=3, scope=seed_scope, block_size=8,
                         **_DIMS)
    snap = {n: np.asarray(seed_scope.get(n)).copy()
            for n, v in seed._program.current_block().vars.items()
            if v.persistable and getattr(v, "trainable", False)}

    def fresh():
        s = Scope()
        for n, a in snap.items():
            s.set_var(n, a.copy())
        return s

    base = PagedKVEngine(n_slots=3, scope=fresh(), block_size=8,
                         quant="int8", **_DIMS)
    want = _drive(base)
    spec = PagedKVEngine(
        n_slots=3, scope=fresh(), block_size=8, quant="int8",
        speculative=SpecConfig(gamma=4, draft="int8"), **_DIMS)
    got = _drive(spec)
    assert got == want
    # int8 draft over the int8 target's own payload numerics agrees far
    # more often than chance — a corrupted verify scores near zero
    assert spec.spec.stats()["acceptance_rate"] > 0.2
    spec.pager.pool.check()


def test_kv_quant_pool_expansion():
    """At the same byte budget the int8 pool admits MORE blocks than the
    f32 default, and the engine reports the freed bytes."""
    scope = Scope()
    f32 = PagedKVEngine(n_slots=2, scope=scope, block_size=8, **_DIMS)
    q = PagedKVEngine(n_slots=2, scope=scope, block_size=8,
                      kv_quant=True, **_DIMS)
    assert q.n_blocks > f32.n_blocks
    assert q.kv_quant_freed_bytes > 0
    assert q.stats()["kv_quant"]["enabled"] is True


# ---------------------------------------------------------------------------
# rejection sampling preserves the target distribution
# ---------------------------------------------------------------------------


def test_rejection_sampling_preserves_target_distribution():
    rng = np.random.RandomState(1234)
    p = np.array([0.5, 0.25, 0.125, 0.1, 0.025])
    q = np.array([0.05, 0.05, 0.4, 0.4, 0.1])   # adversarial draft
    n = 40_000
    counts = np.zeros(5)
    accepted = 0
    for _ in range(n):
        d = rng.choice(5, p=q)
        tok, acc = rejection_sample(p, q, d, rng)
        counts[tok] += 1
        accepted += acc
    emp = counts / n
    # the emitted marginal is exactly p: 3-sigma multinomial bands
    sigma = np.sqrt(p * (1 - p) / n)
    assert np.all(np.abs(emp - p) < 3.5 * sigma + 1e-3), (emp, p)
    # and the acceptance rate is sum(min(p, q)) in expectation
    want_acc = float(np.minimum(p, q).sum())
    assert abs(accepted / n - want_acc) < 0.02


def test_rejection_sampling_identical_distributions_always_accept():
    rng = np.random.RandomState(7)
    p = np.array([0.25, 0.25, 0.25, 0.25])
    for _ in range(200):
        d = rng.choice(4, p=p)
        tok, acc = rejection_sample(p, p, d, rng)
        assert acc and tok == d


def test_sampling_mode_runs_and_completes():
    scope = Scope()
    eng = ContinuousBatchingEngine(
        n_slots=2, scope=scope,
        speculative=SpecConfig(gamma=3, draft="int8", sampling=True,
                               seed=11), **_DIMS)
    toks = _drive(eng, n_requests=4, max_new=8)
    assert all(len(t) == 8 for t in toks)
    assert eng.spec.stats()["rounds"] > 0


# ---------------------------------------------------------------------------
# pool-invariant rollback: zero leaks across 100 evict/reuse cycles
# ---------------------------------------------------------------------------


def test_rollback_pool_invariants_100_cycles(monkeypatch):
    monkeypatch.setenv("PTPU_SPEC_POOL_CHECK", "1")  # check every round
    scope = Scope()
    # int4 draft => real mismatches => real rollbacks; a small pool =>
    # prefix-cache eviction pressure every cycle
    eng = PagedKVEngine(
        n_slots=2, scope=scope, block_size=4, n_blocks=11,
        speculative=SpecConfig(gamma=4, draft="int4"), **_DIMS)
    pool = eng.pager.pool
    rng = np.random.RandomState(3)
    for cycle in range(100):
        p = rng.randint(1, _DIMS["vocab"],
                        size=rng.randint(2, 6)).tolist()
        eng.submit(p, max_new=6)
        if cycle % 3 == 0:
            eng.submit(p, max_new=4)       # prefix-sharing candidate
        eng.run_until_idle(max_ticks=2000)
        pool.check()                       # refcount reconciliation
        assert pool.n_used + pool.n_free == pool.n_blocks - 1
    assert eng.n_active == 0 and eng.n_pending == 0
    # rollbacks actually happened — the invariant held under fire, not
    # in the absence of the mechanism
    assert eng.spec.stats()["rolled_back_blocks"] > 0
    assert eng.pager.stats()["rolled_back_blocks"] \
        == eng.spec.stats()["rolled_back_blocks"]


# ---------------------------------------------------------------------------
# census + observability
# ---------------------------------------------------------------------------


def test_draft_params_census_category():
    from paddle_tpu.framework.costs import state_category

    class _V:
        trainable = True
        persistable = True

    assert state_category(_V(), "draft_l0_attn_q.w_0") == "params_draft"
    assert state_category(_V(), "draft_tok_emb@qparam") == "params_draft"
    assert state_category(_V(), "draft_tok_emb@qscale") == "params_draft"
    assert state_category(_V(), "l0_attn_q.w_0") == "params"


def test_draft_param_bytes_reconcile():
    """spec.draft_param_bytes (the census category) equals a hand sum of
    the resident draft payload arrays."""
    from paddle_tpu.observability.memory import per_device_bytes
    scope = Scope()
    eng = ContinuousBatchingEngine(
        n_slots=2, scope=scope,
        speculative=SpecConfig(gamma=2, draft="int8"), **_DIMS)
    # everything under the draft_ namespace is draft weight state: the
    # quantized payload+scale pairs plus the params the quantize pass
    # leaves f32 (biases, layer norms) — the draft's caches live under
    # the engine's cache prefix, not draft_
    want = sum(int(per_device_bytes(scope.get(name)))
               for name in scope.local_var_names()
               if name.startswith("draft_"))
    got = eng.spec.draft_param_bytes()
    assert got == want and got > 0
    # and the quantized payloads are a real part of it
    assert any(name.startswith("draft_") and "@qparam" in name
               for name in scope.local_var_names())
    assert eng.stats()["speculative"]["draft_param_bytes"] == got


def test_spec_spans_and_gauges():
    from paddle_tpu.core import flags
    from paddle_tpu.observability import tracing
    scope = Scope()
    eng = ContinuousBatchingEngine(
        n_slots=2, scope=scope,
        speculative=SpecConfig(gamma=2, draft="int8"), **_DIMS)
    old = flags.get_flag("trace")
    flags.set_flag("trace", True)
    try:
        m = tracing.mark()
        _drive(eng, n_requests=3, max_new=6)
        kinds = {s.kind for s in tracing.spans_since(m)}
    finally:
        flags.set_flag("trace", old)
    assert "speculate" in kinds and "verify" in kinds
    text = eng.metrics_registry.expose()
    for name in ("ptpu_engine_spec_acceptance_rate",
                 "ptpu_engine_spec_draft_overhead",
                 "ptpu_engine_spec_tokens_per_target_forward",
                 "ptpu_engine_spec_rolled_back_blocks"):
        assert name in text, name
    s = eng.spec.stats()
    assert 0.0 <= s["acceptance_rate"] <= 1.0
    assert 0.0 < s["draft_overhead"] < 1.0


def test_subphases_ride_phases():
    scope = Scope()
    eng = ContinuousBatchingEngine(
        n_slots=2, scope=scope,
        speculative=SpecConfig(gamma=2, draft="int8"), **_DIMS)
    req = eng.submit([3, 4, 5], max_new=6)
    eng.run_until_idle(max_ticks=2000)
    ph = req.phases()
    assert set(ph) == {"queue_wait", "prefill", "decode", "transport"}
    sub = req.phases(subphases=True)
    assert sub["spec_draft"] > 0 and sub["spec_verify"] > 0
    # sub-phases nest inside the prefill+decode window
    assert sub["spec_draft"] + sub["spec_verify"] \
        <= (ph["prefill"] + ph["decode"]) * 1.05


def test_costs_speculative_section():
    from paddle_tpu.framework.costs import speculative_expectation
    s = speculative_expectation(gamma=4, acceptance=0.7,
                                draft_layers=1, num_layers=2,
                                draft_bits=4)
    # truncated geometric: (1 - 0.7^5) / 0.3
    assert abs(s["expected_tokens_per_round"]
               - (1 - 0.7 ** 5) / 0.3) < 1e-12
    assert s["tokens_per_target_forward"] == s["expected_tokens_per_round"]
    assert abs(s["draft_cost_ratio"] - 0.5 * (4 / 32)) < 1e-12
    assert s["speedup_vs_plain_decode"] > 1.0
    # measured-acceptance hook: a callable is evaluated
    s2 = speculative_expectation(gamma=4, acceptance=lambda: 1.0)
    assert s2["expected_tokens_per_round"] == 5.0


def test_spec_config_validation():
    from paddle_tpu.core.enforce import InvalidArgumentError
    with pytest.raises(InvalidArgumentError):
        SpecConfig(gamma=0)
    with pytest.raises(InvalidArgumentError):
        SpecConfig(draft="fp8")
    with pytest.raises(InvalidArgumentError):
        ContinuousBatchingEngine(
            n_slots=2, speculative=SpecConfig(draft_layers=9), **_DIMS)
