"""Real models through ParallelExecutor on the 8-device CPU mesh,
compared against single-device trajectories.

≙ reference test_parallel_executor_mnist.py / test_parallel_executor_
seresnext.py / test_parallel_executor_transformer.py (SURVEY.md §4
"Multi-device tests": run real models via PE with 1..N devices, compare
losses vs single-device run).
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import BuildStrategy, ParallelExecutor, \
    ReduceStrategy


def _snapshot_params(scope):
    return {n: np.asarray(scope.get(n)).copy()
            for n in scope.local_var_names()}


def _restore_params(scope, snap):
    for n, v in snap.items():
        scope.set_var(n, v.copy())


def _compare_pe_vs_single(build_model, feed, rng, steps=5, rtol=2e-3,
                          build_strategy=None, lr=0.05):
    """Train the same model from identical init: single-device Executor vs
    8-device PE; loss trajectories must match (data-parallel SGD over the
    same global batch is mathematically identical)."""
    loss = build_model()
    pt.optimizer.SGDOptimizer(learning_rate=lr).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    init = _snapshot_params(scope)

    single = [float(exe.run(feed=feed, fetch_list=[loss])[0])
              for _ in range(steps)]

    _restore_params(scope, init)
    pe = ParallelExecutor(loss_name=loss.name,
                          build_strategy=build_strategy or BuildStrategy())
    parallel = [float(pe.run(feed=feed, fetch_list=[loss])[0])
                for _ in range(steps)]

    np.testing.assert_allclose(parallel, single, rtol=rtol, atol=1e-4)
    assert parallel[-1] < parallel[0]
    return single, parallel


class TestParallelExecutorMnist:
    def _model(self):
        img = layers.data("img", shape=[784])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, size=64, act="relu")
        logits = layers.fc(h, size=10)
        return layers.mean(
            layers.softmax_with_cross_entropy(logits, label))

    def test_pe_matches_single_device(self, rng):
        feed = {"img": rng.rand(32, 784).astype("float32"),
                "label": rng.randint(0, 10, (32, 1)).astype("int64")}
        _compare_pe_vs_single(self._model, feed, rng)

    def test_pe_zero1_matches_single_device(self, rng):
        feed = {"img": rng.rand(32, 784).astype("float32"),
                "label": rng.randint(0, 10, (32, 1)).astype("int64")}
        _compare_pe_vs_single(
            self._model, feed, rng,
            build_strategy=BuildStrategy(
                reduce_strategy=ReduceStrategy.Reduce))


class TestParallelExecutorConv:
    def test_cnn_pe_matches_single_device(self, rng):
        """Conv/BN path through PE (≙ test_parallel_executor_mnist conv
        model). BN uses per-shard batch stats under dp — trajectories match
        while stats stay consistent because every shard sees the same
        per-device distribution here."""
        def model():
            img = layers.data("img", shape=[1, 16, 16])
            label = layers.data("label", shape=[1], dtype="int64")
            c = layers.conv2d(img, num_filters=8, filter_size=3, padding=1,
                              act="relu")
            p = layers.pool2d(c, pool_size=2, pool_stride=2)
            logits = layers.fc(p, size=10)
            return layers.mean(
                layers.softmax_with_cross_entropy(logits, label))

        feed = {"img": rng.rand(16, 1, 16, 16).astype("float32"),
                "label": rng.randint(0, 10, (16, 1)).astype("int64")}
        _compare_pe_vs_single(model, feed, rng, rtol=5e-3, lr=0.005)


class TestParallelExecutorSeResNeXt:
    def test_seresnext_trains_on_pe(self, rng):
        """≙ test_parallel_executor_seresnext: the grouped-conv + SE model
        trains through the 8-device PE."""
        from paddle_tpu.models import se_resnext

        img = layers.data("img", shape=[32, 32, 3])
        label = layers.data("label", shape=[1], dtype="int64")
        loss, acc, _ = se_resnext.se_resnext_imagenet(
            img=img, label=label, depth=50, class_num=10, cardinality=8,
            reduction_ratio=4)
        pt.optimizer.MomentumOptimizer(learning_rate=0.01, momentum=0.9) \
            .minimize(loss)
        pt.Executor().run(pt.default_startup_program())
        pe = ParallelExecutor(loss_name=loss.name)
        feed = {"img": rng.rand(8, 32, 32, 3).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}
        losses = [float(pe.run(feed=feed, fetch_list=[loss])[0])
                  for _ in range(3)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestParallelExecutorTransformer:
    def test_transformer_trains_on_pe(self, rng):
        """≙ test_parallel_executor_transformer (tiny config)."""
        from paddle_tpu.models import transformer

        loss, _ = transformer.transformer_lm(
            vocab=64, max_len=16, d_model=32, d_inner=64, num_heads=4,
            num_layers=1, dropout=0.0)
        pt.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)
        pt.Executor().run(pt.default_startup_program())
        pe = ParallelExecutor(loss_name=loss.name)
        toks = rng.randint(0, 64, (16, 16)).astype("int64")
        sl = np.full((16,), 16, dtype="int32")
        tg = rng.randint(0, 64, (16, 16)).astype("int64")
        feed = {"tokens": toks, "tokens@SEQLEN": sl, "targets": tg}
        losses = [float(pe.run(feed=feed, fetch_list=[loss])[0])
                  for _ in range(4)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
