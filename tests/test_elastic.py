"""Elastic fault-tolerant training (parallel/elastic.py).

Atomic two-phase-commit snapshots, async writer, deterministic resume,
dp-world resize with error-feedback re-mapping, fault injection, the
Supervisor retry/backoff loop, and the crash-mid-save atomicity property
(subprocess SIGKILL at randomized byte offsets of the staged payload).
docs/fault_tolerance.md documents the protocol these tests pin.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.enforce import EnforceError
from paddle_tpu.parallel import ParallelExecutor, elastic
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECOVERY_SMOKE = os.path.join(REPO, "tools", "recovery_smoke.py")


def _build_model():
    x = layers.data("x", shape=[16])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=4), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return loss


def _feeds(n, batch=8):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(batch, 16).astype("float32"),
             "label": rng.randint(0, 4, (batch, 1)).astype("int64")}
            for _ in range(n)]


def _strategy(quant=""):
    bst = BuildStrategy()
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    if quant:
        bst.quant_comm = quant
        bst.comm_error_feedback = True
    return bst


def _fresh_world(dp, quant=""):
    """(loss, pexe) over a fresh program/scope on a dp-device mesh."""
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = _build_model()
    pexe = ParallelExecutor(loss_name=loss.name,
                            build_strategy=_strategy(quant),
                            mesh=DeviceMesh(jax.devices()[:dp],
                                            {"dp": dp}))
    pt.Executor().run(pt.default_startup_program())
    return loss, pexe


def _host_snapshot_args(seed=7):
    rng = np.random.RandomState(seed)
    return {f"w_{k}": rng.randn(16, 4).astype("f4") for k in range(3)}


def _save_host_arrays(root, arrays, step=0, **kw):
    """Mesh-free save: a program declaring the vars + a scope holding
    them is all save_train_state needs."""
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework.scope import Scope
    prog, startup = Program(), Program()
    scope = Scope()
    with program_guard(prog, startup):
        for name, val in arrays.items():
            prog.global_block().create_var(name=name,
                                           shape=list(val.shape),
                                           dtype="float32",
                                           persistable=True)
            scope.set_var(name, val)
    out = elastic.save_train_state(root, program=prog, scope=scope,
                                   step=step, **kw)
    return out, prog, scope


def _restore_host_arrays(path, arrays_template, **kw):
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework.scope import Scope
    prog, startup = Program(), Program()
    scope = Scope()
    with program_guard(prog, startup):
        for name, val in arrays_template.items():
            prog.global_block().create_var(name=name,
                                           shape=list(val.shape),
                                           dtype="float32",
                                           persistable=True)
    meta = elastic.restore_train_state(path, program=prog, scope=scope,
                                       **kw)
    return meta, {n: np.asarray(scope.get(n)) for n in arrays_template
                  if scope.has_var(n)}


# ---------------------------------------------------------------------------
# commit protocol
# ---------------------------------------------------------------------------

class TestCommitProtocol:
    def test_commit_marker_written_last_and_validates(self, tmp_path):
        arrays = _host_snapshot_args()
        path, _, _ = _save_host_arrays(str(tmp_path), arrays, step=5)
        assert os.path.basename(path).startswith(elastic.SNAPSHOT_PREFIX)
        assert elastic.is_committed(path)
        elastic.validate_snapshot(path)           # no raise
        marker = json.load(open(os.path.join(path, elastic.COMMIT_MARKER)))
        # the marker records every payload file at its exact size AND
        # content digest (format 2), plus the commit timestamp and world
        # config the tie-break/skip rules read
        assert marker["format"] == elastic.COMMIT_FORMAT
        assert marker["commit_ts"] > 0
        assert marker["world"]["world_size"] == 1
        for name, entry in marker["files"].items():
            fpath = os.path.join(path, name)
            assert os.path.getsize(fpath) == entry["size"]
            assert elastic.file_digest(fpath) == entry["crc32"]
        meta = elastic.read_meta(path)
        assert meta["step"] == 5 and meta["format"] == 2

    def test_uncommitted_dir_skipped_and_rejected(self, tmp_path):
        arrays = _host_snapshot_args()
        p0, _, _ = _save_host_arrays(str(tmp_path), arrays, step=1)
        p1, _, _ = _save_host_arrays(str(tmp_path), arrays, step=2)
        os.unlink(os.path.join(p1, elastic.COMMIT_MARKER))
        # latest committed is the OLDER dir: uncommitted ones are skipped
        assert elastic.latest_snapshot(str(tmp_path)) == p0
        meta, _ = _restore_host_arrays(str(tmp_path), arrays)
        assert meta["step"] == 1
        # restoring the uncommitted dir EXPLICITLY raises a clear error
        with pytest.raises(EnforceError) as ei:
            elastic.validate_snapshot(p1)
        assert elastic.COMMIT_MARKER in str(ei.value)
        assert p1 in str(ei.value)

    def test_truncated_shard_rejected_naming_file(self, tmp_path):
        arrays = _host_snapshot_args()
        path, _, _ = _save_host_arrays(str(tmp_path), arrays)
        shard = os.path.join(path, "shard-0.pts")
        with open(shard, "r+b") as f:
            f.truncate(os.path.getsize(shard) // 2)
        with pytest.raises(EnforceError) as ei:
            elastic.validate_snapshot(path)
        assert "shard-0.pts" in str(ei.value)
        assert "truncated" in str(ei.value)

    def test_missing_manifest_rejected(self, tmp_path):
        arrays = _host_snapshot_args()
        path, _, _ = _save_host_arrays(str(tmp_path), arrays)
        os.unlink(os.path.join(path, "manifest-0.json"))
        with pytest.raises(EnforceError) as ei:
            elastic.validate_snapshot(path)
        assert "manifest-0.json" in str(ei.value)

    def test_no_committed_snapshot_message(self, tmp_path):
        arrays = _host_snapshot_args()
        path, _, _ = _save_host_arrays(str(tmp_path), arrays)
        os.unlink(os.path.join(path, elastic.COMMIT_MARKER))
        with pytest.raises(EnforceError) as ei:
            _restore_host_arrays(str(tmp_path), arrays)
        assert "committed" in str(ei.value)

    def test_retention_keeps_newest_committed(self, tmp_path):
        arrays = _host_snapshot_args()
        for step in range(5):
            _save_host_arrays(str(tmp_path), arrays, step=step,
                              max_snapshots=2)
        snaps = elastic.list_snapshots(str(tmp_path))
        assert len(snaps) == 2
        assert elastic.read_meta(snaps[-1][1])["step"] == 4

    def test_strict_missing_var_and_seed_mismatch(self, tmp_path):
        arrays = _host_snapshot_args()
        _save_host_arrays(str(tmp_path), arrays)
        grown = dict(arrays)
        grown["w_new"] = np.zeros((4, 4), np.float32)
        with pytest.raises(EnforceError) as ei:
            _restore_host_arrays(str(tmp_path), grown)
        assert "w_new" in str(ei.value)
        # strict=False warm-starts the missing var instead
        meta, back = _restore_host_arrays(str(tmp_path), grown,
                                          strict=False)
        for k in arrays:
            np.testing.assert_array_equal(back[k], arrays[k])

    def test_fault_config_parse(self, monkeypatch):
        monkeypatch.setenv("PTPU_FAULT_INJECT",
                           "crash_at_step:3, slow_writer:0.5")
        cfg = elastic.fault_injection_config()
        assert cfg == {"crash_at_step": 3.0, "slow_writer": 0.5}
        monkeypatch.setenv("PTPU_FAULT_INJECT", "bogus:1")
        with pytest.raises(EnforceError):
            elastic.fault_injection_config()


# ---------------------------------------------------------------------------
# async snapshot path
# ---------------------------------------------------------------------------

class TestAsyncSnapshot:
    def test_async_copy_at_boundary_write_in_background(self, tmp_path,
                                                        monkeypatch):
        from paddle_tpu.observability import tracing
        monkeypatch.setenv("PTPU_FAULT_INJECT", "slow_writer:0.3")
        arrays = _host_snapshot_args()
        mark = tracing.mark()
        saves0 = elastic.metrics_registry().get(
            "ptpu_ckpt_saves_total").value
        handle, prog, scope = _save_host_arrays(str(tmp_path), arrays,
                                                step=3, block=False)
        assert isinstance(handle, elastic.AsyncSnapshot)
        # the d2h copy already happened: mutating live state NOW must not
        # leak into the snapshot the writer commits later
        for name in arrays:
            scope.set_var(name, np.zeros_like(arrays[name]))
        path = handle.result(timeout=30)
        assert elastic.is_committed(path)
        _, back = _restore_host_arrays(str(tmp_path), arrays)
        for k, v in arrays.items():
            np.testing.assert_array_equal(back[k], v)
        kinds = {(s.kind, s.name) for s in tracing.spans_since(mark)}
        assert ("checkpoint", "elastic/snapshot_d2h") in kinds
        assert ("checkpoint", "elastic/snapshot_write") in kinds
        assert ("checkpoint", "elastic/commit") in kinds
        reg = elastic.metrics_registry()
        assert reg.get("ptpu_ckpt_saves_total").value == saves0 + 1
        assert reg.get("ptpu_ckpt_save_bytes_total").value > 0
        assert reg.get("ptpu_ckpt_save_seconds").count >= 1

    def test_overlapping_async_saves_commit_distinct_serials(
            self, tmp_path, monkeypatch):
        """Two async saves in flight at once: serial allocation is
        locked and the staging sweep spares live writers, so BOTH
        commit — the second must not clobber or delete the first."""
        monkeypatch.setenv("PTPU_FAULT_INJECT", "slow_writer:0.2")
        arrays = _host_snapshot_args()
        h1, _, _ = _save_host_arrays(str(tmp_path), arrays, step=1,
                                     block=False)
        h2, _, _ = _save_host_arrays(
            str(tmp_path), {k: v + 1 for k, v in arrays.items()},
            step=2, block=False)
        p1, p2 = h1.result(timeout=30), h2.result(timeout=30)
        assert p1 != p2
        assert elastic.is_committed(p1) and elastic.is_committed(p2)
        steps = {elastic.read_meta(p)["step"] for _, p in
                 elastic.list_snapshots(str(tmp_path))}
        assert steps == {1, 2}

    def test_wait_for_pending_flushes(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PTPU_FAULT_INJECT", "slow_writer:0.2")
        arrays = _host_snapshot_args()
        handle, _, _ = _save_host_arrays(str(tmp_path), arrays,
                                         block=False)
        elastic.wait_for_pending(timeout=30)
        assert handle.done
        assert elastic.latest_snapshot(str(tmp_path)) is not None


# ---------------------------------------------------------------------------
# deterministic resume + elastic resize
# ---------------------------------------------------------------------------

class TestDeterministicResume:
    def test_same_world_resume_is_bitwise_exact(self, tmp_path):
        """SIGKILL-equivalent resume at the same dp: params, ZeRO-1
        accumulator shards, int8 error-feedback residuals, and the RNG
        run counter all round-trip, so the resumed fixed-seed loss
        trajectory equals the uninterrupted one EXACTLY."""
        feeds = _feeds(6)
        loss, pexe = _fresh_world(2, quant="int8")
        ref = []
        for i, f in enumerate(feeds):
            ref.append(float(pexe.run(feed=f, fetch_list=[loss])[0]))
            if i == 2:
                elastic.save_train_state(str(tmp_path), executor=pexe,
                                         step=3)
        loss, pexe2 = _fresh_world(2, quant="int8")
        meta = elastic.restore_train_state(str(tmp_path), executor=pexe2)
        assert meta["step"] == 3
        assert pexe2._run_counter == meta["run_counter"]
        got = [float(pexe2.run(feed=f, fetch_list=[loss])[0])
               for f in feeds[3:]]
        assert got == ref[3:], (got, ref[3:])

    def test_dp_resize_2_to_4_loss_parity(self, tmp_path):
        """N→M restore: fp32-wire trajectories agree to reduction-order
        ulps (the r09/r11 parity regime); placement is statically
        verified before the first step."""
        feeds = _feeds(6)
        loss, pexe = _fresh_world(2)
        ref = []
        for i, f in enumerate(feeds):
            ref.append(float(pexe.run(feed=f, fetch_list=[loss])[0]))
            if i == 2:
                elastic.save_train_state(str(tmp_path), executor=pexe,
                                         step=3)
        loss, pexe4 = _fresh_world(4)
        meta = elastic.restore_train_state(str(tmp_path), executor=pexe4)
        assert meta["world"] == {"dp": 2}
        got = [float(pexe4.run(feed=f, fetch_list=[loss])[0])
               for f in feeds[3:]]
        assert max(abs(a - b) for a, b in zip(ref[3:], got)) <= 1e-5

    def test_restored_placement_matches_policy(self, tmp_path):
        """Restored ZeRO-1 accumulators land dp-sharded, params
        replicated — verified through the executor's own policy (what
        restore_train_state enforces internally)."""
        feeds = _feeds(3)
        loss, pexe = _fresh_world(2)
        for f in feeds:
            pexe.run(feed=f, fetch_list=[loss])
        elastic.save_train_state(str(tmp_path), executor=pexe, step=3)
        loss, pexe4 = _fresh_world(4)
        elastic.restore_train_state(str(tmp_path), executor=pexe4)
        prog = pexe4.prepare_program()
        scope = pt.global_scope()
        assert elastic.verify_restored_placement(pexe4, prog, scope) == []
        # and a deliberately mis-placed var is caught
        name = next(v.name for b in prog.blocks for v in b.vars.values()
                    if getattr(v, "dp_shard_update", False))
        scope.set_var(name, jax.device_put(
            np.asarray(scope.get(name)), pexe4.mesh.replicated()))
        bad = elastic.verify_restored_placement(pexe4, prog, scope)
        assert bad and name in bad[0]

    def test_random_seed_mismatch_rejected(self, tmp_path):
        arrays = _host_snapshot_args()
        _, prog, scope = _save_host_arrays(str(tmp_path), arrays)
        from paddle_tpu.framework.program import Program, program_guard
        prog2, startup2 = Program(), Program()
        prog2.random_seed = 1234
        with program_guard(prog2, startup2):
            for name, val in arrays.items():
                prog2.global_block().create_var(
                    name=name, shape=list(val.shape), dtype="float32",
                    persistable=True)
        from paddle_tpu.framework.scope import Scope
        with pytest.raises(EnforceError) as ei:
            elastic.restore_train_state(str(tmp_path), program=prog2,
                                        scope=Scope())
        assert "random_seed" in str(ei.value)


class TestErrorFeedbackResize:
    def test_resize_rows_pad_fold_identity(self):
        rows = np.arange(12, dtype=np.float32).reshape(4, 3) + 1.0
        up = elastic._resize_replica_rows(rows, 8)
        assert up.shape == (8, 3)
        np.testing.assert_array_equal(up[:4], rows * 2.0)  # scaled M/N
        np.testing.assert_array_equal(up[4:], 0.0)
        back = elastic._resize_replica_rows(up, 4)
        np.testing.assert_array_equal(back, rows)  # exact round trip
        # shrink folds rows modulo M, preserving the effective mass:
        # (1/N)·Σ == (1/M)·Σ' exactly for power-of-two ratios
        down = elastic._resize_replica_rows(rows, 2)
        np.testing.assert_array_equal(
            down, (rows[:2] + rows[2:]) * np.float32(0.5))
        assert np.sum(down) / 2 == np.sum(rows) / 4

    def test_ef_state_n_to_m_to_n_round_trip(self, tmp_path):
        """The satellite parity bar: snapshot at dp2 (int8 + error
        feedback), restore onto dp4, snapshot again, restore back onto
        dp2 — params, optimizer accumulators AND error-feedback
        residuals come back bit-exact (pad-then-fold identity at a
        power-of-two ratio)."""
        feeds = _feeds(4)
        loss, pexe = _fresh_world(2, quant="int8")
        for f in feeds:
            pexe.run(feed=f, fetch_list=[loss])
        root_a = str(tmp_path / "a")
        root_b = str(tmp_path / "b")
        elastic.save_train_state(root_a, executor=pexe, step=4)
        from paddle_tpu.sharded_checkpoint import ShardedCheckpoint
        snap_a = elastic.latest_snapshot(root_a)
        orig = {n: ShardedCheckpoint(snap_a).read(n)
                for n in ShardedCheckpoint(snap_a).names()}
        ef_names_2 = [n for n in orig if n.startswith("dp_comm_err")]
        assert ef_names_2, "test premise: error-feedback state exists"
        assert any(np.abs(orig[n]).max() > 0 for n in ef_names_2), \
            "test premise: residuals are non-trivial"

        # dp2 -> dp4: restore, snapshot WITHOUT stepping
        loss, pexe4 = _fresh_world(4, quant="int8")
        elastic.restore_train_state(root_a, executor=pexe4)
        elastic.save_train_state(root_b, executor=pexe4, step=4)
        meta_b = elastic.read_meta(root_b)
        assert meta_b["ef_layout"]["dp"] == 4
        # EF var names are layout-digested: the dp4 snapshot holds
        # DIFFERENT vars than the dp2 one
        snap_b = elastic.latest_snapshot(root_b)
        ef_names_4 = [n for n in ShardedCheckpoint(snap_b).names()
                      if n.startswith("dp_comm_err")]
        assert ef_names_4 and set(ef_names_4) != set(ef_names_2)

        # dp4 -> dp2: every piece of state returns bit-exact
        loss, pexe2 = _fresh_world(2, quant="int8")
        elastic.restore_train_state(root_b, executor=pexe2)
        scope = pt.global_scope()
        for name, want in orig.items():
            got = np.asarray(scope.get(name))
            np.testing.assert_array_equal(
                got, want, err_msg=f"{name} did not round-trip")

    def test_quant_config_mismatch_rejected(self, tmp_path):
        feeds = _feeds(2)
        loss, pexe = _fresh_world(2, quant="int8")
        for f in feeds:
            pexe.run(feed=f, fetch_list=[loss])
        elastic.save_train_state(str(tmp_path), executor=pexe, step=2)
        loss, pexe_b = _fresh_world(2, quant="bf16")
        with pytest.raises(EnforceError) as ei:
            elastic.restore_train_state(str(tmp_path), executor=pexe_b)
        assert "quant" in str(ei.value)


# ---------------------------------------------------------------------------
# COMMIT integrity: digests, deterministic selection
# ---------------------------------------------------------------------------

class TestDigestIntegrity:
    def test_bit_flip_rejected_naming_file(self, tmp_path):
        """The satellite bar: a SILENT bit-flip (size unchanged) inside
        a shard container is caught by the content digest in the COMMIT
        record — size-only validation (digests=False) is blind to it."""
        arrays = _host_snapshot_args()
        path, _, _ = _save_host_arrays(str(tmp_path), arrays)
        shard = os.path.join(path, "shard-0.pts")
        with open(shard, "r+b") as f:
            f.seek(os.path.getsize(shard) // 2)
            byte = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([byte[0] ^ 0xFF]))
        elastic.validate_snapshot(path, digests=False)   # blind
        fails0 = elastic.metrics_registry().get(
            "ptpu_ckpt_digest_failures_total").value
        with pytest.raises(EnforceError) as ei:
            elastic.validate_snapshot(path)
        assert "shard-0.pts" in str(ei.value)
        assert "digest" in str(ei.value)
        assert elastic.metrics_registry().get(
            "ptpu_ckpt_digest_failures_total").value == fails0 + 1
        # restore re-validates: the corrupted snapshot cannot restore
        with pytest.raises(EnforceError) as ei:
            _restore_host_arrays(path, arrays)
        assert "digest" in str(ei.value)

    def test_meta_corruption_caught_too(self, tmp_path):
        arrays = _host_snapshot_args()
        path, _, _ = _save_host_arrays(str(tmp_path), arrays)
        meta = os.path.join(path, elastic.META_FILE)
        size = os.path.getsize(meta)
        with open(meta, "r+b") as f:
            f.write(b"X" * min(4, size))
        with pytest.raises(EnforceError) as ei:
            elastic.validate_snapshot(path)
        assert elastic.META_FILE in str(ei.value)


class TestLatestSelection:
    def test_equal_step_tie_breaks_by_commit_ts(self, tmp_path):
        """Two committed snapshots at the SAME step (concurrent/stale
        writers racing on one root): the one with the LATER commit
        timestamp wins regardless of serial order."""
        arrays = _host_snapshot_args()
        p0, _, _ = _save_host_arrays(str(tmp_path), arrays, step=7)
        p1, _, _ = _save_host_arrays(str(tmp_path), arrays, step=7)
        assert elastic.latest_snapshot(str(tmp_path)) == p1
        # forge a newer commit_ts on the OLDER serial: it must now win
        marker = os.path.join(p0, elastic.COMMIT_MARKER)
        record = json.load(open(marker))
        record["commit_ts"] = record["commit_ts"] + 1e6
        with open(marker, "w") as f:
            json.dump(record, f)
        assert elastic.latest_snapshot(str(tmp_path)) == p0

    def test_higher_step_wins_over_higher_serial(self, tmp_path):
        """A stale writer minting a LATER serial at an EARLIER step must
        not shadow newer training state."""
        arrays = _host_snapshot_args()
        p0, _, _ = _save_host_arrays(str(tmp_path), arrays, step=9)
        p1, _, _ = _save_host_arrays(str(tmp_path), arrays, step=4)
        assert int(os.path.basename(p1)[len(elastic.SNAPSHOT_PREFIX):]) \
            > int(os.path.basename(p0)[len(elastic.SNAPSHOT_PREFIX):])
        assert elastic.latest_snapshot(str(tmp_path)) == p0

    def test_retention_ranks_like_selection(self, tmp_path):
        """Retention prunes by the SAME (step, commit_ts, serial) key
        selection uses: a stale writer minting LATER serials at EARLIER
        steps must never push the newest-step snapshot out of the
        retention window."""
        arrays = _host_snapshot_args()
        p_new, _, _ = _save_host_arrays(str(tmp_path), arrays, step=100)
        for step in (50, 51, 52):    # stale writer: later serials
            _save_host_arrays(str(tmp_path), arrays, step=step,
                              max_snapshots=2)
        assert os.path.isdir(p_new), \
            "retention evicted the newest-step snapshot"
        assert elastic.latest_snapshot(str(tmp_path)) == p_new
        kept = {elastic.read_meta(p)["step"]
                for _, p in elastic.list_snapshots(str(tmp_path))}
        assert kept == {52, 100}

    def test_newer_world_config_skipped_with_warn_once(self, tmp_path):
        """A COMMIT record written by a NEWER protocol/world config is
        skipped (never half-understood) and counted/warned exactly once
        per directory."""
        arrays = _host_snapshot_args()
        p0, _, _ = _save_host_arrays(str(tmp_path), arrays, step=1)
        p1, _, _ = _save_host_arrays(str(tmp_path), arrays, step=2)
        marker = os.path.join(p1, elastic.COMMIT_MARKER)
        record = json.load(open(marker))
        record["format"] = elastic.COMMIT_FORMAT + 1
        with open(marker, "w") as f:
            json.dump(record, f)
        skipped0 = elastic.metrics_registry().get(
            "ptpu_ckpt_skipped_foreign_total").value
        assert elastic.latest_snapshot(str(tmp_path)) == p0
        assert elastic.latest_snapshot(str(tmp_path)) == p0  # again
        assert elastic.metrics_registry().get(
            "ptpu_ckpt_skipped_foreign_total").value == skipped0 + 1
        # named explicitly, the foreign dir is rejected with the reason
        with pytest.raises(EnforceError) as ei:
            elastic.validate_snapshot(p1)
        assert "newer" in str(ei.value)


# ---------------------------------------------------------------------------
# mesh-to-mesh resize: the resharding planner + three distinct re-layouts
# ---------------------------------------------------------------------------

class TestReshardPlanner:
    def test_schedule_algebra_matches_costs_prediction(self):
        from paddle_tpu.framework import costs
        from paddle_tpu.parallel import reshard
        # refinement (dp2 -> dp4 on dim 0): dynamic-slice, zero wire
        steps = reshard.schedule_steps("v", (8, 4), 4, (2, 1), (4, 1))
        assert [s.kind for s in steps] == ["refine-slice"]
        assert sum(s.wire_bytes for s in steps) == 0.0
        assert costs.reshard_wire_bytes(128, (2, 1), (4, 1)) == 0.0
        # unshard (tp2 on dim 1 -> replicated): one all-gather; ring
        # accounting sends out*(g-1)/g = nbytes/2 per device
        nbytes = 16 * 48 * 4
        steps = reshard.schedule_steps("w", (16, 48), 4, (1, 2), (1, 1))
        assert [s.kind for s in steps] == ["all-gather"]
        assert steps[0].group == 2 and steps[0].out_bytes == nbytes
        assert steps[0].wire_bytes == nbytes / 2
        assert costs.reshard_wire_bytes(nbytes, (1, 2), (1, 1)) \
            == nbytes / 2
        # dim move (dp2 on dim 0 -> tp2 on dim 1): refine dim 1 FIRST
        # (memory-efficient ordering), then gather dim 0 at the refined
        # other-dim factor — out = nbytes/2, wire = nbytes/4
        nbytes = 8 * 8 * 4
        steps = reshard.schedule_steps("m", (8, 8), 4, (2, 1), (1, 2))
        assert [s.kind for s in steps] == ["refine-slice", "all-gather"]
        assert steps[1].out_bytes == nbytes // 2
        assert steps[1].wire_bytes == nbytes / 4
        assert costs.reshard_wire_bytes(nbytes, (2, 1), (1, 2)) \
            == nbytes / 4
        # identity
        steps = reshard.schedule_steps("i", (4,), 4, (2,), (2,))
        assert [s.kind for s in steps] == ["identity"]

    def test_random_factor_sweep_balances_exactly(self):
        """Property: for every (old, new) factor pair the step-priced
        schedule equals the closed-form prediction EXACTLY."""
        from paddle_tpu.framework import costs
        from paddle_tpu.parallel import reshard
        rng = np.random.RandomState(0)
        factors = (1, 2, 4, 8)
        for _ in range(60):
            rank = int(rng.randint(1, 4))
            old = tuple(int(rng.choice(factors)) for _ in range(rank))
            new = tuple(int(rng.choice(factors)) for _ in range(rank))
            shape = tuple(8 * max(o, n) for o, n in zip(old, new))
            steps = reshard.schedule_steps("v", shape, 4, old, new)
            nbytes = int(np.prod(shape)) * 4
            got = sum(s.wire_bytes for s in steps)
            want = costs.reshard_wire_bytes(nbytes, old, new)
            assert got == want, (old, new, got, want)

    def test_coverage_factors_from_chunk_grid(self):
        from paddle_tpu.parallel import reshard
        entry = {"chunks": [
            {"start": [0, 0], "shape": [4, 8]},
            {"start": [4, 0], "shape": [4, 8]}]}
        assert reshard._coverage_factors(entry, (8, 8)) == (2, 1)
        entry = {"chunks": [{"start": [], "shape": []}]}
        assert reshard._coverage_factors(entry, ()) == ()

    def test_plan_reads_match_what_restore_actually_loads(self,
                                                          tmp_path):
        """"Reads only the byte ranges each new rank needs" is pinned
        against the real reader: the chunks the plan lists for a var are
        EXACTLY the chunks ShardedCheckpoint loads when restoring it
        onto the new placement."""
        from paddle_tpu.parallel import reshard
        from paddle_tpu.sharded_checkpoint import (ShardedCheckpoint,
                                                   restore_array)
        feeds = _feeds(2)
        loss, pexe = _fresh_world(2)
        for f in feeds:
            pexe.run(feed=f, fetch_list=[loss])
        elastic.save_train_state(str(tmp_path), executor=pexe, step=2)
        snap = elastic.latest_snapshot(str(tmp_path))
        meta = elastic.read_meta(snap)

        loss, pexe4 = _fresh_world(4)
        prepared = pexe4.prepare_program()
        ckpt = ShardedCheckpoint(snap)
        plan = reshard.plan_restore(ckpt, meta, prepared, pexe4)
        assert reshard.validate_schedule(plan) == []
        # pick a ZeRO-1 sharded accumulator (its coverage is split) and
        # a replicated parameter
        shard_var = next(n for n, v in plan.variables.items()
                         if v.old_factors and v.old_factors[0] == 2)
        for name in [shard_var]:
            ckpt2 = ShardedCheckpoint(snap)
            sharding = pexe4.state_sharding(prepared, name)
            restore_array(ckpt2, name, sharding)
            loaded = {(f, k) for f, k in ckpt2._cache}
            planned = {(f, k) for f, k, _ in plan.variables[name].reads}
            assert loaded == planned, (name, loaded, planned)


VOCAB_R, T_R, D_R, HEADS_R, LAYERS_R = 32, 4, 16, 2, 2


def _tfm_build():
    from paddle_tpu.models import transformer
    loss, _ = transformer.transformer_lm(
        vocab=VOCAB_R, max_len=T_R, d_model=D_R, d_inner=2 * D_R,
        num_heads=HEADS_R, num_layers=LAYERS_R, mean_loss=True)
    pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return loss


def _tfm_feeds(n=6, bs=8):
    rng = np.random.RandomState(5)
    return [{
        "tokens": rng.randint(0, VOCAB_R, (bs, T_R)).astype("int64"),
        "tokens@SEQLEN": np.full((bs,), T_R, dtype="int32"),
        "targets": rng.randint(0, VOCAB_R, (bs, T_R)).astype("int64")}
        for _ in range(n)]


def _tfm_world(axes, annotate=False, stages=0, micro=0, quant=""):
    """Fresh transformer training world on a named-axes mesh — the
    builder every side of a mesh-to-mesh resize shares (identical var
    names via the unique_name guard, identical random_seed)."""
    from paddle_tpu.parallel import annotate_tp
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = _tfm_build()
    if annotate:
        assert annotate_tp()
    n = int(np.prod(list(axes.values())))
    bst = BuildStrategy(**(dict(pipeline_stages=stages,
                                num_microbatches=micro) if stages
                           else {}))
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    if quant:
        bst.quant_comm = quant
        bst.comm_error_feedback = True
    pexe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                            mesh=DeviceMesh(jax.devices()[:n], axes))
    pt.Executor().run(pt.default_startup_program())
    return loss, pexe


class TestMeshResize:
    """The acceptance bar: three DISTINCT mesh-to-mesh re-layouts
    restore with fixed-seed loss parity <= 1e-5 vs the uninterrupted
    run — dp-grow (TestDeterministicResume.test_dp_resize_2_to_4),
    dp×tp → dp, and dp×pp → dp×tp — with the emitted redistribution
    schedule's wire bytes matching the costs.py prediction exactly
    (validate_schedule is enforced inside restore; asserted again here
    via the returned summary)."""

    @pytest.fixture(autouse=True)
    def _f32_matmuls(self):
        """Parity runs compare f32-exact: splitting a bf16 contraction
        over tp changes rounding — precision noise, not a resize bug."""
        from paddle_tpu.core import flags
        old = flags.get_flag("use_bf16_matmul")
        flags.set_flag("use_bf16_matmul", False)
        yield
        flags.set_flag("use_bf16_matmul", old)

    def _run_and_snapshot(self, root, axes, feeds, at_step, **kw):
        """Train the full trajectory on one world, snapshotting at
        `at_step` without perturbing it: the uninterrupted reference and
        the donor snapshot in one run."""
        loss, pexe = _tfm_world(axes, **kw)
        ref = []
        for i, f in enumerate(feeds):
            ref.append(float(pexe.run(feed=f, fetch_list=[loss])[0]))
            if i + 1 == at_step:
                elastic.save_train_state(root, executor=pexe,
                                         step=at_step)
        return ref

    def test_dp_tp_to_dp_resize(self, tmp_path):
        """dp2×tp2 → dp4: the tp axis disappears — every tp-sharded
        parameter/accumulator must all-gather (wire bytes > 0, matching
        the costs prediction exactly); ZeRO-1 dp slices re-partition
        2→4."""
        feeds = _tfm_feeds()
        ref = self._run_and_snapshot(str(tmp_path),
                                     {"dp": 2, "tp": 2}, feeds,
                                     at_step=3, annotate=True)
        loss, pexe4 = _tfm_world({"dp": 4})
        meta = elastic.restore_train_state(str(tmp_path),
                                           executor=pexe4)
        assert meta["world"] == {"dp": 2, "tp": 2}
        rs = meta["reshard"]
        assert rs["new_world"] == {"dp": 4}
        assert rs["wire_bytes"] > 0          # tp state really moved
        assert rs["steps"].get("all-gather", 0) > 0
        got = [float(pexe4.run(feed=f, fetch_list=[loss])[0])
               for f in feeds[3:]]
        worst = max(abs(a - b) for a, b in zip(ref[3:], got))
        assert worst <= 1e-5, f"dp2x tp2 -> dp4 parity {worst}"

    def test_dp_pp_to_dp_tp_resize(self, tmp_path):
        """dp2×pp2 → dp2×tp2: the pipeline axis disappears and a tensor
        axis appears — replicated params SLICE onto tp (zero wire: the
        re-layout is pure refinement), and the restored program is the
        tp-rewritten one (r10/r13-verified before the first step)."""
        feeds = _tfm_feeds()
        ref = self._run_and_snapshot(str(tmp_path),
                                     {"dp": 2, "pp": 2}, feeds,
                                     at_step=3, stages=2, micro=2)
        loss, pexe_tp = _tfm_world({"dp": 2, "tp": 2}, annotate=True)
        meta = elastic.restore_train_state(str(tmp_path),
                                           executor=pexe_tp)
        assert meta["world"] == {"dp": 2, "pp": 2}
        rs = meta["reshard"]
        assert rs["new_world"] == {"dp": 2, "tp": 2}
        # replicated -> sharded is dynamic-slice only: nothing on the
        # wire, exactly as costs.reshard_wire_bytes predicts
        assert rs["wire_bytes"] == 0.0
        assert rs["steps"].get("all-gather", 0) == 0
        got = [float(pexe_tp.run(feed=f, fetch_list=[loss])[0])
               for f in feeds[3:]]
        worst = max(abs(a - b) for a, b in zip(ref[3:], got))
        assert worst <= 1e-5, f"dp2x pp2 -> dp2x tp2 parity {worst}"

    def test_ef_state_round_trips_across_tp_change(self, tmp_path):
        """Error-feedback residuals re-map through the GLOBAL gradient
        space across a tp change: dp2×tp2 (int8 + EF) → dp4 → dp2×tp2.
        Params and accumulators return bit-exact; inside the EF state,
        tp-SHARDED gradient segments re-slice bit-exact (pad-then-fold
        dp identity at a power-of-two ratio), and tp-replicated segments
        come back as the MEAN of their per-shard rows — per-shard
        residuals legitimately differ (quant scale blocks span
        neighboring tp-local bucket segments), so the mean is the
        documented mass-preserving semantic, and it round-trips exactly
        once collapsed."""
        from paddle_tpu.sharded_checkpoint import ShardedCheckpoint
        feeds = _tfm_feeds(4)
        root_a = str(tmp_path / "a")
        root_b = str(tmp_path / "b")
        loss, pexe = _tfm_world({"dp": 2, "tp": 2}, annotate=True,
                                quant="int8")
        for f in feeds:
            pexe.run(feed=f, fetch_list=[loss])
        elastic.save_train_state(root_a, executor=pexe, step=4)
        snap_a = elastic.latest_snapshot(root_a)
        ckpt_a = ShardedCheckpoint(snap_a)
        orig = {n: ckpt_a.read(n) for n in ckpt_a.names()}
        ef_a = [n for n in orig if n.startswith("dp_comm_err")]
        assert ef_a and any(np.abs(orig[n]).max() > 0 for n in ef_a), \
            "test premise: non-trivial residuals exist"
        meta_a = elastic.read_meta(snap_a)
        layout = meta_a["ef_layout"]
        assert layout["tp"] == 2
        assert any(d is not None for t in layout["transfers"]
                   for d in t["tp_dims"]), \
            "test premise: tp-sharded gradient segments exist"

        # dp2 x tp2 -> dp4: restore (tp disappears), snapshot again
        loss, pexe4 = _tfm_world({"dp": 4}, quant="int8")
        elastic.restore_train_state(root_a, executor=pexe4)
        elastic.save_train_state(root_b, executor=pexe4, step=4)
        meta_b = elastic.read_meta(root_b)
        assert meta_b["ef_layout"]["tp"] == 1
        assert meta_b["ef_layout"]["dp"] == 4

        # dp4 -> dp2 x tp2: non-EF state bit-exact; EF per documented
        # semantics
        loss, pexe_back = _tfm_world({"dp": 2, "tp": 2}, annotate=True,
                                     quant="int8")
        elastic.restore_train_state(root_b, executor=pexe_back)
        scope = pt.global_scope()
        for name, want in orig.items():
            if name.startswith("dp_comm_err"):
                continue
            got = np.asarray(scope.get(name))
            np.testing.assert_array_equal(
                got, want, err_msg=f"{name} did not round-trip across "
                                   f"the tp resize")
        tp, dp = layout["tp"], layout["dp"]
        for t in layout["transfers"]:
            old = orig[t["var"]].reshape(tp, dp, t["flat"])
            got = np.asarray(scope.get(t["var"])) \
                .reshape(tp, dp, t["flat"])
            off = 0
            for g, n, tp_dim in zip(t["grads"], t["numels"],
                                    t["tp_dims"]):
                want_seg = old[:, :, off:off + n]
                got_seg = got[:, :, off:off + n]
                if tp_dim is not None:
                    np.testing.assert_array_equal(
                        got_seg, want_seg,
                        err_msg=f"tp-sharded segment {g} not bit-exact")
                else:
                    mean = want_seg.mean(axis=0)
                    for ti in range(tp):
                        np.testing.assert_array_equal(
                            got_seg[ti], mean,
                            err_msg=f"replicated segment {g} != tp-mean")
                off += n


# ---------------------------------------------------------------------------
# trainer integration + supervisor
# ---------------------------------------------------------------------------

class TestTrainerIntegration:
    def _trainer(self, tmp_path, **cfg_kw):
        from paddle_tpu.trainer import CheckpointConfig, Trainer

        def train_func():
            x = layers.data("x", shape=[4])
            y = layers.fc(x, size=2)
            return layers.reduce_mean(y)

        def opt_func():
            return pt.optimizer.SGD(learning_rate=0.01)

        cfg = CheckpointConfig(checkpoint_dir=str(tmp_path),
                               step_interval=2, elastic=True, **cfg_kw)
        # fresh name generator per construction: the resumed trainer must
        # rebuild the SAME var names the saving trainer used
        with pt.core.unique_name.guard():
            return Trainer(train_func, opt_func,
                           checkpoint_config=cfg), cfg

    def test_elastic_trainer_resumes_step(self, tmp_path):
        def reader():
            rng = np.random.RandomState(3)
            for _ in range(6):
                yield [(rng.rand(4).astype("f4"),)]

        pt.reset_default_programs()
        pt.reset_global_scope()
        trainer, cfg = self._trainer(tmp_path)
        seen = []
        trainer.train(num_epochs=1, event_handler=lambda e: seen.append(e),
                      reader=reader, feed_order=["x"])
        assert elastic.latest_snapshot(str(tmp_path)) is not None
        meta = elastic.read_meta(str(tmp_path))
        assert meta["extra"]["epoch_id"] == 1
        # a new trainer over the same dir resumes past the trained work
        pt.reset_default_programs()
        pt.reset_global_scope()
        trainer2, cfg2 = self._trainer(tmp_path)
        assert cfg2.epoch_id == 1
        steps = []
        trainer2.train(num_epochs=1,
                       event_handler=lambda e: steps.append(e),
                       reader=reader, feed_order=["x"])
        from paddle_tpu.trainer import BeginStepEvent
        assert not any(isinstance(e, BeginStepEvent) for e in steps)

    def test_async_save_requires_elastic(self, tmp_path):
        from paddle_tpu.trainer import CheckpointConfig
        with pytest.raises(EnforceError):
            CheckpointConfig(checkpoint_dir=str(tmp_path),
                             async_save=True)


class TestSupervisor:
    def test_restarts_with_backoff_until_success(self, tmp_path):
        from paddle_tpu.trainer import Supervisor
        marker = str(tmp_path / "attempts")
        prog = (f"import os,sys\n"
                f"p={marker!r}\n"
                f"n=int(open(p).read()) if os.path.exists(p) else 0\n"
                f"open(p,'w').write(str(n+1))\n"
                f"sys.exit(0 if n >= 2 else 9)\n")
        delays = []
        sup = Supervisor([sys.executable, "-c", prog], max_restarts=5,
                         backoff_s=0.1, backoff_factor=2.0,
                         sleep_fn=delays.append)
        assert sup.run() == 0
        assert sup.restarts == 2
        assert sup.exit_codes == [9, 9, 0]
        assert delays == [0.1, 0.2]

    def test_budget_exhaustion_returns_last_code(self):
        from paddle_tpu.trainer import Supervisor
        delays = []
        sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(7)"],
                         max_restarts=1, backoff_s=0.05,
                         sleep_fn=delays.append)
        assert sup.run() == 7
        assert sup.exit_codes == [7, 7]
        assert sup.exhausted

    def test_budget_exhaustion_raises_terminal_error(self):
        """The satellite bar: a crash-looping child ends in a CLEAR
        terminal error, not an exit code the caller may ignore."""
        from paddle_tpu.trainer import (Supervisor,
                                        SupervisorExhaustedError)
        sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(5)"],
                         max_restarts=1, backoff_s=0.01,
                         raise_on_exhaust=True, sleep_fn=lambda s: None)
        with pytest.raises(SupervisorExhaustedError) as ei:
            sup.run()
        assert ei.value.exit_code == 5
        assert ei.value.exit_codes == [5, 5]
        assert "crash-looping" in str(ei.value)

    def test_backoff_jitter_decorrelates_delays(self):
        """Jittered backoff: each sleep is the exponential delay scaled
        by a uniform factor in [1-j, 1+j] from the injected rng."""
        from paddle_tpu.trainer import Supervisor

        class _Rng:
            def __init__(self):
                self.calls = []

            def uniform(self, a, b):
                self.calls.append((a, b))
                return 0.5 * (a + b) + 0.1   # deterministic: +0.1

        rng = _Rng()
        delays = []
        sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(3)"],
                         max_restarts=2, backoff_s=0.1,
                         backoff_factor=2.0, backoff_jitter=0.25,
                         rng=rng, sleep_fn=delays.append)
        assert sup.run() == 3
        assert rng.calls == [(-0.25, 0.25), (-0.25, 0.25)]
        np.testing.assert_allclose(delays, [0.1 * 1.1, 0.2 * 1.1])

    def test_healthy_run_resets_backoff(self):
        """A child that ran past healthy_run_s before dying restarts at
        the BASE backoff (a preemption pattern), while instant deaths
        keep compounding it (a crash loop)."""
        from paddle_tpu.trainer import Supervisor
        child = ("import sys,time\n"
                 "time.sleep(0.25)\n"
                 "sys.exit(9)\n")
        delays = []
        sup = Supervisor([sys.executable, "-c", child], max_restarts=2,
                         backoff_s=0.05, backoff_factor=4.0,
                         healthy_run_s=0.2, sleep_fn=delays.append)
        assert sup.run() == 9
        assert delays == [0.05, 0.05]   # reset each time, never 0.2
        delays2 = []
        sup2 = Supervisor([sys.executable, "-c", "import sys; sys.exit(9)"],
                          max_restarts=2, backoff_s=0.05,
                          backoff_factor=4.0, healthy_run_s=10.0,
                          sleep_fn=delays2.append)
        assert sup2.run() == 9
        assert delays2 == [0.05, 0.2]   # compounding: not healthy

    def test_world_gang_restarts_together(self, tmp_path):
        """world_size > 1: one rank dying kills the rest of the gang and
        the WHOLE world relaunches (the restart granularity the barrier
        protocol assumes). Each rank sees its identity in the env."""
        from paddle_tpu.trainer import Supervisor
        marker = str(tmp_path / "rank1_died")
        child = (
            "import os, sys, time\n"
            f"p = {marker!r}\n"
            "rank = os.environ['PTPU_WORLD_RANK']\n"
            "assert os.environ['PTPU_WORLD_SIZE'] == '3'\n"
            "if rank == '1' and not os.path.exists(p):\n"
            "    open(p, 'w').write('1')\n"
            "    sys.exit(6)          # first incarnation: rank 1 dies\n"
            "if not os.path.exists(p):\n"
            "    time.sleep(30)       # others hang until terminated\n"
            "sys.exit(0)\n")
        sup = Supervisor([sys.executable, "-c", child], world_size=3,
                         max_restarts=3, backoff_s=0.05,
                         sleep_fn=lambda s: None)
        assert sup.run() == 0
        assert sup.restarts == 1
        assert sup.exit_codes == [6, 0]


# ---------------------------------------------------------------------------
# subprocess crash tests (real SIGKILL through PTPU_FAULT_INJECT)
# ---------------------------------------------------------------------------

def _child_env(fault=None):
    env = dict(os.environ)
    env.pop("PTPU_FAULT_INJECT", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    if fault:
        env["PTPU_FAULT_INJECT"] = fault
    return env


def _run_atomic_child(root, fault=None, timeout=120):
    return subprocess.run(
        [sys.executable, RECOVERY_SMOKE, "--atomic-child", "--root",
         str(root)] + (["--fault", fault] if fault else []),
        env=_child_env(), timeout=timeout).returncode


class TestCrashMidSaveAtomicity:
    """The property the two-phase commit exists for: SIGKILL the writer
    at ANY byte offset of the staged payload — every surviving directory
    either restores exactly (a committed generation) or is cleanly
    skipped/rejected; a partially written generation is NEVER
    restorable. The child commits generation 0, then saves generation 1
    under the fault."""

    def _check_surviving_state(self, root):
        arrays0 = _host_snapshot_args()       # the child's generation 0
        snaps = elastic.list_snapshots(str(root), committed_only=True)
        assert len(snaps) >= 1, "generation 0 must have survived"
        for _, path in elastic.list_snapshots(str(root),
                                              committed_only=False):
            if not elastic.is_committed(path):
                with pytest.raises(EnforceError):
                    elastic.validate_snapshot(path)   # cleanly rejected
            else:
                elastic.validate_snapshot(path)
        latest = elastic.latest_snapshot(str(root))
        meta, back = _restore_host_arrays(latest, arrays0)
        if meta["step"] == 0:
            expect = arrays0
        else:
            assert meta["step"] == 1
            expect = {k: v + 1.0 for k, v in arrays0.items()}
        for k, v in expect.items():
            np.testing.assert_array_equal(back[k], v)

    def test_killed_at_randomized_offsets(self, tmp_path):
        # learn the payload size from an unfaulted run
        ref_root = tmp_path / "ref"
        assert _run_atomic_child(ref_root) == 0
        snaps = elastic.list_snapshots(str(ref_root))
        assert len(snaps) == 2
        marker = json.load(open(os.path.join(snaps[-1][1],
                                             elastic.COMMIT_MARKER)))
        total = sum(e["size"] for e in marker["files"].values())

        rng = np.random.RandomState(20260804)
        offsets = sorted({0, total // 2, total, total + 1,
                          *rng.randint(1, total, size=3)})
        for off in offsets:
            root = tmp_path / f"off{off}"
            rc = _run_atomic_child(root, fault=f"crash_mid_save:{off}")
            assert rc == -9, f"offset {off}: child exited {rc}, " \
                             f"expected SIGKILL"
            self._check_surviving_state(root)
            committed = {elastic.read_meta(p)["step"] for _, p in
                         elastic.list_snapshots(str(root))}
            if off <= total:
                assert committed == {0}, \
                    f"offset {off}: generation 1 committed early"
            else:
                assert committed == {0, 1}, \
                    f"offset {off}: post-commit kill lost generation 1"


class TestKillMidRunRecovery:
    """The acceptance bar: SIGKILL a real training process mid-run,
    restart, restore the latest committed snapshot, and reproduce the
    uninterrupted fixed-seed loss trajectory — exactly at the same dp,
    within the fp32 parity band after an N→M dp resize."""

    STEPS = 6
    CRASH = 4

    def _run_train_child(self, root, out, dp=2, fault=None, timeout=240):
        return subprocess.run(
            [sys.executable, RECOVERY_SMOKE, "--child", "--root",
             str(root), "--out", str(out), "--dp", str(dp),
             "--steps", str(self.STEPS), "--snap_every", "2"],
            env=_child_env(fault), timeout=timeout).returncode

    def _losses(self, out):
        got = {}
        with open(out) as f:
            for line in f:
                row = json.loads(line)
                got[row["step"]] = row["loss"]
        return got

    def test_sigkill_restart_and_resize(self, tmp_path):
        ref_out = tmp_path / "ref.jsonl"
        assert self._run_train_child(tmp_path / "ref", ref_out) == 0
        ref = self._losses(ref_out)
        assert sorted(ref) == list(range(self.STEPS))

        # crash a run mid-step-stream, then restart twice from copies:
        # once at the same dp (exact), once resized to dp4 (parity band)
        root = tmp_path / "crash"
        out = tmp_path / "crash.jsonl"
        rc = self._run_train_child(root, out,
                                   fault=f"crash_at_step:{self.CRASH}")
        assert rc == -9, f"expected SIGKILL death, got {rc}"
        import shutil as _sh
        root4 = tmp_path / "crash4"
        out4 = tmp_path / "crash4.jsonl"
        _sh.copytree(root, root4)
        _sh.copy(out, out4)

        assert self._run_train_child(root, out, dp=2) == 0
        got = self._losses(out)
        assert all(got[i] == ref[i] for i in range(self.STEPS)), \
            f"same-dp resume not exact: {got} vs {ref}"

        assert self._run_train_child(root4, out4, dp=4) == 0
        got4 = self._losses(out4)
        worst = max(abs(got4[i] - ref[i]) for i in range(self.STEPS))
        assert worst <= 1e-5, f"dp4 resume parity {worst} > 1e-5"


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-x", "-q"]))
