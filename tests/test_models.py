"""Model-zoo tests (≙ reference benchmark/fluid/models + book tests: build
each model family, train a few steps, loss drops / stays finite)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models


def _train(loss, feed, steps=5, lr=1e-2, opt=None):
    (opt or pt.optimizer.Adam(learning_rate=lr)).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    vals = []
    for _ in range(steps):
        out, = exe.run(feed=feed, fetch_list=[loss])
        vals.append(float(out))
    assert all(np.isfinite(v) for v in vals), vals
    return vals


def test_mnist_mlp(rng):
    loss, acc, _ = models.mnist.mlp()
    x = rng.rand(16, 784).astype("float32")
    y = rng.randint(0, 10, (16, 1)).astype("int64")
    vals = _train(loss, {"img": x, "label": y}, steps=10)
    assert vals[-1] < vals[0]


def test_mnist_conv(rng):
    loss, acc, _ = models.mnist.conv_net()
    x = rng.rand(8, 1, 28, 28).astype("float32")
    y = rng.randint(0, 10, (8, 1)).astype("int64")
    vals = _train(loss, {"img": x, "label": y}, steps=6)
    assert vals[-1] < vals[0]


def test_resnet_cifar(rng):
    loss, acc, _ = models.resnet.resnet_cifar10(depth=20)
    x = rng.rand(4, 32, 32, 3).astype("float32")
    y = rng.randint(0, 10, (4, 1)).astype("int64")
    vals = _train(loss, {"img": x, "label": y}, steps=4,
                  opt=pt.optimizer.MomentumOptimizer(learning_rate=0.05,
                                                     momentum=0.9))
    assert vals[-1] < vals[0] * 1.5  # BN + tiny batch: just sane + moving


def test_resnet_imagenet_builds(rng):
    """ResNet-50 builds and runs one forward/backward step on small feed."""
    loss, acc, _ = models.resnet.resnet_imagenet(depth=50, class_num=100,
                                                 use_bf16=False)
    x = rng.rand(2, 224, 224, 3).astype("float32")
    y = rng.randint(0, 100, (2, 1)).astype("int64")
    _train(loss, {"img": x, "label": y}, steps=1)


def test_vgg_cifar(rng):
    loss, acc, _ = models.vgg.vgg16_cifar()
    x = rng.rand(4, 3, 32, 32).astype("float32")
    y = rng.randint(0, 10, (4, 1)).astype("int64")
    _train(loss, {"img": x, "label": y}, steps=2)


def test_stacked_lstm(rng):
    loss, acc, _ = models.stacked_lstm.stacked_lstm_net(
        dict_dim=500, emb_dim=16, hid_dim=16, stacked_num=2, max_len=12)
    w = rng.randint(0, 500, (8, 12)).astype("int64")
    sl = rng.randint(1, 13, (8,)).astype("int32")
    y = rng.randint(0, 2, (8, 1)).astype("int64")
    vals = _train(loss, {"words": w, "words@SEQLEN": sl, "label": y},
                  steps=8)
    assert vals[-1] < vals[0]


def test_lstm_language_model(rng):
    loss, _ = models.stacked_lstm.lstm_language_model(
        vocab_size=200, emb_dim=16, hid_dim=16, num_layers=2, max_len=10)
    t = rng.randint(0, 200, (4, 10)).astype("int64")
    sl = rng.randint(1, 11, (4,)).astype("int32")
    tg = rng.randint(0, 200, (4, 10)).astype("int64")
    vals = _train(loss, {"tokens": t, "tokens@SEQLEN": sl, "targets": tg},
                  steps=6)
    assert vals[-1] < vals[0]


def test_transformer_lm(rng):
    loss, _ = models.transformer.transformer_lm(
        vocab=300, max_len=12, d_model=32, d_inner=64, num_heads=4,
        num_layers=2)
    t = rng.randint(0, 300, (4, 12)).astype("int64")
    sl = np.full((4,), 12, dtype="int32")
    tg = rng.randint(0, 300, (4, 12)).astype("int64")
    vals = _train(loss, {"tokens": t, "tokens@SEQLEN": sl, "targets": tg},
                  steps=6, lr=3e-3)
    assert vals[-1] < vals[0]


def test_transformer_nmt(rng):
    loss, _ = models.transformer.transformer(
        src_vocab=200, tgt_vocab=200, max_len=10, d_model=32, d_inner=64,
        num_heads=4, num_layers=1, dropout=0.0)
    s = rng.randint(0, 200, (4, 10)).astype("int64")
    t = rng.randint(0, 200, (4, 10)).astype("int64")
    sl = np.full((4,), 10, dtype="int32")
    lb = rng.randint(0, 200, (4, 10)).astype("int64")
    vals = _train(loss, {"src": s, "src@SEQLEN": sl, "tgt": t,
                         "tgt@SEQLEN": sl, "lbl": lb}, steps=5, lr=3e-3)
    assert vals[-1] < vals[0]


def test_deepfm(rng):
    loss, pred = models.deepfm.deepfm(num_fields=5, vocab_size=500,
                                      embed_dim=8, fc_sizes=(32,))
    ids = rng.randint(0, 500, (16, 5)).astype("int64")
    vals_ = rng.rand(16, 5).astype("float32")
    y = rng.randint(0, 2, (16, 1)).astype("float32")
    vals = _train(loss, {"feat_ids": ids, "feat_vals": vals_, "label": y},
                  steps=8)
    assert vals[-1] < vals[0]


def test_wide_and_deep(rng):
    loss, pred = models.deepfm.wide_and_deep(
        wide_fields=4, deep_fields=6, wide_vocab=300, deep_vocab=300,
        embed_dim=4, fc_sizes=(16,))
    wi = rng.randint(0, 300, (8, 4)).astype("int64")
    di = rng.randint(0, 300, (8, 6)).astype("int64")
    y = rng.randint(0, 2, (8, 1)).astype("float32")
    vals = _train(loss, {"wide_ids": wi, "deep_ids": di, "label": y},
                  steps=8)
    assert vals[-1] < vals[0]


def test_transformer_tp_sharded(rng):
    """TP/SP/EP-annotated transformer trains on an 8-device mesh
    (dp2 x tp2 x sp2) under ZeRO-1."""
    from paddle_tpu.parallel import (BuildStrategy, ParallelExecutor,
                                     ReduceStrategy, annotate_tp, make_mesh)
    loss, _ = models.transformer.transformer_lm(
        vocab=256, max_len=16, d_model=64, d_inner=128, num_heads=4,
        num_layers=2)
    pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    annotated = annotate_tp()
    assert any("attn_q" in k for k in annotated)
    assert annotated["tok_emb"][0] == "tp"  # vocab-sharded (EP analogue)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    bs = BuildStrategy(reduce_strategy=ReduceStrategy.Reduce,
                       enable_sequence_parallel=True)
    pe = ParallelExecutor(loss_name=loss.name, mesh=mesh, build_strategy=bs)
    t = rng.randint(0, 256, (8, 16)).astype("int64")
    sl = np.full((8,), 16, dtype="int32")
    tg = rng.randint(0, 256, (8, 16)).astype("int64")
    vals = []
    for _ in range(3):
        out, = pe.run(fetch_list=[loss],
                      feed={"tokens": t, "tokens@SEQLEN": sl, "targets": tg})
        vals.append(float(out))
    assert vals[-1] < vals[0]


def test_se_resnext_trains_tiny(rng):
    """SE-ResNeXt on tiny shapes: forward+backward runs, loss finite,
    grouped conv + SE gating wired (≙ dist_se_resnext.py model)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.models import se_resnext

    img = layers.data("img", shape=[32, 32, 3])
    label = layers.data("label", shape=[1], dtype="int64")
    loss, acc, logits = se_resnext.se_resnext_imagenet(
        img=img, label=label, depth=50, class_num=10, cardinality=8,
        reduction_ratio=4)
    pt.optimizer.MomentumOptimizer(learning_rate=0.01, momentum=0.9) \
        .minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"img": rng.rand(2, 32, 32, 3).astype("float32"),
            "label": rng.randint(0, 10, (2, 1)).astype("int64")}
    l0 = exe.run(feed=feed, fetch_list=[loss])[0]
    l1 = exe.run(feed=feed, fetch_list=[loss])[0]
    assert np.isfinite(l0).all() and np.isfinite(l1).all()
    assert logits.shape[-1] == 10


def test_googlenet_trains_tiny(rng):
    """GoogLeNet inception stack on tiny shapes (≙ benchmark googlenet)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.models import googlenet

    img = layers.data("img", shape=[64, 64, 3])
    label = layers.data("label", shape=[1], dtype="int64")
    loss, acc, logits = googlenet.googlenet_imagenet(
        img=img, label=label, class_num=10)
    pt.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"img": rng.rand(2, 64, 64, 3).astype("float32"),
            "label": rng.randint(0, 10, (2, 1)).astype("int64")}
    l0 = exe.run(feed=feed, fetch_list=[loss, acc])
    assert np.isfinite(l0[0]).all()


def test_alexnet_trains_tiny(rng):
    """AlexNet 5-conv + 3-fc stack (≙ benchmark/paddle/image/alexnet.py);
    full 224x224 geometry so every stride/pad survives the conv math."""
    import paddle_tpu as pt
    from paddle_tpu.models import alexnet

    loss, acc, logits = alexnet.alexnet_imagenet(class_num=10)
    pt.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"img": rng.rand(2, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 10, (2, 1)).astype("int64")}
    l0 = exe.run(feed=feed, fetch_list=[loss, acc])
    l1 = exe.run(feed=feed, fetch_list=[loss, acc])
    assert np.isfinite(l0[0]).all() and np.isfinite(l1[0]).all()
    assert logits.shape[-1] == 10


def test_ssd_detector_trains_and_decodes(rng):
    """SSD zoo model: backbone + multi_box_head + ssd_loss trains (loss
    decreases), and ssd_decode emits [label, score, box] rows under NMS —
    the reference's SSD stack as one composed model (≙ reference
    layers/detection.py multi_box_head:211 / ssd_loss:264)."""
    import paddle_tpu as pt
    from paddle_tpu.models import ssd

    B, G = 2, 4
    loss, head = ssd.ssd_detector(num_classes=4, image_shape=(3, 64, 64),
                                  num_gt=G)
    pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    gb = np.zeros((B, G, 4), "float32")
    gl = np.zeros((B, G), "int64")
    for b in range(B):
        gb[b, 0] = [0.1, 0.1, 0.45, 0.45]
        gl[b, 0] = 1
        gb[b, 1] = [0.5, 0.5, 0.95, 0.95]
        gl[b, 1] = 2
    feed = {"img": rng.rand(B, 3, 64, 64).astype("float32"),
            "gt_box": gb, "gt_label": gl}
    l0 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    for _ in range(10):
        l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 < l0

    out, num = ssd.ssd_decode(*head, keep_top_k=20)
    res, cnt = exe.run(feed=feed, fetch_list=[out, num])
    assert res.shape == (B, 20, 6)
    assert (cnt >= 0).all() and (cnt <= 20).all()


def test_crnn_ctc_trains_and_decodes(rng):
    """CRNN-CTC OCR zoo model: conv columns -> BiGRU -> warpctc trains to
    decreasing loss; greedy CTC decode emits merged label sequences
    (≙ reference warpctc/ctc_align OCR recipe)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.models import ocr_crnn

    B, L, NC = 2, 4, 10
    loss, logits, seqlen = ocr_crnn.crnn_ctc(
        num_classes=NC, image_shape=(1, 32, 64), max_label_len=L, hidden=32)
    pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"img": rng.rand(B, 1, 32, 64).astype("float32"),
            "label": rng.randint(0, NC, (B, L)).astype("int64")}
    l0 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    for _ in range(12):
        l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 < l0

    dec, dec_len = layers.sequence.ctc_greedy_decoder(
        layers.softmax(logits), blank=NC, input_length=seqlen)
    d, dl = exe.run(feed=feed, fetch_list=[dec, dec_len])
    assert d.shape[0] == B and (dl >= 0).all()
    # decoded ids are real classes only (blank removed by the aligner)
    for b in range(B):
        assert (d[b, :int(dl[b, 0])] < NC).all()


def test_transformer_lm_generate_kv_cache(rng):
    """Autoregressive generation with the per-layer KV cache: train a tiny
    LM on a DETERMINISTIC next-token map (tok' = (13*tok+7) % V), build the
    decode graph sharing weights by name, and check the greedy generation
    follows the learned map (≙ the reference transformer fast decoder)."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    V, D, T = 50, 64, 12
    loss, _ = transformer.transformer_lm(
        vocab=V, max_len=T, d_model=D, d_inner=128, num_heads=4,
        num_layers=2, dropout=0.0)
    pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    def batch(b=32):
        toks = np.empty((b, T + 1), np.int64)
        toks[:, 0] = rng.randint(0, V, (b,))
        for i in range(1, T + 1):
            toks[:, i] = (toks[:, i - 1] * 13 + 7) % V
        return {"tokens": toks[:, :-1].copy(),
                "tokens@SEQLEN": np.full((b,), T, "int32"),
                "targets": toks[:, 1:].copy()}

    last = None
    for _ in range(120):
        last = float(exe.run(feed=batch(), fetch_list=[loss])[0])
    assert last < 0.2, f"LM did not learn the map (loss {last})"

    G = 8
    # decode graph in its OWN program (the train program would demand its
    # feeds); trained parameters are shared through the scope by name
    gen_prog, gen_startup = pt.Program(), pt.Program()
    from paddle_tpu.core import unique_name
    from paddle_tpu.framework.program import program_guard
    with program_guard(gen_prog, gen_startup), unique_name.guard():
        seqs, scores = transformer.transformer_lm_generate(
            vocab=V, max_gen=G, d_model=D, d_inner=128, num_heads=4,
            num_layers=2, bos_id=0, beam_size=1)
    # bos_id deliberately differs from the fed prompt token: the decode
    # must condition on the PROMPT VALUES, not the constant
    out, sc = exe.run(program=gen_prog,
                      feed={"prompt": np.full((4, 1), 5, "int64")},
                      fetch_list=[seqs, scores])
    assert out.shape == (4, G, 1)
    chain = [5]
    for _ in range(G):
        chain.append((chain[-1] * 13 + 7) % V)
    hits = sum(int(out[0, i, 0]) == chain[i + 1] for i in range(G))
    assert hits >= G - 1, (out[0, :, 0].tolist(), chain[1:])


def test_transformer_generate_encoder_decoder(rng):
    """Encoder-decoder generation: train the NMT transformer on a
    pointwise translation (tgt token = (src token + 5) % V, teacher
    forced from BOS), then beam-decode with the cached generator and
    check the emitted sequence is the source's translation."""
    import paddle_tpu as pt
    from paddle_tpu.core import unique_name
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.models import transformer

    V, D, Ts, BOS = 40, 32, 8, 0
    loss, _ = transformer.transformer(
        src_vocab=V, tgt_vocab=V, max_len=Ts, d_model=D, d_inner=64,
        num_heads=4, num_layers=2, dropout=0.0, label_smooth=0.0)
    pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    def batch(b=32):
        src = rng.randint(2, V, (b, Ts)).astype("int64")
        out = (src + 5) % V
        tgt = np.concatenate([np.full((b, 1), BOS, "int64"),
                              out[:, :-1]], axis=1)
        return {"src": src, "src@SEQLEN": np.full((b,), Ts, "int32"),
                "tgt": tgt, "tgt@SEQLEN": np.full((b,), Ts, "int32"),
                "lbl": out}

    last = None
    for _ in range(450):
        last = float(exe.run(feed=batch(), fetch_list=[loss])[0])
    assert last < 0.3, f"NMT did not learn the map (loss {last})"

    G, K = 6, 2
    gen_prog, gen_startup = pt.Program(), pt.Program()
    with program_guard(gen_prog, gen_startup), unique_name.guard():
        seqs, scores = transformer.transformer_generate(
            src_vocab=V, tgt_vocab=V, max_src_len=Ts, max_gen=G,
            d_model=D, d_inner=64, num_heads=4, num_layers=2,
            bos_id=BOS, eos_id=-1, beam_size=K)  # no EOS in this task
    src = rng.randint(2, V, (3, Ts)).astype("int64")
    out, sc = exe.run(program=gen_prog,
                      feed={"src": src,
                            "src@SEQLEN": np.full((3,), Ts, "int32")},
                      fetch_list=[seqs, scores])
    assert out.shape == (3, G, K)
    expect = (src + 5) % V
    for b in range(3):
        best = int(np.argmax(sc[b]))
        hits = sum(int(out[b, i, best]) == expect[b, i] for i in range(G))
        assert hits >= G - 1, (out[b, :, best].tolist(),
                               expect[b, :G].tolist())
