"""Tests for the native recordio container + threaded loader
(≙ reference recordio tests + reader-op tests)."""

import os

import numpy as np
import pytest

from paddle_tpu.data.recordio import (ParallelRecordLoader, RecordIOScanner,
                                      RecordIOWriter, read_numpy_records,
                                      write_numpy_records)


def _write(path, records, **kw):
    with RecordIOWriter(path, **kw) as w:
        for r in records:
            w.write(r)


class TestRoundTrip:
    def test_simple(self, tmp_path):
        path = str(tmp_path / "a.rio")
        recs = [b"hello", b"", b"x" * 10000, b"world"]
        _write(path, recs)
        with RecordIOScanner(path) as s:
            assert list(s) == recs
            assert s.skipped_chunks == 0

    def test_compressed(self, tmp_path):
        path = str(tmp_path / "a.rio")
        recs = [os.urandom(100) for _ in range(50)] + [b"a" * 50000]
        _write(path, recs, compress=True)
        with RecordIOScanner(path) as s:
            assert list(s) == recs

    def test_multi_chunk(self, tmp_path):
        path = str(tmp_path / "a.rio")
        recs = [bytes([i % 256]) * 1000 for i in range(100)]
        _write(path, recs, max_chunk_bytes=8192)
        with RecordIOScanner(path) as s:
            assert list(s) == recs

    def test_corruption_resync(self, tmp_path):
        """Flipping bytes mid-file loses only the damaged chunk; the scanner
        resyncs on the next chunk magic (≙ recordio CRC/seek semantics)."""
        path = str(tmp_path / "a.rio")
        recs = [bytes([i]) * 512 for i in range(64)]
        _write(path, recs, max_chunk_bytes=2048)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF  # corrupt a payload byte
        open(path, "wb").write(bytes(data))
        with RecordIOScanner(path) as s:
            got = list(s)
            assert s.skipped_chunks >= 1
        assert 0 < len(got) < len(recs)
        assert all(g in recs for g in got)  # surviving records intact


class TestLoader:
    def test_parallel_loader_all_records(self, tmp_path):
        paths = []
        expect = set()
        for i in range(6):
            p = str(tmp_path / f"f{i}.rio")
            recs = [f"{i}:{j}".encode() for j in range(200)]
            _write(p, recs, max_chunk_bytes=512)
            expect.update(recs)
            paths.append(p)
        with ParallelRecordLoader(paths, num_threads=3,
                                  queue_capacity=32) as ld:
            got = list(ld)
        assert set(got) == expect
        assert len(got) == len(expect)

    def test_loader_early_close(self, tmp_path):
        p = str(tmp_path / "f.rio")
        _write(p, [b"r" * 100] * 1000, max_chunk_bytes=512)
        ld = ParallelRecordLoader([p], num_threads=2, queue_capacity=4)
        it = iter(ld)
        next(it)
        ld.close()  # must not deadlock with blocked producers


class TestNumpyRecords:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "n.rio")
        rng = np.random.RandomState(0)
        data = [(rng.rand(8, 4).astype("float32"),
                 np.array([i], dtype="int64")) for i in range(20)]
        n = write_numpy_records(path, iter(data))
        assert n == 20
        with RecordIOScanner(path) as s:
            out = list(read_numpy_records(s))
        assert len(out) == 20
        for (a, b), (x, y) in zip(data, out):
            np.testing.assert_array_equal(a, x)
            np.testing.assert_array_equal(b, y)

    def test_missing_file(self):
        with pytest.raises(Exception):
            RecordIOScanner("/nonexistent/file.rio")


class TestFailureModes:
    def test_truncated_file_counts_skipped(self, tmp_path):
        """A file truncated mid-chunk loses only the tail; the short read is
        counted as a skipped chunk, not reported as clean EOF."""
        path = str(tmp_path / "t.rio")
        recs = [bytes([i]) * 512 for i in range(64)]
        _write(path, recs, max_chunk_bytes=2048)
        data = open(path, "rb").read()
        open(path, "wb").write(data[:len(data) - 100])
        with RecordIOScanner(path) as s:
            got = list(s)
            assert s.skipped_chunks >= 1
        assert 0 < len(got) < len(recs)
        assert all(g in recs for g in got)

    def test_corrupt_length_header_resyncs(self, tmp_path):
        """Inflating a chunk's comp_len header must not silently drop the
        rest of the file — scanner resyncs on the next chunk magic."""
        import struct
        path = str(tmp_path / "h.rio")
        recs = [bytes([i]) * 512 for i in range(64)]
        _write(path, recs, max_chunk_bytes=2048)
        data = bytearray(open(path, "rb").read())
        # first chunk header: magic(4) num_records(4) raw_len(4) comp_len(4)
        data[12:16] = struct.pack("<I", len(data) * 2)
        open(path, "wb").write(bytes(data))
        with RecordIOScanner(path) as s:
            got = list(s)
            assert s.skipped_chunks >= 1
        assert len(got) > 0  # later chunks recovered
        assert all(g in recs for g in got)

    def test_loader_missing_file_raises(self, tmp_path):
        path = str(tmp_path / "ok.rio")
        _write(path, [b"x"])
        with pytest.raises(Exception):
            ParallelRecordLoader([path, str(tmp_path / "nope.rio")])

    def test_writer_del_flushes(self, tmp_path):
        path = str(tmp_path / "d.rio")
        w = RecordIOWriter(path)
        w.write(b"tail-record")
        del w
        import gc
        gc.collect()
        with RecordIOScanner(path) as s:
            assert list(s) == [b"tail-record"]

    def test_huge_comp_len_header_no_abort(self, tmp_path):
        """comp_len corrupted to ~4GB must be bounded by remaining file size
        (skipped chunk), never a std::bad_alloc aborting the process."""
        import struct
        path = str(tmp_path / "big.rio")
        recs = [bytes([i]) * 512 for i in range(64)]
        _write(path, recs, max_chunk_bytes=2048)
        data = bytearray(open(path, "rb").read())
        data[12:16] = struct.pack("<I", 0xFFFFFFF0)
        open(path, "wb").write(bytes(data))
        with RecordIOScanner(path) as s:
            got = list(s)
            assert s.skipped_chunks >= 1
        assert len(got) > 0


# ---------------------------------------------------------------------------
# native tensor container (tensor_store.cc)
# ---------------------------------------------------------------------------

class TestTensorStore:
    def test_roundtrip_many_dtypes(self, rng, tmp_path):
        import ml_dtypes
        from paddle_tpu.data.tensor_store import (list_tensors, load_tensors,
                                                  save_tensors)
        path = str(tmp_path / "ckpt.pts")
        tensors = {
            "w": rng.randn(17, 9).astype("float32"),
            "step": np.asarray(123, dtype="int64"),
            "mask": (rng.rand(5) > 0.5),
            "bf": rng.randn(8, 8).astype(ml_dtypes.bfloat16),
            "emb": rng.randn(100, 4).astype("float64"),
        }
        save_tensors(path, tensors)
        assert sorted(list_tensors(path)) == sorted(tensors)
        back = load_tensors(path)
        for k, v in tensors.items():
            assert back[k].dtype == v.dtype
            np.testing.assert_array_equal(
                back[k].view(np.uint8) if v.dtype.name == "bfloat16"
                else back[k],
                v.view(np.uint8) if v.dtype.name == "bfloat16" else v)

    def test_subset_load(self, rng, tmp_path):
        from paddle_tpu.data.tensor_store import load_tensors, save_tensors
        path = str(tmp_path / "c.pts")
        save_tensors(path, {"a": np.zeros(3, "float32"),
                            "b": np.ones(4, "float32")})
        got = load_tensors(path, ["b"])
        assert list(got) == ["b"]

    def test_truncated_file_rejected(self, rng, tmp_path):
        from paddle_tpu.data.tensor_store import load_tensors, save_tensors
        path = str(tmp_path / "t.pts")
        save_tensors(path, {"a": rng.rand(64).astype("float32")})
        data = open(path, "rb").read()
        open(path, "wb").write(data[:-10])   # chop the footer
        with pytest.raises(IOError):
            load_tensors(path)

    def test_corrupt_payload_detected(self, rng, tmp_path):
        from paddle_tpu.data.tensor_store import load_tensors, save_tensors
        path = str(tmp_path / "x.pts")
        save_tensors(path, {"a": rng.rand(64).astype("float32")})
        data = bytearray(open(path, "rb").read())
        data[60] ^= 0xFF                      # flip a payload byte
        open(path, "wb").write(bytes(data))
        with pytest.raises((IOError, KeyError)):
            load_tensors(path, ["a"])

    def test_io_save_load_vars_native_format(self, rng, tmp_path):
        """save_vars/load_vars route *.pts filenames through the native
        container (≙ save_combine/load_combine single-file flow)."""
        import paddle_tpu as pt
        from paddle_tpu import layers

        x = layers.data("x", shape=[8])
        layers.fc(x, size=4, name="ts_fc")
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        scope = pt.global_scope()
        before = {n: np.asarray(scope.get(n)).copy()
                  for n in scope.local_var_names()}

        pt.io.save_params(exe, str(tmp_path), filename="params.pts")
        for n in before:
            scope.set_var(n, np.zeros_like(before[n]))
        pt.io.load_params(exe, str(tmp_path), filename="params.pts")
        for n, v in before.items():
            np.testing.assert_allclose(np.asarray(scope.get(n)), v)
