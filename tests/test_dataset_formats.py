"""Real-format dataset ingestion (VERDICT r2 #7).

Each test writes a tiny VALID file in the real on-disk format — MNIST IDX
gzip, CIFAR python-pickle tar.gz, aclImdb tar.gz of review .txt files,
PTB sentence text — and parses it back through the dataset classes
(≙ reference python/paddle/dataset/{mnist,cifar,imdb,imikolov}.py +
common.py download/cache, which these modules translate).
"""

import gzip
import io
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.data import common, datasets

pytestmark = pytest.mark.quick  # run_ci.sh quick smoke tier


@pytest.fixture()
def data_home(tmp_path, monkeypatch):
    monkeypatch.setattr(datasets, "DATA_HOME", str(tmp_path))
    return tmp_path


def _write_idx(tmp, images, labels, prefix):
    """Write REAL IDX format: >II magic 2049 + labels, >IIII magic 2051 +
    images, both gzipped."""
    n, rows, cols = images.shape
    with gzip.open(tmp / f"{prefix}-labels-idx1-ubyte.gz", "wb") as f:
        f.write(struct.pack(">II", 2049, n) + labels.astype(np.uint8)
                .tobytes())
    with gzip.open(tmp / f"{prefix}-images-idx3-ubyte.gz", "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, rows, cols) +
                images.astype(np.uint8).tobytes())


class TestMnistIdx:
    def test_idx_files_parse(self, data_home):
        d = data_home / "mnist"
        d.mkdir()
        rng = np.random.RandomState(0)
        imgs = rng.randint(0, 256, (5, 28, 28)).astype(np.uint8)
        labels = np.array([3, 1, 4, 1, 5], np.uint8)
        _write_idx(d, imgs, labels, "train")
        samples = list(datasets.mnist.train()())
        assert len(samples) == 5
        x0, y0 = samples[0]
        assert x0.shape == (784,) and x0.dtype == np.float32
        assert [y for _, y in samples] == [3, 1, 4, 1, 5]
        # reference normalization: uint8 -> [-1, 1]
        np.testing.assert_allclose(
            x0, imgs[0].reshape(-1).astype(np.float32) / 127.5 - 1.0)

    def test_end_to_end_train_from_idx_fixture(self, data_home):
        """Book-style flow on a REAL-format fixture: IDX files -> reader ->
        feeder -> train step, loss decreases."""
        from paddle_tpu import layers
        from paddle_tpu.data.feeder import DataFeeder

        d = data_home / "mnist"
        d.mkdir()
        rng = np.random.RandomState(1)
        # learnable data: label = brightest quadrant (0..3)
        imgs = np.zeros((64, 28, 28), np.uint8)
        labels = rng.randint(0, 4, 64).astype(np.uint8)
        for i, y in enumerate(labels):
            r0, c0 = (y // 2) * 14, (y % 2) * 14
            imgs[i, r0:r0 + 14, c0:c0 + 14] = 200
        _write_idx(d, imgs, labels, "train")

        img = layers.data(name="img", shape=[784])
        label = layers.data(name="label", shape=[1], dtype="int64")
        logits = layers.fc(img, size=4)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feeder = DataFeeder(feed_list=[img, label])

        reader = datasets.mnist.train()
        losses = []
        for _ in range(6):
            batch = [(x, [int(y)]) for x, y in reader()][:32]
            losses.append(float(exe.run(feed=feeder.feed(batch),
                                        fetch_list=[loss])[0]))
        assert losses[-1] < losses[0]


class TestCifarPickle:
    def _write_tar(self, path, members):
        with tarfile.open(path, "w:gz") as tf:
            for name, obj in members.items():
                raw = pickle.dumps(obj)
                info = tarfile.TarInfo(name)
                info.size = len(raw)
                tf.addfile(info, io.BytesIO(raw))

    def test_cifar10_tar_parses(self, data_home):
        d = data_home / "cifar"
        d.mkdir()
        rng = np.random.RandomState(2)
        data1 = rng.randint(0, 256, (3, 3072)).astype(np.uint8)
        data2 = rng.randint(0, 256, (2, 3072)).astype(np.uint8)
        self._write_tar(d / datasets.cifar.TAR10, {
            "cifar-10-batches-py/data_batch_1":
                {b"data": data1, b"labels": [0, 5, 9]},
            "cifar-10-batches-py/data_batch_2":
                {b"data": data2, b"labels": [2, 7]},
            "cifar-10-batches-py/test_batch":
                {b"data": data1[:1], b"labels": [1]},
        })
        train = list(datasets.cifar.train10()())
        assert len(train) == 5
        x, y = train[0]
        assert x.shape == (3072,) and x.dtype == np.float32
        np.testing.assert_allclose(x, data1[0].astype(np.float32) / 255.0)
        assert [y for _, y in train] == [0, 5, 9, 2, 7]
        test = list(datasets.cifar.test10()())
        assert len(test) == 1 and test[0][1] == 1

    def test_cifar100_fine_labels(self, data_home):
        d = data_home / "cifar"
        d.mkdir()
        data = np.arange(2 * 3072, dtype=np.uint8).reshape(2, 3072)
        self._write_tar(d / datasets.cifar.TAR100, {
            "cifar-100-python/train":
                {b"data": data, b"fine_labels": [42, 99]},
        })
        train = list(datasets.cifar.train100()())
        assert [y for _, y in train] == [42, 99]


class TestImdbText:
    def _write_acl_tar(self, path, reviews):
        with tarfile.open(path, "w:gz") as tf:
            for name, text in reviews.items():
                raw = text.encode()
                info = tarfile.TarInfo(name)
                info.size = len(raw)
                tf.addfile(info, io.BytesIO(raw))

    def test_word_dict_and_readers(self, data_home):
        d = data_home / "imdb"
        d.mkdir()
        self._write_acl_tar(d / "aclImdb_v1.tar.gz", {
            "aclImdb/train/pos/0_9.txt": "great great movie",
            "aclImdb/train/neg/0_2.txt": "bad movie, bad!",
            "aclImdb/test/pos/0_8.txt": "great fun",
            "aclImdb/test/neg/0_3.txt": "awful",
        })
        wd = datasets.imdb.word_dict(min_word_freq=0)
        # train split only: bad/great/movie all freq 2, alphabetical
        # tie-break -> bad=0, great=1, movie=2
        assert wd["bad"] == 0 and wd["great"] == 1 and wd["movie"] == 2
        assert "<unk>" in wd

        train = list(datasets.imdb.train(wd)())
        assert len(train) == 2
        ids, label = train[0]          # sorted order: neg before pos
        assert label == 1              # neg -> 1 (reference convention)
        # "bad movie bad" -> [bad, movie, bad]
        assert ids[0] == wd["bad"] and ids[2] == wd["bad"]
        test = list(datasets.imdb.test(wd)())
        assert len(test) == 2
        # unseen word 'awful' maps to <unk>
        neg_ids = [s for s, l in test if l == 1][0]
        assert neg_ids[0] == wd["<unk>"]


class TestImikolovText:
    def test_ngrams_from_ptb_text(self, data_home):
        d = data_home / "imikolov"
        d.mkdir()
        (d / "ptb.train.txt").write_text(
            "the cat sat\nthe dog sat\n")
        (d / "ptb.valid.txt").write_text("the cat sat\n")
        wd = datasets.imikolov.build_dict(min_word_freq=0)
        assert wd["<s>"] == wd.get("<s>")      # present
        assert wd["the"] < wd["cat"]           # freq 2 before freq 1
        grams = list(datasets.imikolov.train(wd, n=3)())
        # per line: <s> w1 w2 w3 <e> -> 3 trigrams
        assert len(grams) == 6
        assert grams[0] == (wd["<s>"], wd["the"], wd["cat"])
        valid = list(datasets.imikolov.test(wd, n=3)())
        assert len(valid) == 3


class TestDownloadCache:
    def test_download_file_url_md5_and_cache(self, data_home, tmp_path):
        src = tmp_path / "payload.bin"
        src.write_bytes(b"hello dataset")
        md5 = common.md5file(str(src))
        url = "file://" + str(src)
        got = common.download(url, "probe", md5sum=md5)
        assert os.path.exists(got)
        assert open(got, "rb").read() == b"hello dataset"
        # cached: removing the source must not matter
        src.unlink()
        got2 = common.download(url, "probe", md5sum=md5)
        assert got2 == got

    def test_download_md5_mismatch_raises(self, data_home, tmp_path):
        src = tmp_path / "payload2.bin"
        src.write_bytes(b"corrupt")
        with pytest.raises(Exception) as ei:
            common.download("file://" + str(src), "probe2",
                            md5sum="0" * 32, retries=1)
        assert "md5" in str(ei.value) or "download" in str(ei.value)


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-x", "-q"]))
