"""Native C++ training entry (ptpu_train) — VERDICT r3 missing #1.

Exports ONE TRAIN STEP (params+batch in -> params+loss out) via
io.export_train_program, builds native/ptpu_train (TF C API +
XlaCallModule/XLA:CPU), drives K steps from the pure-C++ binary, and pins
the per-step loss trajectory and final parameters against the Python
Executor running the SAME program — the C++-trains-what-Python-trains
parity the reference proves with train/demo/demo_trainer.cc:55-80.
"""

import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native")


@pytest.fixture(scope="session")
def ptpu_train_bin():
    binpath = os.path.join(NATIVE_DIR, "ptpu_train")
    src = os.path.join(NATIVE_DIR, "ptpu_train.cc")
    if (not os.path.exists(binpath)
            or os.path.getmtime(binpath) < os.path.getmtime(src)):
        r = subprocess.run(["sh", "build.sh", "train"], cwd=NATIVE_DIR,
                           capture_output=True, text=True, timeout=600)
        if r.returncode != 0 or not os.path.exists(binpath):
            pytest.skip(f"cannot build ptpu_train: {r.stderr[-800:]}")
    return binpath


def _build_train_model():
    """Small deterministic (dropout-free) regression net with momentum —
    both a parameter and an optimizer accumulator must be carried."""
    x = layers.data(name="x", shape=[8])
    y = layers.data(name="y", shape=[1])
    h = layers.fc(x, size=16, act="relu", name="nt_fc1")
    pred = layers.fc(h, size=1, name="nt_fc2")
    loss = layers.reduce_mean(layers.square(pred - y))
    opt = pt.optimizer.MomentumOptimizer(learning_rate=0.05, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe, loss


def _batch(rng):
    xb = rng.rand(32, 8).astype("float32")
    W = np.random.RandomState(7).randn(8, 1).astype("float32")
    return {"x": xb, "y": (xb @ W).astype("float32")}


class TestNativeTrain:
    def test_export_train_artifacts(self, tmp_path, rng):
        exe, loss = _build_train_model()
        d = str(tmp_path / "train_export")
        pt.io.export_train_program(d, ["x", "y"], [loss])
        assert os.path.exists(os.path.join(d, "__exported_train__.stablehlo"))
        meta = open(os.path.join(d, "__exported_train__.meta")).read()
        assert "in __seed__ int32" in meta
        assert "carry " in meta and "init " in meta
        # every state input has an init file
        for line in meta.splitlines():
            if line.startswith("init "):
                assert os.path.exists(os.path.join(d, line.split()[2]))

    def test_cpp_trains_with_loss_and_param_parity(self, tmp_path, rng,
                                                   ptpu_train_bin):
        exe, loss = _build_train_model()
        feed = _batch(rng)
        d = str(tmp_path / "train_export")
        pt.io.export_train_program(d, ["x", "y"], [loss])

        steps = 5
        # Python reference AFTER export (export reads initial state):
        py_losses = []
        for _ in range(steps):
            out, = exe.run(feed=feed, fetch_list=[loss])
            py_losses.append(float(np.asarray(out).ravel()[0]))
        w_final_py = np.asarray(pt.global_scope().get("nt_fc1.w_0"))

        np.save(tmp_path / "in_x.npy", feed["x"])
        np.save(tmp_path / "in_y.npy", feed["y"])
        r = subprocess.run(
            [ptpu_train_bin, d, str(tmp_path / "in_x.npy"),
             str(tmp_path / "in_y.npy"), "--steps", str(steps),
             "--out", str(tmp_path)],
            capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stderr[-1500:]

        cpp_losses = []
        for line in r.stdout.splitlines():
            parts = line.split()
            if parts and parts[0] == "step":
                cpp_losses.append(float(parts[3]))
        assert len(cpp_losses) == steps, r.stdout
        np.testing.assert_allclose(cpp_losses, py_losses, rtol=1e-5,
                                   atol=1e-7)
        assert cpp_losses[-1] < cpp_losses[0]

        # final parameters match too: find nt_fc1.w_0's state slot
        meta = open(os.path.join(d, "__exported_train__.meta")).read()
        in_names = [ln.split()[1] for ln in meta.splitlines()
                    if ln.startswith("in ")]
        idx = in_names.index("nt_fc1.w_0")
        w_final_cpp = np.load(tmp_path / f"state{idx}.npy")
        np.testing.assert_allclose(w_final_cpp, w_final_py, rtol=1e-5,
                                   atol=1e-6)
