"""Ring attention v2 evidence suite (VERDICT r4 #2).

Three committed claims:
  (a) ring-of-1 is exactly the flash formulation (parity incl. gradients);
  (b) a causal ring executes only the live half of the block grid —
      n(n+1)/2 of n^2 — and segment-disjoint steps are skipped too;
  (c) the forward ring's comm structure is exactly n-1 KV ppermute hops
      (x2 arrays), visible in the compiled HLO.

The pallas kernel path itself is exercised through the interpreter
(backend="pallas_interpret") so the CPU suite pins the same code the TPU
runs, block tilings included.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.ring_attention import (
    ring_attention, ring_attention_live_blocks, ring_attention_sharded)


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def make_mesh(axes):
    n = int(np.prod(list(axes.values())))
    return DeviceMesh(jax.devices()[:n], axes)


def _full_reference(q, k, v, causal, seg=None):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    t, tk = q.shape[1], k.shape[1]
    mask = np.ones((t, tk), bool)
    if causal:
        mask &= np.tril(np.ones((t, tk), bool))
    m = jnp.asarray(mask)[None, None]
    if seg is not None:
        m = m & (seg[:, :, None] == seg[:, None, :])[:, None]
    s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.any(m, axis=-1)[..., None], p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class TestRingFlashParity:
    """(a): the ring's per-block computation IS the flash kernel."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_of_1_matches_flash(self, rng, causal):
        from paddle_tpu.ops.pallas_kernels import flash_attention
        mesh = make_mesh({"sp": 1})
        b, t, h, d = 2, 128, 2, 16
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
                   for _ in range(3))
        out = ring_attention_sharded(mesh, q, k, v, causal=causal,
                                     backend="pallas_interpret")
        # flash_attention runs head-major [B, H, T, D]
        ref = flash_attention(
            jnp.transpose(q, (0, 2, 1, 3)), jnp.transpose(k, (0, 2, 1, 3)),
            jnp.transpose(v, (0, 2, 1, 3)), causal=causal,
            backend="pallas_interpret")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(jnp.transpose(ref, (0, 2, 1, 3))),
            rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("n,causal", [(2, False), (2, True), (4, True)])
    def test_ring_pallas_blocks_match_full_attention(self, rng, n, causal):
        mesh = make_mesh({"sp": n})
        b, t, h, d = 1, 128 * n, 1, 16
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
                   for _ in range(3))
        out = ring_attention_sharded(mesh, q, k, v, causal=causal,
                                     backend="pallas_interpret")
        ref = _full_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_pallas_gradients_match_composite(self, rng):
        """Flash-backward ring (global-residual block bwd + dKV rotation)
        against jax autodiff of the dense reference."""
        mesh = make_mesh({"sp": 2})
        b, t, h, d = 1, 256, 1, 16
        q = jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
        k = jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
        v = jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
        w = jnp.asarray(rng.randn(b, t, h, d).astype("float32"))

        def ring_loss(q, k, v):
            out = ring_attention_sharded(mesh, q, k, v, causal=True,
                                         backend="pallas_interpret")
            return jnp.sum(out * w)

        def ref_loss(q, k, v):
            return jnp.sum(_full_reference(q, k, v, True) * w)

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for gr, gf, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gf), rtol=5e-4, atol=5e-5,
                err_msg=f"d{name} mismatch")

    def test_ring_packed_segments_gradients(self, rng):
        """Backward ring WITH segment ids (seg_blk rotation + segment
        masking inside _block_bwd) against autodiff of the dense
        reference."""
        mesh = make_mesh({"sp": 2})
        b, t, h, d = 1, 256, 1, 16
        q = jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
        k = jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
        v = jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
        w = jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
        seg = jnp.asarray(
            np.repeat(np.arange(1, 5), t // 4)[None], jnp.int32)

        def ring_loss(q, k, v):
            out = ring_attention_sharded(mesh, q, k, v, segment_ids=seg,
                                         backend="pallas_interpret")
            return jnp.sum(out * w)

        def ref_loss(q, k, v):
            return jnp.sum(_full_reference(q, k, v, False, seg) * w)

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for gr, gf, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gf), rtol=5e-4, atol=5e-5,
                err_msg=f"d{name} mismatch")

    def test_live_blocks_sums_over_data_axis(self, rng):
        """Heterogeneous packing across a dp-sharded batch: the live
        count is the MESH total, not one data shard's."""
        mesh = make_mesh({"dp": 2, "sp": 4})
        t = 32
        q = jnp.asarray(rng.randn(2, t, 1, 8).astype("float32"))
        # batch row 0: one segment (all 16 sp-blocks live on that shard);
        # batch row 1: four disjoint per-shard segments (only the 4
        # diagonal steps live)
        seg = jnp.asarray(np.stack([
            np.ones(t), np.repeat(np.arange(1, 5), t // 4)]), jnp.int32)
        _, live = ring_attention_live_blocks(mesh, q, q, q,
                                             segment_ids=seg,
                                             backend="xla")
        assert live == 16 + 4, live

    def test_live_blocks_not_inflated_by_replicated_axes(self, rng):
        """Regression (ADVICE r5 #1): on a dp×tp×sp mesh the live-block
        psum must run only over the axes the body is sharded on (dp, sp);
        summing over the replicated tp axis would double the count."""
        mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
        n = 2
        q = jnp.asarray(rng.randn(2, 8 * n, 1, 8).astype("float32"))
        _, live = ring_attention_live_blocks(mesh, q, q, q, causal=True,
                                             backend="xla")
        # per data shard a causal sp=2 ring executes n(n+1)/2 = 3 of 4
        # steps; dp=2 shards -> 6. The tp=2 replicas must NOT double it.
        assert live == 2 * (n * (n + 1) // 2), live

    def test_ring_packed_segments_pallas(self, rng):
        """Packed segment ids through the flash blocks on the ring."""
        mesh = make_mesh({"sp": 2})
        b, t, h, d = 2, 256, 1, 16
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
                   for _ in range(3))
        seg = np.repeat(np.arange(1, 5), t // 4)[None].repeat(b, 0)
        out = ring_attention_sharded(
            mesh, q, k, v, segment_ids=jnp.asarray(seg, jnp.int32),
            backend="pallas_interpret")
        ref = _full_reference(q, k, v, False, jnp.asarray(seg))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


class TestRingDeadStepSkipping:
    """(b): whole ring steps with no visible keys execute nothing."""

    def test_causal_ring_executes_half_the_blocks(self, rng):
        n = 8
        mesh = make_mesh({"sp": n})
        q = jnp.asarray(rng.randn(1, 8 * n, 1, 8).astype("float32"))
        _, live = ring_attention_live_blocks(mesh, q, q, q, causal=True,
                                             backend="xla")
        assert live == n * (n + 1) // 2          # 36 of 64
        _, live_full = ring_attention_live_blocks(mesh, q, q, q,
                                                  causal=False,
                                                  backend="xla")
        assert live_full == n * n

    def test_segment_disjoint_steps_are_dead(self, rng):
        n = 8
        mesh = make_mesh({"sp": n})
        t = 8 * n
        q = jnp.asarray(rng.randn(1, t, 1, 8).astype("float32"))
        # two macro-segments, each spanning half the shards: shards only
        # compute against same-half KV blocks -> 2 * (n/2)^2 live steps
        seg = jnp.asarray(
            np.repeat([1, 2], t // 2)[None], jnp.int32)
        out, live = ring_attention_live_blocks(mesh, q, q, q,
                                               segment_ids=seg,
                                               backend="xla")
        assert live == 2 * (n // 2) ** 2         # 32 of 64
        ref = _full_reference(q, q, q, False, seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_skipping_changes_nothing_numerically(self, rng):
        """Causal output with skipping == dense reference (the dead steps
        contributed exactly nothing)."""
        n = 8
        mesh = make_mesh({"sp": n})
        q = jnp.asarray(rng.randn(2, 8 * n, 2, 8).astype("float32"))
        out, _ = ring_attention_live_blocks(mesh, q, q, q, causal=True,
                                            backend="xla")
        ref = _full_reference(q, q, q, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


class TestRingCommStructure:
    """(c): exactly n-1 KV rotation hops in the forward ring HLO."""

    def _count_collective_permutes(self, fn, *args):
        ex = jax.jit(fn).lower(*args).compile()
        hlo = ex.as_text()
        starts = len(re.findall(r"collective-permute-start", hlo))
        if starts:
            return starts
        return len(re.findall(r"= \S+ collective-permute\(", hlo))

    def test_forward_ring_has_n_minus_1_kv_hops(self, rng):
        n = 8
        mesh = make_mesh({"sp": n})
        q = jnp.asarray(rng.randn(1, 8 * n, 1, 8).astype("float32"))

        def fwd(q):
            return ring_attention_sharded(mesh, q, q, q, causal=True,
                                          backend="xla")

        count = self._count_collective_permutes(fwd, q)
        # k and v each take n-1 hops; XLA may fuse the pair into one
        # collective-permute per hop but must not exceed 2(n-1)
        assert n - 1 <= count <= 2 * (n - 1), count

    def test_backward_ring_comm_volume(self, rng):
        n = 4
        mesh = make_mesh({"sp": n})
        q = jnp.asarray(rng.randn(1, 8 * n, 1, 8).astype("float32"))

        def loss(q):
            return ring_attention_sharded(mesh, q, q, q, causal=True,
                                          backend="xla").sum()

        count = self._count_collective_permutes(jax.grad(loss), q)
        # fwd ring: 2(n-1) (k, v) + bwd ring: 2(n-1) (k, v) + 2n (dk, dv);
        # allow pairwise fusion down to half
        upper = 4 * (n - 1) + 2 * n
        assert upper // 2 <= count <= upper, count
