"""Continuous-batching serving engine: slot-indexed KV cache + scheduler +
transport (ISSUE r7 tentpole).

Covers the slot-cache contract end to end:
- per-slot `cache_write(batch_axis=...)` parity against a per-row numpy
  reference (the uniform-`Pos` limitation closed for real);
- SlotAllocator alloc/evict/reuse invariants;
- decode-sequence identity when a request joins mid-batch and when a slot
  is REUSED with a stale cache (no reset needed — masked positions prove
  it);
- greedy-identity of the engine's tick loop against the scan-based
  `transformer_lm_generate` on shared weights;
- the engine tick compiles through the r06 fused decode path (structure
  assert: fuse_decode_attention_pass rewrites its attention chains);
- EngineServer/EngineClient RPC incl. pipelined completion reordering;
- transport v2 framing (vectored multi-part frames, pooled recv buffers).
"""

import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                       EngineClient, EngineServer,
                                       SlotAllocator)

pytestmark = pytest.mark.quick

_ENG_DIMS = dict(vocab=50, max_len=16, d_model=32, d_inner=64,
                 num_heads=4, num_layers=2)


@pytest.fixture(scope="module")
def shared_eng():
    """One compiled 3-slot engine shared by the scheduler/RPC tests
    (every test drains it; the tick compile is the expensive part)."""
    return ContinuousBatchingEngine(n_slots=3, **_ENG_DIMS)


def _cache_write_slots_ref(cache, new, pos, axis, batch_axis):
    """Per-row numpy reference: row b (along batch_axis) written at its
    own position pos[b] along axis."""
    out = cache.copy()
    pos = pos.reshape(-1).astype(np.int64)
    for b in range(cache.shape[batch_axis]):
        idx = [slice(None)] * cache.ndim
        idx[batch_axis] = b
        row_idx = list(idx)
        row_idx[axis] = slice(int(pos[b]), int(pos[b]) + new.shape[axis])
        out[tuple(row_idx)] = new[tuple(idx)].reshape(
            out[tuple(row_idx)].shape)
    return out


class TestPerSlotCacheWrite:
    def _run(self, cache, new, pos, axis, batch_axis):
        c = layers.data(name="c", shape=list(cache.shape), dtype="float32",
                        append_batch_size=False)
        n = layers.data(name="n", shape=list(new.shape), dtype="float32",
                        append_batch_size=False)
        p = layers.data(name="p", shape=list(pos.shape), dtype="float32",
                        append_batch_size=False)
        out = layers.cache_write(c, n, p, axis=axis, batch_axis=batch_axis)
        exe = pt.Executor()
        return exe.run(feed={"c": cache, "n": new, "p": pos},
                       fetch_list=[out])[0]

    def test_parity_vs_numpy(self, rng):
        S, nh, T, dh = 5, 3, 8, 4
        cache = rng.randn(S, nh, T, dh).astype("float32")
        new = rng.randn(S, nh, 1, dh).astype("float32")
        pos = rng.randint(0, T, (S,)).astype("float32")
        got = self._run(cache, new, pos, axis=2, batch_axis=0)
        ref = _cache_write_slots_ref(cache, new, pos, axis=2, batch_axis=0)
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_parity_5d_decode_layout(self, rng):
        """The engine's actual [S,1,nh,T,dh] layout, axis=3."""
        S, nh, T, dh = 4, 2, 6, 4
        cache = rng.randn(S, 1, nh, T, dh).astype("float32")
        new = rng.randn(S, 1, nh, 1, dh).astype("float32")
        pos = np.array([0, 5, 2, 2], "float32")
        got = self._run(cache, new, pos, axis=3, batch_axis=0)
        ref = _cache_write_slots_ref(cache, new, pos, axis=3, batch_axis=0)
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_uniform_mode_unchanged(self, rng):
        """batch_axis=None keeps the old single-position semantics."""
        cache = rng.randn(3, 8, 4).astype("float32")
        new = rng.randn(3, 1, 4).astype("float32")
        pos = np.full((3,), 5.0, "float32")
        c = layers.data(name="c", shape=[3, 8, 4], dtype="float32",
                        append_batch_size=False)
        n = layers.data(name="n", shape=[3, 1, 4], dtype="float32",
                        append_batch_size=False)
        p = layers.data(name="p", shape=[3], dtype="float32",
                        append_batch_size=False)
        out = layers.cache_write(c, n, p, axis=1)
        exe = pt.Executor()
        got = exe.run(feed={"c": cache, "n": new, "p": pos},
                      fetch_list=[out])[0]
        ref = cache.copy()
        ref[:, 5:6, :] = new
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_wrong_pos_length_raises(self, rng):
        with pytest.raises(Exception, match="per-slot Pos"):
            self._run(rng.randn(4, 8, 2).astype("float32"),
                      rng.randn(4, 1, 2).astype("float32"),
                      np.zeros((3,), "float32"), axis=1, batch_axis=0)


class TestSlotAllocator:
    def test_alloc_evict_reuse(self):
        a = SlotAllocator(3)
        s = [a.alloc() for _ in range(3)]
        assert sorted(s) == [0, 1, 2]
        assert a.alloc() is None          # exhausted
        assert a.n_free == 0 and a.n_used == 3
        a.free(s[1])
        assert a.n_free == 1
        assert a.alloc() == s[1]          # freed slot is reusable
        with pytest.raises(Exception):
            a.free(99)                    # never allocated
        a.free(s[0])
        with pytest.raises(Exception):
            a.free(s[0])                  # double free

    def test_engine_slot_lifecycle(self, shared_eng):
        eng = shared_eng
        r1 = eng.submit([1], max_new=6)
        r2 = eng.submit([2], max_new=2)
        r3 = eng.submit([3], max_new=2)
        r4 = eng.submit([4], max_new=2)   # must wait for a slot
        eng.step()
        assert eng.n_active == 3 and eng.n_pending == 1
        done = eng.run_until_idle()
        assert {r.rid for r in done} == {r1.rid, r2.rid, r3.rid, r4.rid}
        assert eng._slots.n_free == 3     # all evicted on completion
        assert [len(r.tokens) for r in (r1, r2, r3, r4)] == [6, 2, 2, 2]


def _solo(eng, prompt, max_new):
    """Run one request ALONE to completion on a drained engine: the
    interference-free reference sequence for those weights."""
    assert eng.n_active == 0 and eng.n_pending == 0
    req = eng.submit(prompt, max_new=max_new)
    eng.run_until_idle()
    return list(req.tokens)


class TestDecodeIdentity:
    def test_mid_batch_join_identity(self, shared_eng):
        """A request admitted INTO an in-flight batch must decode the
        exact sequence it decodes alone — slot independence is the whole
        slot-cache contract."""
        eng = shared_eng
        solo = [_solo(eng, p, 8) for p in ([7], [3, 9], [11])]

        long_req = eng.submit([7], max_new=8)
        for _ in range(3):                 # long request mid-flight
            eng.step()
        join1 = eng.submit([3, 9], max_new=8)
        eng.step()
        join2 = eng.submit([11], max_new=8)
        eng.run_until_idle()
        assert long_req.tokens == solo[0]
        assert join1.tokens == solo[1]
        assert join2.tokens == solo[2]

    def test_slot_reuse_no_cache_reset(self):
        """A slot reused after eviction carries a STALE cache from the
        previous tenant; the per-slot position mask must make it
        invisible (prefill rewrites rows before exposing them). Needs a
        FRESH single-slot engine: the reference run must see a provably
        clean (zero-initialized) cache."""
        eng = ContinuousBatchingEngine(n_slots=1, **_ENG_DIMS)
        fresh = _solo(eng, [5, 8], 6)          # clean zero cache
        first = eng.submit([13], max_new=10)   # pollute the slot cache
        eng.run_until_idle()
        assert len(first.tokens) == 10
        again = eng.submit([5, 8], max_new=6)  # same slot, stale rows
        eng.run_until_idle()
        assert again.tokens == fresh

    def test_identity_vs_scan_generator(self):
        """Engine tick loop == transformer_lm_generate greedy on shared
        weights: the continuous-batching path changes scheduling, not
        math."""
        from paddle_tpu.core import unique_name
        from paddle_tpu.framework.program import program_guard
        from paddle_tpu.models import transformer

        G = 6
        dims = _ENG_DIMS
        gen_prog, gen_startup = pt.Program(), pt.Program()
        with program_guard(gen_prog, gen_startup), unique_name.guard():
            seqs, _ = transformer.transformer_lm_generate(
                vocab=dims["vocab"], max_gen=G, d_model=dims["d_model"],
                d_inner=dims["d_inner"], num_heads=dims["num_heads"],
                num_layers=dims["num_layers"], beam_size=1, eos_id=-1)
        exe = pt.Executor()
        exe.run(gen_startup)
        prompts = np.array([[4], [17], [29]], "int64")
        out = exe.run(program=gen_prog, feed={"prompt": prompts},
                      fetch_list=[seqs])[0]          # [B, G, 1]

        eng = ContinuousBatchingEngine(n_slots=3, scope=pt.global_scope(),
                                       **dims)
        reqs = [eng.submit([int(p[0])], max_new=G) for p in prompts]
        eng.run_until_idle()
        for b, req in enumerate(reqs):
            assert req.tokens == out[b, :, 0].astype(int).tolist(), b

    def test_tick_compiles_through_fused_decode(self, shared_eng):
        """Structure assert (the TPU kernel claim's CPU-checkable half):
        the engine's tick program rewrites every per-layer attention
        chain into fused_decode_attention, and its cache writes are the
        per-slot (batch_axis) form."""
        from paddle_tpu.framework.passes import apply_fusion_passes

        eng = shared_eng
        rewritten = apply_fusion_passes(
            eng._program, protected={eng._next_ids.name})
        ops = [op.type for op in rewritten.global_block().ops]
        assert ops.count("fused_decode_attention") == \
            _ENG_DIMS["num_layers"]
        assert ops.count("softmax") == 0
        cw = [op for op in rewritten.global_block().ops
              if op.type == "cache_write"]
        assert len(cw) == 2 * _ENG_DIMS["num_layers"]
        assert all(op.attrs.get("batch_axis") == 0 for op in cw)
        # and the cache write-back targets the persistable slot caches
        for op in cw:
            assert op.outputs["Out"][0] in eng.cache_names

    def test_static_policy_drains_before_refill(self):
        eng = ContinuousBatchingEngine(n_slots=2, policy="static",
                                       **_ENG_DIMS)
        short = eng.submit([1], max_new=2)
        eng.submit([2], max_new=6)
        late = eng.submit([3], max_new=2)
        eng.step()
        assert eng.n_active == 2 and late.slot is None
        # the short batch member finishes early, but static batching must
        # NOT backfill its freed slot until the WHOLE batch drains
        while eng.n_active:
            eng.step()
            if eng.n_active:
                assert late.slot is None
        assert short.done and late.slot is None
        eng.run_until_idle()
        assert len(late.tokens) == 2


class TestEngineServer:
    def test_rpc_roundtrip_and_pipelining(self, shared_eng):
        solo = _solo(shared_eng, [7], 4)
        with EngineServer(shared_eng) as srv:
            host, port = srv.address
            with EngineClient(host, port) as c:
                assert c.generate([7], max_new=4) == solo
                # pipelined: short request admitted mid-flight overtakes
                t_long = c.send_gen([1], max_new=10)
                t_short = c.send_gen([2], max_new=2)
                done = [c.recv_done() for _ in range(2)]
                tags = [d[0] for d in done]
                assert set(tags) == {t_long, t_short}
                by_tag = {d[0]: d[1] for d in done}
                assert len(by_tag[t_long]) == 10
                assert len(by_tag[t_short]) == 2

    def test_oversized_request_errors_cleanly(self, shared_eng):
        with EngineServer(shared_eng) as srv:
            host, port = srv.address
            with EngineClient(host, port) as c:
                c.send_gen(list(range(10)), max_new=100)
                with pytest.raises(RuntimeError, match="server error"):
                    c.recv_done()
                # connection still serves after the rejected request
                assert len(c.generate([3], max_new=2)) == 2


class TestPreparedStep:
    def test_batch_row_mask_injected_per_call(self, rng):
        """A prepared program declaring the reserved batch-row mask must
        keep working when callers feed only their own vars — prepare()
        synthesized the mask into the compiled signature, run() must
        re-inject it (regression: KeyError on every prepared call)."""
        x = layers.data(name="x", shape=[6])
        mask = layers.batch_row_mask()
        per_ex = layers.reduce_sum(layers.fc(x, size=3), dim=[1])
        loss = layers.reduce_sum(layers.elementwise_mul(per_ex, mask)) \
            / layers.reduce_sum(mask)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"x": rng.rand(4, 6).astype("float32")}
        direct = exe.run(feed=dict(feed), fetch_list=[loss])[0]
        prep = exe.prepare(pt.default_main_program(), dict(feed), [loss])
        got = prep.run(dict(feed), return_numpy=True)[0]
        np.testing.assert_allclose(got, direct, rtol=1e-6)
        prep.run(dict(feed))                      # and again: no KeyError

    def test_seed_stream_matches_executor_run(self, rng):
        """PreparedStep must draw from the SAME (program.random_seed,
        run-counter) stream as Executor.run — dropout reproducibility is
        part of the prepared contract (regression: different formula)."""
        x = layers.data(name="x", shape=[32])
        y = layers.dropout(layers.fc(x, size=32, name="ps_fc"),
                           dropout_prob=0.5)
        out = layers.reduce_sum(y, dim=[1])
        pt.default_main_program().random_seed = 7
        pt.Executor().run(pt.default_startup_program())
        feed = {"x": rng.rand(4, 32).astype("float32")}
        # fresh executors so both run counters sit at 0: run call #1 and
        # prepared call #1 must draw the same seed
        a = pt.Executor().run(feed=dict(feed), fetch_list=[out])[0]
        prep = pt.Executor().prepare(pt.default_main_program(),
                                     dict(feed), [out])
        b = prep.run(dict(feed), return_numpy=True)[0]
        np.testing.assert_allclose(a, b, rtol=1e-6)


class TestTransportV2:
    def test_vectored_frame_roundtrip_multi_tensor(self, tmp_path, rng):
        """Multi-feed/multi-fetch predictor through the v2 transport:
        vectored frames + pooled recv + batched writer, values exact."""
        from paddle_tpu.serving import PredictorClient, PredictorServer

        class Echo:
            fetch_names = ["a", "b"]

            def run(self, feed, fetch_names=None, return_numpy=True):
                return [np.ascontiguousarray(feed["a"]) * 2,
                        np.ascontiguousarray(feed["b"]) + 1]

        a = rng.randn(16, 32).astype("float32")
        b = rng.randint(0, 9, (8, 3)).astype("int64")
        with PredictorServer(Echo()) as srv:
            host, port = srv.address
            with PredictorClient(host, port) as c:
                # pipeline several to exercise the batched writer
                for _ in range(6):
                    c.send({"a": a, "b": b})
                for _ in range(6):
                    ra, rb = c.recv()
                    np.testing.assert_allclose(ra, a * 2, rtol=1e-6)
                    np.testing.assert_array_equal(rb, b + 1)

    def test_recv_pool_grows_and_recycles(self):
        from paddle_tpu.serving import _RecvBufferPool

        pool = _RecvBufferPool(2)
        b1 = pool.acquire(100)
        b2 = pool.acquire(10)
        assert len(b1) >= 100 and len(b2) >= 10
        assert pool.acquire(5, timeout=0.05) is None   # both in flight
        pool.release(b1)
        b3 = pool.acquire(50)
        assert b3 is b1                                # reused, big enough
        pool.release(b2)
        pool.release(b3)

    def test_byte_views_zero_copy(self, rng):
        from paddle_tpu.serving import _byte_views

        arr = rng.randn(4, 4).astype("float32")
        views = _byte_views([b"hdr", arr, b""])
        assert len(views) == 2                         # empty part dropped
        assert bytes(views[1]) == arr.tobytes()

    def test_threads_unwound_after_connection(self, rng):
        """The reader/worker/writer trio must fully unwind per closed
        connection (regression guard for the new writer thread)."""
        import time

        from paddle_tpu.serving import PredictorClient, PredictorServer

        class Echo:
            fetch_names = ["x"]

            def run(self, feed, fetch_names=None, return_numpy=True):
                return [np.ascontiguousarray(feed["x"])]

        x = np.ones((4,), "float32")
        with PredictorServer(Echo()) as srv:
            host, port = srv.address
            before = threading.active_count()
            with PredictorClient(host, port) as c:
                c.infer({"x": x})
            deadline = time.time() + 15
            while time.time() < deadline:
                if threading.active_count() <= before:
                    break
                time.sleep(0.1)
            assert threading.active_count() <= before


class TestGracefulDrain:
    """EngineServer SIGTERM drain (the robustness satellite): stop
    admitting, finish in-flight generations, flush the writer threads,
    exit cleanly."""

    def test_drain_idle_server_immediate(self, shared_eng):
        srv = EngineServer(shared_eng).start()
        assert srv.drain(timeout=10) is True
        assert srv._stop.is_set()

    def test_sigterm_finishes_in_flight_and_rejects_new(self, shared_eng):
        import os
        import signal
        import time

        srv = EngineServer(shared_eng).start()
        srv.install_sigterm_handler(exit_process=False)
        try:
            with EngineClient(*srv.address) as c:
                tag = c.send_gen([5], max_new=12)
                deadline = time.time() + 10
                while (shared_eng.n_active == 0
                       and shared_eng.n_pending == 0):
                    assert time.time() < deadline, "never admitted"
                    time.sleep(0.005)
                os.kill(os.getpid(), signal.SIGTERM)
                while not srv._draining.is_set():
                    assert time.time() < deadline, "drain never started"
                    time.sleep(0.005)
                # new work is rejected with an explicit draining error...
                c.send_gen([6], max_new=2)
                with pytest.raises(RuntimeError, match="draining"):
                    c.recv_done()
                # ...while the in-flight generation completes in full and
                # its frame is flushed before the socket closes
                got_tag, tokens, _ = c.recv_done()
                assert got_tag == tag
                assert len(tokens) == 12
            deadline = time.time() + 15
            while not srv._stop.is_set():
                assert time.time() < deadline, "drain never shut down"
                time.sleep(0.01)
            assert shared_eng.n_active == 0 and shared_eng.n_pending == 0
        finally:
            if srv._prev_sigterm is not None:
                signal.signal(signal.SIGTERM, srv._prev_sigterm)
            srv.shutdown()
