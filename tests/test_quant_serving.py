"""Weight-only quantized serving + zero-dispatch bound tick (ISSUE r21
tentpole).

Covers the quantized-serving contract end to end:
- 2-D block quantization numpy parity: per-tile symmetric scales bound the
  elementwise error at scale/2, int4 nibble pack/unpack roundtrips exactly,
  `block_dims_2d` fits non-multiple shapes without padding;
- `quantize_params_pass` structure: lookup_table/mul consumers rewritten
  1:1 to qlookup/qmatmul, payload+scale pairs declared and set, the f32
  weight erased from scope AND block; outputs within a stated bound of the
  f32 program (<=2% of output scale at int8, <=20% at int4);
- quantized fused_decode_attention: time-blocked int8 KV caches through
  KScale/VScale match the f32 kernel within a stated bound;
- greedy decode parity: the int8 engine is token-identical to the f32
  engine on shared weights; int4 may diverge — bounded by a stated
  matching prefix (after the first divergence trajectories legitimately
  differ, so only the prefix is comparable);
- paged+quantized composition: PagedKVEngine over quantized weights is
  token-identical to the quantized slot engine, CoW forks over the paged
  engine's block tables stay isolated (mutating a fork's copy never
  reaches the parent block), and the pool drains leak-free;
- zero-dispatch binding: bind()/run_bound() reproduces plain prepared
  run() exactly — including the dropout seed stream and when bound and
  plain calls INTERLEAVE on one PreparedStep (the paged beam-search
  pattern) — and the engine's dispatch histogram + "dispatch" span record;
- kill switch: PTPU_QUANT_PARAMS=0 keeps the engine f32 (no rewrite, no
  freed bytes) and the flag is part of the executor's compile cache key;
- census reconciliation: predicted params_quantized == measured census ==
  hand-summed payload+scale bytes, and the engine's params-bytes ratio
  clears the ISSUE floors (>=2x int8, >=3.5x int4).
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.parallel.collective import (QUANT_BLOCK_2D, block_dims_2d,
                                            dequantize_blocks_2d, pack_int4,
                                            quantize_blocks_2d, unpack_int4)
from paddle_tpu.serving import ContinuousBatchingEngine, PagedKVEngine

pytestmark = pytest.mark.quick

_DIMS = dict(vocab=50, max_len=16, d_model=32, d_inner=64, num_heads=4,
             num_layers=2)


def _weights(eng):
    """Names of the engine program's trainable persistables (the vars the
    quantize pass may erase from the shared scope)."""
    names = []
    for b in eng._program.blocks:
        for name, v in b.vars.items():
            if v.persistable and getattr(v, "trainable", False):
                names.append(name)
    return names


@pytest.fixture(scope="module")
def quant_engines():
    """f32 + int8 + int4 slot engines and an int8 paged engine on ONE
    scope with the SAME weights. The quantize pass erases the f32 weights
    from the scope, so they are snapshotted after the f32 engine builds
    and restored before each further quantized engine quantizes them."""
    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()
    f32 = ContinuousBatchingEngine(n_slots=3, scope=scope,
                                   cache_prefix="qs_f32", **_DIMS)
    snap = {n: np.asarray(scope.get(n)) for n in _weights(f32)}

    def restore():
        for n, w in snap.items():
            scope.set_var(n, w)

    q8 = ContinuousBatchingEngine(n_slots=3, scope=scope,
                                  cache_prefix="qs_q8", quant="int8",
                                  **_DIMS)
    restore()
    q4 = ContinuousBatchingEngine(n_slots=3, scope=scope,
                                  cache_prefix="qs_q4", quant="int4",
                                  **_DIMS)
    restore()
    p8 = PagedKVEngine(n_slots=3, block_size=4, topk_k=3, scope=scope,
                       cache_prefix="qs_p8", quant="int8", **_DIMS)
    return f32, q8, q4, p8


def _gen(eng, prompts, max_new=6):
    reqs = [eng.submit(list(p), max_new=max_new) for p in prompts]
    eng.run_until_idle()
    assert all(r.done for r in reqs)
    return [list(r.tokens) for r in reqs]


_PROMPTS = ([7], [3, 9], [11, 2, 5])


class TestBlockQuant:
    @pytest.mark.parametrize("shape", [(64, 128), (100, 32), (7, 10)])
    @pytest.mark.parametrize("bits", [8, 4])
    def test_roundtrip_error_bounded_per_tile(self, rng, shape, bits):
        """Symmetric rounding keeps |w - deq(q)| <= scale/2 elementwise,
        with the scale of the tile the element lives in — verified
        against a pure-numpy re-derivation of the tile scales."""
        if bits == 4 and shape[1] % 2:
            pytest.skip("int4 requires even columns")
        w = rng.randn(*shape).astype("float32")
        q, sc = quantize_blocks_2d(w, bits=bits)
        deq = np.asarray(dequantize_blocks_2d(q, sc, bits=bits))
        br, bc = block_dims_2d(shape)
        tiles = w.reshape(shape[0] // br, br, shape[1] // bc, bc)
        amax = np.abs(tiles).max(axis=(1, 3))
        qmax = 127.0 if bits == 8 else 7.0
        ref_scale = np.where(amax > 0, amax / qmax, 1.0)
        np.testing.assert_allclose(np.asarray(sc), ref_scale, rtol=1e-6)
        bound = np.repeat(np.repeat(ref_scale, br, 0), bc, 1) / 2
        assert (np.abs(w - deq) <= bound + 1e-6).all()

    def test_int4_pack_unpack_exact(self, rng):
        q = rng.randint(-7, 8, (13, 12)).astype(np.int8)
        packed = np.asarray(pack_int4(q))
        assert packed.shape == (13, 6) and packed.dtype == np.int8
        np.testing.assert_array_equal(np.asarray(unpack_int4(packed)), q)
        # numpy parity: byte k holds columns (2k, 2k+1) as (low, high)
        ref = (q[:, 0::2] & 0x0F) | (q[:, 1::2].astype(np.int16) << 4)
        np.testing.assert_array_equal(packed, ref.astype(np.int8))

    def test_block_dims_fit_without_padding(self):
        assert block_dims_2d((1000, 64)) == (50, 64)
        assert block_dims_2d((128, 128)) == (QUANT_BLOCK_2D, QUANT_BLOCK_2D)
        assert block_dims_2d((7, 10)) == (7, 10)


def _build_embed_fc(rng, vocab=40, d=32):
    ids = layers.data(name="ids", shape=[1], dtype="int64")
    emb = layers.embedding(ids, size=[vocab, d])
    h = layers.fc(emb, size=48, act="relu")
    out = layers.fc(h, size=16)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"ids": rng.randint(0, vocab, (6, 1)).astype("int64")}
    return exe, feed, out


class TestQuantizeParamsPass:
    @pytest.mark.parametrize("bits,rel_bound", [(8, 0.02), (4, 0.20)])
    def test_rewrite_structure_and_error_bound(self, rng, bits, rel_bound):
        from paddle_tpu.framework.passes import get_pass
        exe, feed, out = _build_embed_fc(rng)
        ref = exe.run(feed=feed, fetch_list=[out])[0]
        prog = pt.default_main_program()
        f32_weights = [n for n, v in prog.global_block().vars.items()
                       if v.persistable and getattr(v, "trainable", False)
                       and len(v.shape or ()) == 2]
        assert len(f32_weights) == 3           # embedding + two fc weights
        get_pass("quantize_params_pass", bits=bits)(prog, pt.global_scope())
        ops = [op.type for op in prog.global_block().ops]
        assert "qlookup" in ops and ops.count("qmatmul") == 2
        assert "lookup_table" not in ops and "mul" not in ops
        blk = prog.global_block()
        for w in f32_weights:
            assert not blk.has_var(w)                 # f32 weight erased
            assert not pt.global_scope().has_var(w)
            assert blk.has_var(w + "@qparam")
            assert blk.var(w + "@qparam").dtype == "int8"
            assert blk.has_var(w + "@qscale")
        got = exe.run(feed=feed, fetch_list=[out])[0]
        err = np.abs(got - ref).max()
        assert err <= rel_bound * np.abs(ref).max(), err

    def test_biases_and_written_vars_left_f32(self, rng):
        """Only 2-D read-only mul.Y / lookup_table.W weights quantize:
        1-D biases stay, and anything an op WRITES is ineligible."""
        from paddle_tpu.framework.passes import get_pass
        x = layers.data(name="x", shape=[8])
        y = layers.fc(x, size=4)
        layers.reduce_sum(y, dim=[1])
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        prog = pt.default_main_program()
        get_pass("quantize_params_pass", bits=8)(prog, pt.global_scope())
        blk = prog.global_block()
        biases = [n for n, v in blk.vars.items()
                  if v.persistable and getattr(v, "trainable", False)
                  and len(v.shape or ()) == 1]
        assert biases                                  # the fc bias
        assert all(not blk.has_var(b + "@qparam") for b in biases)


class TestQuantizedDecodeAttention:
    def test_kv_time_block_roundtrip_and_fused_parity(self, rng):
        from paddle_tpu.fusion.decode_attention import (
            dequantize_kv_time_blocks, fused_decode_attention,
            quantize_kv_time_blocks)
        B, nh, T, dh = 3, 4, 24, 16
        q = rng.randn(B, nh, 1, dh).astype("float32")
        k = rng.randn(B, nh, T, dh).astype("float32")
        v = rng.randn(B, nh, T, dh).astype("float32")
        bias = np.where(np.arange(T) < 17, 0.0, -1e30).astype(
            "float32").reshape(1, 1, 1, T)
        kq, ksc = quantize_kv_time_blocks(k)
        assert kq.dtype == np.int8 and kq.shape == k.shape
        assert ksc.shape == (B, nh, 3)                 # T=24 / bt=8
        rt = np.asarray(dequantize_kv_time_blocks(kq, ksc))
        assert np.abs(rt - k).max() <= np.abs(k).max() / 127 / 2 + 1e-6
        vq, vsc = quantize_kv_time_blocks(v)
        ref = np.asarray(fused_decode_attention(q, k, v, bias, scale=0.25))
        got = np.asarray(fused_decode_attention(
            q, kq, vq, bias, scale=0.25, k_scale=ksc, v_scale=vsc))
        # int8 cache error stays a small fraction of the output scale
        assert np.abs(got - ref).max() <= 0.05 * np.abs(ref).max()


class TestGreedyDecodeParity:
    def test_int8_token_identical(self, quant_engines):
        """int8 weight error (~0.4% of the per-tile amax) does not move
        any argmax on this model: token-for-token identity is the int8
        contract here."""
        f32, q8, _, _ = quant_engines
        assert _gen(q8, _PROMPTS) == _gen(f32, _PROMPTS)

    def test_int4_bounded_divergence(self, quant_engines):
        """int4 (~4% weight error) may flip a near-tie argmax on this
        UNTRAINED random model; the stated bound: every sequence matches
        f32 on its FIRST greedy token. Beyond the first divergence the
        trajectories condition on different tokens and are legitimately
        incomparable token-wise — the bench artifact (BENCH_QSERVE)
        quantifies the rest as max first-tick logit error."""
        f32, _, q4, _ = quant_engines
        ref = _gen(f32, _PROMPTS)
        got = _gen(q4, _PROMPTS)
        for r, g in zip(ref, got):
            assert r[:1] == g[:1], (r, g)

    def test_freed_bytes_accounted(self, quant_engines):
        _, q8, q4, p8 = quant_engines
        for eng in (q8, q4, p8):
            assert eng.quant_freed_bytes > 0
            assert (eng.params_bytes_f32 - eng.params_bytes_quantized
                    == eng.quant_freed_bytes)


class TestPagedQuantComposition:
    def test_paged_matches_slot_engine_quantized(self, quant_engines):
        _, q8, _, p8 = quant_engines
        assert _gen(p8, _PROMPTS) == _gen(q8, _PROMPTS)

    def test_cow_fork_isolated_over_quantized_weights(self, quant_engines):
        """CoW forks over the quantized engine's block tables: mutating
        the fork's copied block must not reach the parent's physical
        block (the r20 mutation pin, now over a quantized tick)."""
        *_, p8 = quant_engines
        assert p8.n_active == 0 and p8.n_pending == 0
        pager = p8.pager
        pager.index.evict_all(pager.pool)          # deterministic pool
        t1 = pager.try_admit(list(range(1, 9)), 12)   # 3 blocks
        assert t1 is not None and len(t1.blocks) == 3
        name = p8.cache_names[0]
        a = np.array(p8.scope.get(name))
        a[t1.blocks[1]] = 7.0                      # sentinel in the partial
        p8.scope.set_var(name, a)
        t2 = pager.fork(t1, 6, p8._copy_block)     # 1 full + 2 in part
        assert t2.blocks[0] == t1.blocks[0]        # full block SHARED
        assert t2.blocks[1] != t1.blocks[1]        # divergence COPIED
        a = np.array(p8.scope.get(name))
        a[t2.blocks[1]] = -3.0                     # mutate the fork's copy
        p8.scope.set_var(name, a)
        a = np.array(p8.scope.get(name))
        assert float(a[t1.blocks[1]].min()) == 7.0    # parent untouched
        pager.release(t1)
        pager.release(t2)
        pager.pool.check()

    def test_pool_drains_leak_free(self, quant_engines):
        *_, p8 = quant_engines
        _gen(p8, _PROMPTS, max_new=4)
        pager = p8.pager
        pager.pool.check()
        pager.index.evict_all(pager.pool)
        assert pager.pool.n_used == 0
        pager.pool.check()


class TestZeroDispatchBinding:
    def _prep(self, rng):
        x = layers.data(name="x", shape=[16])
        h = layers.dropout(layers.fc(x, size=16, name="zd_fc"),
                           dropout_prob=0.5)
        out = layers.reduce_sum(h, dim=[1])
        pt.default_main_program().random_seed = 11
        pt.Executor().run(pt.default_startup_program())
        feed = {"x": rng.rand(4, 16).astype("float32")}
        return feed, out

    def test_run_bound_matches_plain_run_and_seed_stream(self, rng):
        """bind()/run_bound() must replay the exact (program.random_seed,
        run-counter) stream plain run() draws from, tick after tick —
        including when the caller mutates the bound feed IN PLACE."""
        feed, out = self._prep(rng)
        pa = pt.Executor().prepare(pt.default_main_program(),
                                   dict(feed), [out])
        pb = pt.Executor().prepare(pt.default_main_program(),
                                   dict(feed), [out])
        bound_feed = {"x": feed["x"].copy()}
        pb.bind(bound_feed)
        for tick in range(3):
            a = pa.run(dict(feed), return_numpy=True)[0]
            b = np.asarray(pb.run_bound()[0])
            np.testing.assert_allclose(a, b, rtol=1e-6, err_msg=str(tick))
            feed["x"] += 0.25                  # next tick: new feed values
            bound_feed["x"] += 0.25            # mutated in place, no rebind

    def test_bound_and_plain_calls_interleave(self, rng):
        """The paged beam-search pattern: plain run() calls on a step
        whose rw buffers a binding donated must refresh the binding (the
        stale donated arrays are dead) — the interleaved sequence equals
        a pure plain-run sequence drawing the same seed stream."""
        feed, out = self._prep(rng)
        ref_p = pt.Executor().prepare(pt.default_main_program(),
                                      dict(feed), [out])
        mix_p = pt.Executor().prepare(pt.default_main_program(),
                                      dict(feed), [out])
        mix_p.bind({"x": feed["x"].copy()})
        ref = [ref_p.run(dict(feed), return_numpy=True)[0]
               for _ in range(3)]
        mix = [np.asarray(mix_p.run_bound()[0]),
               mix_p.run(dict(feed), return_numpy=True)[0],
               np.asarray(mix_p.run_bound()[0])]
        for r, m in zip(ref, mix):
            np.testing.assert_allclose(r, m, rtol=1e-6)

    def test_engine_dispatch_histogram_and_span(self, quant_engines):
        from paddle_tpu.observability import tracing
        f32, *_ = quant_engines
        prev = flags.get_flag("trace")
        flags.set_flag("trace", True)
        try:
            m = tracing.mark()
            _gen(f32, ([4],), max_new=2)
        finally:
            flags.set_flag("trace", prev)
        kinds = {(s.kind, s.name) for s in tracing.spans_since(m)}
        assert ("dispatch", "engine/dispatch") in kinds
        assert f32._m_dispatch.quantile(0.5) > 0


class TestKillSwitch:
    def test_flag_off_keeps_engine_f32(self):
        """PTPU_QUANT_PARAMS=0: quant='int8' becomes a no-op — no
        rewrite, no freed bytes, and the engine reports quant=None."""
        prev = flags.get_flag("quant_params")
        flags.set_flag("quant_params", False)
        try:
            eng = ContinuousBatchingEngine(n_slots=2,
                                           cache_prefix="qs_off",
                                           quant="int8", **_DIMS)
        finally:
            flags.set_flag("quant_params", prev)
        assert eng.quant is None and eng.quant_freed_bytes == 0
        ops = [op.type for op in eng._program.global_block().ops]
        assert "qmatmul" not in ops and "qlookup" not in ops
        assert _gen(eng, ([3],), max_new=2)[0]         # still serves

    def test_flag_in_compile_cache_key(self):
        from paddle_tpu.framework.executor import _fusion_flags_key
        prev = flags.get_flag("quant_params")
        try:
            flags.set_flag("quant_params", True)
            on = _fusion_flags_key()
            flags.set_flag("quant_params", False)
            off = _fusion_flags_key()
        finally:
            flags.set_flag("quant_params", prev)
        assert on != off

    def test_bad_quant_mode_rejected(self):
        with pytest.raises(Exception, match="quant"):
            ContinuousBatchingEngine(n_slots=2, cache_prefix="qs_bad",
                                     quant="fp8", **_DIMS)


class TestCensusReconciliation:
    def test_predicted_equals_measured_equals_handsum(self, quant_engines):
        from paddle_tpu.framework import costs
        from paddle_tpu.observability.memory import state_census
        _, q8, _, _ = quant_engines
        cats = costs.memory_categories(q8._program, dp=1, nominal_batch=1)
        hand = 0
        names = []
        for name, v in q8._program.global_block().vars.items():
            if name.endswith("@qparam") or name.endswith("@qscale"):
                names.append(name)
                hand += np.asarray(q8.scope.get(name)).nbytes
        assert names and cats["params_quantized"] == hand
        c = state_census(q8.scope, q8._program, names)
        assert c["categories"]["params_quantized"] == hand
        # the remaining f32 params are the layer norms only
        assert cats["params"] < cats["params_quantized"]

    def test_compression_ratio_floors(self, quant_engines):
        _, q8, q4, _ = quant_engines
        assert q8.params_bytes_f32 / q8.params_bytes_quantized >= 2.0
        assert q4.params_bytes_f32 / q4.params_bytes_quantized >= 3.5
