"""Per-op checks: elementwise / activations / reductions / tensor manip.

≙ reference tests/unittests/test_elementwise_*_op.py, test_activation_op.py,
test_reduce_op.py, test_reshape_op.py etc. — forward vs numpy + numeric grad.
"""

import numpy as np
import pytest

from op_test import check_grad, check_output, run_op

pytestmark = pytest.mark.quick  # run_ci.sh quick smoke tier


class TestElementwise:
    def test_add_forward_and_grad(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        y = rng.rand(3, 4).astype(np.float32)
        check_output("elementwise_add", {"X": x, "Y": y}, {"Out": x + y})
        check_grad("elementwise_add", {"X": x, "Y": y}, ["X", "Y"])

    def test_add_broadcast_axis(self, rng):
        x = rng.rand(2, 3, 4, 5).astype(np.float32)
        y = rng.rand(3, 4).astype(np.float32)
        out = run_op("elementwise_add", {"X": x, "Y": y}, {"axis": 1})
        np.testing.assert_allclose(out["Out"][0],
                                   x + y.reshape(1, 3, 4, 1), rtol=1e-6)

    def test_sub_mul_div(self, rng):
        x = rng.rand(4, 5).astype(np.float32) + 1.0
        y = rng.rand(4, 5).astype(np.float32) + 1.0
        check_output("elementwise_sub", {"X": x, "Y": y}, {"Out": x - y})
        check_output("elementwise_mul", {"X": x, "Y": y}, {"Out": x * y})
        check_output("elementwise_div", {"X": x, "Y": y}, {"Out": x / y},
                     rtol=1e-5)
        check_grad("elementwise_div", {"X": x, "Y": y}, ["X", "Y"])

    def test_max_min_pow(self, rng):
        x = rng.rand(3, 4).astype(np.float32) + 0.5
        y = rng.rand(3, 4).astype(np.float32) + 0.5
        check_output("elementwise_max", {"X": x, "Y": y},
                     {"Out": np.maximum(x, y)})
        check_output("elementwise_min", {"X": x, "Y": y},
                     {"Out": np.minimum(x, y)})
        check_output("elementwise_pow", {"X": x, "Y": y},
                     {"Out": np.power(x, y)}, rtol=1e-4)

    def test_scale(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        check_output("scale", {"X": x}, {"Out": 2.5 * x + 1.0},
                     attrs={"scale": 2.5, "bias": 1.0})
        check_grad("scale", {"X": x}, ["X"], attrs={"scale": 2.5, "bias": 1.0})

    def test_clip(self, rng):
        x = (rng.rand(5, 5).astype(np.float32) - 0.5) * 4
        check_output("clip", {"X": x}, {"Out": np.clip(x, -1, 1)},
                     attrs={"min": -1.0, "max": 1.0})


class TestActivations:
    @pytest.mark.parametrize("op,ref", [
        ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
        ("tanh", np.tanh),
        ("relu", lambda x: np.maximum(x, 0)),
        ("exp", np.exp),
        ("square", np.square),
        ("softsign", lambda x: x / (1 + np.abs(x))),
        ("abs", np.abs),
    ])
    def test_forward(self, rng, op, ref):
        x = (rng.rand(4, 6).astype(np.float32) - 0.5) * 2
        check_output(op, {"X": x}, {"Out": ref(x)}, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("op", ["sigmoid", "tanh", "softplus", "gelu"])
    def test_grad(self, rng, op):
        x = (rng.rand(3, 4).astype(np.float32) - 0.5) * 2
        check_grad(op, {"X": x}, ["X"])

    def test_leaky_relu(self, rng):
        x = (rng.rand(4, 4).astype(np.float32) - 0.5) * 2
        check_output("leaky_relu", {"X": x},
                     {"Out": np.where(x >= 0, x, 0.1 * x)},
                     attrs={"alpha": 0.1})

    def test_log_sqrt_positive(self, rng):
        x = rng.rand(4, 4).astype(np.float32) + 0.5
        check_output("log", {"X": x}, {"Out": np.log(x)}, rtol=1e-5)
        check_output("sqrt", {"X": x}, {"Out": np.sqrt(x)}, rtol=1e-5)
        check_grad("log", {"X": x}, ["X"])


class TestReduce:
    def test_reduce_sum(self, rng):
        x = rng.rand(3, 4, 5).astype(np.float32)
        check_output("reduce_sum", {"X": x}, {"Out": x.sum(axis=1)},
                     attrs={"dim": [1]}, rtol=1e-5)
        check_output("reduce_sum", {"X": x}, {"Out": x.sum()},
                     attrs={"reduce_all": True}, rtol=1e-5)
        check_grad("reduce_sum", {"X": x}, ["X"], attrs={"dim": [1]})

    def test_reduce_mean_keepdim(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        check_output("reduce_mean", {"X": x},
                     {"Out": x.mean(axis=0, keepdims=True)},
                     attrs={"dim": [0], "keep_dim": True}, rtol=1e-5)

    def test_mean_sum(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        y = rng.rand(3, 4).astype(np.float32)
        check_output("mean", {"X": x}, {"Out": x.mean()}, rtol=1e-5)
        check_output("sum", {"X": [x, y]}, {"Out": x + y})
        check_grad("mean", {"X": x}, ["X"])

    def test_topk_argmax(self, rng):
        x = rng.rand(4, 10).astype(np.float32)
        out = run_op("top_k", {"X": x}, {"k": 3})
        ref = np.sort(x, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(out["Out"][0], ref, rtol=1e-6)
        out = run_op("arg_max", {"X": x}, {"axis": 1})
        np.testing.assert_array_equal(out["Out"][0], x.argmax(axis=1))


class TestManip:
    def test_reshape_zero_dim(self, rng):
        x = rng.rand(2, 3, 4).astype(np.float32)
        out = run_op("reshape", {"X": x}, {"shape": [0, 12]})
        assert out["Out"][0].shape == (2, 12)
        out = run_op("reshape", {"X": x}, {"shape": [-1, 6]})
        assert out["Out"][0].shape == (4, 6)

    def test_transpose_concat_split(self, rng):
        x = rng.rand(2, 3, 4).astype(np.float32)
        out = run_op("transpose", {"X": x}, {"axis": [2, 0, 1]})
        np.testing.assert_allclose(out["Out"][0], x.transpose(2, 0, 1))
        y = rng.rand(2, 3, 4).astype(np.float32)
        out = run_op("concat", {"X": [x, y]}, {"axis": 1})
        np.testing.assert_allclose(out["Out"][0],
                                   np.concatenate([x, y], axis=1))
        out = run_op("split", {"X": x}, {"num": 2, "axis": 2, "sections": []})
        assert len(out["Out"]) == 2 and out["Out"][0].shape == (2, 3, 2)

    def test_gather_scatter(self, rng):
        x = rng.rand(10, 4).astype(np.float32)
        idx = np.array([0, 3, 5], dtype=np.int32)
        out = run_op("gather", {"X": x, "Index": idx}, {})
        np.testing.assert_allclose(out["Out"][0], x[idx])
        upd = rng.rand(3, 4).astype(np.float32)
        out = run_op("scatter", {"X": x, "Ids": idx, "Updates": upd}, {})
        ref = x.copy()
        ref[idx] = upd
        np.testing.assert_allclose(out["Out"][0], ref)

    def test_one_hot_cast_fill(self, rng):
        ids = np.array([[1], [3], [0]], dtype=np.int32)
        out = run_op("one_hot", {"X": ids}, {"depth": 4})
        assert out["Out"][0].shape == (3, 4)
        assert out["Out"][0][1, 3] == 1.0
        x = rng.rand(3, 3).astype(np.float32)
        out = run_op("cast", {"X": x}, {"out_dtype": "int32"})
        assert out["Out"][0].dtype == np.int32
        out = run_op("fill_constant", {}, {"shape": [2, 3], "value": 7.0,
                                           "dtype": "float32"})
        np.testing.assert_allclose(out["Out"][0], np.full((2, 3), 7.0))

    def test_pad_slice_expand(self, rng):
        x = rng.rand(2, 3).astype(np.float32)
        out = run_op("pad", {"X": x}, {"paddings": [0, 1, 2, 0],
                                       "pad_value": 9.0})
        assert out["Out"][0].shape == (3, 5)
        assert out["Out"][0][2, 0] == 9.0
        out = run_op("slice", {"X": x}, {"axes": [1], "starts": [1],
                                         "ends": [3]})
        np.testing.assert_allclose(out["Out"][0], x[:, 1:3])
        out = run_op("expand", {"X": x}, {"expand_times": [2, 1]})
        assert out["Out"][0].shape == (4, 3)

    def test_cumsum(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        out = run_op("cumsum", {"X": x}, {"axis": 1})
        np.testing.assert_allclose(out["Out"][0], np.cumsum(x, axis=1),
                                   rtol=1e-5)
        out = run_op("cumsum", {"X": x}, {"axis": 1, "reverse": True})
        ref = np.flip(np.cumsum(np.flip(x, 1), axis=1), 1)
        np.testing.assert_allclose(out["Out"][0], ref, rtol=1e-5)

    def test_lookup_table(self, rng):
        w = rng.rand(20, 8).astype(np.float32)
        ids = np.array([[1], [5], [19]], dtype=np.int32)
        out = run_op("lookup_table", {"W": w, "Ids": ids}, {})
        np.testing.assert_allclose(out["Out"][0], w[[1, 5, 19]])


class TestSelectedRowsAndDistHelpers:
    """≙ reference selected_rows.h + split_ids/merge_ids/
    lookup_sparse_table pserver helpers (test_split_ids_op.py etc.)."""

    def test_selected_rows_dense_roundtrip(self, rng):
        from paddle_tpu import SelectedRows
        dense = np.zeros((6, 3), "float32")
        dense[1] = rng.rand(3)
        dense[4] = rng.rand(3)
        sr = SelectedRows.from_dense(dense)
        assert sorted(sr.rows.tolist()) == [1, 4]
        np.testing.assert_allclose(sr.to_dense(), dense)

    def test_selected_rows_merge_add(self, rng):
        from paddle_tpu import SelectedRows
        sr = SelectedRows([2, 0, 2], rng.rand(3, 4).astype("float32"), 5)
        merged = sr.merge_add()
        assert merged.rows.tolist() == [0, 2]
        np.testing.assert_allclose(merged.to_dense(), sr.to_dense(),
                                   rtol=1e-6)

    def test_sharded_lookup_roundtrip(self, rng):
        """The pserver prefetch flow: split ids by shard, look each shard
        up in its own table slice, merge rows back into query order."""
        from op_test import run_op
        V, D, N, S = 12, 4, 7, 3
        table = rng.rand(V, D).astype("float32")
        ids = rng.randint(0, V, (N,)).astype("int64")

        split = run_op("split_ids", {"Ids": ids},
                       attrs={"num_shards": S})
        shard_ids = split["Out"]
        counts = split["Count"][0]
        assert int(counts.sum()) == N
        # each shard owns its modulo class
        for s in range(S):
            valid = shard_ids[s][shard_ids[s] >= 0]
            assert all(v % S == s for v in valid.tolist())

        rows = [run_op("lookup_sparse_table",
                       {"W": table, "Ids": shard_ids[s]})["Out"][0]
                for s in range(S)]
        merged = run_op("merge_ids",
                        {"Ids": ids, "X": list(shard_ids),
                         "Rows": rows})["Out"][0]
        np.testing.assert_allclose(merged, table[ids], rtol=1e-6)

    def test_lookup_sparse_table_padded_ids_zero(self, rng):
        from op_test import run_op
        table = rng.rand(5, 3).astype("float32")
        ids = np.array([2, -1, 4], dtype="int64")
        out = run_op("lookup_sparse_table",
                     {"W": table, "Ids": ids})["Out"][0]
        np.testing.assert_allclose(out[0], table[2], rtol=1e-6)
        np.testing.assert_array_equal(out[1], 0)


def test_lod_reset_and_max_sequence_len(rng):
    """≙ reference lod_reset_op / max_sequence_len_op (static-shape LoD
    translation: companion @SEQLEN re-tagging)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.layers.sequence import lod_reset, max_sequence_len

    x1 = layers.data("x1", shape=[6, 4], lod_level=1)
    x2 = layers.data("x2", shape=[6, 4], lod_level=1)
    y = lod_reset(x1, x2)
    m = max_sequence_len(y)
    pooled = layers.sequence_pool(y, pool_type="sum")
    exe = pt.Executor()
    feed = {"x1": np.ones((2, 6, 4), "float32"),
            "x1@SEQLEN": np.array([6, 6], "int32"),
            "x2": np.zeros((2, 6, 4), "float32"),
            "x2@SEQLEN": np.array([2, 3], "int32")}
    mv, pv = exe.run(feed=feed, fetch_list=[m, pooled])
    assert mv == 3
    assert pv[0, 0] == 2.0 and pv[1, 0] == 3.0


def test_lod_reset_does_not_alias_input(rng):
    """Regression: lod_reset returns a fresh var; the input keeps its own
    lengths (the reference op writes a new output var)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.layers.sequence import lod_reset

    x1 = layers.data("x1", shape=[6, 4], lod_level=1)
    x2 = layers.data("x2", shape=[6, 4], lod_level=1)
    y = lod_reset(x1, x2)
    assert y.name != x1.name
    pooled_x1 = layers.sequence_pool(x1, pool_type="sum")   # original tags
    pooled_y = layers.sequence_pool(y, pool_type="sum")     # new tags
    exe = pt.Executor()
    feed = {"x1": np.ones((2, 6, 4), "float32"),
            "x1@SEQLEN": np.array([6, 6], "int32"),
            "x2": np.zeros((2, 6, 4), "float32"),
            "x2@SEQLEN": np.array([2, 3], "int32")}
    a, b = exe.run(feed=feed, fetch_list=[pooled_x1, pooled_y])
    assert a[0, 0] == 6.0 and a[1, 0] == 6.0
    assert b[0, 0] == 2.0 and b[1, 0] == 3.0


def test_lod_reset_rejects_non_lengths(rng):
    import pytest as _pytest
    from paddle_tpu import layers
    from paddle_tpu.core.enforce import InvalidArgumentError
    from paddle_tpu.layers.sequence import lod_reset, max_sequence_len

    x = layers.data("xq", shape=[6, 4], lod_level=1)
    bad = layers.data("badf", shape=[6, 4])   # float, untagged
    with _pytest.raises(InvalidArgumentError):
        lod_reset(x, bad)
    with _pytest.raises(InvalidArgumentError):
        lod_reset(x, target_lod=[0, 2, 5])    # python list: not a Variable
    # a plain [B] int lengths var IS accepted
    lens = layers.data("plain_lens", shape=[], dtype="int32")
    assert max_sequence_len(lens) is not None


def test_random_batch_size_like_variants(rng):
    """≙ uniform/gaussian_random_batch_size_like ops (SURVEY §2.2)."""
    from op_test import run_op
    ref = np.zeros((5, 7), "float32")
    u = run_op("uniform_random_batch_size_like",
               {"Input": ref}, attrs={"shape": [-1, 3], "min": 0.0,
                                      "max": 1.0, "seed": 7})["Out"][0]
    assert u.shape == (5, 3) and (u >= 0).all() and (u <= 1).all()
    g = run_op("gaussian_random_batch_size_like",
               {"Input": ref}, attrs={"shape": [-1, 4], "mean": 10.0,
                                      "std": 0.1, "seed": 7})["Out"][0]
    assert g.shape == (5, 4) and abs(float(g.mean()) - 10.0) < 0.5
