"""Loss-family op checks vs numpy references + numeric grads.

≙ reference tests/unittests/test_{rank_loss,margin_rank_loss,hinge_loss,
log_loss,cos_sim,bilinear_tensor_product,squared_l2_norm,
squared_l2_distance,nce,hsigmoid}_op.py.
"""

import math

import numpy as np

from op_test import check_grad, check_output, run_op


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


class TestPairwiseLosses:
    def test_rank_loss(self, rng):
        label = rng.randint(0, 2, (8, 1)).astype(np.float32)
        left = rng.randn(8, 1).astype(np.float32)
        right = rng.randn(8, 1).astype(np.float32)
        o = left - right
        want = np.log(1.0 + np.exp(o)) - label * o
        check_output("rank_loss", {"Label": label, "Left": left,
                                   "Right": right}, {"Out": want}, rtol=1e-4)
        check_grad("rank_loss", {"Label": label, "Left": left,
                                 "Right": right}, ["Left", "Right"])

    def test_margin_rank_loss(self, rng):
        label = (rng.randint(0, 2, (8, 1)) * 2 - 1).astype(np.float32)
        x1 = rng.randn(8, 1).astype(np.float32)
        x2 = rng.randn(8, 1).astype(np.float32)
        want = np.maximum(0.0, -label * (x1 - x2) + 0.1)
        check_output("margin_rank_loss", {"Label": label, "X1": x1, "X2": x2},
                     {"Out": want}, attrs={"margin": 0.1})

    def test_hinge_loss(self, rng):
        pred = rng.randn(10, 1).astype(np.float32)
        label = rng.randint(0, 2, (10, 1)).astype(np.float32)
        want = np.maximum(0.0, 1.0 - pred * (2 * label - 1))
        check_output("hinge_loss", {"Logits": pred, "Labels": label},
                     {"Loss": want})

    def test_log_loss(self, rng):
        pred = rng.uniform(0.05, 0.95, (10, 1)).astype(np.float32)
        label = rng.randint(0, 2, (10, 1)).astype(np.float32)
        eps = 1e-4
        want = (-label * np.log(pred + eps)
                - (1 - label) * np.log(1 - pred + eps))
        check_output("log_loss", {"Predicted": pred, "Labels": label},
                     {"Loss": want}, attrs={"epsilon": eps}, rtol=1e-4)
        check_grad("log_loss", {"Predicted": pred, "Labels": label},
                   ["Predicted"], out_slot="Loss", attrs={"epsilon": eps})


class TestSimilarity:
    def test_cos_sim(self, rng):
        x = rng.randn(6, 8).astype(np.float32)
        y = rng.randn(6, 8).astype(np.float32)
        want = (np.sum(x * y, 1) /
                (np.linalg.norm(x, axis=1) *
                 np.linalg.norm(y, axis=1)))[:, None]
        check_output("cos_sim", {"X": x, "Y": y}, {"Out": want}, rtol=1e-4)
        check_grad("cos_sim", {"X": x, "Y": y}, ["X", "Y"])

    def test_cos_sim_broadcast(self, rng):
        x = rng.randn(6, 8).astype(np.float32)
        y = rng.randn(1, 8).astype(np.float32)
        want = (np.sum(x * y, 1) /
                (np.linalg.norm(x, axis=1) * np.linalg.norm(y)))[:, None]
        check_output("cos_sim", {"X": x, "Y": y}, {"Out": want}, rtol=1e-4)

    def test_squared_l2_norm(self, rng):
        x = rng.randn(4, 5).astype(np.float32)
        check_output("squared_l2_norm", {"X": x},
                     {"Out": np.array([np.sum(x ** 2)])}, rtol=1e-4)
        check_grad("squared_l2_norm", {"X": x}, ["X"])

    def test_squared_l2_distance(self, rng):
        x = rng.randn(4, 5).astype(np.float32)
        y = rng.randn(4, 5).astype(np.float32)
        want = np.sum((x - y) ** 2, axis=1, keepdims=True)
        check_output("squared_l2_distance", {"X": x, "Y": y}, {"Out": want},
                     rtol=1e-4)
        check_grad("squared_l2_distance", {"X": x, "Y": y}, ["X"])

    def test_bilinear_tensor_product(self, rng):
        x = rng.randn(3, 4).astype(np.float32)
        y = rng.randn(3, 5).astype(np.float32)
        w = rng.randn(2, 4, 5).astype(np.float32)
        b = rng.randn(1, 2).astype(np.float32)
        want = np.einsum("nd,kde,ne->nk", x, w, y) + b
        check_output("bilinear_tensor_product",
                     {"X": x, "Y": y, "Weight": w, "Bias": b},
                     {"Out": want}, rtol=1e-4)
        check_grad("bilinear_tensor_product",
                   {"X": x, "Y": y, "Weight": w, "Bias": b},
                   ["X", "Y", "Weight"])


class TestNCE:
    def test_nce_shapes_and_grad_flow(self, rng):
        n, d, c, k = 6, 8, 20, 5
        x = rng.randn(n, d).astype(np.float32)
        label = rng.randint(0, c, (n, 1)).astype(np.int64)
        w = rng.randn(c, d).astype(np.float32) * 0.1
        b = rng.randn(c).astype(np.float32) * 0.1
        out = run_op("nce", {"Input": x, "Label": label, "Weight": w,
                             "Bias": b},
                     {"num_total_classes": c, "num_neg_samples": k})
        assert out["Cost"][0].shape == (n, 1)
        assert np.all(out["Cost"][0] > 0)
        assert out["SampleLogits"][0].shape == (n, k + 1)
        assert out["SampleLabels"][0].shape == (n, k + 1)
        # positive column holds the true label
        np.testing.assert_array_equal(out["SampleLabels"][0][:, 0],
                                      label.reshape(-1))
        # sampling is deterministic per seed: same seed → same cost
        out2 = run_op("nce", {"Input": x, "Label": label, "Weight": w,
                              "Bias": b},
                      {"num_total_classes": c, "num_neg_samples": k})
        np.testing.assert_allclose(out["Cost"][0], out2["Cost"][0])
        check_grad("nce", {"Input": x, "Label": label, "Weight": w,
                           "Bias": b},
                   ["Input", "Weight"], out_slot="Cost",
                   attrs={"num_total_classes": c, "num_neg_samples": k})

    def test_nce_learns(self, rng):
        """Training with NCE pulls the true class logit above others."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        n, d, c = 32, 16, 10
        x_np = rng.randn(n, d).astype(np.float32)
        wtrue = rng.randn(d, c).astype(np.float32)
        y_np = np.argmax(x_np @ wtrue, 1).astype(np.int64)[:, None]

        inp = layers.data(name="x", shape=[d])
        lab = layers.data(name="y", shape=[1], dtype="int64")
        cost = layers.nce(inp, lab, num_total_classes=c, num_neg_samples=5)
        loss = layers.mean(cost)
        pt.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        losses = []
        for _ in range(40):
            (lo,) = exe.run(pt.default_main_program(),
                            feed={"x": x_np, "y": y_np}, fetch_list=[loss])
            losses.append(float(lo))
        assert losses[-1] < losses[0] * 0.8, losses[::10]


class TestHSigmoid:
    @staticmethod
    def _ref_hsigmoid(x, label, w, b, num_classes):
        n = x.shape[0]
        cost = np.zeros((n, 1), dtype=np.float64)
        for i in range(n):
            c = int(label[i, 0]) + num_classes
            length = c.bit_length() - 1
            for j in range(length):
                node = (c >> (j + 1)) - 1
                bit = (c >> j) & 1
                pre = float(x[i] @ w[node]) + float(b[node, 0])
                cost[i, 0] += math.log1p(math.exp(pre)) - bit * pre
        return cost

    def test_hsigmoid_matches_bitcode_reference(self, rng):
        n, d, c = 5, 6, 7
        x = rng.randn(n, d).astype(np.float32) * 0.5
        label = rng.randint(0, c, (n, 1)).astype(np.int64)
        w = rng.randn(c - 1, d).astype(np.float32) * 0.5
        b = rng.randn(c - 1, 1).astype(np.float32) * 0.5
        want = self._ref_hsigmoid(x, label, w, b, c)
        check_output("hierarchical_sigmoid",
                     {"X": x, "Label": label, "W": w, "Bias": b},
                     {"Out": want.astype(np.float32)},
                     attrs={"num_classes": c}, rtol=1e-3, atol=1e-4)
        check_grad("hierarchical_sigmoid",
                   {"X": x, "Label": label, "W": w, "Bias": b},
                   ["X", "W"], attrs={"num_classes": c})

    def test_hsigmoid_layer_trains(self, rng):
        import paddle_tpu as pt
        from paddle_tpu import layers
        n, d, c = 32, 12, 8
        x_np = rng.randn(n, d).astype(np.float32)
        wtrue = rng.randn(d, c).astype(np.float32)
        y_np = np.argmax(x_np @ wtrue, 1).astype(np.int64)[:, None]

        inp = layers.data(name="x", shape=[d])
        lab = layers.data(name="y", shape=[1], dtype="int64")
        cost = layers.hsigmoid(inp, lab, num_classes=c)
        loss = layers.mean(cost)
        pt.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        losses = []
        for _ in range(40):
            (lo,) = exe.run(pt.default_main_program(),
                            feed={"x": x_np, "y": y_np}, fetch_list=[loss])
            losses.append(float(lo))
        assert losses[-1] < losses[0] * 0.7, losses[::10]
