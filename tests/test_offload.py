"""Host-offload tier (ISSUE r23): the pinned pool + transfer stream
substrate, two-tier paged-KV accounting and decode identity, ZeRO-
offload optimizer state, planner-priced stash-to-host, and the offload
schedule lint.

Covers the two-tier contract end to end:
- PinnedHostPool ledger exactness: per-category census, capacity and
  under-release ENFORCED (and enforced-before-mutated: a refused alloc
  leaves the ledger untouched), peak watermark, unknown-category error;
- TransferStream byte census == submitted nbytes exactly; a failed
  background copy re-raises at wait() (r14 async-d2h discipline);
- 100 random evict/prefetch-reload/rollback cycles at the pager level
  with `check_two_tier` (used_dev + used_host + free_dev + free_host ==
  total) asserted after EVERY cycle — composing the r22 speculative
  rollback with host spills on the same tables — then a full drain
  back to empty on both tiers;
- decode token identity: a two-tier engine under enough pressure to
  actually spill (asserted) matches an unconstrained-pool engine
  bitwise, with the wire-byte census predicted == measured EXACTLY;
  same again with r22 speculative decoding stacked on top;
- ZeRO-offload optimizer state: loss bitwise-identical offload on/off
  over a dp=8 mesh, state host-resident between steps, the
  PTPU_OFFLOAD=0 kill switch, and the HostOptimizerState unit
  round-trip (offload erases, restore reproduces bitwise);
- costs.predict `offload` section: PCIe roofline keys, the residual
  charged into predicted_step_seconds, section absent when the knob is
  off;
- memory_plan stash-to-host: candidate absent when the knob is off,
  REFUSED (fits_budget False) when the transfer cannot hide, chosen +
  advisory + attrs set + NAMED freed-bytes key when it hides;
- the offload schedule lint: clean kv-prefetch and optimizer-roundtrip
  schedules produce NO diagnostics, and each mutation (arrival after
  read, issue after read, late restore) fires exactly
  `offload-use-before-arrival` — the r13 mutation-test-per-code
  discipline for the new named diagnostic.
"""

import dataclasses

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.framework import offload as ofl
from paddle_tpu.framework.scope import Scope
from paddle_tpu.serving import HostTierConfig, KVPager, PagedKVEngine

pytestmark = pytest.mark.quick

_DIMS = dict(vocab=50, max_len=16, d_model=32, d_inner=64, num_heads=4,
             num_layers=2)


# ---------------------------------------------------------------------------
# pinned host pool
# ---------------------------------------------------------------------------


class TestPinnedHostPool:
    def test_category_census_and_free(self):
        pool = ofl.PinnedHostPool()
        buf = pool.alloc((8,), np.float32, "kv")
        assert pool.used_bytes("kv") == 32
        assert pool.used_bytes() == 32
        rows = pool.rows()
        assert rows["host_kv_bytes"] == 32
        assert rows["host_total_bytes"] == 32
        assert rows["host_peak_bytes"] == 32
        pool.free(buf)
        pool.free(buf)                       # double free is a no-op
        assert pool.used_bytes() == 0
        assert pool.rows()["host_peak_bytes"] == 32   # peak sticks

    def test_lease_adopts_and_releases(self):
        pool = ofl.PinnedHostPool()
        lease = pool.lease(100, "staging")
        assert pool.used_bytes("staging") == 100
        lease.release()
        lease.release()                      # idempotent
        assert pool.used_bytes("staging") == 0

    def test_under_release_enforced(self):
        pool = ofl.PinnedHostPool()
        with pytest.raises(InvalidArgumentError):
            pool._credit("kv", -1)

    def test_unknown_category_enforced(self):
        pool = ofl.PinnedHostPool()
        with pytest.raises(InvalidArgumentError):
            pool.alloc((4,), np.float32, "bogus")

    def test_capacity_enforced_before_mutation(self):
        pool = ofl.PinnedHostPool(capacity_bytes=64)
        pool.alloc((8,), np.float32, "kv")          # 32 of 64
        with pytest.raises(InvalidArgumentError):
            pool.alloc((16,), np.float32, "optimizer")
        # the refused alloc must not have moved the ledger
        assert pool.used_bytes() == 32
        pool.alloc((8,), np.float32, "optimizer")   # exactly fits
        assert pool.used_bytes() == 64


# ---------------------------------------------------------------------------
# transfer stream
# ---------------------------------------------------------------------------


class TestTransferStream:
    def test_byte_census_exact(self):
        stream = ofl.TransferStream()
        for nb in (10, 20, 30):
            stream.submit("d2h", lambda: None, nb, tag="t").wait(10)
        stream.submit("h2d", lambda: None, 7, tag="t").wait(10)
        c = stream.counters()
        assert c["d2h_bytes"] == 60 and c["d2h_jobs"] == 3
        assert c["h2d_bytes"] == 7 and c["h2d_jobs"] == 1

    def test_error_surfaces_at_wait(self):
        stream = ofl.TransferStream()

        def boom():
            raise RuntimeError("copy failed")

        t = stream.submit("d2h", boom, 4, tag="bad")
        with pytest.raises(RuntimeError, match="copy failed"):
            t.wait(10)
        # the stream survives a failed job
        assert stream.submit("d2h", lambda: 5, 4, tag="ok").wait(10) == 5


# ---------------------------------------------------------------------------
# two-tier pager accounting: 100 random cycles + r22 rollback
# ---------------------------------------------------------------------------


class TestTwoTierAccounting:
    def test_100_cycle_random_evict_reload_rollback(self):
        rng = np.random.RandomState(7)
        pager = KVPager(n_blocks=9, block_size=4, prefix_sharing=False,
                        host_tier=HostTierConfig(host_blocks=16,
                                                 prefetch_distance=2,
                                                 rotate_quantum=4))
        resident, suspended = [], []
        spills = reloads = rollbacks = 0
        for _ in range(100):
            op = rng.randint(4)
            if op == 0:
                prompt = rng.randint(1, 50, size=rng.randint(2, 9))
                t = pager.try_admit(prompt.tolist(), len(prompt) + 4)
                if t is not None:
                    resident.append([t, len(prompt)])
            elif op == 1 and resident:
                t, wl = resident.pop(rng.randint(len(resident)))
                rec = pager.evict_table_to_host(t, wl)
                if rec is None:              # host tier full: refused
                    resident.append([t, wl])
                else:
                    spills += 1
                    suspended.append([t, rec, wl])
            elif op == 2 and suspended:
                t, rec, wl = suspended.pop(rng.randint(len(suspended)))
                moves = pager.reload_table_from_host(t, rec)
                if moves is None:            # device full: rolled back
                    suspended.append([t, rec, wl])
                else:
                    reloads += 1
                    assert [j for j, _ in moves] == rec.spilled
                    resident.append([t, wl])
            elif op == 3 and resident:
                i = rng.randint(len(resident))
                t, wl = resident[i]
                if wl >= 2:                  # r22 speculative rollback
                    keep = int(rng.randint(1, wl))
                    pager.rollback(t, keep, wl)
                    resident[i][1] = keep
                    rollbacks += 1
            pager.check_two_tier()           # exact after EVERY cycle
        assert spills > 5 and reloads > 5 and rollbacks > 5
        # drain: everything reloads and releases back to empty tiers
        for t, _ in resident:
            pager.release(t)
        for t, rec, _ in suspended:
            moves = pager.reload_table_from_host(t, rec)
            assert moves is not None
            pager.release(t)
        pager.check_two_tier()
        assert pager.pool.n_used == 0
        assert pager.host_blocks_used == 0
        assert pager.host_evictions == pager.host_reloads

    def test_spill_refused_when_host_tier_full(self):
        pager = KVPager(n_blocks=9, block_size=4, prefix_sharing=False,
                        host_tier=HostTierConfig(host_blocks=1))
        t = pager.try_admit([1, 2, 3, 4, 5, 6, 7, 8], 10)
        assert t is not None
        assert pager.evict_table_to_host(t, 8) is None   # needs 2 > 1
        pager.check_two_tier()
        pager.release(t)

    def test_two_tier_check_requires_host_tier_for_spill(self):
        pager = KVPager(n_blocks=9, block_size=4, prefix_sharing=False)
        t = pager.try_admit([1, 2, 3], 5)
        with pytest.raises(InvalidArgumentError):
            pager.evict_table_to_host(t, 3)
        pager.release(t)


# ---------------------------------------------------------------------------
# decode identity under real spill pressure (+ r22 composition)
# ---------------------------------------------------------------------------


def _drive_upfront(eng, prompts, max_new=6):
    reqs = [eng.submit(list(p), max_new=max_new) for p in prompts]
    eng.run_until_idle(max_ticks=6000)
    assert all(r.done for r in reqs)
    return [list(r.tokens) for r in reqs]


def _prompts(rng, n):
    return [rng.randint(1, _DIMS["vocab"],
                        size=rng.randint(3, 9)).tolist() for _ in range(n)]


class TestTwoTierDecodeIdentity:
    def test_token_identical_with_exact_wire_census(self):
        ofl.reset_offload()
        scope = Scope()
        rng = np.random.RandomState(3)
        prompts = _prompts(rng, 8)
        base = PagedKVEngine(n_slots=6, block_size=4, scope=scope,
                             **_DIMS)
        want = _drive_upfront(base, prompts)
        tier = HostTierConfig(host_blocks=32, prefetch_distance=2,
                              rotate_quantum=4)
        two = PagedKVEngine(n_slots=6, block_size=4, n_blocks=9,
                            scope=scope, host_tier=tier, **_DIMS)
        got = _drive_upfront(two, prompts)
        assert got == want
        # the pressure was real and the census is exact
        assert two.pager.host_evictions > 0
        per = two._ht_per_block_bytes
        assert two.ht_d2h_bytes == two.pager.host_evictions * per
        assert two.ht_h2d_bytes == two.pager.host_reloads * per
        two.pager.check_two_tier()

    def test_speculative_with_host_tier_is_guarded(self):
        # engine-level host_tier x speculative is explicitly refused
        # (a speculative round's rollback remaps blocks the suspend/
        # resume swap may hold in flight on the stream) — the pager-
        # level rollback/spill composition is what's supported, and the
        # 100-cycle test above exercises it. Pin the guard by name so
        # a silent un-guarding shows up here.
        from paddle_tpu.serving import SpecConfig
        scope = Scope()
        tier = HostTierConfig(host_blocks=32, prefetch_distance=2,
                              rotate_quantum=4)
        with pytest.raises(InvalidArgumentError,
                           match="does not compose with speculative"):
            PagedKVEngine(n_slots=6, block_size=4, n_blocks=9,
                          scope=scope, host_tier=tier,
                          speculative=SpecConfig(gamma=3), **_DIMS)


# ---------------------------------------------------------------------------
# ZeRO-offload optimizer state
# ---------------------------------------------------------------------------


def _train_mlp(offload, steps=3):
    import jax
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    ofl.reset_offload()
    pt.reset_default_programs()
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        x = layers.data("x", shape=[32])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=64, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.AdamOptimizer(0.01).minimize(loss)
    scope = Scope()
    pt.Executor().run(program=start, scope=scope)
    bst = BuildStrategy()
    bst.reduce_strategy = ReduceStrategy.Reduce
    bst.offload_optimizer_state = offload
    exe = ParallelExecutor(loss_name=loss.name,
                           mesh=DeviceMesh(jax.devices(), {"dp": 8}),
                           build_strategy=bst, main_program=prog,
                           scope=scope)
    rng = np.random.RandomState(11)
    losses = []
    for _ in range(steps):
        feed = {"x": rng.rand(16, 32).astype("float32"),
                "label": rng.randint(0, 10, (16, 1)).astype("int64")}
        out = exe.run(feed=feed, fetch_list=[loss])
        losses.append(np.asarray(out[0]).tobytes())
    return losses, exe


class TestHostOptimizerState:
    def test_loss_bitwise_identical_and_host_resident(self):
        base, _ = _train_mlp(False)
        off, exe = _train_mlp(True)
        assert base == off                   # bitwise, not approx
        ho = exe._host_opt
        assert ho is not None and ho.offloaded
        assert ho.roundtrips >= 2
        assert ofl.shared_host_pool().used_bytes("optimizer") > 0

    def test_kill_switch_disables(self, monkeypatch):
        monkeypatch.setenv("PTPU_OFFLOAD", "0")
        _, exe = _train_mlp(True, steps=1)
        assert getattr(exe, "_host_opt", None) is None

    def test_unit_roundtrip_bitwise(self):
        pool = ofl.PinnedHostPool()
        stream = ofl.TransferStream()
        scope = Scope()
        rng = np.random.RandomState(0)
        vals = {f"adam_m_{i}": rng.rand(4, 5).astype("float32")
                for i in range(3)}
        for k, v in vals.items():
            scope.set_var(k, v)
        ho = ofl.HostOptimizerState(scope, sorted(vals), stream=stream,
                                    pool=pool)
        ho.offload()
        assert ho.offloaded
        assert not any(scope.has_var(k) for k in vals)   # erased
        assert pool.used_bytes("optimizer") == sum(
            v.nbytes for v in vals.values())
        ho.restore()
        for k, v in vals.items():
            assert np.asarray(scope.get(k)).tobytes() == v.tobytes()
        ho.release()
        assert pool.used_bytes("optimizer") == 0

    def test_empty_names_enforced(self):
        with pytest.raises(InvalidArgumentError):
            ofl.HostOptimizerState(Scope(), [])


# ---------------------------------------------------------------------------
# costs.predict offload section
# ---------------------------------------------------------------------------


def _train_program():
    pt.reset_default_programs()
    pt.reset_global_scope()
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.AdamOptimizer(0.01).minimize(loss)
    return pt.default_main_program()


class TestCostsOffloadSection:
    def test_section_shape_and_residual_charged(self):
        from paddle_tpu.framework import costs
        from paddle_tpu.parallel.strategy import (BuildStrategy,
                                                  ReduceStrategy)
        prog = _train_program()
        bst = BuildStrategy()
        bst.reduce_strategy = ReduceStrategy.Reduce
        rep0 = costs.predict(prog, bst, dp=8, nominal_batch=16)
        assert rep0["offload"] is None       # knob off: no section
        bst.offload_optimizer_state = True
        rep = costs.predict(prog, bst, dp=8, nominal_batch=16)
        off = rep["offload"]
        assert off is not None
        assert off["optimizer_state_bytes"] > 0
        assert off["pcie_bps"] == costs.V5E_PCIE_BPS
        assert off["pcie_roundtrip_s"] == pytest.approx(
            2.0 * off["optimizer_state_bytes"] / off["pcie_bps"])
        assert off["residual_s"] >= 0.0
        assert off["hides"] == (off["pcie_roundtrip_s"]
                                <= off["overlap_window_s"])
        # an unhidden round-trip is CHARGED, never free
        s0 = costs.predicted_step_seconds(rep0, mesh_axes={"dp": 8})
        s1 = costs.predicted_step_seconds(rep, mesh_axes={"dp": 8})
        assert s1["offload_s"] >= 0.0
        assert s1["total_s"] >= s0["total_s"]

    def test_hbm_freed_lowers_device_bytes(self):
        from paddle_tpu.framework import costs
        from paddle_tpu.parallel.strategy import (BuildStrategy,
                                                  ReduceStrategy)
        prog = _train_program()
        bst = BuildStrategy()
        bst.reduce_strategy = ReduceStrategy.Reduce
        bst.offload_optimizer_state = True
        bst.comm_bucket_bytes = 1024         # tiny resident window
        rep = costs.predict(prog, bst, dp=8, nominal_batch=16)
        off = rep["offload"]
        assert off["resident_bytes"] <= 1024
        assert off["hbm_freed_bytes"] == (off["optimizer_state_bytes"]
                                          - off["resident_bytes"])
        bst.comm_bucket_bytes = 0
        rep_full = costs.predict(prog, bst, dp=8, nominal_batch=16)
        assert (costs.predicted_device_bytes(rep)
                < costs.predicted_device_bytes(rep_full))


# ---------------------------------------------------------------------------
# memory_plan stash-to-host candidate
# ---------------------------------------------------------------------------


def _deep_mlp(d):
    pt.reset_default_programs()
    pt.reset_global_scope()
    x = layers.data("x", shape=[d])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=2 * d, act="relu")
    h = layers.fc(h, size=2 * d, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return pt.default_main_program()


def _stash_record(d, stash_to_host):
    from paddle_tpu.framework import memory_plan as mp
    planned = mp.plan_program(_deep_mlp(d), nominal_batch=64,
                              stash_to_host=stash_to_host)
    rec = mp.plan_report(planned).get("remat") or {}
    cand = next((c for c in rec.get("candidates", ())
                 if c.get("policy") == "stash_to_host"), None)
    return planned, rec, cand


class TestStashToHost:
    def test_candidate_absent_when_knob_off(self):
        _, rec, cand = _stash_record(64, False)
        assert cand is None

    def test_planner_refuses_unhidden_transfer(self):
        _, rec, cand = _stash_record(64, True)
        assert cand is not None
        assert cand["pcie_transfer_s"] > cand["overlap_window_s"]
        assert cand["fits_budget"] is False
        assert rec.get("chosen") != "stash_to_host"

    def test_winner_is_advisory_with_named_freed_bytes(self):
        from paddle_tpu.framework import memory_plan as mp
        planned, rec, cand = _stash_record(2048, True)
        assert cand["fits_budget"] is True
        assert rec["chosen"] == "stash_to_host"
        assert rec["executed"] == "advisory"
        report = mp.plan_report(planned)
        assert report["stash_to_host_freed_bytes"] > 0
        # advisory: the freed bytes ride the NAMED key, never the
        # executed peak prediction
        assert (report["predicted_peak_before"]
                - report["predicted_peak_after"]
                < report["stash_to_host_freed_bytes"])
        marked = [op for b in planned.blocks for op in b.ops
                  if op.attrs.get("stash_to_host")]
        assert marked


# ---------------------------------------------------------------------------
# offload schedule lint: r13 mutation test per diagnostic code
# ---------------------------------------------------------------------------


class TestScheduleLint:
    def test_prefetch_issue_tick_is_shared_policy(self):
        assert ofl.prefetch_issue_tick(10, 2) == 8
        # a pre-trace issue tick means "issue immediately"; the lint
        # only flags arrivals AFTER the read, never early issues
        assert ofl.prefetch_issue_tick(1, 5) == -4

    def test_clean_kv_schedule_no_diagnostics(self):
        events = ofl.kv_prefetch_events({"r1": 5, "r2": 9}, 2)
        assert len(events) == 2
        assert ofl.check_schedule(events) == []

    def test_mutated_arrival_fires_named_code(self):
        events = ofl.kv_prefetch_events({"r1": 5}, 2)
        late = dataclasses.replace(events[0],
                                   arrive_tick=events[0].read_tick + 1)
        diags = ofl.check_schedule([late])
        assert len(diags) == 1
        assert diags[0].code == "offload-use-before-arrival"
        assert diags[0].severity == "error"

    def test_mutated_issue_fires_named_code(self):
        events = ofl.kv_prefetch_events({"r1": 5}, 2)
        bad = dataclasses.replace(events[0],
                                  issue_tick=events[0].read_tick + 3,
                                  arrive_tick=events[0].read_tick + 3)
        diags = ofl.check_schedule([bad])
        assert diags and all(d.code == "offload-use-before-arrival"
                             for d in diags)

    def test_optimizer_roundtrip_clean_and_mutated(self):
        prog = _train_program()
        events = ofl.optimizer_roundtrip_events(prog)
        assert events                         # adam state is round-tripped
        assert ofl.check_schedule(events) == []
        # mutate: restore lands AFTER the first optimizer read
        first_read = min(e.read_tick for e in events
                         if e.direction == "h2d")
        late = ofl.optimizer_roundtrip_events(prog,
                                              restore_at=first_read + 1)
        diags = ofl.check_schedule(late)
        assert diags
        assert {d.code for d in diags} == {"offload-use-before-arrival"}


# ---------------------------------------------------------------------------
# fleet counters
# ---------------------------------------------------------------------------


class TestOffloadCounters:
    def test_stats_roundtrip(self):
        ofl.reset_offload()
        ofl.note_eviction(3)
        ofl.note_prefetch(True)
        ofl.note_prefetch(False)
        s = ofl.offload_stats()
        assert s["evictions_total"] == 3
        assert s["prefetch_hits_total"] == 1
        assert s["prefetch_misses_total"] == 1
        ofl.reset_offload()
        assert ofl.offload_stats()["evictions_total"] == 0
