"""Byte-lean input staging (layers.data staging_dtype).

The host->device link is the input-pipeline bottleneck (reference keeps the
device fed via buffered_reader, paddle/fluid/operators/reader/
buffered_reader.h:27); staging uint8 and de-quantizing on device ships 1/4
the bytes of fp32. These tests pin: (a) uint8-fed results match fp32-fed
results to staging quantization error, (b) the compiled HLO really takes a
u8 parameter (the bytes saving is in the executable, not just the intent),
(c) the host-side conversion helpers round-trip, (d) the prefetcher applies
staging on its worker thread.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.data.feeder import stage_array, stage_batch, staging_specs

pytestmark = pytest.mark.quick  # run_ci.sh quick smoke tier


def _build_staged_net():
    img = layers.data(name="img", shape=[8, 8, 3], staging_dtype="uint8")
    y = layers.reduce_mean(img * 3.0 + 0.5)
    return img, y


class TestStagedFeed:
    def test_uint8_feed_matches_fp32_feed(self):
        _, y = _build_staged_net()
        exe = pt.Executor()
        rng = np.random.RandomState(0)
        x = rng.rand(4, 8, 8, 3).astype(np.float32)

        out_fp32 = exe.run(feed={"img": x}, fetch_list=[y])[0]
        staged = stage_array(x, (np.uint8, 1.0 / 255.0))
        assert staged.dtype == np.uint8
        out_u8 = exe.run(feed={"img": staged}, fetch_list=[y])[0]
        # max quantization error per element is scale/2 = 1/510; the mean
        # reduces it further
        np.testing.assert_allclose(out_u8, out_fp32, atol=3 * (1 / 510))

    def test_hlo_parameter_is_u8(self):
        _, y = _build_staged_net()
        exe = pt.Executor()
        staged = np.zeros((4, 8, 8, 3), np.uint8)
        compiled = exe._lookup_or_compile(
            pt.default_main_program(), {"img": staged}, [y.name],
            pt.global_scope())
        import jax.numpy as jnp
        hlo = compiled.fn.lower(
            (jnp.asarray(staged),), (), (), np.uint32(0)).as_text()
        assert "tensor<4x8x8x3xui8>" in hlo

    def test_plain_data_var_rejects_mismatched_dtype_silently_casts_not(self):
        # A var WITHOUT a staging declaration must not get the magic cast:
        # the fed dtype flows through as-is (existing behavior unchanged).
        x = layers.data(name="x", shape=[3])
        y = layers.reduce_sum(x)
        exe = pt.Executor()
        out = exe.run(feed={"x": np.ones((2, 3), np.float32) * 2},
                      fetch_list=[y])[0]
        np.testing.assert_allclose(out, 12.0)

    def test_bf16_staging_no_scale(self):
        import ml_dtypes
        img = layers.data(name="xb", shape=[16], staging_dtype="bfloat16")
        assert img.staging == ("bfloat16", None)
        y = layers.reduce_sum(img)
        exe = pt.Executor()
        x = np.linspace(0, 1, 32, dtype=np.float32).reshape(2, 16)
        staged = stage_array(x, img.staging)
        assert staged.dtype == ml_dtypes.bfloat16
        out = exe.run(feed={"xb": staged}, fetch_list=[y])[0]
        np.testing.assert_allclose(out, x.sum(), rtol=1e-2)


class TestHostHelpers:
    def test_stage_array_round_trip(self):
        x = np.random.RandomState(1).rand(5, 7).astype(np.float32)
        spec = (np.uint8, 1.0 / 255.0)
        w = stage_array(x, spec)
        back = w.astype(np.float32) * (1.0 / 255.0)
        assert np.abs(back - x).max() <= (1 / 255.0) / 2 + 1e-7

    def test_stage_array_clips(self):
        x = np.array([-1.0, 0.0, 1.0, 2.0], np.float32)
        w = stage_array(x, (np.uint8, 1.0 / 255.0))
        assert w.min() == 0 and w.max() == 255

    def test_np_dtype_spelling_gets_default_scale(self):
        """Regression: staging_dtype=np.uint8 (not the string) must still
        get the 1/255 default scale — string-keyed default was silently
        dropping it."""
        v = layers.data(name="npdt", shape=[4], staging_dtype=np.uint8)
        assert v.staging[0] == np.dtype(np.uint8)
        assert v.staging[1] == pytest.approx(1.0 / 255.0)

    def test_stage_array_idempotent_on_uint8(self):
        """Regression: already-uint8 data (decoded JPEGs) must pass through
        untouched, for every spelling of the wire dtype — a str() compare
        was re-quantizing (x*255 then clip -> all white)."""
        x = np.array([10, 200], np.uint8)
        for spelling in ("uint8", np.uint8, np.dtype("uint8")):
            np.testing.assert_array_equal(
                stage_array(x, (spelling, 1.0 / 255.0)), x)

    def test_kv_segment_ids_alone_rejected(self):
        q = layers.data(name="qq", shape=[2, 8, 4])
        kv_seg = layers.data(name="kvs", shape=[8], dtype="int32")
        with pytest.raises(ValueError):
            layers.fused_attention(q, q, q, kv_segment_ids=kv_seg)

    def test_staging_specs_from_program(self):
        layers.data(name="a", shape=[4], staging_dtype="uint8")
        layers.data(name="b", shape=[4])
        specs = staging_specs()
        assert "a" in specs and "b" not in specs
        assert specs["a"][0] == "uint8"

    def test_stage_batch_leaves_unspecced(self):
        feed = {"a": np.ones((2, 4), np.float32),
                "b": np.ones((2, 4), np.float32)}
        out = stage_batch(feed, {"a": (np.uint8, 1.0 / 255.0)})
        assert out["a"].dtype == np.uint8
        assert out["b"].dtype == np.float32


class TestPrefetcherStaging:
    def test_prefetcher_stages_uint8(self):
        from paddle_tpu.data.prefetch import DevicePrefetcher
        rng = np.random.RandomState(2)

        def it():
            for _ in range(3):
                yield {"img": rng.rand(2, 8, 8, 3).astype(np.float32)}

        pf = DevicePrefetcher(it, staging={"img": ("uint8", 1.0 / 255.0)})
        batches = list(pf)
        assert len(batches) == 3
        for b in batches:
            assert str(b["img"].dtype) == "uint8"

    def test_end_to_end_train_with_staged_prefetcher(self):
        """A tiny staged-input model trains through the prefetcher and the
        loss decreases — the full byte-lean path exercised end to end."""
        from paddle_tpu.data.prefetch import DevicePrefetcher
        img = layers.data(name="img", shape=[8, 8, 3],
                          staging_dtype="uint8")
        label = layers.data(name="label", shape=[1], dtype="int64")
        flat = layers.reshape(img, shape=[-1, 8 * 8 * 3])
        logits = layers.fc(flat, size=4)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, label))
        opt = pt.optimizer.SGDOptimizer(learning_rate=0.5)
        opt.minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())

        rng = np.random.RandomState(3)
        xs = rng.rand(8, 8, 8, 3).astype(np.float32)
        ys = (xs.mean(axis=(1, 2, 3)) > 0.5).astype(np.int64)[:, None]

        def it():
            for _ in range(20):
                yield {"img": xs, "label": ys}

        specs = staging_specs()
        losses = []
        for feed in DevicePrefetcher(it, staging=specs):
            losses.append(float(exe.run(feed=feed,
                                        fetch_list=[loss])[0]))
        assert losses[-1] < losses[0]


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-x", "-q"]))


class TestPrefetcherLifecycle:
    def test_abandoned_iterator_releases_producer(self):
        """Regression: breaking out of a DevicePrefetcher loop must not
        leave the producer thread blocked in put() forever (pinning the
        pool and up to `capacity` staged device batches)."""
        import threading
        import time

        from paddle_tpu.data.prefetch import DevicePrefetcher

        batches = [{"x": np.zeros((4, 4), "float32")} for _ in range(50)]
        before = threading.active_count()
        it = iter(DevicePrefetcher(lambda: iter(batches), capacity=2,
                                   stage_threads=2))
        next(it)
        next(it)
        it.close()  # what an early `break` does to the generator
        deadline = time.time() + 10
        while time.time() < deadline:
            if threading.active_count() <= before:
                break
            time.sleep(0.1)
        assert threading.active_count() <= before, \
            "producer/pool threads leaked after abandoning the iterator"
