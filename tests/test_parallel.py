"""Parallelism tests on the virtual 8-device CPU mesh.

≙ reference test_parallel_executor_*.py (SURVEY.md §4: run real models via PE
over N devices and compare against single-device results).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import (BuildStrategy, DeviceMesh, ParallelExecutor,
                                 ReduceStrategy, make_mesh)
from paddle_tpu.parallel.pipeline import pipeline_apply
from paddle_tpu.parallel.ring_attention import ring_attention_sharded
from paddle_tpu.parallel.sharded_embedding import sharded_embedding_lookup


def _build_mlp():
    img = layers.data(name="img", shape=[16], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    h = layers.fc(img, size=32, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label))
    return loss


def _run_startup(scope=None):
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), scope=scope)
    return exe


class TestParallelExecutor:
    def _train(self, build_strategy, rng, steps=4):
        loss = _build_mlp()
        opt = pt.optimizer.AdamOptimizer(learning_rate=1e-2)
        opt.minimize(loss)
        _run_startup()
        pe = ParallelExecutor(loss_name=loss.name,
                              build_strategy=build_strategy)
        assert pe.device_count == 8
        losses = []
        x = rng.rand(32, 16).astype("float32")
        y = rng.randint(0, 10, (32, 1)).astype("int64")
        for _ in range(steps):
            out, = pe.run(fetch_list=[loss], feed={"img": x, "label": y})
            losses.append(float(out))
        return losses

    def test_allreduce_trains(self, rng):
        losses = self._train(BuildStrategy(), rng)
        assert losses[-1] < losses[0]

    def test_reduce_zero1_trains(self, rng):
        bs = BuildStrategy(reduce_strategy=ReduceStrategy.Reduce)
        losses = self._train(bs, rng)
        assert losses[-1] < losses[0]

    def test_matches_single_device(self, rng):
        """PE over 8 devices must produce the same loss trajectory as the
        plain Executor (global-batch semantics — ≙ the reference's
        PE-vs-single-device comparison tests)."""
        x = rng.rand(16, 16).astype("float32")
        y = rng.randint(0, 10, (16, 1)).astype("int64")

        def run(use_pe):
            pt.reset_default_programs()
            pt.reset_global_scope()
            from paddle_tpu.core import unique_name
            with unique_name.guard():
                loss = _build_mlp()
                opt = pt.optimizer.SGDOptimizer(learning_rate=0.1)
                opt.minimize(loss)
                _run_startup()
                exe = (ParallelExecutor(loss_name=loss.name) if use_pe
                       else pt.Executor())
                out = []
                for _ in range(3):
                    if use_pe:
                        r, = exe.run(fetch_list=[loss],
                                     feed={"img": x, "label": y})
                    else:
                        r, = exe.run(feed={"img": x, "label": y},
                                     fetch_list=[loss])
                    out.append(float(r))
                return out

        single = run(False)
        multi = run(True)
        np.testing.assert_allclose(single, multi, rtol=2e-4)

    def test_indivisible_batch_padded_and_runs(self, rng):
        """Round 4: a non-dp-divisible batch no longer raises when the
        program declares layers.batch_row_mask() and weights its loss by
        it — the feed is padded to the next dp multiple by wrapping real
        rows and the mask zeroes the wrapped ones (full loss-parity
        coverage in tests/test_uneven_batch.py, including the guard that a
        plain-mean program still raises; ≙ reference
        details/data_balance_op_handle.cc)."""
        img = layers.data(name="img", shape=[16], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        h = layers.fc(img, size=32, act="relu")
        logits = layers.fc(h, size=10)
        mask = layers.batch_row_mask()
        per_ex = layers.softmax_with_cross_entropy(logits, label)
        loss = layers.reduce_sum(per_ex * mask) / layers.reduce_sum(mask)
        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        _run_startup()
        pe = ParallelExecutor(loss_name=loss.name)
        out, = pe.run(fetch_list=[loss],
                      feed={"img": rng.rand(9, 16).astype("float32"),
                            "label": rng.randint(0, 10,
                                                 (9, 1)).astype("int64")})
        assert np.isfinite(np.asarray(out)).all()


class TestMesh:
    def test_mesh_axes(self):
        m = make_mesh({"dp": 2, "tp": 4})
        assert m.num_devices == 8
        assert m.axis_size("dp") == 2
        assert m.axis_size("pp") == 1

    def test_sharding_filters_unknown_axes(self):
        m = make_mesh({"dp": 8})
        s = m.sharding("dp", "tp", None)  # tp not in mesh -> replicated dim
        assert s is not None


class TestRingAttention:
    def _reference_attn(self, q, k, v, causal):
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if causal:
            t = q.shape[1]
            mask = np.tril(np.ones((t, t), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, rng, causal):
        mesh = make_mesh({"dp": 2, "sp": 4})
        b, t, h, d = 2, 32, 2, 8
        q = rng.randn(b, t, h, d).astype("float32")
        k = rng.randn(b, t, h, d).astype("float32")
        v = rng.randn(b, t, h, d).astype("float32")
        out = ring_attention_sharded(mesh, q, k, v, causal=causal)
        ref = self._reference_attn(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_segment_mask(self, rng):
        mesh = make_mesh({"dp": 2, "sp": 4})
        b, t, h, d = 2, 16, 1, 4
        q = rng.randn(b, t, h, d).astype("float32")
        k = rng.randn(b, t, h, d).astype("float32")
        v = rng.randn(b, t, h, d).astype("float32")
        seg = np.repeat(np.arange(4), 4)[None, :].repeat(b, 0)
        out = ring_attention_sharded(mesh, q, k, v,
                                     segment_ids=jnp.asarray(seg))
        # manual block-diagonal reference
        scale = 1.0 / np.sqrt(d)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        same = seg[:, :, None] == seg[:, None, :]
        s = jnp.where(same[:, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_gradients_flow(self, rng):
        mesh = make_mesh({"sp": 8})
        q = jnp.asarray(rng.randn(1, 16, 1, 4).astype("float32"))

        def f(q):
            return ring_attention_sharded(mesh, q, q, q, causal=True).sum()

        g = jax.grad(f)(q)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


class TestPipeline:
    def test_pipeline_matches_sequential(self, rng):
        mesh = make_mesh({"pp": 8})
        n_stage, d = 8, 16
        ws = jnp.asarray(rng.randn(n_stage, d, d).astype("float32") * 0.1)
        x = jnp.asarray(rng.randn(32, d).astype("float32"))

        def stage(p, h):
            return jnp.tanh(h @ p["w"])

        y = pipeline_apply(mesh, stage, {"w": ws}, x, num_microbatches=4)
        ref = x
        for i in range(n_stage):
            ref = stage({"w": ws[i]}, ref)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_pipeline_differentiable(self, rng):
        mesh = make_mesh({"pp": 4, "dp": 2})
        ws = jnp.asarray(rng.randn(4, 8, 8).astype("float32") * 0.1)
        x = jnp.asarray(rng.randn(8, 8).astype("float32"))

        def stage(p, h):
            return jnp.tanh(h @ p["w"])

        def loss(ws):
            y = pipeline_apply(mesh, stage, {"w": ws}, x,
                               num_microbatches=2)
            return (y ** 2).sum()

        g = jax.grad(loss)(ws)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


class TestShardedEmbedding:
    def test_lookup_matches_dense(self, rng):
        mesh = make_mesh({"dp": 2, "tp": 4})
        table = jnp.asarray(rng.randn(64, 8).astype("float32"))
        ids = jnp.asarray(rng.randint(0, 64, (4, 7)))
        out = sharded_embedding_lookup(mesh, table, ids, axis_name="tp")
        ref = jnp.take(table, ids, axis=0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)

    def test_lookup_gradient_sparse(self, rng):
        mesh = make_mesh({"tp": 8})
        table = jnp.asarray(rng.randn(16, 4).astype("float32"))
        ids = jnp.asarray([0, 3, 3, 15])

        def f(t):
            return sharded_embedding_lookup(mesh, t, ids, axis_name="tp").sum()

        g = np.asarray(jax.grad(f)(table))
        assert g[0].sum() == pytest.approx(4.0)
        assert g[3].sum() == pytest.approx(8.0)   # id 3 twice
        assert g[1].sum() == 0.0


class TestTensorParallel:
    def test_column_row_pair_matches_dense(self, rng):
        from paddle_tpu.parallel import tensor_parallel as tp
        mesh = make_mesh({"dp": 2, "tp": 4})
        x = jnp.asarray(rng.randn(8, 16).astype("float32"))
        w1 = jnp.asarray(rng.randn(16, 32).astype("float32") * 0.1)
        b1 = jnp.asarray(rng.randn(32).astype("float32") * 0.1)
        w2 = jnp.asarray(rng.randn(32, 16).astype("float32") * 0.1)

        @jax.jit
        def mlp(x, w1, b1, w2):
            with mesh.jax_mesh:
                h = tp.column_parallel_matmul(x, w1, b1)
                h = jax.nn.relu(h)
                return tp.row_parallel_matmul(h, w2)

        with mesh.jax_mesh:
            y = mlp(x, w1, b1, w2)
        ref = jax.nn.relu(x @ w1 + b1) @ w2
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_specs(self):
        from paddle_tpu.parallel import tensor_parallel as tp
        assert tp.column_parallel_spec(2)[-1] == "tp"
        assert tp.row_parallel_spec(2)[0] == "tp"


class TestPipelineShapeCheck:
    def test_shape_changing_stage_raises(self, rng):
        mesh = make_mesh({"pp": 8})
        ws = jnp.asarray(rng.randn(8, 16, 8).astype("float32"))
        x = jnp.asarray(rng.randn(16, 16).astype("float32"))
        with pytest.raises(ValueError, match="same shape/dtype"):
            pipeline_apply(mesh, lambda p, h: h @ p["w"], {"w": ws}, x, 4)


class TestRingAttentionPrecondition:
    def test_missing_sp_axis_raises(self, rng):
        mesh = make_mesh({"dp": 8})
        q = jnp.asarray(rng.randn(2, 8, 1, 4).astype("float32"))
        with pytest.raises(ValueError, match="requires a 'sp' axis"):
            ring_attention_sharded(mesh, q, q, q)
