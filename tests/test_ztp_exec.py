"""Executor-level tensor-parallel suite: fixed-seed parity of the
tp_shard_pass + full-manual shard_map path vs the single-device baseline
on tp2 / dp2xtp2 / dp2xpp2xtp2 CPU meshes (ReduceScatter mode), the HLO
tp-collective census asserted against the analytic ring model, quantized
composition, and the PTPU_TP_SHARD kill switch.

(Named test_ztp_* so the heavyweight compiles sort after the whole suite —
the same discipline as test_zero_comm.py / test_zpipeline_exec.py; the
fast propagation/pass/gate unit half lives in tests/test_sharding_prop.py.)
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core import flags
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.framework.sharding import tp_analytic_wire_bytes
from paddle_tpu.parallel import ParallelExecutor, annotate_tp
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from probe_common import collective_census  # noqa: E402

VOCAB, T, D, HEADS, LAYERS = 64, 8, 32, 4, 2


def _build(mean_loss=True):
    from paddle_tpu.models import transformer
    loss, _ = transformer.transformer_lm(
        vocab=VOCAB, max_len=T, d_model=D, d_inner=2 * D,
        num_heads=HEADS, num_layers=LAYERS, mean_loss=mean_loss)
    pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return loss


def _feeds(n=3, bs=8):
    rng = np.random.RandomState(7)
    out = []
    for _ in range(n):
        out.append({
            "tokens": rng.randint(0, VOCAB, (bs, T)).astype("int64"),
            "tokens@SEQLEN": np.full((bs,), T, dtype="int32"),
            "targets": rng.randint(0, VOCAB, (bs, T)).astype("int64")})
    return out


@pytest.fixture(autouse=True)
def _f32_matmuls():
    """Parity runs compare f32-exact: splitting a bf16 contraction over tp
    changes its rounding, which is precision noise, not a sharding bug."""
    old = flags.get_flag("use_bf16_matmul")
    flags.set_flag("use_bf16_matmul", False)
    yield
    flags.set_flag("use_bf16_matmul", old)


def _baseline(feeds):
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = _build()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]


def _tp_run(feeds, axes, stages=0, micro=0, quant="", use_steps=False):
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = _build()
    annotated = annotate_tp()
    assert annotated
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    n = int(np.prod(list(axes.values())))
    kw = {}
    if stages:
        kw = dict(pipeline_stages=stages, num_microbatches=micro)
    bst = BuildStrategy(**kw)
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    bst.quant_comm = quant
    mesh = DeviceMesh(jax.devices()[:n], axes)
    pe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                          build_strategy=bst)
    if use_steps:
        out = pe.run_steps(feeds, fetch_list=[loss])
        losses = [float(v) for v in np.asarray(out[0]).ravel()]
    else:
        losses = [float(pe.run(feed=f, fetch_list=[loss])[0])
                  for f in feeds]
    return losses, pe, loss


def _compiled_hlo(exe, feed):
    scope = pt.global_scope()
    cs = list(exe._cache.values())[-1]
    feed_vals = tuple(jnp.asarray(feed[n]) for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    return cs.fn.lower(feed_vals, ro, rw, np.uint32(0)).compile().as_text()


# ---------------------------------------------------------------------------
# fixed-seed parity vs the single-device baseline (the acceptance bar)
# ---------------------------------------------------------------------------


class TestTpParity:
    @pytest.mark.quick
    def test_tp2_parity(self):
        feeds = _feeds()
        base = _baseline(feeds)
        got, exe, _ = _tp_run(feeds, {"dp": 1, "tp": 2})
        np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)
        prog = exe._prepare_program(pt.default_main_program(),
                                    pt.global_scope())
        assert prog._tp_applied and prog._tp_size == 2

    def test_dp2_tp2_parity(self):
        feeds = _feeds()
        base = _baseline(feeds)
        got, _, _ = _tp_run(feeds, {"dp": 2, "tp": 2})
        np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)

    def test_dp2_pp2_tp2_parity_3d_mesh(self):
        """The full 3D composition: explicit dp reduce-scatter pipeline +
        1F1B pipeline schedule + tp collectives on one dp x pp x tp mesh."""
        feeds = _feeds()
        base = _baseline(feeds)
        got, exe, _ = _tp_run(feeds, {"dp": 2, "pp": 2, "tp": 2},
                              stages=2, micro=4)
        np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)
        prog = exe._prepare_program(pt.default_main_program(),
                                    pt.global_scope())
        assert prog._tp_applied and prog._dp_comm_applied \
            and prog._pp_applied

    def test_run_steps_scan_fused_tp(self):
        feeds = _feeds()
        base = _baseline(feeds)
        got, _, _ = _tp_run(feeds, {"dp": 2, "tp": 2}, use_steps=True)
        np.testing.assert_allclose(got, base, rtol=0, atol=1e-5)


# ---------------------------------------------------------------------------
# census: the compiled step's tp collectives == the analytic plan
# ---------------------------------------------------------------------------


class TestTpCensus:
    def test_allreduce_census_matches_analytic(self):
        """On a tp-only mesh (dp=1) every >=8-byte all-reduce in the
        compiled HLO is a tp collective the pass spliced (fwd psums +
        tp_ident backward psums + vocab-lookup psums): their total output
        bytes must equal the analytic model's psum'd bytes exactly."""
        feeds = _feeds(1)
        got, exe, _ = _tp_run(feeds, {"dp": 1, "tp": 2})
        prog = exe._prepare_program(pt.default_main_program(),
                                    pt.global_scope())
        w = tp_analytic_wire_bytes(prog, 2, nominal_batch=8)
        assert w is not None and w["tp_wire_bytes"] > 0
        census = collective_census(_compiled_hlo(exe, feeds[0]))
        ar_census = sum(b for b, _ in census.get("all-reduce", [])
                        if b >= 8)
        # analytic all-reduce wire = 2 n (tp-1)/tp over psum'd bytes n:
        # invert the ring factor to compare OUTPUT bytes with the census
        tp = 2
        ar_analytic = w["tp_allreduce_wire_bytes"] / (2 * (tp - 1) / tp)
        assert ar_census == int(ar_analytic), (
            ar_census, ar_analytic, {k: len(v) for k, v in census.items()})

    def test_counts_and_kinds(self):
        feeds = _feeds(1)
        _, exe, _ = _tp_run(feeds, {"dp": 1, "tp": 2})
        prog = exe._prepare_program(pt.default_main_program(),
                                    pt.global_scope())
        w = tp_analytic_wire_bytes(prog, 2, nominal_batch=8)
        counts = w["tp_op_counts"]
        # the Megatron recipe on a 2-layer decoder: one fwd psum per
        # attention out-proj + per ffn down-proj + the lm head row matmul,
        # plus the vocab-sharded embedding lookup
        assert counts["tp_allreduce"] == 2 * LAYERS + 1
        assert counts["tp_vocab_lookup"] == 1
        assert counts["tp_ident"] >= LAYERS  # deduped per variable
        # the lm head is Megatron's row entry: its (replicated,
        # post-layernorm) input is locally sliced, backward all-gathers
        assert counts["tp_split"] == 1
        ops = [op.type for op in prog.global_block().ops]
        assert ops.count("tp_vocab_lookup") == 1


# ---------------------------------------------------------------------------
# quantized-dp composition
# ---------------------------------------------------------------------------


class TestQuantComposition:
    def test_dp2_tp2_quant_bf16_runs_close(self):
        """bf16 wire quantization under tp: not bit-exact (gradients lose
        mantissa on the wire) but the 3-step trajectory stays within wire-
        precision distance of the exact run, and the error-feedback state
        is laid out per (dp x tp) coordinate."""
        feeds = _feeds()
        base = _baseline(feeds)
        bst_losses, exe, _ = _tp_run(feeds, {"dp": 2, "tp": 2},
                                     quant="bf16")
        np.testing.assert_allclose(bst_losses, base, rtol=0, atol=5e-2)
        assert all(np.isfinite(v) for v in bst_losses)

    def test_error_feedback_state_covers_dp_x_tp(self):
        pt.reset_default_programs()
        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            loss = _build()
        annotate_tp()
        pt.Executor().run(pt.default_startup_program())
        bst = BuildStrategy()
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
        bst.quant_comm = "int8"
        bst.comm_error_feedback = True
        mesh = DeviceMesh(jax.devices()[:4], {"dp": 2, "tp": 2})
        pe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                              build_strategy=bst)
        prog = pe._prepare_program(pt.default_main_program(),
                                   pt.global_scope())
        errs = [v for v in prog.global_block().vars.values()
                if getattr(v, "dp_replica_state", False)]
        assert errs
        for v in errs:
            assert v.shape[0] == 4  # dp * tp coordinates
            assert getattr(v, "tp_spec", None) == ("tp", None)


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------


class TestKillSwitch:
    def test_tp_shard_off_restores_the_gate(self):
        pt.reset_default_programs()
        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            loss = _build()
        annotate_tp()
        pt.Executor().run(pt.default_startup_program())
        bst = BuildStrategy()
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
        mesh = DeviceMesh(jax.devices()[:2], {"dp": 1, "tp": 2})
        pe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                              build_strategy=bst)
        old = flags.get_flag("tp_shard")
        try:
            flags.set_flag("tp_shard", False)
            with pytest.raises(InvalidArgumentError,
                               match="PTPU_TP_SHARD"):
                pe.run(feed=_feeds(1)[0], fetch_list=[loss])
        finally:
            flags.set_flag("tp_shard", old)
