"""Detection op family tests vs numpy references.

≙ reference tests test_iou_similarity_op.py, test_box_coder_op.py,
test_prior_box_op.py, test_anchor_generator_op.py, test_bipartite_match_op
.py, test_target_assign_op.py, test_multiclass_nms_op.py, test_roi_pool_op
.py + layers/detection.py coverage (test_detection.py).
"""

import numpy as np
import pytest

from op_test import check_grad, run_op


def np_iou(x, y):
    n, m = x.shape[0], y.shape[0]
    out = np.zeros((n, m))
    for i in range(n):
        for j in range(m):
            ax = max(x[i, 2] - x[i, 0], 0) * max(x[i, 3] - x[i, 1], 0)
            ay = max(y[j, 2] - y[j, 0], 0) * max(y[j, 3] - y[j, 1], 0)
            iw = min(x[i, 2], y[j, 2]) - max(x[i, 0], y[j, 0])
            ih = min(x[i, 3], y[j, 3]) - max(x[i, 1], y[j, 1])
            inter = max(iw, 0) * max(ih, 0)
            u = ax + ay - inter
            out[i, j] = inter / u if u > 0 else 0.0
    return out


def _rand_boxes(rng, n, scale=1.0):
    xy = rng.rand(n, 2) * 0.6 * scale
    wh = (rng.rand(n, 2) * 0.3 + 0.05) * scale
    return np.concatenate([xy, xy + wh], axis=1).astype("float32")


class TestIoUAndCoder:
    def test_iou_matches_numpy(self, rng):
        x, y = _rand_boxes(rng, 5), _rand_boxes(rng, 7)
        out = run_op("iou_similarity", {"X": x, "Y": y})["Out"][0]
        np.testing.assert_allclose(out, np_iou(x, y), atol=1e-5)

    def test_box_coder_roundtrip(self, rng):
        """decode(encode(t)) == t for every (target, prior) pair."""
        prior = _rand_boxes(rng, 6)
        pvar = (rng.rand(6, 4) * 0.2 + 0.1).astype("float32")
        target = _rand_boxes(rng, 3)
        enc = run_op("box_coder",
                     {"PriorBox": prior, "PriorBoxVar": pvar,
                      "TargetBox": target},
                     attrs={"code_type": "encode_center_size"})["OutputBox"][0]
        dec = run_op("box_coder",
                     {"PriorBox": prior, "PriorBoxVar": pvar,
                      "TargetBox": enc},
                     attrs={"code_type": "decode_center_size"})["OutputBox"][0]
        # every row of dec should reproduce the original target box
        for j in range(6):
            np.testing.assert_allclose(dec[:, j, :], target, atol=1e-4)


class TestPriorsAnchors:
    def test_prior_box_shapes_and_geometry(self, rng):
        feat = rng.rand(1, 8, 4, 4).astype("float32")
        img = rng.rand(1, 3, 64, 64).astype("float32")
        out = run_op("prior_box", {"Input": feat, "Image": img},
                     attrs={"min_sizes": [16.0], "max_sizes": [32.0],
                            "aspect_ratios": [2.0], "flip": True,
                            "clip": True})
        boxes, var = out["Boxes"][0], out["Variances"][0]
        # P = 1 (ar=1) + 2 (ar=2, flip) + 1 (sqrt(min*max)) = 4
        assert boxes.shape == (4, 4, 4, 4) and var.shape == boxes.shape
        assert (boxes >= 0).all() and (boxes <= 1).all()
        # the ar=1 prior at cell (0,0): centered at offset*step=8, size 16
        b = boxes[0, 0, 0] * 64
        np.testing.assert_allclose(b, [0, 0, 16, 16], atol=1e-4)
        assert (boxes[..., 2] >= boxes[..., 0]).all()

    def test_density_prior_box_count(self, rng):
        feat = rng.rand(1, 8, 2, 2).astype("float32")
        img = rng.rand(1, 3, 32, 32).astype("float32")
        out = run_op("density_prior_box", {"Input": feat, "Image": img},
                     attrs={"fixed_sizes": [8.0], "fixed_ratios": [1.0],
                            "densities": [2]})
        assert out["Boxes"][0].shape == (2, 2, 4, 4)

    def test_anchor_generator(self, rng):
        feat = rng.rand(1, 8, 3, 3).astype("float32")
        out = run_op("anchor_generator", {"Input": feat},
                     attrs={"anchor_sizes": [32.0, 64.0],
                            "aspect_ratios": [1.0],
                            "stride": [16.0, 16.0]})
        anchors = out["Anchors"][0]
        assert anchors.shape == (3, 3, 2, 4)
        # size-32 anchor at cell center (8, 8): 32x32 box
        a = anchors[0, 0, 0]
        np.testing.assert_allclose(a[2] - a[0], 32.0, atol=1e-3)
        np.testing.assert_allclose((a[0] + a[2]) / 2, 8.0, atol=1e-3)


class TestMatching:
    def test_bipartite_greedy_matches_best_pairs(self):
        # row 0 best with col 1 (0.9); row 1 best remaining with col 0 (0.6)
        dist = np.array([[0.3, 0.9, 0.1],
                         [0.6, 0.8, 0.2]], dtype="float32")
        out = run_op("bipartite_match", {"DistMat": dist})
        idx = out["ColToRowMatchIndices"][0]
        d = out["ColToRowMatchDist"][0]
        assert idx[1] == 0 and d[1] == pytest.approx(0.9)
        assert idx[0] == 1 and d[0] == pytest.approx(0.6)
        assert idx[2] == -1

    def test_per_prediction_threshold(self):
        dist = np.array([[0.3, 0.9, 0.45]], dtype="float32")
        out = run_op("bipartite_match", {"DistMat": dist},
                     attrs={"match_type": "per_prediction",
                            "dist_threshold": 0.4})
        idx = out["ColToRowMatchIndices"][0]
        # col1 bipartite-matched; col2 clears 0.4 threshold; col0 does not
        assert idx[1] == 0 and idx[2] == 0 and idx[0] == -1

    def test_target_assign(self):
        x = np.arange(12, dtype="float32").reshape(1, 3, 4)   # 3 gt rows
        match = np.array([[1, -1, 0, 2]], dtype="int32")
        out = run_op("target_assign", {"X": x, "MatchIndices": match},
                     attrs={"mismatch_value": 7})
        got, w = out["Out"][0], out["OutWeight"][0]
        np.testing.assert_array_equal(got[0, 0], x[0, 1])
        np.testing.assert_array_equal(got[0, 1], [7, 7, 7, 7])
        np.testing.assert_array_equal(got[0, 2], x[0, 0])
        np.testing.assert_array_equal(w[0, :, 0], [1, 0, 1, 1])


class TestNMS:
    def test_multiclass_nms_suppresses_overlaps(self):
        # two heavily-overlapping boxes + one distant; class 1 only
        boxes = np.array([[[0.0, 0.0, 0.4, 0.4],
                           [0.01, 0.01, 0.41, 0.41],
                           [0.6, 0.6, 0.9, 0.9]]], dtype="float32")
        scores = np.zeros((1, 2, 3), dtype="float32")
        scores[0, 1] = [0.9, 0.8, 0.7]
        out = run_op("multiclass_nms",
                     {"BBoxes": boxes, "Scores": scores},
                     attrs={"score_threshold": 0.1, "nms_threshold": 0.5,
                            "keep_top_k": 5, "background_label": 0})
        rows, num = out["Out"][0], out["NmsRoisNum"][0]
        assert num[0] == 2          # overlap suppressed
        assert rows[0, 0, 0] == 1 and rows[0, 0, 1] == pytest.approx(0.9)
        np.testing.assert_allclose(rows[0, 1, 2:], [0.6, 0.6, 0.9, 0.9],
                                   atol=1e-5)
        assert (rows[0, 2:, 0] == -1).all()   # padding

    def test_background_class_excluded(self):
        boxes = np.array([[[0.0, 0.0, 0.4, 0.4]]], dtype="float32")
        scores = np.zeros((1, 2, 1), dtype="float32")
        scores[0, 0, 0] = 0.95     # background
        scores[0, 1, 0] = 0.4
        out = run_op("multiclass_nms",
                     {"BBoxes": boxes, "Scores": scores},
                     attrs={"score_threshold": 0.1, "keep_top_k": 3,
                            "background_label": 0})
        assert out["NmsRoisNum"][0][0] == 1
        assert out["Out"][0][0, 0, 0] == 1


class TestRoiPool:
    def test_matches_manual_max(self, rng):
        x = rng.rand(1, 2, 8, 8).astype("float32")
        rois = np.array([[0, 0, 0, 3, 3],     # 4x4 region -> 2x2 bins
                         [0, 4, 4, 7, 7]], dtype="float32")
        out = run_op("roi_pool", {"X": x, "ROIs": rois},
                     attrs={"pooled_height": 2, "pooled_width": 2,
                            "spatial_scale": 1.0})["Out"][0]
        assert out.shape == (2, 2, 2, 2)
        np.testing.assert_allclose(out[0, 0, 0, 0], x[0, 0, 0:2, 0:2].max(),
                                   rtol=1e-6)
        np.testing.assert_allclose(out[1, 1, 1, 1], x[0, 1, 6:8, 6:8].max(),
                                   rtol=1e-6)

    def test_grad_flows_to_features(self, rng):
        x = rng.rand(1, 1, 6, 6).astype("float32")
        rois = np.array([[0, 0, 0, 5, 5]], dtype="float32")
        check_grad("roi_pool", {"X": x, "ROIs": rois},
                   grad_slots=["X"],
                   attrs={"pooled_height": 2, "pooled_width": 2,
                          "spatial_scale": 1.0}, atol=5e-2, rtol=5e-2)


class TestSSDPipeline:
    def test_ssd_loss_trains_detection_head(self, rng):
        """End-to-end: multi_box_head + ssd_loss trains; detection_output
        decodes (≙ book SSD flow built from the detection layers)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.layers import detection as det

        B, G = 2, 3
        img = layers.data("img", shape=[3, 32, 32])
        gt_box = layers.data("gt_box", shape=[G, 4])
        gt_label = layers.data("gt_label", shape=[G], dtype="int64")

        feat = layers.conv2d(img, num_filters=8, filter_size=3, padding=1,
                             act="relu")
        feat = layers.pool2d(feat, pool_size=4, pool_stride=4)  # [B,8,8,8]
        locs, confs, boxes, variances = det.multi_box_head(
            [feat], img, num_classes=3, min_sizes=[[8.0]],
            aspect_ratios=[[1.0]], name="mbh")
        loss = det.ssd_loss(locs, confs, gt_box, gt_label, boxes,
                            overlap_threshold=0.3)
        pt.optimizer.AdamOptimizer(learning_rate=1e-2).minimize(loss)

        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        x = rng.rand(B, 3, 32, 32).astype("float32")
        gb = np.zeros((B, G, 4), dtype="float32")
        gl = np.zeros((B, G), dtype="int64")
        for b in range(B):
            gb[b, 0] = [0.1, 0.1, 0.4, 0.4]
            gl[b, 0] = 1
            gb[b, 1] = [0.5, 0.5, 0.9, 0.9]
            gl[b, 1] = 2
            # row 2 stays zero-area = padding
        feed = {"img": x, "gt_box": gb, "gt_label": gl}
        l0 = float(exe.run(feed=feed, fetch_list=[loss])[0])
        for _ in range(15):
            l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
        assert np.isfinite(l1) and l1 < l0

        # inference: decode + NMS over the trained head
        probs = layers.softmax(confs)
        scores = layers.transpose(probs, perm=[0, 2, 1])   # [B,C,M]
        out, num = det.detection_output(locs, scores, boxes, variances,
                                        score_threshold=0.01,
                                        keep_top_k=10)
        res, cnt = exe.run(feed=feed, fetch_list=[out, num])
        assert res.shape == (B, 10, 6)
        assert (cnt >= 0).all()
