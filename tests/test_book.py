"""End-to-end "book" flows: train -> save inference model -> reload -> infer.

≙ reference tests/book/test_{fit_a_line, word2vec, recommender_system,
understand_sentiment}.py (SURVEY.md §4 "End-to-end book tests" — each
trains briefly, saves an inference model, reloads it in a fresh scope, and
infers). recognize_digits / image_classification / machine_translation /
label_semantic_roles equivalents live in test_mnist_mlp.py,
test_models.py, test_machine_translation.py, test_sequence_labeling.py.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.data import datasets as D


def _train_save_load(loss, feed_fn, feed_names, targets, tmp_path, steps=30,
                     lr=0.01, opt="sgd"):
    """Shared book-flow driver; returns (infer_fn, first_loss, last_loss)."""
    optimizer = (pt.optimizer.AdamOptimizer(lr) if opt == "adam"
                 else pt.optimizer.SGDOptimizer(lr))
    optimizer.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    first = last = None
    for i in range(steps):
        out = exe.run(feed=feed_fn(i), fetch_list=[loss])[0]
        first = out if first is None else first
        last = out
    model_dir = str(tmp_path / "model")
    pt.io.save_inference_model(model_dir, feed_names, targets, exe)

    # fresh process equivalent: new scope + program loaded from disk
    pt.reset_default_programs()
    pt.reset_global_scope()
    exe2 = pt.Executor()
    program, feeds, fetches = pt.io.load_inference_model(model_dir, exe2)

    def infer(feed):
        return exe2.run(program, feed=feed, fetch_list=fetches)

    return infer, float(np.asarray(first).reshape(-1)[0]), \
        float(np.asarray(last).reshape(-1)[0])


class TestFitALine:
    def test_linear_regression_book_flow(self, rng, tmp_path):
        """≙ book test_fit_a_line: uci_housing linear regressor."""
        batch = [s for _, s in zip(range(64), D.uci_housing.train()())]
        xs = np.stack([b[0] for b in batch]).astype("float32")
        ys = np.asarray([b[1] for b in batch], "float32").reshape(-1, 1)

        x = layers.data("x", shape=[xs.shape[1]])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))

        infer, first, last = _train_save_load(
            loss, lambda i: {"x": xs, "y": ys}, ["x"], [pred], tmp_path,
            steps=50, lr=0.01, opt="adam")
        assert last < first
        out = infer({"x": xs[:4]})[0]
        assert out.shape == (4, 1) and np.isfinite(out).all()


class TestWord2Vec:
    def test_ngram_lm_book_flow(self, rng, tmp_path):
        """≙ book test_word2vec: N-gram next-word model over shared
        embeddings."""
        V, E, N = 200, 16, 4
        samples = [s for _, s in zip(range(128), D.imikolov.train(n=N + 1)())]
        grams = np.asarray([s[:N] for s in samples], "int64") % V
        nxt = np.asarray([s[N] for s in samples], "int64").reshape(-1, 1) % V

        words = [layers.data(f"w{i}", shape=[1], dtype="int64")
                 for i in range(N)]
        embs = [layers.embedding(w, size=[V, E],
                                 param_attr=pt.ParamAttr(name="shared_emb"))
                for w in words]
        concat = layers.concat([layers.reshape(e, shape=[-1, E])
                                for e in embs], axis=1)
        h = layers.fc(concat, size=64, act="relu")
        logits = layers.fc(h, size=V)
        label = layers.data("next", shape=[1], dtype="int64")
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))

        def feed(i):
            f = {f"w{k}": grams[:, k:k + 1] for k in range(N)}
            f["next"] = nxt
            return f

        infer, first, last = _train_save_load(
            loss, feed, [f"w{i}" for i in range(N)], [logits], tmp_path,
            steps=40, lr=5e-3, opt="adam")
        assert last < first
        out = infer({f"w{k}": grams[:2, k:k + 1] for k in range(N)})[0]
        assert out.shape == (2, V)


class TestRecommenderSystem:
    def test_movielens_book_flow(self, rng, tmp_path):
        """≙ book test_recommender_system: user/movie towers -> cos_sim
        rating."""
        samples = [s for _, s in zip(range(256), D.movielens.train()())]
        uid = np.asarray([s[0] for s in samples], "int64").reshape(-1, 1)
        gender = np.asarray([s[1] for s in samples], "int64").reshape(-1, 1)
        age = np.asarray([s[2] for s in samples], "int64").reshape(-1, 1)
        job = np.asarray([s[3] for s in samples], "int64").reshape(-1, 1)
        mid = np.asarray([s[4] for s in samples], "int64").reshape(-1, 1)
        rating = np.asarray([s[7] for s in samples],
                            "float32").reshape(-1, 1)

        def tower(name, inputs_sizes):
            feats = []
            for nm, vocab in inputs_sizes:
                v = layers.data(nm, shape=[1], dtype="int64")
                feats.append(layers.reshape(
                    layers.embedding(v, size=[vocab, 16]), shape=[-1, 16]))
            return layers.fc(layers.concat(feats, axis=1), size=32,
                             act="tanh", name=name)

        usr = tower("usr_fc", [("uid", D.movielens.MAX_USER + 1),
                               ("gender", 2),
                               ("age", D.movielens.NUM_AGES),
                               ("job", D.movielens.NUM_JOBS)])
        mov = tower("mov_fc", [("mid", D.movielens.MAX_MOVIE + 1)])
        sim = layers.cos_sim(usr, mov)
        scaled = layers.scale(sim, scale=5.0)
        label = layers.data("rating", shape=[1])
        loss = layers.mean(layers.square_error_cost(scaled, label))

        feed_all = {"uid": uid, "gender": gender, "age": age, "job": job,
                    "mid": mid, "rating": rating}
        infer, first, last = _train_save_load(
            loss, lambda i: feed_all,
            ["uid", "gender", "age", "job", "mid"], [scaled], tmp_path,
            steps=60, lr=5e-3, opt="adam")
        assert last < first
        out = infer({k: v[:4] for k, v in feed_all.items()
                     if k != "rating"})[0]
        assert out.shape == (4, 1)
        assert np.isfinite(out).all()


class TestUnderstandSentiment:
    def test_stacked_lstm_book_flow(self, rng, tmp_path):
        """≙ book test_understand_sentiment (stacked LSTM variant) over the
        synthetic sentiment set."""
        from paddle_tpu.models import stacked_lstm

        T = 24
        samples = [s for _, s in zip(range(64), D.sentiment.train()())]
        toks = np.zeros((len(samples), T), "int64")
        lens = np.zeros((len(samples),), "int32")
        labels = np.zeros((len(samples), 1), "int64")
        for i, (t, y) in enumerate(samples):
            n = min(len(t), T)
            toks[i, :n] = t[:n]
            lens[i] = n
            labels[i, 0] = y

        loss, acc, logits = stacked_lstm.stacked_lstm_net(
            dict_dim=D.sentiment.VOCAB, emb_dim=32, hid_dim=32,
            stacked_num=2, max_len=T)
        feed = {"words": toks, "words@SEQLEN": lens, "label": labels}
        infer, first, last = _train_save_load(
            loss, lambda i: feed, ["words", "words@SEQLEN"], [logits],
            tmp_path, steps=25, lr=2e-3, opt="adam")
        assert last < first
        out = infer({"words": toks[:4], "words@SEQLEN": lens[:4]})[0]
        assert out.shape == (4, 2)


class TestLabelSemanticRoles:
    def test_bilstm_crf_book_flow(self, rng, tmp_path):
        """Book chapter 7 (label_semantic_roles) flow: embedding -> BiLSTM
        -> CRF trained end to end, Viterbi decode against the trained
        transitions, chunk-level F1 — the last book chapter as one flow
        (≙ reference book/07.label_semantic_roles built over
        linear_chain_crf/crf_decoding/chunk_eval)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.layers import sequence as seq

        B, T, V, NT = 16, 10, 60, 5         # NT tag types
        words = layers.data("words", shape=[T], dtype="int64",
                            lod_level=1)
        label = layers.data("label", shape=[T], dtype="int64")
        length = seq.get_seqlen(words)

        emb = layers.embedding(words, size=[V, 24])
        emb = seq.tag_sequence(emb, length)
        fwd_in = seq.tag_sequence(
            layers.fc(emb, size=32 * 4, num_flatten_dims=2), length)
        bwd_in = seq.tag_sequence(
            layers.fc(emb, size=32 * 4, num_flatten_dims=2), length)
        fwd, _ = seq.dynamic_lstm(fwd_in, size=32 * 4)
        bwd, _ = seq.dynamic_lstm(bwd_in, size=32 * 4, is_reverse=True)
        hidden = seq.tag_sequence(layers.concat([fwd, bwd], axis=2),
                                  length)
        emission = layers.fc(hidden, size=NT, num_flatten_dims=2)

        crf_cost = layers.linear_chain_crf(
            emission, label, length,
            param_attr=pt.ParamAttr(name="srl_crfw"))
        loss = layers.mean(crf_cost)
        pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)

        exe = pt.Executor()
        exe.run(pt.default_startup_program())

        def batch():
            w = rng.randint(0, V, (B, T)).astype("int64")
            lab = (w % NT).astype("int64")   # learnable tagging rule
            return {"words": w, "words@SEQLEN": np.full((B,), T, "int32"),
                    "label": lab}

        feed = batch()
        first = float(exe.run(feed=feed, fetch_list=[loss])[0])
        for _ in range(60):
            feed = batch()
            last = float(exe.run(feed=feed, fetch_list=[loss])[0])
        assert last < first * 0.5, (first, last)

        # inference: Viterbi decode with the trained transitions + F1
        path = layers.sequence.crf_decoding(
            emission, length, param_attr=pt.ParamAttr(name="srl_crfw"))
        p, r, f1, *_ = layers.sequence.chunk_eval(
            path, label, length, chunk_scheme="plain", num_chunk_types=NT)
        feed = batch()
        decoded, f1_val = exe.run(feed=feed, fetch_list=[path, f1])
        expect = feed["words"] % NT
        acc = float((decoded == expect).mean())
        assert acc > 0.9, acc
        assert 0.0 <= float(f1_val) <= 1.0
