"""Static program analyzer tests (framework/analysis.py).

Three layers under test, mirroring the subsystem:
1. shape/dtype inference — every model builder in paddle_tpu/models verifies
   clean (train AND cloned-for-test programs), and seeded corruption (a
   shape lie) is caught with block/op#/op.type provenance;
2. structural + parallel verification — dropped producers, duplicate
   writers, broken pp_send/pp_recv pairs, displaced dp_grad_comm;
3. pass sanitizer — a deliberately broken pass rewrite is attributed to the
   pass by name (≙ the HLO verifier failing between two XLA passes).
"""

import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.core.enforce import NotFoundError
from paddle_tpu.framework import analysis
from paddle_tpu.framework.passes import Pass, get_pass, register_pass


def _errors(diags):
    return [d for d in diags if d.severity == "error"]


def _codes(diags):
    return {d.code for d in diags}


# ---------------------------------------------------------------------------
# every model builder verifies clean (train + cloned-for-test)
# ---------------------------------------------------------------------------


def _mt_train():
    from paddle_tpu.models import machine_translation as mt
    src = layers.data("src", shape=[6], dtype="int64")
    src_lens = layers.data("src_lens", shape=[], dtype="int64")
    tgt_in = layers.data("tgt_in", shape=[6], dtype="int64")
    tgt_out = layers.data("tgt_out", shape=[6], dtype="int64")
    tgt_mask = layers.data("tgt_mask", shape=[6], dtype="float32")
    return mt.train_net(src, src_lens, tgt_in, tgt_out, tgt_mask,
                        dict_size=200, embed_dim=16, hidden_dim=16)[0]


def _tp_transformer():
    """tp-annotated transformer (framework/sharding.py): analyze_program
    folds sharding propagation in whenever live tp annotations exist, so
    this builder keeps the propagation rules green on the flagship DAG."""
    from paddle_tpu.parallel import annotate_tp
    loss, _ = models.transformer.transformer_lm(
        vocab=256, max_len=16, d_model=32, d_inner=64, num_heads=2,
        num_layers=2, mean_loss=True)
    annotate_tp()
    return loss


def _decode_tick():
    """The continuous-batching engine's compiled step: an INFERENCE
    program (returns None — no loss to minimize), linted plain-config
    only like the serving path in tools/lint_program.py."""
    models.transformer.transformer_lm_decode_tick(
        n_slots=2, vocab=100, max_len=16, d_model=32, d_inner=64,
        num_heads=4, num_layers=2)
    return None


def _prefill():
    """The teacher-forced prefill + generation program the engine's
    prompt phase shares weights with."""
    models.transformer.transformer_lm_generate(
        vocab=100, max_gen=4, d_model=32, d_inner=64, num_heads=4,
        num_layers=2, beam_size=4)
    return None


def _paged_decode_tick():
    """The paged engine's compiled step (serving/kv_pager.py): block-table
    gather + paged_cache_write over the shared pools."""
    models.transformer.transformer_lm_paged_decode_tick(
        n_slots=2, n_blocks=9, block_size=4, blocks_per_req=4,
        vocab=100, d_model=32, d_inner=64, num_heads=4, num_layers=2)
    return None


def _quant_decode_tick():
    """The weight-only quantized engine's compiled step: the decode tick
    rewritten in place by quantize_params_pass (startup runs first so the
    pass has real weight arrays to quantize) — keeps qmatmul/qlookup
    shape inference green in the analyzer."""
    import paddle_tpu as pt
    from paddle_tpu.framework.passes import get_pass
    models.transformer.transformer_lm_decode_tick(
        n_slots=2, vocab=100, max_len=16, d_model=32, d_inner=64,
        num_heads=4, num_layers=2)
    pt.Executor().run(pt.default_startup_program())
    get_pass("quantize_params_pass", bits=8)(
        pt.default_main_program(), pt.global_scope())
    return None


def _draft_tick():
    """The speculative draft model's compiled tick (serving/speculative.py):
    the target architecture at half depth under the draft_ prefix, logp
    emitted for rejection sampling."""
    models.transformer.transformer_lm_decode_tick(
        n_slots=2, vocab=100, max_len=16, d_model=32, d_inner=64,
        num_heads=4, num_layers=1, cache_prefix="sadr",
        param_prefix="draft_", emit_logp=True)
    return None


def _spec_verify_tick():
    """The speculative verify forward: γ+1 window positions scored
    through one target forward against the slot caches."""
    models.transformer.transformer_lm_spec_verify_tick(
        n_slots=2, gamma=3, vocab=100, max_len=16, d_model=32,
        d_inner=64, num_heads=4, num_layers=2)
    return None


def _paged_spec_verify_tick():
    """... and its paged twin: the same window through the block-table
    gather + paged_cache_write path."""
    models.transformer.transformer_lm_paged_spec_verify_tick(
        n_slots=2, gamma=3, n_blocks=9, block_size=4,
        blocks_per_req=4, vocab=100, d_model=32, d_inner=64,
        num_heads=4, num_layers=2)
    return None


# one builder per model module (small configs: the analyzer only cares
# about the op DAG, not widths)
MODEL_BUILDERS = {
    "mnist_mlp": lambda: models.mnist.mlp()[0],
    "mnist_conv": lambda: models.mnist.conv_net()[0],
    "resnet_cifar10": lambda: models.resnet.resnet_cifar10(depth=20)[0],
    "resnet_imagenet": lambda: models.resnet.resnet_imagenet(depth=50)[0],
    "vgg16_cifar": lambda: models.vgg.vgg16_cifar()[0],
    "alexnet": lambda: models.alexnet.alexnet_imagenet()[0],
    "googlenet": lambda: models.googlenet.googlenet_imagenet()[0],
    "se_resnext": lambda: models.se_resnext.se_resnext_imagenet(
        depth=50)[0],
    "deepfm": lambda: models.deepfm.deepfm()[0],
    "ssd": lambda: models.ssd.ssd_detector()[0],
    "ocr_crnn": lambda: models.ocr_crnn.crnn_ctc()[0],
    "stacked_lstm": lambda: models.stacked_lstm.stacked_lstm_net(
        dict_dim=1000, emb_dim=64, hid_dim=64)[0],
    "lstm_lm": lambda: models.stacked_lstm.lstm_language_model(
        vocab_size=1000, emb_dim=32, hid_dim=32)[0],
    "transformer_lm": lambda: models.transformer.transformer_lm(
        vocab=256, max_len=16, d_model=32, d_inner=64, num_heads=2,
        num_layers=2)[0],
    "transformer_lm_tp": _tp_transformer,
    "transformer_lm_decode_tick": _decode_tick,
    "transformer_lm_paged_decode_tick": _paged_decode_tick,
    "transformer_lm_quant_decode_tick": _quant_decode_tick,
    "transformer_lm_draft_tick": _draft_tick,
    "transformer_lm_spec_verify_tick": _spec_verify_tick,
    "transformer_lm_paged_spec_verify_tick": _paged_spec_verify_tick,
    "transformer_lm_prefill": _prefill,
    "machine_translation": _mt_train,
}


def test_builder_tables_cover_the_same_models():
    """tools/lint_program.py keeps its own builder table (realistic sizes
    for the memory estimate; this file uses small configs for speed) —
    this guard keeps the two name sets from drifting: a model added to
    one table must be added to the other."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_lint_program", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "lint_program.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    lint_names = set(mod._builders())
    test_names = set(MODEL_BUILDERS)
    # lint's "mnist"/"resnet"/"vgg" = this file's mnist_mlp/resnet_imagenet/
    # vgg16_cifar; normalize the aliases before comparing
    alias = {"mnist": "mnist_mlp", "mnist_conv": "mnist_conv",
             "resnet": "resnet_imagenet", "vgg": "vgg16_cifar"}
    lint_names = {alias.get(n, n) for n in lint_names}
    assert lint_names == test_names, (
        sorted(lint_names ^ test_names))


@pytest.mark.parametrize("name", sorted(MODEL_BUILDERS))
def test_model_programs_analyze_clean(name):
    loss = MODEL_BUILDERS[name]()
    if loss is not None:            # None = inference/serving program
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = pt.default_main_program()
    errs = _errors(analysis.analyze_program(prog))
    assert not errs, "\n".join(str(d) for d in errs)
    test_errs = _errors(analysis.analyze_program(prog.clone(for_test=True)))
    assert not test_errs, "\n".join(str(d) for d in test_errs)


def test_decode_programs_analyze_clean():
    models.transformer.transformer_lm_generate(
        vocab=100, max_gen=4, d_model=32, d_inner=64, num_heads=4,
        num_layers=2, beam_size=4)
    errs = _errors(analysis.analyze_program(pt.default_main_program()))
    assert not errs, "\n".join(str(d) for d in errs)


# ---------------------------------------------------------------------------
# shape/dtype inference layer
# ---------------------------------------------------------------------------


def _mlp_program():
    x = layers.data("x", shape=[16])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return pt.default_main_program(), h, logits, loss


def test_infer_propagates_symbolic_batch():
    prog, h, logits, loss = _mlp_program()
    res = analysis.infer_program(prog)
    assert res.n_skipped == 0 and not res.errors
    hs = res.types[(0, h.name)]
    assert tuple(hs.shape) == (analysis.BATCH_SENTINEL, 32)
    assert tuple(res.types[(0, logits.name)].shape) == \
        (analysis.BATCH_SENTINEL, 10)
    assert res.types[(0, loss.name)].shape == ()
    # gradients mirror their targets through the vjp_region rule
    w = prog.global_block().ops[0].inputs["Y"][0]
    assert tuple(res.types[(0, w + "@GRAD")].shape) == (16, 32)


def test_seeded_shape_lie_caught_with_op_provenance():
    """The acceptance-criterion case: lie about a declared shape and the
    analyzer names the producing op (block/op#/op.type) and the var."""
    prog, h, logits, loss = _mlp_program()
    block = prog.global_block()
    block.vars[h.name].shape = (analysis.BATCH_SENTINEL and -1, 31)  # lie
    diags = _errors(analysis.analyze_program(prog))
    hits = [d for d in diags if d.code == "shape-mismatch"
            and h.name in d.message]
    assert hits, diags
    assert "op#" in hits[0].loc
    assert any(t in hits[0].loc
               for t in ("'mul'", "'elementwise_add'", "'relu'"))
    with pytest.raises(analysis.ProgramAnalysisError, match=h.name):
        analysis.check_program(prog)


def test_seeded_dtype_lie_caught():
    prog, h, logits, loss = _mlp_program()
    prog.global_block().vars[logits.name].dtype = np.dtype("int32")
    diags = _errors(analysis.analyze_program(prog))
    assert any(d.code == "dtype-mismatch" and logits.name in d.message
               for d in diags), diags


def test_infer_covers_at_least_90_percent_of_registry():
    import paddle_tpu.parallel  # noqa: F401 — registers dp/pp ops
    covered, waived = analysis.infer_coverage()
    total = len(covered) + len(waived)
    assert len(covered) / total >= 0.90, (len(covered), total)
    for op, reason in waived.items():
        assert isinstance(reason, str) and reason, op


# ---------------------------------------------------------------------------
# structural verification layer
# ---------------------------------------------------------------------------


def test_dropped_producer_caught():
    prog, h, logits, loss = _mlp_program()
    block = prog.global_block()
    # drop the first op (the mul producing the hidden pre-activation)
    dropped = block.ops[0]
    del block.ops[0]
    diags = _errors(analysis.verify_program(prog))
    assert any(d.code == "def-before-use"
               and dropped.outputs["Out"][0] in d.message
               for d in diags), diags


def test_duplicate_writer_caught():
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="a", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="t", shape=[4], dtype="float32")
    blk.append_op("relu", inputs={"X": ["a"]}, outputs={"Out": ["t"]})
    blk.append_op("tanh", inputs={"X": ["a"]}, outputs={"Out": ["t"]})
    diags = _errors(analysis.verify_program(prog))
    assert any(d.code == "duplicate-writer" and "'t'" in d.message
               for d in diags), diags


def test_in_place_self_update_not_flagged():
    """increment(in_place=True) re-writes the var it reads — an ordered
    in-place update, not a rebinding hazard; the old CheckPass accepted
    these and the folded verifier must keep doing so."""
    x = layers.data("x", shape=[4])
    ctr = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    layers.increment(ctr, value=1.0, in_place=True)
    layers.fc(x, size=2)
    diags = _errors(analysis.verify_program(pt.default_main_program()))
    assert not any(d.code == "duplicate-writer" for d in diags), diags


def test_check_pass_alias_still_registered():
    """Folding CheckPass into the verifier keeps the registered name and
    the NotFoundError contract for existing callers."""
    x = layers.data("x", shape=[4])
    layers.fc(x, size=2)
    prog = pt.default_main_program()
    pt.Analyzer(passes=["check_pass"]).run(prog, pt.global_scope())

    bad = pt.Program()
    blk = bad.global_block()
    blk.create_var(name="ghost", shape=[2], dtype="float32")
    blk.create_var(name="out", shape=[2], dtype="float32")
    blk.append_op("relu", inputs={"X": ["ghost"]}, outputs={"Out": ["out"]})
    with pytest.raises(NotFoundError, match="ghost"):
        get_pass("check_pass")(bad)


# ---------------------------------------------------------------------------
# parallel invariants
# ---------------------------------------------------------------------------


def _pipelined_program():
    x = layers.data("x", shape=[32])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=64, act="relu")
    h = layers.fc(h, size=64, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return get_pass("pipeline_partition_pass", num_stages=2,
                    num_microbatches=4,
                    schedule="1f1b")(pt.default_main_program())


def test_pipelined_program_analyzes_clean():
    pp = _pipelined_program()
    errs = _errors(analysis.analyze_program(pp))
    assert not errs, "\n".join(str(d) for d in errs)


def test_broken_pp_send_recv_pair_caught():
    pp = _pipelined_program()
    block = pp.global_block()
    ridx, recv = next((i, op) for i, op in enumerate(block.ops)
                      if op.type == "pp_recv")
    del block.ops[ridx]
    diags = _errors(analysis.verify_program(pp))
    assert any(d.code == "pp-unmatched-boundary" for d in diags), diags


def test_pp_recv_name_mismatch_caught():
    pp = _pipelined_program()
    block = pp.global_block()
    recv = next(op for op in block.ops if op.type == "pp_recv")
    recv.outputs["Out"] = ["not_the_cut_var"]
    diags = _errors(analysis.verify_program(pp))
    assert any(d.code == "pp-unmatched-boundary"
               and "not_the_cut_var" in d.message for d in diags), diags


def _dp_comm_program():
    from paddle_tpu.parallel.grad_comm import comm_optimize_pass
    x = layers.data("x", shape=[32])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=64, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    cfg = {"shard_update": True, "quant": "", "block": 512,
           "error_feedback": False, "bucket_bytes": 1 << 20}
    return comm_optimize_pass(pt.default_main_program(), 4, cfg)


def test_dp_comm_program_analyzes_clean():
    dp = _dp_comm_program()
    errs = _errors(analysis.analyze_program(dp))
    assert not errs, "\n".join(str(d) for d in errs)


def test_dp_comm_bypass_caught():
    """An optimizer rewired back to the raw (un-reduced) gradient — the
    exact hazard the comm pass placement contract forbids."""
    dp = _dp_comm_program()
    block = dp.global_block()
    comm = next(op for op in block.ops if op.type == "dp_grad_comm")
    raw = comm.inputs["X"][0]
    consumer = next(op for op in block.ops
                    if raw + "@COMM" in op.input_names())
    for slot, names in consumer.inputs.items():
        consumer.inputs[slot] = [raw if n == raw + "@COMM" else n
                                 for n in names]
    diags = _errors(analysis.verify_program(dp))
    assert any(d.code == "dp-comm-bypass" and raw in d.message
               for d in diags), diags


def test_dp_comm_misplaced_caught():
    dp = _dp_comm_program()
    block = dp.global_block()
    cidx = next(i for i, op in enumerate(block.ops)
                if op.type == "dp_grad_comm")
    comm = block.ops.pop(cidx)
    block.ops.insert(0, comm)          # before the backward region
    diags = _errors(analysis.verify_program(dp))
    assert any(d.code == "dp-comm-misplaced" for d in diags), diags


def test_dp_divisibility_caught():
    dp = _dp_comm_program()
    block = dp.global_block()
    comm = next(op for op in block.ops if op.type == "dp_grad_comm")
    si = comm.attrs["kinds"].index("sharded")
    comm.attrs["shapes"][si] = [63] + comm.attrs["shapes"][si][1:]
    diags = _errors(analysis.verify_program(dp))
    assert any(d.code == "dp-divisibility" for d in diags), diags


# ---------------------------------------------------------------------------
# pass sanitizer
# ---------------------------------------------------------------------------


@register_pass("_ta_bad_rewrite_pass")
class _BadRewritePass(Pass):
    """Deliberately broken rewrite: drops the first producer but leaves
    its consumers — the malformed-pass case the sanitizer must attribute."""

    allowed_attrs = ()

    def apply(self, program, scope=None):
        del program.global_block().ops[0]
        return program


def test_sanitizer_attributes_broken_rewrite_to_pass_by_name():
    prog, *_ = _mlp_program()
    from paddle_tpu.core import flags
    assert flags.get_flag("verify_passes"), \
        "sanitizer must be on under the test tier (PTPU_VERIFY_PASSES=1)"
    with pytest.raises(analysis.PassSanitizerError,
                       match="_ta_bad_rewrite_pass") as ei:
        get_pass("_ta_bad_rewrite_pass")(prog)
    assert ei.value.pass_name == "_ta_bad_rewrite_pass"
    assert any(d.code == "def-before-use" for d in ei.value.diagnostics)


def test_sanitizer_blames_only_new_violations():
    """Pre-existing violations belong to the caller: applying a HEALTHY
    pass to an already-broken program must not raise."""
    prog, *_ = _mlp_program()
    del prog.global_block().ops[0]     # caller-broken
    assert _errors(analysis.verify_program(prog))
    get_pass("graph_viz_pass", path="/dev/null")(prog)   # no new violations


@register_pass("_ta_renumbering_noop_pass")
class _RenumberingNoopPass(Pass):
    """Healthy rewrite that inserts one harmless op at index 0, renumbering
    every pre-existing op#."""

    allowed_attrs = ()

    def apply(self, program, scope=None):
        blk = program.global_block()
        blk.create_var(name="_ta_noop_c", shape=[1], dtype="float32")
        blk.append_op("fill_constant", inputs={},
                      outputs={"Out": ["_ta_noop_c"]},
                      attrs={"shape": [1], "value": 0.0, "dtype": "float32"})
        blk.ops.insert(0, blk.ops.pop())
        return program


def test_sanitizer_ignores_renumbered_preexisting_violations():
    """A pass that inserts/removes ops shifts every later op# — a
    pre-existing violation whose loc merely renumbered must stay the
    caller's, not be blamed on the healthy pass."""
    prog, *_ = _mlp_program()
    del prog.global_block().ops[0]     # caller-broken: def-before-use
    assert _errors(analysis.verify_program(prog))
    get_pass("_ta_renumbering_noop_pass")(prog)     # must not raise


def test_sanitizer_kill_switch():
    from paddle_tpu.core import flags
    prog, *_ = _mlp_program()
    old = flags.get_flag("verify_passes")
    flags.set_flag("verify_passes", False)
    try:
        get_pass("_ta_bad_rewrite_pass")(prog)   # no raise with switch down
    finally:
        flags.set_flag("verify_passes", old)


# ---------------------------------------------------------------------------
# static memory estimate
# ---------------------------------------------------------------------------


def test_peak_live_bytes_reports_provenance_and_scales_with_batch():
    prog, *_ = _mlp_program()
    small = analysis.peak_live_bytes(prog, nominal_batch=8)
    big = analysis.peak_live_bytes(prog, nominal_batch=64)
    assert small["peak_transient_bytes"] > 0
    assert big["peak_transient_bytes"] > small["peak_transient_bytes"]
    assert small["persistent_bytes"] == big["persistent_bytes"]
    assert "op#" in small["peak_at"]
