"""Compile-level scaling evidence (VERDICT r4 #6).

Loss parity across worlds shows the programs compute the right thing; these
tests assert the *communication structure* of the compiled HLO — the
strongest scaling evidence a single-host environment can commit, ≙ the
reference's multi-devices graph invariants
(framework/details/multi_devices_graph_check_pass.cc):

  - dp:      total all-reduce bytes == gradient bytes (+ scalar loss
             reductions), nothing more
  - ZeRO-1:  gradients travel as reduce-scatter + all-gather, not
             all-reduce
  - tp:      a column->row Megatron pair costs exactly ONE all-reduce
  - pp:      the microbatch ring is collective-permutes, no all-to-all
  - ep:      a sharded-embedding lookup combines with exactly one psum
             (plus the id broadcast's gather machinery), table stays put

All on the 8-virtual-CPU-device mesh; byte counts parsed from the
partitioned, optimized HLO.
"""

import os
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel.mesh import DeviceMesh

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
# census shared with the benchmark's grad_bytes_on_wire reporting and the
# explicit-pipeline suite (tests/test_zero_comm.py) — one byte model
from probe_common import collective_census  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)


@pytest.fixture(autouse=True)
def _fresh():
    pt.reset_default_programs()
    pt.reset_global_scope()
    yield


def _compiled_step_hlo(exe, feed, loss, scope=None):
    """Optimized (post-SPMD-partitioning) HLO of the last compiled step."""
    scope = scope or pt.global_scope()
    cs = list(exe._cache.values())[-1]
    feed_vals = tuple(feed[n] for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    return cs.fn.lower(feed_vals, ro, rw, np.uint32(0)).compile().as_text()


def _build_mlp(bs):
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return x, label, loss


def _param_grad_bytes():
    """f32 bytes of every trainable parameter (== gradient bytes)."""
    scope = pt.global_scope()
    prog = pt.default_main_program()
    total = 0
    for v in prog.global_block().vars.values():
        if getattr(v, "persistable", False) and scope.has_var(v.name) \
                and not getattr(v, "is_optimizer_state", False) \
                and not v.name.startswith("learning_rate"):
            n = 1
            for d in v.shape:
                n *= d
            total += n * 4
    return total


class TestDataParallelStructure:
    def test_allreduce_bytes_equal_grad_bytes(self, rng):
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.strategy import BuildStrategy

        mesh = DeviceMesh(jax.devices(), {"dp": 8})
        bs = 32
        _build = _build_mlp(bs)
        loss = _build[2]
        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = ParallelExecutor(loss_name=loss.name, mesh=mesh)
        pt.Executor().run(pt.default_startup_program())
        feed = {"x": rng.rand(bs, 64).astype("float32"),
                "label": rng.randint(0, 10, (bs, 1)).astype("int64")}
        exe.run(feed=feed, fetch_list=[loss])
        hlo = _compiled_step_hlo(
            exe, {k: jnp.asarray(v) for k, v in feed.items()}, loss)

        census = collective_census(hlo)
        grad_bytes = _param_grad_bytes()
        assert grad_bytes == (64 * 128 + 128 + 128 * 10 + 10) * 4
        ar_bytes = sum(b for b, _ in census.get("all-reduce", []))
        # every gradient is all-reduced exactly once; the only other
        # all-reduces are scalar/row loss+softmax reductions (mean over the
        # sharded batch). No reduce-scatter (that is ZeRO's signature).
        assert ar_bytes >= grad_bytes, (ar_bytes, grad_bytes)
        assert ar_bytes <= grad_bytes + 64 * 1024, (ar_bytes, grad_bytes)
        assert "reduce-scatter" not in census, census.keys()
        assert "all-to-all" not in census, census.keys()
        # no all-gather either: replicated params update redundantly on
        # every shard — the ZeRO-1 test asserts the opposite
        assert "all-gather" not in census, census.keys()


class TestZeroStructure:
    def test_zero1_uses_reduce_scatter_plus_all_gather(self, rng):
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.strategy import (BuildStrategy,
                                                  ReduceStrategy)

        mesh = DeviceMesh(jax.devices(), {"dp": 8})
        bs = 32
        x = layers.data("x", shape=[64])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=128, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        # momentum: a [shape]-sized accumulator per param, sharded by ZeRO-1
        pt.optimizer.MomentumOptimizer(learning_rate=0.1,
                                       momentum=0.9).minimize(loss)
        bst = BuildStrategy()
        bst.reduce_strategy = ReduceStrategy.Reduce
        exe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                               build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        feed = {"x": rng.rand(bs, 64).astype("float32"),
                "label": rng.randint(0, 10, (bs, 1)).astype("int64")}
        exe.run(feed=feed, fetch_list=[loss])
        hlo = _compiled_step_hlo(
            exe, {k: jnp.asarray(v) for k, v in feed.items()}, loss)

        census = collective_census(hlo)
        # the ZeRO-1 signature vs plain dp: the updated param comes BACK
        # via an all-gather (each data shard owns 1/8 of the accumulator
        # and applies 1/8 of the update). The grad half is reduce-scatter
        # where the partitioner forms it; XLA:CPU lowers psum+slice as
        # all-reduce + dynamic-slice instead (bitwise the same movement on
        # the virtual mesh; the TPU partitioner emits reduce-scatter) — so
        # accept either, but the all-gather is non-negotiable.
        assert "all-gather" in census, census.keys()
        ag_bytes = sum(b for b, _ in census.get("all-gather", []))
        shardable = 64 * 128 * 4  # w1 bytes (f32): dim0 % 8 == 0 -> shards
        assert ag_bytes >= shardable, (ag_bytes, shardable)
        if "reduce-scatter" in census:
            rs_bytes = sum(b for b, _ in census["reduce-scatter"])
            assert rs_bytes >= shardable // 8, (rs_bytes, shardable)
        # the sharded optimizer math is real: the all-gather's operand is
        # the fused update computation, not a plain parameter copy
        ag_line = census["all-gather"][0][1]
        assert "fusion" in ag_line or "subtract" in ag_line, ag_line[:160]


class TestTensorParallelStructure:
    def test_column_row_pair_costs_one_allreduce(self, rng):
        from paddle_tpu.parallel import tensor_parallel as tp

        mesh = DeviceMesh(jax.devices(), {"tp": 8})
        x = jnp.asarray(rng.rand(16, 64).astype("float32"))
        w1 = jnp.asarray(rng.rand(64, 128).astype("float32"))
        w2 = jnp.asarray(rng.rand(128, 64).astype("float32"))

        @jax.jit
        def mlp(x, w1, w2):
            with mesh.jax_mesh:
                h = jax.nn.relu(tp.column_parallel_matmul(x, w1))
                return tp.row_parallel_matmul(h, w2)

        with mesh.jax_mesh:
            hlo = mlp.lower(x, w1, w2).compile().as_text()
        census = collective_census(hlo)
        ars = census.get("all-reduce", [])
        assert len(ars) == 1, [l[:120] for _, l in ars]
        # ... and it moves exactly the row-matmul's output [16, 64] f32
        assert ars[0][0] == 16 * 64 * 4, ars[0]
        assert "all-to-all" not in census
        assert "collective-permute" not in census

    def test_two_pairs_cost_two_allreduces(self, rng):
        from paddle_tpu.parallel import tensor_parallel as tp

        mesh = DeviceMesh(jax.devices(), {"tp": 8})
        x = jnp.asarray(rng.rand(16, 64).astype("float32"))
        ws = [jnp.asarray(rng.rand(64, 128).astype("float32")),
              jnp.asarray(rng.rand(128, 64).astype("float32")),
              jnp.asarray(rng.rand(64, 128).astype("float32")),
              jnp.asarray(rng.rand(128, 64).astype("float32"))]

        @jax.jit
        def mlp2(x, w1, w2, w3, w4):
            with mesh.jax_mesh:
                h = jax.nn.relu(tp.column_parallel_matmul(x, w1))
                h = tp.row_parallel_matmul(h, w2)
                h = jax.nn.relu(tp.column_parallel_matmul(h, w3))
                return tp.row_parallel_matmul(h, w4)

        with mesh.jax_mesh:
            hlo = mlp2.lower(x, *ws).compile().as_text()
        ars = collective_census(hlo).get("all-reduce", [])
        assert len(ars) == 2, [l[:120] for _, l in ars]


class TestPipelineStructure:
    def test_ring_is_collective_permutes_only(self, rng):
        from paddle_tpu.parallel.pipeline import pipeline_apply

        n_stage, d, mb = 8, 16, 4
        mesh = DeviceMesh(jax.devices(), {"pp": 8})
        ws = jnp.asarray(rng.randn(n_stage, d, d).astype("float32") * 0.1)
        x = jnp.asarray(rng.randn(32, d).astype("float32"))

        def stage(p, h):
            return jnp.tanh(h @ p["w"])

        @jax.jit
        def run(ws, x):
            return pipeline_apply(mesh, stage, {"w": ws}, x,
                                  num_microbatches=mb)

        hlo = run.lower(ws, x).compile().as_text()
        census = collective_census(hlo)
        assert "collective-permute" in census, census.keys()
        assert "all-to-all" not in census
        # the schedule is a ROLLED lax.scan: exactly ONE collective-permute
        # instruction lives in the loop body and executes M + n - 1 times;
        # the loop structure itself must be present in the module
        n_cp = len(census["collective-permute"])
        assert n_cp == 1, n_cp
        assert re.search(r"\bwhile\(", hlo), "pipeline loop was unrolled?"
        # one final psum surfaces the last stage's outputs
        ars = census.get("all-reduce", [])
        assert len(ars) == 1, [l[:120] for _, l in ars]
        # the rotation moves one microbatch activation [mb-rows, d] f32
        assert census["collective-permute"][0][0] == (32 // mb) * d * 4, \
            census["collective-permute"][0]


class TestShardedEmbeddingStructure:
    def test_lookup_is_one_psum_table_stays_put(self, rng):
        from paddle_tpu.parallel.sharded_embedding import (
            sharded_embedding_lookup)

        mesh = DeviceMesh(jax.devices(), {"tp": 8})
        table = jnp.asarray(rng.rand(64, 16).astype("float32"))
        ids = jnp.asarray(rng.randint(0, 64, (4, 7)))

        @jax.jit
        def lookup(table, ids):
            return sharded_embedding_lookup(mesh, table, ids,
                                            axis_name="tp")

        hlo = lookup.lower(table, ids).compile().as_text()
        census = collective_census(hlo)
        ars = census.get("all-reduce", [])
        assert len(ars) == 1, [l[:120] for _, l in ars]
        # the psum moves activation-sized data ([4, 7, 16] f32), NOT the
        # table: shipping rows, not the table, is the point of EP
        assert ars[0][0] == 4 * 7 * 16 * 4, ars[0]
        table_bytes = 64 * 16 * 4
        for kind, items in census.items():
            for b, line in items:
                assert b < table_bytes, (kind, b, line[:120])
