"""Multi-process (N > 2) distributed depth tests (VERDICT r3 #2 / #5).

≙ reference test_dist_base.py:27 forking N-trainer worlds over
nccl_helper.h:118's multi-rank bootstrap. Capabilities the 2-process
suite (test_dist_multiproc.py) cannot witness:

1. FOUR- and EIGHT-process jax.distributed worlds;
2. a dp×tp mesh whose TENSOR-parallel groups span process boundaries
   (tp=4 over 2-device processes ⇒ every tp collective crosses processes),
   with loss parity against the single-process 8-device run — plain,
   scan-fused run_steps, and ZeRO-1;
3. a pp=8 pipeline ring and an 8-way-sharded embedding table whose every
   ppermute hop / psum combine crosses processes;
4. elastic resize 4→2: a 4-process world saves a sharded checkpoint
   (4 per-process shard manifests), a FRESH 2-process world re-shards it
   onto half the processes and finishes training with loss parity against
   an uninterrupted single-process run.
"""

from __future__ import annotations

import glob
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# jaxlib < 0.5 cannot run multi-process computations on the CPU backend at
# all ("Multiprocess computations aren't implemented on the CPU backend")
# — the cross-process CPU client landed later. Skip the whole module there:
# the capability under test does not exist in that runtime, and a red X
# would misread as a product regression.
def _cpu_multiproc_supported():
    import jax
    major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    return (major, minor) >= (0, 5)


pytestmark = pytest.mark.skipif(
    not _cpu_multiproc_supported(),
    reason="jaxlib < 0.5: no multi-process CPU backend")


_BOOT = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax._src.xla_bridge as _xb
_xb._backend_factories.pop("axon", None)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, __REPO__)
"""


def _script(body):
    return body.replace("__REPO__", repr(REPO))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_world(tmp_path, script, n, port, extra_env=None):
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(n),
            "PADDLE_COORDINATOR_ENDPOINT": f"127.0.0.1:{port}",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _script(script)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(tmp_path)))
    return procs


def _join_world(procs, timeout=420):
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        rec = json.loads(out.strip().splitlines()[-1])
        results[rec["rank"]] = rec
    return results


# ---------------------------------------------------------------------------
# shared tp model: column-parallel fc -> row-parallel fc, tp groups span
# process boundaries on the 4x2 world
# ---------------------------------------------------------------------------

_TP_MODEL = r"""
import numpy as np


def build_and_train(steps=5, fused=False, zero1=False, dp=2, tp=4):
    import jax
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import unique_name
    from paddle_tpu.parallel import (BuildStrategy, DeviceMesh,
                                     ParallelExecutor, ReduceStrategy)

    with unique_name.guard():
        x = layers.data("x", shape=[8])
        y = layers.data("y", shape=[1])
        # column-parallel then row-parallel: the Megatron pair — forward
        # needs one cross-process all-reduce on the row-parallel output
        h = layers.fc(x, size=16, act="relu", name="tp_fc1",
                      param_attr=pt.ParamAttr(name="tp_fc1.w",
                                              sharding_spec=(None, "tp")))
        pred = layers.fc(h, size=1, name="tp_fc2",
                         param_attr=pt.ParamAttr(name="tp_fc2.w",
                                                 sharding_spec=("tp", None)))
        loss = layers.reduce_mean(layers.square(pred - y))
        pt.optimizer.MomentumOptimizer(learning_rate=0.05,
                                       momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    bs = BuildStrategy()
    if zero1:
        bs.reduce_strategy = ReduceStrategy.Reduce
    mesh = DeviceMesh(jax.devices(), axes={"dp": dp, "tp": tp})
    pe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                          build_strategy=bs)

    W = np.random.RandomState(7).randn(8, 1).astype("float32")
    feeds = []
    for i in range(steps):
        rb = np.random.RandomState(100 + i)
        xb = rb.rand(16, 8).astype("float32")          # global batch
        feeds.append({"x": xb, "y": (xb @ W).astype("float32")})
    if fused:
        return [float(v) for v in
                pe.run_steps(feeds, fetch_list=[loss.name])[0]]
    return [float(pe.run(feed=f, fetch_list=[loss.name])[0])
            for f in feeds]
"""

_TP_SINGLE = r"""
import json
import paddle_tpu as pt
from tp_model import build_and_train
out = {"plain": build_and_train()}
pt.reset_default_programs(); pt.reset_global_scope()
out["zero1"] = build_and_train(zero1=True)
print(json.dumps(out), flush=True)
"""

_TP_MULTI = _BOOT + r"""
import json
import jax
import paddle_tpu as pt
from paddle_tpu.distributed import init_parallel_env
from tp_model import build_and_train

env = init_parallel_env()
assert jax.process_count() == 4, jax.process_count()
assert len(jax.devices()) == 8
out = {"rank": env.trainer_id, "plain": build_and_train()}
pt.reset_default_programs(); pt.reset_global_scope()
out["zero1"] = build_and_train(zero1=True)
pt.reset_default_programs(); pt.reset_global_scope()
out["fused"] = build_and_train(fused=True)
print(json.dumps(out), flush=True)
"""


def test_four_process_tp_spanning_parity(tmp_path):
    with open(tmp_path / "tp_model.py", "w") as f:
        f.write(_TP_MODEL)

    # single-process reference: 8 virtual devices, same dp=2 x tp=4 mesh
    boot8 = _BOOT.replace("host_platform_device_count=2",
                          "host_platform_device_count=8")
    ref = subprocess.run(
        [sys.executable, "-c", _script(boot8 + _TP_SINGLE)],
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path))
    assert ref.returncode == 0, ref.stderr[-3000:]
    ref_losses = json.loads(ref.stdout.strip().splitlines()[-1])

    procs = _spawn_world(tmp_path, _TP_MULTI, 4, _free_port())
    results = _join_world(procs)

    assert set(results) == {0, 1, 2, 3}
    # scan-fused == per-step on the 4-process world
    np.testing.assert_allclose(results[0]["fused"], results[0]["plain"],
                               rtol=2e-4)
    for variant in ("plain", "zero1"):
        for rank in (1, 2, 3):
            np.testing.assert_allclose(results[0][variant],
                                       results[rank][variant], rtol=1e-6)
        np.testing.assert_allclose(results[0][variant],
                                   ref_losses[variant], rtol=2e-4)
        assert results[0][variant][-1] < results[0][variant][0]


# ---------------------------------------------------------------------------
# pipeline ring spanning processes: pp=8 over 4x2-device processes means
# EVERY ppermute hop crosses a process boundary (the reference never ran a
# pipeline schedule at all; this witnesses ours at multi-host topology)
# ---------------------------------------------------------------------------

_PP_MODEL = r"""
import numpy as np


def run_pipeline():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import DeviceMesh
    from paddle_tpu.parallel.pipeline import pipeline_apply

    n, d, b, m = 8, 16, 32, 4
    mesh = DeviceMesh(jax.devices(), axes={"pp": n})
    rng = np.random.RandomState(11)
    stacked_w = jnp.asarray(
        rng.randn(n, d, d).astype("float32") / np.sqrt(d))
    x = jnp.asarray(rng.randn(b, d).astype("float32"))
    tgt = jnp.asarray(rng.randn(b, d).astype("float32"))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def loss_fn(w, x):
        y = pipeline_apply(mesh, stage_fn, w, x, num_microbatches=m)
        return jnp.mean((y - tgt) ** 2)

    loss, grad = jax.jit(jax.value_and_grad(loss_fn))(stacked_w, x)
    y = jax.jit(lambda w, x: pipeline_apply(mesh, stage_fn, w, x,
                                            num_microbatches=m))(stacked_w, x)
    return {"loss": float(loss),
            "grad_norm": float(jnp.linalg.norm(grad)),
            "y_head": np.asarray(y)[0, :4].tolist()}
"""

_PP_SINGLE = r"""
import json
from pp_model import run_pipeline
print(json.dumps(run_pipeline()), flush=True)
"""

_PP_MULTI = _BOOT + r"""
import json
import jax
from paddle_tpu.distributed import init_parallel_env
from pp_model import run_pipeline

env = init_parallel_env()
assert jax.process_count() == 4
out = run_pipeline()
out["rank"] = env.trainer_id
print(json.dumps(out), flush=True)
"""


def test_four_process_pipeline_ring_parity(tmp_path):
    with open(tmp_path / "pp_model.py", "w") as f:
        f.write(_PP_MODEL)

    boot8 = _BOOT.replace("host_platform_device_count=2",
                          "host_platform_device_count=8")
    ref = subprocess.run(
        [sys.executable, "-c", _script(boot8 + _PP_SINGLE)],
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path))
    assert ref.returncode == 0, ref.stderr[-3000:]
    expect = json.loads(ref.stdout.strip().splitlines()[-1])

    results = _join_world(_spawn_world(tmp_path, _PP_MULTI, 4, _free_port()))
    assert set(results) == {0, 1, 2, 3}
    for rank in range(4):
        got = results[rank]
        np.testing.assert_allclose(got["loss"], expect["loss"], rtol=2e-5)
        np.testing.assert_allclose(got["grad_norm"], expect["grad_norm"],
                                   rtol=2e-4)
        np.testing.assert_allclose(got["y_head"], expect["y_head"],
                                   rtol=2e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# sharded embedding (EP) spanning processes: the table's 8 row-shards live
# on 4x2-device processes, so every lookup's psum combine crosses process
# boundaries (≙ reference distributed lookup table, the pserver-sharded
# capability; here the gradient also stays sharded)
# ---------------------------------------------------------------------------

_EP_MODEL = r"""
import numpy as np


def run_ep():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import DeviceMesh
    from paddle_tpu.parallel.sharded_embedding import (
        embedding_table_sharding, sharded_embedding_lookup)

    v, d, n_ids = 64, 8, 12
    mesh = DeviceMesh(jax.devices(), axes={"tp": 8})
    rng = np.random.RandomState(21)
    table_h = rng.randn(v, d).astype("float32")
    ids_h = rng.randint(0, v, (n_ids,))
    table = jax.device_put(jnp.asarray(table_h),
                           embedding_table_sharding(mesh, "tp"))
    ids = jnp.asarray(ids_h.astype("int32"))

    vals = jax.jit(
        lambda t, i: sharded_embedding_lookup(mesh, t, i, "tp"))(table, ids)
    expect = table_h[ids_h]

    def loss_fn(t):
        y = sharded_embedding_lookup(mesh, t, ids, "tp")
        return jnp.sum(y * y)

    grad = jax.jit(jax.grad(loss_fn))(table)
    # dense reference: d/dt sum((t[ids])^2) scatters 2*t[row] per hit
    gref = np.zeros_like(table_h)
    for r in ids_h:
        gref[r] += 2.0 * table_h[r]
    # the gradient is row-sharded across PROCESSES (non-addressable here),
    # so compare in-graph and fetch only replicated scalars
    gerr = jax.jit(lambda g: jnp.max(jnp.abs(g - jnp.asarray(gref))))(grad)
    gnorm = jax.jit(jnp.linalg.norm)(grad)
    return {"lookup_ok": bool(np.allclose(np.asarray(vals), expect,
                                          atol=1e-5)),
            "grad_ok": bool(float(gerr) < 1e-4),
            "grad_norm": float(gnorm)}
"""

_EP_MULTI = _BOOT + r"""
import json
import jax
from paddle_tpu.distributed import init_parallel_env
from ep_model import run_ep

env = init_parallel_env()
assert jax.process_count() == 4
out = run_ep()
out["rank"] = env.trainer_id
print(json.dumps(out), flush=True)
"""


def test_four_process_sharded_embedding_parity(tmp_path):
    with open(tmp_path / "ep_model.py", "w") as f:
        f.write(_EP_MODEL)

    results = _join_world(_spawn_world(tmp_path, _EP_MULTI, 4, _free_port()))
    assert set(results) == {0, 1, 2, 3}
    norms = []
    for rank in range(4):
        assert results[rank]["lookup_ok"], results[rank]
        assert results[rank]["grad_ok"], results[rank]
        norms.append(results[rank]["grad_norm"])
    np.testing.assert_allclose(norms, norms[0], rtol=1e-6)


# ---------------------------------------------------------------------------
# elastic resize 4 -> 2 via sharded checkpoint re-shard
# ---------------------------------------------------------------------------

_RS_MODEL = r"""
import numpy as np


def build():
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import unique_name
    with unique_name.guard():
        x = layers.data("x", shape=[8])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, size=16, act="relu", name="rs_fc1")
        pred = layers.fc(h, size=1, name="rs_fc2")
        loss = layers.reduce_mean(layers.square(pred - y))
        pt.optimizer.MomentumOptimizer(learning_rate=0.05,
                                       momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe, loss


def step_feed(i):
    W = np.random.RandomState(7).randn(8, 1).astype("float32")
    rb = np.random.RandomState(100 + i)
    xb = rb.rand(16, 8).astype("float32")
    return {"x": xb, "y": (xb @ W).astype("float32")}
"""

_RS_PHASE_A = _BOOT + r"""
import glob, json, time
import jax
import paddle_tpu as pt
from paddle_tpu.distributed import init_parallel_env
from paddle_tpu.parallel import DeviceMesh, ParallelExecutor
from rs_model import build, step_feed

env = init_parallel_env()
assert jax.process_count() == 4
exe, loss = build()
pe = ParallelExecutor(loss_name=loss.name, mesh=DeviceMesh(jax.devices()))
losses = []
for i in range(3):
    losses.append(float(pe.run(feed=step_feed(i),
                               fetch_list=[loss.name])[0]))
d = os.path.join(os.environ["RS_WORK"], "ckpt")
pt.io.save_persistables(dirname=d, sharded=True)
# a 4-process checkpoint is complete once all 4 manifests landed
while len(glob.glob(os.path.join(d, "manifest-*.json"))) < 4:
    time.sleep(0.05)
print(json.dumps({"rank": env.trainer_id, "losses": losses}), flush=True)
"""

_RS_PHASE_B = _BOOT + r"""
import json
import jax
import paddle_tpu as pt
from paddle_tpu.distributed import init_parallel_env
from paddle_tpu.parallel import DeviceMesh, ParallelExecutor
from rs_model import build, step_feed

env = init_parallel_env()
assert jax.process_count() == 2          # the RESIZED world
exe, loss = build()
# restore the 4-process (8-way) checkpoint onto this 2-process (4-way)
# world: ShardedCheckpoint re-assembles slices per var and re-shards
pt.io.load_persistables(dirname=os.path.join(os.environ["RS_WORK"], "ckpt"),
                        sharded=True)
pe = ParallelExecutor(loss_name=loss.name, mesh=DeviceMesh(jax.devices()))
losses = []
for i in range(3, 6):
    losses.append(float(pe.run(feed=step_feed(i),
                               fetch_list=[loss.name])[0]))
print(json.dumps({"rank": env.trainer_id, "losses": losses}), flush=True)
"""

_RS_REF = r"""
import json
from rs_model import build, step_feed
import jax
from paddle_tpu.parallel import DeviceMesh, ParallelExecutor
exe, loss = build()
pe = ParallelExecutor(loss_name=loss.name, mesh=DeviceMesh(jax.devices()))
print(json.dumps([float(pe.run(feed=step_feed(i),
                               fetch_list=[loss.name])[0])
                  for i in range(6)]), flush=True)
"""


def test_elastic_resize_4_to_2(tmp_path):
    with open(tmp_path / "rs_model.py", "w") as f:
        f.write(_RS_MODEL)

    # uninterrupted single-process reference (4 devices)
    boot4 = _BOOT.replace("host_platform_device_count=2",
                          "host_platform_device_count=4")
    ref = subprocess.run(
        [sys.executable, "-c", _script(boot4 + _RS_REF)],
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path))
    assert ref.returncode == 0, ref.stderr[-3000:]
    ref_losses = json.loads(ref.stdout.strip().splitlines()[-1])

    extra = {"RS_WORK": str(tmp_path)}
    a = _join_world(_spawn_world(tmp_path, _RS_PHASE_A, 4, _free_port(),
                                 extra))
    assert set(a) == {0, 1, 2, 3}
    manifests = glob.glob(str(tmp_path / "ckpt" / "manifest-*.json"))
    assert len(manifests) == 4       # one shard manifest per process

    b = _join_world(_spawn_world(tmp_path, _RS_PHASE_B, 2, _free_port(),
                                 extra))
    assert set(b) == {0, 1}

    full = a[0]["losses"] + b[0]["losses"]
    np.testing.assert_allclose(b[0]["losses"], b[1]["losses"], rtol=1e-6)
    np.testing.assert_allclose(full, ref_losses, rtol=2e-4)
    assert full[-1] < full[0]


# ---------------------------------------------------------------------------
# eight-process world, one device per process: the largest rank count the
# suite witnesses (≙ reference N-trainer worlds, nccl_helper.h:118) — pure
# dp over 8 single-device processes with loss parity vs single-process
# ---------------------------------------------------------------------------

_DP8_MULTI = _BOOT.replace(
    "host_platform_device_count=2", "host_platform_device_count=1") + r"""
import json
import jax
import paddle_tpu as pt
from paddle_tpu.distributed import init_parallel_env
from tp_model import build_and_train

env = init_parallel_env()
assert jax.process_count() == 8, jax.process_count()
assert len(jax.devices()) == 8
out = {"rank": env.trainer_id,
       "plain": build_and_train(dp=8, tp=1)}
print(json.dumps(out), flush=True)
"""

_DP8_SINGLE = r"""
import json
from tp_model import build_and_train
print(json.dumps(build_and_train(dp=8, tp=1)), flush=True)
"""


def test_eight_process_dp_parity(tmp_path):
    with open(tmp_path / "tp_model.py", "w") as f:
        f.write(_TP_MODEL)

    boot8 = _BOOT.replace("host_platform_device_count=2",
                          "host_platform_device_count=8")
    ref = subprocess.run(
        [sys.executable, "-c", _script(boot8 + _DP8_SINGLE)],
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path))
    assert ref.returncode == 0, ref.stderr[-3000:]
    expect = json.loads(ref.stdout.strip().splitlines()[-1])

    results = _join_world(_spawn_world(tmp_path, _DP8_MULTI, 8,
                                       _free_port()), timeout=600)
    assert set(results) == set(range(8))
    for rank in range(8):
        np.testing.assert_allclose(results[rank]["plain"], expect,
                                   rtol=2e-4)
    assert expect[-1] < expect[0]
