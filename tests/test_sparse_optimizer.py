"""Sparse (SelectedRows) optimizer path: embedding grads as (rows, values).

≙ reference SelectedRows optimizer kernels (operators/adam_op.h
SparseAdamFunctor, sgd_op.h, momentum_op.h SelectedRows branches +
math/selected_rows_functor.cc MergeAdd). With embedding(is_sparse=True),
the vjp region ships the table gradient as (rows, values) and the
sgd/momentum/adam lowerings update ONLY the looked-up rows of the param
and accumulators — O(batch*dim) instead of O(vocab*dim) per step.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

VOCAB, DIM = 32, 4


def _build(optimizer, is_sparse=True, lr=0.1):
    ids = layers.data("ids", shape=[3], dtype="int64")
    emb = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=is_sparse,
                           param_attr=pt.ParamAttr(name="emb_w"))
    loss = layers.reduce_mean(layers.square(emb))
    optimizer.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe, loss


def _table():
    return np.asarray(pt.global_scope().get("emb_w")).copy()


class TestSparseSGD:
    def test_matches_dense_exactly(self, rng):
        """SGD is linear in the gradient, so sparse scatter-add and the dense
        update must agree bit-for-bit on every row."""
        ids = rng.randint(0, VOCAB, (4, 3)).astype("int64")

        exe, loss = _build(pt.optimizer.SGD(learning_rate=0.1),
                           is_sparse=True)
        w0 = _table()
        exe.run(feed={"ids": ids}, fetch_list=[loss])
        sparse_w = _table()

        pt.reset_default_programs()
        pt.reset_global_scope()
        exe, loss = _build(pt.optimizer.SGD(learning_rate=0.1),
                           is_sparse=False)
        pt.global_scope().set_var("emb_w", w0)  # identical init
        exe.run(feed={"ids": ids}, fetch_list=[loss])
        dense_w = _table()

        np.testing.assert_allclose(sparse_w, dense_w, rtol=1e-6, atol=1e-7)

    def test_untouched_rows_unchanged(self, rng):
        ids = np.array([[1, 5, 9]], dtype="int64")
        exe, loss = _build(pt.optimizer.SGD(learning_rate=0.5))
        w0 = _table()
        exe.run(feed={"ids": ids}, fetch_list=[loss])
        w1 = _table()
        touched = {1, 5, 9}
        for r in range(VOCAB):
            if r in touched:
                assert not np.allclose(w0[r], w1[r]), f"row {r} should move"
            else:
                np.testing.assert_array_equal(w0[r], w1[r])


class TestSparseMomentum:
    @pytest.mark.parametrize("nesterov", [False, True])
    def test_matches_dense_exactly_across_disjoint_steps(self, rng,
                                                         nesterov):
        """Momentum has NO lazy reference mode: velocity decays on every row
        each step (≙ SparseMomentumFunctor iterates all rows with g=0 for
        absent ones), so sparse and dense must agree exactly — including on
        rows touched at step 1 but absent at step 2, which keep moving via
        decayed velocity."""
        step_ids = [np.array([[1, 3, 5]], dtype="int64"),
                    np.array([[2, 4, 6]], dtype="int64")]  # disjoint

        def train(is_sparse, w_init, steps):
            pt.reset_default_programs()
            pt.reset_global_scope()
            opt = pt.optimizer.MomentumOptimizer(
                learning_rate=0.2, momentum=0.9, use_nesterov=nesterov)
            exe, loss = _build(opt, is_sparse=is_sparse)
            pt.global_scope().set_var("emb_w", w_init)
            for ids in step_ids[:steps]:
                exe.run(feed={"ids": ids}, fetch_list=[loss])
            return _table()

        pt.reset_default_programs()
        pt.reset_global_scope()
        _build(pt.optimizer.MomentumOptimizer(
            learning_rate=0.2, momentum=0.9), is_sparse=True)
        w0 = _table()

        sparse_w = train(True, w0, steps=2)
        dense_w = train(False, w0, steps=2)
        np.testing.assert_allclose(sparse_w, dense_w, rtol=1e-6, atol=1e-7)
        # row 1: touched at step 1, absent at step 2 — must keep moving at
        # step 2 via decayed velocity. A lazy sparse branch would leave it
        # at its post-step-1 value.
        after_one = train(True, w0, steps=1)
        assert not np.allclose(sparse_w[1], after_one[1])


class TestSparseAdam:
    def test_lazy_rows_vs_numpy_reference(self, rng):
        """Two steps with different id sets against a hand-computed lazy-adam
        reference (≙ SparseAdamFunctor semantics: untouched rows keep stale
        moments and do not move)."""
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.1
        exe, loss = _build(pt.optimizer.Adam(
            learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps))
        w = _table().astype(np.float64)
        m = np.zeros_like(w)
        v = np.zeros_like(w)
        b1p, b2p = b1, b2  # paddle initializes beta pows to beta^1

        step_ids = [np.array([[1, 1, 7]], dtype="int64"),
                    np.array([[7, 2, 2]], dtype="int64")]
        for ids in step_ids:
            exe.run(feed={"ids": ids}, fetch_list=[loss])
            # numpy reference: loss = mean(emb^2) -> d/d emb = 2*emb/n
            flat = ids.reshape(-1)
            n = flat.size * DIM
            g = np.zeros_like(w)
            np.add.at(g, flat, 2.0 * w[flat] / n)
            rows = np.unique(flat)
            m[rows] = b1 * m[rows] + (1 - b1) * g[rows]
            v[rows] = b2 * v[rows] + (1 - b2) * g[rows] ** 2
            lr_t = lr * np.sqrt(1 - b2p) / (1 - b1p)
            w[rows] = w[rows] - lr_t * m[rows] / (np.sqrt(v[rows]) + eps)
            b1p *= b1
            b2p *= b2

        np.testing.assert_allclose(_table(), w, rtol=1e-4, atol=1e-6)

    def test_duplicate_ids_aggregate_before_update(self, rng):
        """Duplicates must merge (MergeAdd) BEFORE the nonlinear adam update:
        applying per-occurrence would double-decay the moments."""
        exe, loss = _build(pt.optimizer.Adam(learning_rate=0.1))
        ids = np.array([[3, 3, 3]], dtype="int64")
        w0 = _table()
        exe.run(feed={"ids": ids}, fetch_list=[loss])
        w1 = _table()
        # row 3 moved, everything else intact
        assert not np.allclose(w0[3], w1[3])
        mask = np.ones(VOCAB, bool)
        mask[3] = False
        np.testing.assert_array_equal(w0[mask], w1[mask])


def _table_op_kinds(mlir_text, vocab, dim):
    """StableHLO op kinds appearing on lines that mention the full-table
    tensor type."""
    import re
    table_t = f"tensor<{vocab}x{dim}xf32>"
    kinds = set()
    for ln in mlir_text.splitlines():
        if table_t not in ln:
            continue
        m = re.search(r"stablehlo\.([a-z_]+)", ln)
        if m:
            kinds.add(m.group(1))
    return kinds


class TestCompiledSparsity:
    def test_hlo_has_no_dense_table_update(self, rng, monkeypatch):
        """With the dense-masked policy disabled (the EP-scale setting),
        the compiled train step must touch the table only via gather and
        row-scatter: no [vocab, dim]-shaped elementwise update ops. This is
        the property that makes the update O(batch*dim) — asserted on the
        HLO so a regression to dense math fails CI even where wall-clock
        differences are masked by runtime overhead."""
        import jax.numpy as jnp

        # force the row path (default policy dense-masks small tables
        # because the merge SORT dominates on TPU — see optimizer_ops)
        from paddle_tpu.core import flags as _flags
        monkeypatch.setattr(_flags._REGISTRY["sparse_dense_apply_max_bytes"],
                            "value", 0)
        big_v = 4096  # big enough that a dense update would be visible
        ids = layers.data("ids", shape=[3], dtype="int64")
        emb = layers.embedding(ids, size=[big_v, DIM], is_sparse=True,
                               param_attr=pt.ParamAttr(name="emb_w"))
        loss = layers.reduce_mean(layers.square(emb))
        pt.optimizer.Adam(learning_rate=0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"ids": jnp.asarray(rng.randint(0, big_v, (4, 3))
                                   .astype("int64"))}
        exe.run(feed=feed, fetch_list=[loss])
        cs = list(exe._cache.values())[-1]
        feed_vals = tuple(feed[n] for n in cs.feed_names)
        ro = tuple(pt.global_scope().get(n) for n in cs.ro_names)
        rw = tuple(pt.global_scope().get(n) for n in cs.rw_names)
        mlir = cs.fn.lower(feed_vals, ro, rw, np.uint32(0)).as_text()
        kinds = _table_op_kinds(mlir, big_v, DIM)
        # gathers/scatters/params only — a dense adam emits full-table
        # multiply/add/subtract/sqrt/divide
        banned = {"multiply", "add", "subtract", "divide", "sqrt", "rsqrt"}
        assert "gather" in kinds or "scatter" in kinds, (
            f"parser found no table ops at all — format drift? {kinds}")
        assert not (kinds & banned), (
            f"dense table-shaped math leaked into the sparse step: "
            f"{sorted(kinds & banned)}")


class TestDenseMaskedPolicy:
    def test_dense_masked_matches_row_path(self, rng, monkeypatch):
        """The size-thresholded dense-MASKED lazy adam (no sort — the
        round-4 TPU win) must match the merged-rows path numerically,
        including untouched rows staying bit-identical."""
        from paddle_tpu.core import flags as _flags
        ids_batches = [rng.randint(0, VOCAB, (4, 3)).astype("int64")
                       for _ in range(3)]
        ids_batches[1][0, :2] = ids_batches[1][0, 2]  # duplicates

        def train(max_bytes, w0=None):
            pt.reset_default_programs()
            pt.reset_global_scope()
            monkeypatch.setattr(
                _flags._REGISTRY["sparse_dense_apply_max_bytes"],
                "value", max_bytes)
            exe, loss = _build(pt.optimizer.Adam(learning_rate=0.1))
            if w0 is None:
                w_init = _table()
            else:
                pt.global_scope().set_var("emb_w", w0)
                w_init = w0
            for ids in ids_batches:
                exe.run(feed={"ids": ids}, fetch_list=[loss])
            return w_init, _table()

        w0, w_rows = train(0)
        _, w_dense = train(1 << 30, w0=w0)
        np.testing.assert_allclose(w_dense, w_rows, rtol=1e-6, atol=1e-7)
        untouched = sorted(set(range(VOCAB))
                           - set(np.concatenate(ids_batches).ravel()))
        np.testing.assert_array_equal(w_dense[untouched], w0[untouched])


class TestFallbacks:
    def test_grad_fetch_forces_dense(self, rng):
        """Fetching the table grad must yield a dense [vocab, dim] array
        (the sparse carrier never escapes the trace)."""
        ids_v = np.array([[1, 5, 9]], dtype="int64")
        ids = layers.data("ids", shape=[3], dtype="int64")
        emb = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                               param_attr=pt.ParamAttr(name="emb_w"))
        loss = layers.reduce_mean(layers.square(emb))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        g, = exe.run(feed={"ids": ids_v}, fetch_list=["emb_w@GRAD"])
        assert g.shape == (VOCAB, DIM)
        nz = {r for r in range(VOCAB) if np.any(g[r] != 0)}
        assert nz == {1, 5, 9}

    def test_dense_embedding_unaffected(self, rng):
        """is_sparse=False keeps the plain dense path end to end."""
        ids_v = np.array([[0, 2, 4]], dtype="int64")
        # lr well below the init scale so adam's normalized step descends
        exe, loss = _build(pt.optimizer.Adam(learning_rate=1e-3),
                           is_sparse=False)
        l0 = float(exe.run(feed={"ids": ids_v}, fetch_list=[loss])[0])
        for _ in range(10):
            last = float(exe.run(feed={"ids": ids_v}, fetch_list=[loss])[0])
        assert last < l0
