"""Test configuration: run on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated
on XLA's host platform with 8 virtual devices (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os
import sys

# Every pass apply in the suite runs under the pass sanitizer
# (framework/analysis.py): existing pass tests double as sanitizer tests.
# Hard-set (not setdefault): an inherited PTPU_VERIFY_PASSES=0 must not
# silently un-verify the tier; use flags.set_flag in a test to opt out.
os.environ["PTPU_VERIFY_PASSES"] = "1"

# Same discipline for the KV shadow-state sanitizer (serving/sanitizer.py):
# every KVPager the suite constructs mirrors its block-lifetime mutations
# against the abstract ownership model and raises SanitizerDivergence on
# the first drift — existing serving tests double as protocol tests.
os.environ["PTPU_KV_SANITIZE"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# Single source of truth for the axon-plugin workaround + virtual-device
# bootstrap (shared with the driver's multichip dryrun).
from __graft_entry__ import _ensure_virtual_cpu_devices  # noqa: E402

_ensure_virtual_cpu_devices(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_state():
    """Each test gets fresh default programs / scope / name generator,
    and profiler/tracer state never bleeds between tests: the old
    profiler's module globals (_completed events, the _enabled bit) used
    to leak across suites — profiler.reset() restores every global and
    tracing.clear() empties the span ring."""
    import paddle_tpu as pt
    from paddle_tpu.core import unique_name
    from paddle_tpu.observability import flight_recorder, tracing
    pt.reset_default_programs()
    pt.reset_global_scope()
    pt.profiler.reset()
    tracing.clear()
    flight_recorder.reset()
    with unique_name.guard():
        yield
    pt.profiler.reset()
    flight_recorder.reset()


@pytest.fixture
def rng():
    return np.random.RandomState(42)
