"""Test configuration: run on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated
on XLA's host platform with 8 virtual devices (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags +
                               " --xla_force_host_platform_device_count=8")

# Drop any TPU-tunnel backend factory (e.g. the axon PJRT plugin registered by
# sitecustomize): CPU-only tests must never block on remote-device client
# creation, and the plugin's get_backend hook initializes it even under
# JAX_PLATFORMS=cpu.
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

for _plugin in ("axon",):
    # NOTE: only the axon tunnel plugin is dropped. The stock "tpu" platform
    # must stay registered (deviceless): removing it makes platform "tpu"
    # unknown to MLIR lowering registration, which breaks importing
    # jax.experimental.pallas.tpu even for interpret-mode runs.
    _xb._backend_factories.pop(_plugin, None)
# the plugin's register() may have pinned jax_platforms=axon in jax.config
# before this conftest ran — force CPU for the test session.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_state():
    """Each test gets fresh default programs / scope / name generator."""
    import paddle_tpu as pt
    from paddle_tpu.core import unique_name
    pt.reset_default_programs()
    pt.reset_global_scope()
    with unique_name.guard():
        yield


@pytest.fixture
def rng():
    return np.random.RandomState(42)
