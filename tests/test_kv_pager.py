"""Paged KV-cache subsystem: block pool + radix prefix index + paged
engine (ISSUE r20 tentpole).

Covers the paging contract end to end:
- BlockPool refcount/free-list invariants (null block reserved, alloc
  exhaustion, share/release, `check()` exactness);
- RadixPrefixIndex register/match/LRU-evict semantics incl. the
  missing-ancestor no-op and index-owned refs;
- `paged_cache_write` op parity against a per-row numpy reference;
- greedy decode identity: paged engine token-identical to the slot
  engine AND to a paged engine with prefix sharing disabled — shared
  prefixes change WHERE the KV bytes live, never the tokens;
- prefix-cache hits on a second wave over a warm index;
- CoW at the divergence block, pinned by a mutation test (writing the
  fork's copy must not alter the parent's physical block);
- beam search over forked tables: shared-vs-unshared identity;
- leak-free release/evict/reuse: after run_until_idle the only live
  blocks are the index's cached prefixes, and evict_all returns the
  pool to empty — twice;
- pool-capacity admission keeps requests PENDING (head-of-line) until
  blocks free, while submission-side limits raise with the block-table
  span named;
- census/watermark reconciliation: kv_cache category == pool bytes,
  used watermark == used blocks x per-block bytes;
- the paged tick compiles through the r06 fused decode path.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.serving import (BlockPool, ContinuousBatchingEngine,
                                KVPager, PagedKVEngine,
                                RadixPrefixIndex, paged_beam_search)

pytestmark = pytest.mark.quick

_DIMS = dict(vocab=50, max_len=16, d_model=32, d_inner=64, num_heads=4,
             num_layers=2)
_PREFIX = [2, 7, 1, 9, 4, 8, 5, 6]          # two full 4-token blocks


@pytest.fixture(scope="module")
def engines():
    """slot + paged + paged-without-sharing on ONE scope (same weights:
    identity tests compare token streams across all three)."""
    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()
    slot = ContinuousBatchingEngine(n_slots=3, scope=scope, **_DIMS)
    paged = PagedKVEngine(n_slots=3, block_size=4, topk_k=3,
                          scope=scope, **_DIMS)
    unshared = PagedKVEngine(n_slots=3, block_size=4, topk_k=3,
                             prefix_sharing=False, scope=scope, **_DIMS)
    return slot, paged, unshared


def _gen(eng, prompts, max_new=6):
    reqs = [eng.submit(list(p), max_new=max_new) for p in prompts]
    eng.run_until_idle()
    assert all(r.done for r in reqs)
    return [list(r.tokens) for r in reqs]


class TestBlockPool:
    def test_refcount_free_list_invariants(self):
        p = BlockPool(5, 2)                  # 4 data blocks + null
        bs = [p.alloc() for _ in range(4)]
        assert 0 not in bs and None not in bs
        assert p.alloc() is None             # exhausted
        assert p.n_used == 4 and p.n_free == 0
        p.share(bs[0])
        assert p.refcount(bs[0]) == 2
        assert p.release(bs[0]) is False     # still held
        assert p.release(bs[0]) is True      # now freed
        for b in bs[1:]:
            assert p.release(b) is True
        p.check()
        assert p.n_used == 0 and p.n_free == 4
        b = p.alloc()                        # freed blocks are reusable
        assert b in bs
        p.release(b)

    def test_null_block_protected(self):
        p = BlockPool(3, 2)
        with pytest.raises(InvalidArgumentError):
            p.release(0)
        with pytest.raises(InvalidArgumentError):
            p.share(0)
        b = p.alloc()
        p.release(b)
        with pytest.raises(InvalidArgumentError):
            p.release(b)                     # double free


class TestRadixPrefixIndex:
    def test_register_match_evict(self):
        pool = BlockPool(10, 4)
        idx = RadixPrefixIndex(4)
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        assert idx.match(prompt) == []
        b0, b1 = pool.alloc(), pool.alloc()
        assert idx.register(prompt, 0, b0, pool)
        assert idx.register(prompt, 1, b1, pool)
        m = idx.match(prompt + [9, 9])       # longer prompt, same lead
        assert [n.block for n in m] == [b0, b1]
        assert idx.match([1, 2, 3, 4, 0, 0, 0, 0]) \
            and idx.match([1, 2, 3, 4, 0, 0, 0, 0])[0].block == b0
        # drop the caller's refs: the index's own refs keep them live
        pool.release(b0)
        pool.release(b1)
        assert pool.n_used == 2
        assert idx.evict_one(pool)           # LRU leaf first: b1
        assert pool.n_used == 1 and pool.refcount(b0) == 1
        assert idx.evict_all(pool) == 1
        assert pool.n_used == 0
        pool.check()

    def test_missing_ancestor_is_noop(self):
        pool = BlockPool(10, 4)
        idx = RadixPrefixIndex(4)
        b = pool.alloc()
        assert not idx.register([1, 2, 3, 4, 5, 6, 7, 8], 1, b, pool)
        assert pool.refcount(b) == 1         # no index ref taken
        pool.release(b)
        pool.check()


class TestPagedCacheWriteOp:
    def test_parity_vs_numpy(self, rng):
        NB, nh, bs, dh = 6, 2, 4, 3
        pool = rng.randn(NB, nh, bs, dh).astype("float32")
        new = rng.randn(2, nh, dh).astype("float32")
        blocks = np.array([2, 5], "int64")
        offs = np.array([1, 3], "int64")
        c = layers.data(name="pc", shape=[NB, nh, bs, dh],
                        dtype="float32", append_batch_size=False)
        n = layers.data(name="pn", shape=[2, nh, dh], dtype="float32",
                        append_batch_size=False)
        b = layers.data(name="pb", shape=[2], dtype="int64",
                        append_batch_size=False)
        o = layers.data(name="po", shape=[2], dtype="int64",
                        append_batch_size=False)
        out = layers.paged_cache_write(c, n, b, o)
        got = pt.Executor().run(
            feed={"pc": pool, "pn": new, "pb": blocks, "po": offs},
            fetch_list=[out])[0]
        ref = pool.copy()
        for i in range(2):
            ref[blocks[i], :, offs[i], :] = new[i]
        np.testing.assert_allclose(got, ref, rtol=1e-6)


class TestDecodeIdentity:
    PROMPTS = [[7, 8, 9], [7, 8, 9], [1, 2, 3, 4, 5, 6],
               _PREFIX + [3], _PREFIX + [11, 12]]

    def test_paged_matches_slot_engine(self, engines):
        slot, paged, _ = engines
        assert _gen(paged, self.PROMPTS) == _gen(slot, self.PROMPTS)

    def test_shared_prefix_wave_token_identical_and_hits(self, engines):
        _, paged, unshared = engines
        # wave 1 fills + registers the prefix blocks; wave 2 must HIT
        wave1 = [_PREFIX + [3]]
        wave2 = [_PREFIX + [11], _PREFIX + [12, 13], _PREFIX + [3, 14]]
        _gen(paged, wave1)
        hits0 = paged.pager.prefix_hits
        got = _gen(paged, wave2)
        assert paged.pager.prefix_hits >= hits0 + len(wave2)
        _gen(unshared, wave1)
        assert got == _gen(unshared, wave2)
        assert unshared.pager.prefix_hits == 0
        paged.pager.pool.check()
        unshared.pager.pool.check()


class TestCopyOnWrite:
    def test_fork_shares_full_blocks_and_copies_divergence(self,
                                                           engines):
        _, _, eng = engines                  # unshared: empty index
        pager = eng.pager
        t1 = pager.try_admit(list(range(1, 9)), 12)   # 3 blocks
        assert t1 is not None and len(t1.blocks) == 3
        name = eng.cache_names[0]
        a = np.array(eng.scope.get(name))
        a[t1.blocks[1]] = 7.0                # sentinel in the partial
        eng.scope.set_var(name, a)
        t2 = pager.fork(t1, 6, eng._copy_block)   # 1 full + 2 in part
        assert t2.blocks[0] == t1.blocks[0]       # full block SHARED
        assert pager.pool.refcount(t1.blocks[0]) == 2
        assert t2.blocks[1] != t1.blocks[1]       # divergence COPIED
        assert t2.blocks[2] != t1.blocks[2]       # unwritten: fresh
        a = np.array(eng.scope.get(name))
        np.testing.assert_array_equal(a[t2.blocks[1]],
                                      a[t1.blocks[1]])
        # the mutation test: writing the fork's copy must not reach
        # the parent's physical block (and vice versa)
        a[t2.blocks[1]] = -3.0
        eng.scope.set_var(name, a)
        a = np.array(eng.scope.get(name))
        assert float(a[t1.blocks[1]].min()) == 7.0
        assert float(a[t2.blocks[1]].max()) == -3.0
        pager.release(t1)
        pager.release(t2)
        pager.pool.check()
        assert pager.cow_copies >= 1


class TestPagedBeamSearch:
    def test_shared_vs_unshared_identical(self, engines):
        _, paged, unshared = engines
        prompt = list(_PREFIX)
        a = paged_beam_search(paged, prompt, max_new=5, beam_size=3)
        b = paged_beam_search(unshared, prompt, max_new=5, beam_size=3)
        assert a == b
        assert len(a) == 3 and a[0][1] >= a[-1][1]   # sorted best-first
        assert paged.pager.cow_copies > 0
        paged.pager.pool.check()
        unshared.pager.pool.check()


class TestLeakFree:
    def test_release_evict_reuse_cycles(self, engines):
        _, paged, _ = engines
        pager = paged.pager
        for _ in range(2):
            _gen(paged, [_PREFIX + [11], _PREFIX + [12, 13],
                         [9, 9, 9, 9, 9]])
            pager.pool.check()
            # idle: the ONLY live blocks are the index's cached
            # prefixes — every request ref was dropped
            assert pager.pool.n_used == pager.stats()["blocks_cached"]
        pager.index.evict_all(pager.pool)
        assert pager.pool.n_used == 0
        pager.pool.check()
        # pool drained to empty is immediately reusable
        _gen(paged, [_PREFIX + [11]])
        pager.pool.check()


class TestCapacityAdmission:
    def test_head_of_line_waits_for_blocks(self):
        eng = PagedKVEngine(n_slots=2, max_len=8, block_size=4,
                            n_blocks=3, prefix_sharing=False, vocab=50,
                            d_model=32, d_inner=64, num_heads=4,
                            num_layers=2)
        r1 = eng.submit([1, 2, 3, 4], max_new=4)      # pins both blocks
        r2 = eng.submit([5, 6, 7, 8], max_new=4)
        eng.step()
        # a slot is free but the POOL is not: r2 must stay pending
        assert eng.n_active == 1 and eng.n_pending == 1
        eng.run_until_idle()
        assert r1.done and r2.done
        assert len(r1.tokens) == 4 and len(r2.tokens) == 4
        eng.pager.pool.check()
        assert eng.pager.pool.n_used == 0

    def test_submit_error_names_block_table_span(self):
        eng = PagedKVEngine(n_slots=2, max_len=8, block_size=4,
                            n_blocks=3, prefix_sharing=False, vocab=50,
                            d_model=32, d_inner=64, num_heads=4,
                            num_layers=2)
        with pytest.raises(InvalidArgumentError,
                           match="block-table span"):
            eng.submit(list(range(1, 8)), max_new=4)
        with pytest.raises(InvalidArgumentError, match="ADMISSION"):
            eng.submit(list(range(1, 8)), max_new=4)


class TestCensusReconciliation:
    def test_kv_category_and_watermarks_match_pool(self, engines):
        from paddle_tpu.observability.memory import (state_census,
                                                     watermark_board)
        _, paged, _ = engines
        c = state_census(paged.scope, paged._program, paged.cache_names,
                         kv_names=paged.cache_names)
        assert c["categories"]["kv_cache"] == pytest.approx(
            paged._kv_bytes_static)
        paged._stamp_kv_watermarks({})
        board = watermark_board()
        assert board["kv_cache_bytes"]["current"] == pytest.approx(
            paged._kv_bytes_static)
        per_block = paged._kv_bytes_static / paged.n_blocks
        assert board["kv_cache_used_bytes"]["current"] == pytest.approx(
            paged.pager.pool.n_used * per_block)
        # reserved covers used: the paging invariant in byte terms
        assert (board["kv_cache_used_bytes"]["current"]
                <= board["kv_cache_bytes"]["current"])


class TestFusedDecodeStructure:
    def test_paged_tick_fuses_attention(self):
        from paddle_tpu.framework.passes import FuseDecodeAttentionPass
        from paddle_tpu.models.transformer import \
            transformer_lm_paged_decode_tick
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            transformer_lm_paged_decode_tick(
                n_slots=2, n_blocks=5, block_size=4, blocks_per_req=2,
                vocab=50, d_model=32, d_inner=64, num_heads=4,
                num_layers=2, cache_prefix="tstpgd")
        FuseDecodeAttentionPass().apply(main)
        fused = [op for op in main.blocks[0].ops
                 if op.type == "fused_decode_attention"]
        assert len(fused) == 2               # one per layer
