"""r24 serving-tier ownership verifier.

Three layers under test, mirroring docs/static_analysis.md §5:

1. the abstract transition model + depth-bounded exhaustive model
   checker (framework/ownership.py) — the shipped protocol is clean at
   small scope and every seeded K-bug mutation is caught BY NAME;
2. the runtime shadow-state sanitizer (serving/sanitizer.py) — zero
   divergences on real KVPager traffic (differential fuzz, the whole
   serving suite runs under the conftest pin), and every seeded
   runtime bug raises SanitizerDivergence under its diagnostic code;
3. the static serving lints — cache-write aliasing over tick programs
   (framework/dataflow.py cache_write_aliasing) and the
   rollback-window extension of the transfer-schedule check
   (framework/offload.py check_schedule).
"""

import numpy as np
import pytest

from paddle_tpu.core import flags
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.framework import offload as ofl
from paddle_tpu.framework.offload import HostTierConfig
from paddle_tpu.framework.ownership import (DIAGNOSTICS, MUTATIONS,
                                            AbstractState, ModelChecker,
                                            OwnershipViolation)
from paddle_tpu.serving.kv_pager import KVPager
from paddle_tpu.serving import sanitizer as skv
from paddle_tpu.serving.sanitizer import SanitizerDivergence


@pytest.fixture(autouse=True)
def _sanitize_on():
    """Every test in this file runs with the kill switch ON (the suite
    pins it via PTPU_KV_SANITIZE=1 in conftest, but this file must hold
    standalone); individual tests flip it off through flags.set_flag."""
    prev = flags.get_flag("kv_sanitize")
    flags.set_flag("kv_sanitize", True)
    yield
    flags.set_flag("kv_sanitize", prev)


def _pager(n_blocks=9, block_size=4, host_blocks=None, **kw):
    tier = (HostTierConfig(host_blocks=host_blocks)
            if host_blocks is not None else None)
    p = KVPager(n_blocks=n_blocks, block_size=block_size, host_tier=tier,
                **kw)
    assert p.sanitizer is not None
    return p


# ---------------------------------------------------------------------------
# 1a. the abstract model: transitions and named preconditions
# ---------------------------------------------------------------------------


class TestAbstractModel:
    def test_admit_write_release_roundtrip(self):
        st = AbstractState(n_blocks=5, block_size=2)
        assert st.admit(0, prompt_len=3, need_len=5)
        for _ in range(5):
            st.write(0)
        st.check_invariants()
        st.release_table(0)
        st.check_invariants()
        # full prompt block 0 stays pinned by the index chain
        assert len(st.index_chain) == 1
        assert sum(st.ref) == 1

    def test_double_release_is_kv_double_free(self):
        st = AbstractState(n_blocks=5, block_size=2)
        st.alloc_at(1)
        st.release(1)
        with pytest.raises(OwnershipViolation) as e:
            st.release(1)
        assert e.value.code == "kv-double-free"

    def test_share_of_freed_block_is_use_after_free(self):
        st = AbstractState(n_blocks=5, block_size=2)
        with pytest.raises(OwnershipViolation) as e:
            st.share(2)
        assert e.value.code == "kv-use-after-free"

    def test_write_to_shared_block_is_cow_violation(self):
        st = AbstractState(n_blocks=7, block_size=2)
        assert st.admit(0, prompt_len=3, need_len=4)
        for _ in range(4):
            st.write(0)
        assert st.fork(0, 1)
        with pytest.raises(OwnershipViolation) as e:
            # position 0 lives in a block both hypotheses now hold
            st.note_write(st.tables[1].blocks, 0)
        assert e.value.code == "kv-write-shared-block"

    def test_two_tier_spill_reload_and_double_spill(self):
        st = AbstractState(n_blocks=5, block_size=2, host_blocks=4)
        assert st.admit(0, prompt_len=3, need_len=5)
        for _ in range(4):
            st.write(0)
        assert st.spill(0)
        st.check_invariants()
        assert st.host_used == 2
        with pytest.raises(OwnershipViolation) as e:
            st.spill(0)
        assert e.value.code == "kv-double-spill"
        assert st.reload(0)
        st.check_invariants()
        assert st.host_used == 0
        st.release_table(0)
        st.check_invariants()

    def test_commit_before_arrival_is_prefetch_after_use(self):
        st = AbstractState(n_blocks=5, block_size=2, host_blocks=4)
        assert st.admit(0, prompt_len=3, need_len=5)
        for _ in range(4):
            st.write(0)
        assert st.spill(0)
        with pytest.raises(OwnershipViolation) as e:
            st.reload(0, wait=False)     # commit with the ticket in flight
        assert e.value.code == "kv-prefetch-after-use"


# ---------------------------------------------------------------------------
# 1b. the model checker: shipped protocol clean, K-bug matrix by name
# ---------------------------------------------------------------------------


class TestModelChecker:
    def test_shipped_protocol_clean_at_default_scope(self):
        res = ModelChecker().run()
        assert res.ok, res.violations
        # deterministic BFS over a deterministic op set: the exact
        # coverage IS the spec — a protocol change must update it here
        # and in docs/static_analysis.md §5 together
        assert (res.states_explored, res.transitions) == (233, 676)

    def test_state_space_closes_exhaustively(self):
        # past depth 33 no new states exist at this scope: raising the
        # bound far beyond it proves TOTAL coverage, not a sample
        res = ModelChecker(depth=64).run()
        assert res.ok, res.violations
        assert res.states_explored == 4886
        assert res.transitions == 28843

    @pytest.mark.parametrize("mutation", sorted(MUTATIONS))
    def test_mutation_caught_by_name(self, mutation):
        res = ModelChecker(mutation=mutation).run()
        assert not res.ok
        assert MUTATIONS[mutation] in res.codes(), (
            f"{mutation} expected {MUTATIONS[mutation]}, got {res.codes()}")

    def test_every_mutation_code_is_documented(self):
        for code in MUTATIONS.values():
            assert code in DIAGNOSTICS


# ---------------------------------------------------------------------------
# 2a. the sanitizer catches every seeded runtime K-bug by name
# ---------------------------------------------------------------------------


class _InFlightTicket:
    def done(self):
        return False


class TestSanitizerCatchesSeededBugs:
    def test_leaked_release_is_kv_block_leak(self):
        pager = _pager(prefix_sharing=False)
        t = pager.try_admit([1, 2, 3, 4, 5], 8)
        t.blocks[-1] = 0                 # seeded: one mapping dropped,
        with pytest.raises(SanitizerDivergence) as e:   # release skipped
            pager.release(t)
        assert e.value.code == "kv-block-leak"

    def test_write_to_shared_block_is_caught(self):
        pager = _pager(prefix_sharing=False)
        t = pager.try_admit([1, 2, 3, 4, 5], 8)
        child = pager.fork(t, 8, copy_block=lambda s, d: None)
        with pytest.raises(SanitizerDivergence) as e:
            # position 0's block is now held by both hypotheses
            pager.sanitizer.note_write(child, 0)
        assert e.value.code == "kv-write-shared-block"

    def test_h2d_commit_in_flight_is_prefetch_after_use(self):
        pager = _pager(host_blocks=8, prefix_sharing=False)
        with pytest.raises(SanitizerDivergence) as e:
            pager.sanitizer.note_h2d_commit(_InFlightTicket())
        assert e.value.code == "kv-prefetch-after-use"

    def test_double_release_is_kv_double_free(self):
        # the rollback-double-free mutation reduces to releasing a
        # rejected block twice; the pool-level shadow precondition fires
        # BEFORE the real release can corrupt the free list
        pager = _pager(prefix_sharing=False)
        t = pager.try_admit([1, 2, 3, 4, 5], 8)
        pager.pool.release(t.blocks[-1])
        with pytest.raises(SanitizerDivergence) as e:
            pager.pool.release(t.blocks[-1])
        assert e.value.code == "kv-double-free"


# ---------------------------------------------------------------------------
# 2b. tampering with the real state diverges under the matching code
# ---------------------------------------------------------------------------


class TestSanitizerDivergenceOnTamper:
    def test_refcount_tamper_is_accounting_identity(self):
        pager = _pager(prefix_sharing=False)
        t = pager.try_admit([1, 2, 3, 4], 6)
        pager.pool._ref[t.blocks[0]] += 1
        with pytest.raises(SanitizerDivergence) as e:
            pager.sanitizer.verify_full("tamper")
        assert e.value.code == "kv-accounting-identity"

    def test_free_list_tamper_is_free_refcount(self):
        pager = _pager(prefix_sharing=False)
        t = pager.try_admit([1, 2, 3, 4], 6)
        pager.pool._free.append(t.blocks[0])
        with pytest.raises(SanitizerDivergence) as e:
            pager.sanitizer.verify_full("tamper")
        assert e.value.code == "kv-free-refcount"

    def test_table_maps_freed_block_is_use_after_free(self):
        pager = _pager(prefix_sharing=False)
        t = pager.try_admit([1, 2, 3, 4], 6)
        pager.pool.release(t.blocks[0])  # legal release, mapping kept
        with pytest.raises(SanitizerDivergence) as e:
            pager.sanitizer.verify_full("census")
        assert e.value.code == "kv-use-after-free"

    def test_host_ledger_tamper_is_host_accounting(self):
        pager = _pager(host_blocks=8, prefix_sharing=False)
        pager.host_blocks_used += 1
        with pytest.raises(SanitizerDivergence) as e:
            pager.sanitizer.verify_full("tamper")
        assert e.value.code == "kv-host-accounting"

    def test_double_spill_blocked_before_real_call(self):
        pager = _pager(host_blocks=8, prefix_sharing=False)
        t = pager.try_admit([1, 2, 3, 4, 5], 8)
        assert pager.evict_table_to_host(t, 5) is not None
        ledger = pager.host_blocks_used
        with pytest.raises(SanitizerDivergence) as e:
            pager.evict_table_to_host(t, 5)
        assert e.value.code == "kv-double-spill"
        assert pager.host_blocks_used == ledger   # no double charge
        pager.check_two_tier()

    def test_unadmitted_table_is_use_after_free(self):
        pager = _pager(prefix_sharing=False)
        t = pager.try_admit([1, 2, 3, 4], 6)
        pager.release(t)
        with pytest.raises(SanitizerDivergence) as e:
            pager.sanitizer.note_write(t, 0)
        assert e.value.code == "kv-use-after-free"


# ---------------------------------------------------------------------------
# 2c. differential fuzz: real KVPager vs the shadow after EVERY op
# ---------------------------------------------------------------------------


def _fuzz_two_tier(pager, n_ops, seed):
    """Random admit/write/spill/reload/rollback/release protocol
    traffic; the sanitizer cross-checks inside every wrapped op and we
    run the full census after each one on top."""
    rng = np.random.RandomState(seed)
    san = pager.sanitizer
    bs = pager.block_size
    resident, suspended = [], []
    ops = 0
    while ops < n_ops:
        op = rng.randint(6)
        if op == 0:
            prompt = rng.randint(1, 50, size=rng.randint(2, 9)).tolist()
            t = pager.try_admit(prompt, len(prompt) + 4)
            if t is not None:
                resident.append([t, len(prompt)])
        elif op == 1 and resident:
            i = rng.randint(len(resident))
            t, wl = resident[i]
            if wl < len(t.blocks) * bs:
                san.note_write(t, wl)
                resident[i][1] = wl + 1
        elif op == 2 and resident and pager.host_tier:
            t, wl = resident.pop(rng.randint(len(resident)))
            rec = pager.evict_table_to_host(t, wl)
            if rec is None:
                resident.append([t, wl])
            else:
                suspended.append([t, rec, wl])
        elif op == 3 and suspended:
            t, rec, wl = suspended.pop(rng.randint(len(suspended)))
            moves = pager.reload_table_from_host(t, rec)
            if moves is None:
                suspended.append([t, rec, wl])
            else:
                resident.append([t, wl])
        elif op == 4 and resident:
            i = rng.randint(len(resident))
            t, wl = resident[i]
            if wl >= 2:
                keep = int(rng.randint(1, wl))
                pager.rollback(t, keep, wl)
                resident[i][1] = keep
        elif op == 5 and len(resident) > 2:
            t, _ = resident.pop(rng.randint(len(resident)))
            pager.release(t)
            pager.refund_host_charge(0)
        ops += 1
        san.verify_full("fuzz")
        pager.check_two_tier() if pager.host_tier else pager.pool.check()
    for t, _ in resident:                # free device space first, then
        pager.release(t)                 # reload+release one at a time
    for t, rec, _ in suspended:
        assert pager.reload_table_from_host(t, rec) is not None
        pager.release(t)
    san.verify_full("fuzz-drain")
    assert pager.pool.n_used == 0 and pager.host_blocks_used == 0
    return san.stats()


class TestDifferentialFuzz:
    def test_fuzz_5k_ops_two_tier(self):
        pager = _pager(n_blocks=9, block_size=4, host_blocks=16,
                       prefix_sharing=False)
        stats = _fuzz_two_tier(pager, 5000, seed=24)
        assert stats["ops_mirrored"] >= 5000
        assert stats["tables_live"] == 0

    def test_fuzz_fork_release(self):
        # the beam-shaped op mix: admit / write / CoW fork / release
        pager = _pager(n_blocks=17, block_size=4, prefix_sharing=False)
        rng = np.random.RandomState(7)
        san, bs = pager.sanitizer, pager.block_size
        live = []
        for _ in range(1500):
            op = rng.randint(4)
            if op == 0 and len(live) < 3:
                prompt = rng.randint(1, 50, size=rng.randint(2, 7)).tolist()
                t = pager.try_admit(prompt, len(prompt) + 4)
                if t is not None:
                    live.append([t, len(prompt)])
            elif op == 1 and live:
                i = rng.randint(len(live))
                t, wl = live[i]
                # positions below a fork point are shared: only the
                # frontier block (refcount 1 by CoW) is writable
                if wl < len(t.blocks) * bs:
                    san.note_write(t, wl)
                    live[i][1] = wl + 1
            elif op == 2 and live and len(live) < 4:
                t, wl = live[rng.randint(len(live))]
                try:
                    child = pager.fork(t, wl,
                                       copy_block=lambda s, d: None)
                except InvalidArgumentError:
                    continue                 # pool dry: fork refused
                live.append([child, wl])
            elif op == 3 and len(live) > 1:
                t, _ = live.pop(rng.randint(len(live)))
                pager.release(t)
            san.verify_full("fork-fuzz")
        for t, _ in live:
            pager.release(t)
        san.verify_full("fork-fuzz-drain")
        assert pager.pool.n_used == 0

    @pytest.mark.slow
    def test_fuzz_25k_ops_two_tier_long(self):
        pager = _pager(n_blocks=13, block_size=4, host_blocks=24,
                       prefix_sharing=False)
        stats = _fuzz_two_tier(pager, 25000, seed=2024)
        assert stats["ops_mirrored"] >= 25000


# ---------------------------------------------------------------------------
# 3a. static lint: cache-write aliasing over tick programs
# ---------------------------------------------------------------------------


def _cache_write_fixture():
    from paddle_tpu import layers
    cache = layers.data("cache", shape=[4, 8], dtype="float32")
    new = layers.data("new", shape=[4, 1], dtype="float32")
    pos = layers.data("pos", shape=[], dtype="int64")
    return cache, new, pos


class TestCacheWriteAliasing:
    def test_shipped_paged_builders_clean(self):
        import paddle_tpu as pt
        from paddle_tpu import models
        from paddle_tpu.framework.dataflow import cache_write_aliasing
        models.transformer.transformer_lm_paged_decode_tick(
            n_slots=2, n_blocks=9, block_size=4, blocks_per_req=2,
            vocab=50, d_model=32, d_inner=64, num_heads=4, num_layers=2)
        prog = pt.default_main_program()
        n_writes = sum(1 for b in prog.blocks for op in b.ops
                       if op.type == "paged_cache_write")
        assert n_writes > 0
        assert cache_write_aliasing(prog) == []

    def test_duplicate_writers_flagged(self):
        from paddle_tpu import layers
        import paddle_tpu as pt
        from paddle_tpu.framework.dataflow import cache_write_aliasing
        cache, new, pos = _cache_write_fixture()
        layers.cache_write(cache, new, pos, axis=1, out=cache)
        layers.cache_write(cache, new, pos, axis=1, out=cache)
        diags = cache_write_aliasing(pt.default_main_program())
        assert [d.code for d in diags] == ["serving-cache-write-alias"]

    def test_persistable_fork_flagged(self):
        from paddle_tpu import layers
        import paddle_tpu as pt
        from paddle_tpu.framework.dataflow import cache_write_aliasing
        cache, new, pos = _cache_write_fixture()
        cache.persistable = True
        layers.cache_write(cache, new, pos, axis=1)      # out: fresh temp
        diags = cache_write_aliasing(pt.default_main_program())
        assert "serving-cache-write-alias" in [d.code for d in diags]

    def test_stale_read_after_fork_flagged(self):
        from paddle_tpu import layers
        import paddle_tpu as pt
        from paddle_tpu.framework.dataflow import cache_write_aliasing
        cache, new, pos = _cache_write_fixture()
        layers.cache_write(cache, new, pos, axis=1)      # out: fresh temp
        layers.elementwise_add(cache, cache)             # stale reader
        diags = cache_write_aliasing(pt.default_main_program())
        assert "serving-cache-stale-read" in [d.code for d in diags]


# ---------------------------------------------------------------------------
# 3b. static lint: transfer schedules under speculative rollback windows
# ---------------------------------------------------------------------------


class TestRollbackWindows:
    def test_shipped_policy_clean_with_windows_at_issue(self):
        events = ofl.kv_prefetch_events({"r1": 6, "r2": 9}, 2)
        # the engine re-issues the prefetch after any rollback, so the
        # worst legal window sits exactly at the issue tick
        windows = {ev.var: [ev.issue_tick] for ev in events}
        assert ofl.check_schedule(events, rollback_windows=windows) == []

    def test_straddling_transfer_flagged_by_name(self):
        events = ofl.kv_prefetch_events({"r1": 6}, 2)   # issue 4, read 6
        diags = ofl.check_schedule(events,
                                   rollback_windows={"r1": [5]})
        assert [d.code for d in diags] == ["offload-stale-after-rollback"]

    def test_no_windows_matches_r13_behavior(self):
        events = [ofl.TransferEvent("v", "h2d", 5, 7, 6)]
        diags = ofl.check_schedule(events)
        assert [d.code for d in diags] == ["offload-use-before-arrival"]


# ---------------------------------------------------------------------------
# kill switch: zero-cost when off, participates in the compile cache key,
# and never perturbs the program IR
# ---------------------------------------------------------------------------


class TestKillSwitch:
    def test_attach_absent_when_off(self):
        flags.set_flag("kv_sanitize", False)
        pager = KVPager(n_blocks=9, block_size=4, prefix_sharing=False)
        assert pager.sanitizer is None
        # instance methods are NOT wrapped: the class attributes resolve
        assert "alloc" not in pager.pool.__dict__
        assert "try_admit" not in pager.__dict__

    def test_flag_participates_in_compile_cache_key(self):
        from paddle_tpu.framework.executor import _fusion_flags_key
        on = _fusion_flags_key()
        flags.set_flag("kv_sanitize", False)
        off = _fusion_flags_key()
        assert on != off

    def test_tick_program_identical_on_off(self):
        import paddle_tpu as pt
        from paddle_tpu import models
        from paddle_tpu.core import unique_name

        def build():
            pt.reset_default_programs()
            with unique_name.guard():
                models.transformer.transformer_lm_paged_decode_tick(
                    n_slots=2, n_blocks=9, block_size=4, blocks_per_req=2,
                    vocab=50, d_model=32, d_inner=64, num_heads=4,
                    num_layers=2)
            prog = pt.default_main_program()
            return [(op.type, sorted(op.inputs.items()),
                     sorted(op.outputs.items()))
                    for b in prog.blocks for op in b.ops]

        with_san = build()
        flags.set_flag("kv_sanitize", False)
        without = build()
        assert with_san == without
