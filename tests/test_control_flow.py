"""Control-flow tests (≙ reference test_while_op.py, test_recurrent_op.py,
test_dyn_rnn.py, conditional-block tests)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.layers.control_flow import (DynamicRNN, IfElse, StaticRNN,
                                            Switch, While, cond)


def _run(fetch, feed=None):
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe.run(feed=feed or {}, fetch_list=fetch)


class TestWhile:
    def test_counts_to_ten(self):
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 10)
        total = layers.fill_constant([1], "float32", 0.0)
        c = layers.less_than(i, n)
        w = While(c)
        with w.block():
            t2 = layers.elementwise_add(total,
                                        layers.cast(i, "float32"))
            layers.assign(t2, output=total)
            i2 = layers.increment(i, value=1)
            layers.assign(i2, output=i)
            layers.less_than(i, n, cond=c)
        out, iv = _run([total, i])
        assert float(out) == sum(range(10))
        assert int(iv) == 10


class TestStaticRNN:
    def test_cumsum_scan(self, rng):
        x = layers.data(name="x", shape=[6, 4])  # [B, T=6, D=4]
        zero = layers.fill_constant_batch_size_like(x, [-1, 4], "float32", 0.0)
        rnn = StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            acc = rnn.memory(init=zero)
            s = layers.elementwise_add(acc, xt)
            rnn.update_memory(acc, s)
            rnn.step_output(s)
        out = rnn()
        xv = rng.rand(3, 6, 4).astype("float32")
        res, = _run([out], feed={"x": xv})
        np.testing.assert_allclose(res, np.cumsum(xv, axis=1), rtol=1e-5)

    def test_rnn_with_fc_trains(self, rng):
        """A trainable RNN built from StaticRNN: gradients flow through
        lax.scan."""
        x = layers.data(name="x", shape=[5, 8])
        y = layers.data(name="y", shape=[1])
        h0 = layers.fill_constant_batch_size_like(x, [-1, 8], "float32", 0.0)
        rnn = StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h = rnn.memory(init=h0)
            nh = layers.fc([xt, h], size=8, act="tanh")
            rnn.update_memory(h, nh)
            rnn.step_output(nh)
        seq = rnn()
        last = layers.slice(seq, axes=[1], starts=[4], ends=[5])
        last = layers.reshape(last, shape=[-1, 8])
        pred = layers.fc(last, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        pt.optimizer.Adam(learning_rate=1e-2).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        xv = rng.rand(8, 5, 8).astype("float32")
        yv = rng.rand(8, 1).astype("float32")
        losses = [float(exe.run(feed={"x": xv, "y": yv},
                                fetch_list=[loss])[0]) for _ in range(10)]
        assert losses[-1] < losses[0]


class TestDynamicRNN:
    def test_respects_lengths(self, rng):
        x = layers.data(name="x", shape=[6, 4], lod_level=1)
        zero = layers.fill_constant_batch_size_like(x, [-1, 4], "float32", 0.0)
        drnn = DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x)
            acc = drnn.memory(init=zero)
            s = layers.elementwise_add(acc, xt)
            drnn.update_memory(acc, s)
            drnn.step_output(s)
        out = drnn()
        final = drnn.final_memories()
        xv = rng.rand(2, 6, 4).astype("float32")
        sl = np.array([3, 6], dtype="int32")
        res, fin = _run([out, final], feed={"x": xv, "x@SEQLEN": sl})
        # sequence 0 freezes after t=3: final == cumsum of first 3 steps
        np.testing.assert_allclose(fin[0], xv[0, :3].sum(0), rtol=1e-5)
        np.testing.assert_allclose(fin[1], xv[1].sum(0), rtol=1e-5)
        # outputs past the length are zero-masked
        assert np.all(res[0, 3:] == 0)


class TestCond:
    def test_ifelse_mask_merge(self, rng):
        x = layers.data(name="x", shape=[4])
        flag = layers.data(name="flag", shape=[1], dtype="bool")
        ie = IfElse(flag)
        with ie.true_block():
            ie.output(layers.scale(x, scale=2.0))
        with ie.false_block():
            ie.output(layers.scale(x, scale=-1.0))
        out, = ie()
        xv = rng.rand(6, 4).astype("float32")
        fv = np.array([[1], [0], [1], [0], [1], [0]], dtype=bool)
        res, = _run([out], feed={"x": xv, "flag": fv})
        exp = np.where(fv, xv * 2.0, -xv)
        np.testing.assert_allclose(res, exp, rtol=1e-6)

    def test_lazy_cond_scalar(self):
        pred = layers.fill_constant([1], "bool", True)
        a = layers.fill_constant([2], "float32", 3.0)
        b = layers.fill_constant([2], "float32", 5.0)
        out = cond(pred,
                   lambda: layers.elementwise_add(a, b),
                   lambda: layers.elementwise_sub(a, b))
        res, = _run([out])
        np.testing.assert_allclose(res, [8.0, 8.0])

    def test_switch_piecewise(self):
        step = layers.fill_constant([1], "float32", 7.0)
        b1 = layers.fill_constant([1], "float32", 5.0)
        b2 = layers.fill_constant([1], "float32", 10.0)
        lr = layers.create_tensor("float32", name="lr_value")
        sw = Switch()
        with sw.case(layers.less_than(step, b1)):
            layers.assign(layers.fill_constant([1], "float32", 0.1),
                          output=lr)
        with sw.case(layers.less_than(step, b2)):
            layers.assign(layers.fill_constant([1], "float32", 0.01),
                          output=lr)
        with sw.default():
            layers.assign(layers.fill_constant([1], "float32", 0.001),
                          output=lr)
        out = sw.finish(lr)
        res, = _run([out])
        np.testing.assert_allclose(res, [0.01])


def test_static_rnn_gradients_reach_cell_params(rng):
    """Regression: static_rnn outputs must not be stop_gradient — the cell's
    parameters (read via Captures) must receive nonzero gradients."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    x = layers.data("x", shape=[4, 8])
    h0 = layers.data("h0", shape=[8])
    rnn = layers.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        h = rnn.memory(init=h0)
        nh = layers.fc(layers.concat([xt, h], axis=1), size=8, act="tanh",
                       name="reg_cell")
        rnn.update_memory(h, nh)
        rnn.step_output(nh)
    loss = layers.mean(rnn())
    pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    g = exe.run(feed={"x": rng.rand(2, 4, 8).astype("float32"),
                      "h0": np.zeros((2, 8), "float32")},
                fetch_list=["reg_cell.w_0@GRAD", "reg_cell.w_1@GRAD"])
    assert np.abs(g[0]).max() > 0 and np.abs(g[1]).max() > 0


class TestTensorArrays:
    def test_write_read_roundtrip(self, rng):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.layers.control_flow import (array_length, array_read,
                                                    array_write,
                                                    create_array)
        x = layers.data("x", shape=[4])
        arr = create_array("float32", max_len=3, shape=[2, 4])
        # functional threading: each write returns the new array
        i0 = layers.fill_constant([], "int64", 0)
        i1 = layers.fill_constant([], "int64", 1)
        arr = array_write(x, i0, arr)
        arr = array_write(x * 2.0, i1, arr)
        got0 = array_read(arr, i0)
        got1 = array_read(arr, i1)
        n = array_length(arr)
        exe = pt.Executor()
        xv = rng.rand(2, 4).astype("float32")
        a, b, ln = exe.run(feed={"x": xv}, fetch_list=[got0, got1, n])
        np.testing.assert_allclose(a, xv, rtol=1e-6)
        np.testing.assert_allclose(b, xv * 2, rtol=1e-6)
        assert ln == 3


class TestCheckPass:
    def test_clean_program_passes(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        x = layers.data("x", shape=[4])
        layers.fc(x, size=2)
        pt.get_pass("check_pass")(pt.default_main_program())

    def test_undefined_read_reported(self):
        import paddle_tpu as pt
        from paddle_tpu.core.enforce import NotFoundError
        prog = pt.Program()
        blk = prog.global_block()
        blk.create_var(name="ghost_in", shape=[2], dtype="float32")
        blk.create_var(name="out", shape=[2], dtype="float32")
        blk.append_op(type="relu", inputs={"X": ["ghost_in"]},
                      outputs={"Out": ["out"]})
        with pytest.raises(NotFoundError, match="ghost_in"):
            pt.get_pass("check_pass")(prog)


def test_check_pass_accepts_static_rnn_programs(rng):
    """Regression: scan-bound sub-block vars (step inputs, memories) are
    binder-defined, not op-produced — check_pass must accept them."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    x = layers.data("x", shape=[4, 8])
    h0 = layers.data("h0", shape=[8])
    rnn = layers.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        h = rnn.memory(init=h0)
        nh = layers.fc(layers.concat([xt, h], axis=1), size=8, act="tanh")
        rnn.update_memory(h, nh)
        rnn.step_output(nh)
    loss = layers.mean(rnn())
    pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    pt.get_pass("check_pass")(pt.default_main_program())


def test_check_pass_catches_grad_read_without_backward():
    """An optimizer op reading w@GRAD with no vjp_region producing it must
    be reported (no blanket @GRAD exemption)."""
    import paddle_tpu as pt
    from paddle_tpu.core.enforce import NotFoundError
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_parameter(name="w", shape=[4], dtype="float32")
    blk.create_var(name="w@GRAD", shape=[4], dtype="float32")
    blk.create_var(name="lr", shape=[], dtype="float32", persistable=True)
    blk.append_op(type="sgd",
                  inputs={"Param": ["w"], "Grad": ["w@GRAD"],
                          "LearningRate": ["lr"]},
                  outputs={"ParamOut": ["w"]})
    with pytest.raises(NotFoundError, match="w@GRAD"):
        pt.get_pass("check_pass")(prog)
