"""Per-pass numerical-equivalence verification on REAL models.

≙ the reference's per-pass inference tests (inference/tests/book/,
inference/analysis/analyzer_tester.cc): every Analyzer/transpiler rewrite
must leave the program numerically equivalent (or boundedly close, for
quantization) on an actual model, not just a toy block.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models


def test_bn_fold_pass_preserves_resnet_cifar10_logits(rng):
    """BN-fold on resnet_cifar10 inference: logits identical (atol) after
    batch_norm ops are folded into the preceding convolutions."""
    from paddle_tpu import Analyzer

    loss, acc, logits = models.resnet.resnet_cifar10(depth=20)
    train_prog = pt.default_main_program()
    pt.optimizer.MomentumOptimizer(learning_rate=0.01,
                                   momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    # a few train steps so BN moving stats move off their init values
    feed = {"img": rng.rand(4, 32, 32, 3).astype("float32"),
            "label": rng.randint(0, 10, (4, 1)).astype("int64")}
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[loss])

    infer = train_prog.clone(for_test=True).prune([logits.name])
    base, = exe.run(infer, feed={"img": feed["img"]}, fetch_list=[logits])

    folded = Analyzer(passes=["bn_fold_pass"]).run(
        infer, pt.global_scope(), targets=[logits])
    types = [op.type for op in folded.global_block().ops]
    assert "batch_norm" not in types, "pass did not fold the BN ops"
    got, = exe.run(folded, feed={"img": feed["img"]}, fetch_list=[logits])
    np.testing.assert_allclose(got, base, atol=2e-3, rtol=2e-3)


def test_memory_optimize_remat_preserves_transformer_train_step(rng):
    """Rematerialization on transformer_lm: the rewritten program's loss AND
    updated parameters match the unoptimized run exactly — remat may only
    trade FLOPs for memory, never change math."""
    from paddle_tpu.core import unique_name

    def build_and_step(remat_level):
        pt.reset_default_programs()
        pt.reset_global_scope()
        with unique_name.guard():
            loss, _ = models.transformer.transformer_lm(
                vocab=64, max_len=8, d_model=32, d_inner=64, num_heads=2,
                num_layers=2)
            pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        prog = pt.default_main_program()
        if remat_level is not None:
            from paddle_tpu.transpiler import memory_optimization
            memory_optimization.memory_optimize(prog, level=remat_level)
            assert any(op.attrs.get("remat")
                       for op in prog.global_block().ops
                       if op.type == "vjp_region"), "remat not applied"
        exe = pt.Executor()
        pt.default_startup_program().random_seed = 7
        exe.run(pt.default_startup_program())
        rng2 = np.random.RandomState(3)
        tok = rng2.randint(0, 64, (4, 8)).astype("int64")
        tgt = rng2.randint(0, 64, (4, 8)).astype("int64")
        sl = np.full((4,), 8, dtype="int32")
        lv = exe.run(feed={"tokens": tok, "tokens@SEQLEN": sl,
                           "targets": tgt}, fetch_list=[loss])[0]
        params = {p.name: np.asarray(pt.global_scope().get(p.name))
                  for p in prog.all_parameters()}
        return float(lv), params

    base_loss, base_params = build_and_step(None)
    for level in (0, 1):
        remat_loss, remat_params = build_and_step(level)
        assert abs(base_loss - remat_loss) < 1e-5, (level, base_loss,
                                                    remat_loss)
        assert base_params.keys() == remat_params.keys()
        for name in base_params:
            np.testing.assert_allclose(
                remat_params[name], base_params[name], atol=1e-5,
                rtol=1e-4, err_msg=f"level={level} param {name}")


def test_quant_freeze_round_trip_mlp(rng):
    """QAT -> train -> freeze: the frozen program's outputs match the
    QAT program's outputs (freezing bakes the SAME quantization the fake
    ops already simulate, so outputs agree to rounding tolerance)."""
    from paddle_tpu.core import unique_name
    from paddle_tpu.transpiler import QuantizeTranspiler

    with unique_name.guard():
        img = layers.data("img", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, size=16, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, label))

    qt = QuantizeTranspiler(weight_bits=8, activation_bits=8)
    qt.training_transpile()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"img": rng.rand(8, 16).astype("float32"),
            "label": rng.randint(0, 4, (8, 1)).astype("int64")}
    for _ in range(5):
        exe.run(feed=feed, fetch_list=[loss])

    qat_prog = pt.default_main_program().clone(for_test=True).prune(
        [logits.name])
    qat_out, = exe.run(qat_prog, feed={"img": feed["img"]},
                       fetch_list=[logits])

    frozen = qt.freeze_program(qat_prog, scope=pt.global_scope())
    froz_out, = exe.run(frozen, feed={"img": feed["img"]},
                        fetch_list=[logits])
    np.testing.assert_allclose(froz_out, qat_out, atol=2e-2, rtol=2e-2)
    # the freeze really quantized: every baked weight tensor now sits on an
    # int8 grid (<= 2^8 distinct values) — an identity "freeze" would keep
    # the continuous float weights and slip past the closeness check above
    for p in frozen.all_parameters():
        if p.name.endswith(".w_0"):
            w = np.asarray(pt.global_scope().get(p.name))
            assert len(np.unique(w)) <= 256, (
                f"{p.name} not on an int8 grid after freeze "
                f"({len(np.unique(w))} distinct values)")
