"""Dataflow analysis layer tests (framework/dataflow.py).

Four areas, mirroring the subsystem:
1. effect sets — slot-derived defaults, registered collective/rng/in-place
   rules, the registration API contract;
2. def-use chains, lifetimes (backward-region extension), interference;
3. the three whole-program detectors — one mutation test per diagnostic
   code, each a seeded-bad program that ONLY that detector catches (the
   assert pins the exact code set), on single-axis AND composed
   dp2 x pp2 x tp2 programs;
4. the satellites riding this layer: whole-program peak_live_bytes
   (sub-blocks + regions) and the lint CLI's --json/exit-code contract.
"""

import json
import os
import subprocess
import sys

import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.core.enforce import AlreadyExistsError, EnforceError
from paddle_tpu.framework import analysis, dataflow
from paddle_tpu.framework import sharding as _sharding  # registers tp_shard_pass
from paddle_tpu.framework.passes import get_pass
from paddle_tpu.framework.program import Operator
from paddle_tpu.framework.registry import register_effects, register_op
from paddle_tpu.parallel import annotate_tp
from paddle_tpu.parallel.grad_comm import comm_optimize_pass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DP_CFG = {"shard_update": True, "quant": "", "block": 512,
           "error_feedback": False, "bucket_bytes": 1 << 20}


def _errors(diags):
    return [d for d in diags if d.severity == "error"]


def _codes(diags):
    return {d.code for d in _errors(diags)}


def _mlp_program():
    x = layers.data("x", shape=[16])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return pt.default_main_program(), h, logits, loss


def _dp_program(dp=4):
    prog, *_ = _mlp_program()
    return comm_optimize_pass(prog, dp, dict(_DP_CFG))


def _tp_spliced_program():
    loss, _ = models.transformer.transformer_lm(
        vocab=64, max_len=8, d_model=32, d_inner=64, num_heads=4,
        num_layers=2, mean_loss=True, dropout=0.1)
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    annotate_tp()
    return get_pass("tp_shard_pass", tp=2)(pt.default_main_program())


def _composed_program():
    """tp2 -> dp2 -> pp2/1F1B, the ParallelExecutor._prepare_program
    order — the full 3D-mesh program the composed mutation tests seed."""
    tp = _tp_spliced_program()
    dp = comm_optimize_pass(tp, 2, dict(_DP_CFG))
    return get_pass("pipeline_partition_pass", num_stages=2,
                    num_microbatches=4, schedule="1f1b", dp_axis="dp",
                    reduce_dp=False)(dp)


# ---------------------------------------------------------------------------
# effect sets
# ---------------------------------------------------------------------------


def test_default_effects_pure_compute():
    prog, h, logits, loss = _mlp_program()
    block = prog.global_block()
    op = next(op for op in block.ops if op.type == "relu")
    eff = dataflow.op_effects(op)
    assert eff.reads and eff.writes
    assert not eff.collective_axes and not eff.resolves_axes \
        and not eff.shards_axes and not eff.rng and not eff.inplace


def test_same_name_in_place_update_is_an_inplace_effect():
    ctr = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    layers.increment(ctr, value=1.0, in_place=True)
    block = pt.default_main_program().global_block()
    op = next(op for op in block.ops if op.type == "increment")
    assert (ctr.name, ctr.name) in dataflow.op_effects(op).inplace


def test_rng_effects_respect_seed_and_is_test():
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="r", shape=[4], dtype="float32")
    op = blk.append_op("uniform_random", outputs={"Out": ["r"]},
                       attrs={"shape": [4]})
    assert dataflow.op_effects(op).rng
    op.attrs["seed"] = 7             # pinned stream: identical on every shard
    assert not dataflow.op_effects(op).rng
    blk.create_var(name="d", shape=[4], dtype="float32")
    blk.create_var(name="m", shape=[4], dtype="float32")
    dop = blk.append_op("dropout", inputs={"X": ["r"]},
                        outputs={"Out": ["d"], "Mask": ["m"]},
                        attrs={"dropout_prob": 0.5})
    assert dataflow.op_effects(dop).rng
    dop.attrs["is_test"] = True      # inference path is deterministic
    assert not dataflow.op_effects(dop).rng


def test_collective_effects_of_the_parallel_ops():
    tp = _tp_spliced_program()
    block = tp.global_block()
    ar = next(op for op in block.ops if op.type == "tp_allreduce")
    eff = dataflow.op_effects(ar)
    assert eff.collective_axes == ("tp",) and eff.resolves_axes == ("tp",)
    sp = next(op for op in block.ops if op.type == "tp_split")
    eff = dataflow.op_effects(sp)
    assert eff.collective_axes == ("tp",) and eff.shards_axes == ("tp",)

    dp = _dp_program()
    block = dp.global_block()
    comm = next(op for op in block.ops if op.type == "dp_grad_comm")
    assert dataflow.op_effects(comm).collective_axes == ("dp",)
    sl = next(op for op in block.ops if op.type == "dp_shard_slice")
    assert dataflow.op_effects(sl).shards_axes == ("dp",)
    ag = next(op for op in block.ops if op.type == "dp_shard_all_gather")
    assert dataflow.op_effects(ag).resolves_axes == ("dp",)


def test_effect_registration_is_once_only():
    register_effects("_tdf_effect_dup_probe")(lambda op: {})
    with pytest.raises(AlreadyExistsError):
        register_effects("_tdf_effect_dup_probe")(lambda op: {})


def test_axis_and_suffix_literals_stay_in_sync():
    """framework/dataflow.py duplicates the mesh-axis names and the ZeRO
    shard suffix as literals (framework/ must not import parallel/) —
    this is the pin that keeps them honest."""
    from paddle_tpu.parallel import grad_comm, mesh
    assert dataflow.DP_AXIS == mesh.DATA_AXIS
    assert dataflow.TP_AXIS == mesh.MODEL_AXIS
    assert dataflow.PP_AXIS == mesh.PIPELINE_AXIS
    assert dataflow._DP_SHARD_SUFFIX == grad_comm.SHARD_SUFFIX


# ---------------------------------------------------------------------------
# def-use, lifetimes, interference
# ---------------------------------------------------------------------------


def test_def_use_chains():
    prog, h, logits, loss = _mlp_program()
    block = prog.global_block()
    du = dataflow.def_use_chains(block)
    widx = du.producers[h.name]
    assert len(widx) == 1
    assert all(i > widx[0] for i in du.consumers[h.name])
    assert du.uses_after(h.name, widx[0]) == du.consumers[h.name]


def test_lifetimes_extend_to_the_backward_region():
    prog, h, logits, loss = _mlp_program()
    block = prog.global_block()
    ridx = next(i for i, op in enumerate(block.ops)
                if op.type == "vjp_region")
    with_region = dataflow.var_lifetimes(block)
    without = dataflow.var_lifetimes(block, include_regions=False)
    # the hidden activation's last FORWARD reader is before the region,
    # but the backward re-runs the segment — it must stay live to ridx
    assert without[h.name][1] < ridx
    assert with_region[h.name][1] == ridx


def test_interference_graph_overlap_semantics():
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    for n in ("a", "b", "c"):
        blk.create_var(name=n, shape=[4], dtype="float32")
    blk.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["a"]})
    blk.append_op("tanh", inputs={"X": ["a"]}, outputs={"Out": ["b"]})
    blk.append_op("relu", inputs={"X": ["b"]}, outputs={"Out": ["c"]})
    g = dataflow.interference_graph(blk)
    assert "b" in g["a"] and "a" in g["b"]      # a live [0,1], b [1,2]
    assert "c" not in g["a"]                    # a dead before c is born
    assert "x" not in g                         # feeds excluded


# ---------------------------------------------------------------------------
# taint engine + replica-divergence detector
# ---------------------------------------------------------------------------


def test_divergence_taints_raw_grads_and_comm_clearing():
    dp = _dp_program()
    block = dp.global_block()
    env = dataflow.divergence_taints(dp)
    comm = next(op for op in block.ops if op.type == "dp_grad_comm")
    raw = comm.inputs["X"][0]
    assert any(t.axis == "dp" and t.kind == "grad"
               for t in env[(0, raw)])
    kinds = comm.attrs["kinds"]
    for i, out in enumerate(comm.outputs["Out"]):
        dp_taints = {t.kind for t in env[(0, out)] if t.axis == "dp"}
        if kinds[i] == "bucket":
            assert not dp_taints               # psum'd: dp-consistent
        else:
            assert dp_taints == {"shard"}      # deliberate ZeRO slice


def test_spmd_program_has_no_taints():
    prog, *_ = _mlp_program()
    assert dataflow.divergence_taints(prog) == {}


def test_replica_divergence_rng_into_optimizer():
    """Seeded-bad program ONLY the divergence detector catches: an rng-
    scaled learning rate feeding the update. dp-comm-bypass cannot see it
    (no raw-gradient name involved); shapes all agree."""
    dp = _dp_program()
    block = dp.global_block()
    opt = next(op for op in block.ops if op.type == "sgd")
    lr = opt.inputs["LearningRate"][0]
    block.create_var(name="lr_noise", shape=[1], dtype="float32")
    block.create_var(name="lr_noised", shape=[1], dtype="float32")
    at = block.ops.index(opt)
    block.ops.insert(at, Operator(
        block, "uniform_random", outputs={"Out": ["lr_noise"]},
        attrs={"shape": [1], "min": 0.9, "max": 1.1}))
    block.ops.insert(at + 1, Operator(
        block, "elementwise_mul",
        inputs={"X": [lr], "Y": ["lr_noise"]},
        outputs={"Out": ["lr_noised"]}, attrs={"axis": -1}))
    opt.inputs["LearningRate"] = ["lr_noised"]
    diags = analysis.verify_program(dp)
    assert _codes(diags) == {"replica-divergence"}, diags
    hit = next(d for d in _errors(diags)
               if d.code == "replica-divergence")
    assert "uniform_random" in hit.message and "lr_noised" in hit.message


def test_replica_divergence_tp_partial_consumed_without_allreduce():
    """Swap a tp_allreduce (Megatron g) for tp_ident: structurally intact,
    shapes identical — only the partial-sum contract catches it."""
    tp = _tp_spliced_program()
    block = tp.global_block()
    ar = next(op for op in block.ops if op.type == "tp_allreduce")
    ar.type = "tp_ident"
    diags = analysis.verify_program(tp)
    assert _codes(diags) == {"replica-divergence"}, diags
    assert any(_sharding.TP_PART_SUFFIX in d.message
               for d in _errors(diags))


def test_zero1_sharded_update_is_sanctioned():
    """The r08 ZeRO-1 path feeds the optimizer dp-SHARDED values by
    design (param slice, comm'd shard, sharded accumulators) — the
    detector must not flag the sanctioned pattern."""
    dp = _dp_program()
    assert "replica-divergence" not in _codes(analysis.verify_program(dp))


# ---------------------------------------------------------------------------
# collective-consistency detector
# ---------------------------------------------------------------------------


def test_collective_axis_mismatch_tp():
    tp = _tp_spliced_program()
    block = tp.global_block()
    ar = next(op for op in block.ops if op.type == "tp_allreduce")
    ar.attrs["axis"] = "dp"
    assert _codes(analysis.verify_program(tp)) == \
        {"collective-axis-mismatch"}


def test_collective_axis_mismatch_dp():
    dp = _dp_program()
    block = dp.global_block()
    comm = next(op for op in block.ops if op.type == "dp_grad_comm")
    comm.attrs["axis"] = "tp"
    assert "collective-axis-mismatch" in _codes(analysis.verify_program(dp))


def test_collective_order_send_in_wrong_stage():
    pp = get_pass("pipeline_partition_pass", num_stages=2,
                  num_microbatches=4,
                  schedule="1f1b")(_mlp_program()[0])
    block = pp.global_block()
    region = next(op for op in block.ops
                  if op.type == "pp_pipeline_region")
    sidx = next(i for i, op in enumerate(block.ops)
                if op.type == "pp_send")
    stages = [list(s) for s in region.attrs["stages"]]
    stages[0].remove(sidx)
    stages[1].insert(0, sidx)        # the send now lives on the consumer
    region.attrs["stages"] = stages
    diags = analysis.verify_program(pp)
    assert _codes(diags) == {"collective-order"}, diags
    assert "deadlock" in next(d for d in _errors(diags)).message


def test_collective_order_send_before_recv_within_stage():
    pp = get_pass("pipeline_partition_pass", num_stages=3,
                  num_microbatches=4,
                  schedule="1f1b")(_mlp_program()[0])
    block = pp.global_block()
    region = next(op for op in block.ops
                  if op.type == "pp_pipeline_region")
    stages = [list(s) for s in region.attrs["stages"]]
    # stage 1 owns recv(cut 0) first and send(cut 1) last: reverse them
    stages[1] = [stages[1][-1]] + stages[1][1:-1] + [stages[1][0]]
    region.attrs["stages"] = stages
    assert "collective-order" in _codes(analysis.verify_program(pp))


def test_collective_divergent_control():
    """A dp collective under control flow whose condition is rng-divergent
    over dp: shards disagree on entering the branch — static deadlock."""
    dp = _dp_program()
    block = dp.global_block()
    h = next(op for op in block.ops if op.type == "relu").outputs["Out"][0]
    block.create_var(name="cflag", shape=[1], dtype="float32")
    block.append_op("uniform_random", outputs={"Out": ["cflag"]},
                    attrs={"shape": [1]})
    sub = dp._create_block(parent_idx=0)
    dp._rollback()
    sub.create_var(name="sub_gathered", shape=[64, 32], dtype="float32")
    sub.append_op("dp_shard_all_gather", inputs={"X": [h]},
                  outputs={"Out": ["sub_gathered"]}, attrs={"axis": "dp"})
    block.append_op("cond_block",
                    inputs={"Cond": ["cflag"], "Captures": [h]},
                    outputs={"Out": []},
                    attrs={"true_block": sub.idx})
    diags = analysis.verify_program(dp)
    assert _codes(diags) == {"collective-divergent-control"}, diags
    hit = next(d for d in _errors(diags))
    assert "uniform_random" in hit.message and "deadlock" in hit.message


# ---------------------------------------------------------------------------
# buffer-reuse / WAR detector
# ---------------------------------------------------------------------------


def test_buffer_reuse_race_on_interfering_slot_mates():
    prog, h, logits, loss = _mlp_program()
    block = prog.global_block()
    g = dataflow.interference_graph(block)
    other = sorted(g[h.name])[0]
    block.vars[h.name].buffer_slot = 0
    block.vars[other].buffer_slot = 0
    diags = analysis.verify_program(prog)
    assert _codes(diags) == {"buffer-reuse-race"}, diags


def test_buffer_war_race_write_lands_on_last_read():
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="x", shape=[8], dtype="float32", is_data=True)
    blk.create_var(name="a", shape=[8], dtype="float32")
    blk.create_var(name="b", shape=[8], dtype="float32")
    blk.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["a"]})
    blk.append_op("tanh", inputs={"X": ["a"]}, outputs={"Out": ["b"]})
    blk.vars["a"].buffer_slot = "s0"
    blk.vars["b"].buffer_slot = "s0"   # b is written BY a's last reader
    diags = analysis.verify_program(prog)
    assert _codes(diags) == {"buffer-war-race"}, diags


def test_buffer_slot_on_disjoint_lifetimes_is_clean():
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="x", shape=[8], dtype="float32", is_data=True)
    for n in ("a", "b", "c"):
        blk.create_var(name=n, shape=[8], dtype="float32")
    blk.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["a"]})
    blk.append_op("tanh", inputs={"X": ["a"]}, outputs={"Out": ["b"]})
    blk.append_op("relu", inputs={"X": ["b"]}, outputs={"Out": ["c"]})
    blk.vars["a"].buffer_slot = 1
    blk.vars["c"].buffer_slot = 1      # a dead (last read op#1) before c
    assert not _errors(analysis.verify_program(prog))


def test_buffer_reuse_catches_non_adjacent_overlap():
    """A short-lived slot mate nested inside a long-lived one must be
    caught even when a third interval sorts between them (adjacent-only
    interval comparison missed this)."""
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="x", shape=[8], dtype="float32", is_data=True)
    for n in ("long", "t1", "mid", "t2", "sink"):
        blk.create_var(name=n, shape=[8], dtype="float32")
    blk.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["long"]})
    blk.append_op("tanh", inputs={"X": ["x"]}, outputs={"Out": ["t1"]})
    blk.append_op("relu", inputs={"X": ["t1"]}, outputs={"Out": ["mid"]})
    blk.append_op("tanh", inputs={"X": ["mid"]}, outputs={"Out": ["t2"]})
    blk.append_op("elementwise_add", inputs={"X": ["long"], "Y": ["t2"]},
                  outputs={"Out": ["sink"]}, attrs={"axis": -1})
    # long lives [0,4]; mid [2,3] nested inside it; t1 [1,2] sorts between
    for n in ("long", "t1", "mid"):
        blk.vars[n].buffer_slot = "s"
    diags = _errors(analysis.verify_program(prog))
    pairs = {d.message for d in diags if d.code == "buffer-reuse-race"}
    assert any("'mid'" in m and "'long'" in m for m in pairs), diags


def test_divergent_capture_without_divergent_condition_is_clean():
    """A shard-varying value CAPTURED into a branch body is sanctioned
    state flow; only a divergent CONDITION deadlocks. The binder check
    must read the Cond slot, not every input."""
    dp = _dp_program()
    block = dp.global_block()
    h = next(op for op in block.ops if op.type == "relu").outputs["Out"][0]
    # rng-divergent value captured, replicated constant as the condition
    block.create_var(name="noise", shape=[1], dtype="float32")
    block.append_op("uniform_random", outputs={"Out": ["noise"]},
                    attrs={"shape": [1]})
    block.create_var(name="flag", shape=[1], dtype="float32")
    block.append_op("fill_constant", outputs={"Out": ["flag"]},
                    attrs={"shape": [1], "value": 1.0, "dtype": "float32"})
    sub = dp._create_block(parent_idx=0)
    dp._rollback()
    sub.create_var(name="gathered", shape=[64, 32], dtype="float32")
    sub.append_op("dp_shard_all_gather", inputs={"X": [h]},
                  outputs={"Out": ["gathered"]}, attrs={"axis": "dp"})
    block.append_op("cond_block",
                    inputs={"Cond": ["flag"], "Captures": ["noise", h]},
                    outputs={"Out": []},
                    attrs={"true_block": sub.idx})
    assert not _errors(analysis.verify_program(dp))


def test_buffer_slot_on_persistable_reports():
    prog, h, logits, loss = _mlp_program()
    block = prog.global_block()
    param = next(n for n, v in block.vars.items() if v.persistable)
    block.vars[param].buffer_slot = 2
    block.vars[h.name].buffer_slot = 2
    assert "buffer-reuse-race" in _codes(analysis.verify_program(prog))


@register_op("_tdf_inplace_bump", stop_gradient=True)
def _tdf_inplace_bump(ctx, ins, attrs):
    return {"Out": [ins["X"][0] + 1.0]}


@register_effects("_tdf_inplace_bump")
def _tdf_inplace_bump_effects(op):
    # declares Out ALIASES X's buffer (a donation-style update)
    return {"inplace": ((op.inputs["X"][0], op.outputs["Out"][0]),)}


def test_inplace_alias_with_later_reader_is_a_war_race():
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="x", shape=[8], dtype="float32", is_data=True)
    for n in ("a", "a2", "late"):
        blk.create_var(name=n, shape=[8], dtype="float32")
    blk.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["a"]})
    blk.append_op("_tdf_inplace_bump", inputs={"X": ["a"]},
                  outputs={"Out": ["a2"]})
    blk.append_op("tanh", inputs={"X": ["a"]},       # reads the OLD buffer
                  outputs={"Out": ["late"]})
    assert _codes(analysis.verify_program(prog)) == {"buffer-war-race"}


# ---------------------------------------------------------------------------
# composed dp2 x pp2 x tp2 programs
# ---------------------------------------------------------------------------


def test_composed_3d_program_is_clean():
    pp = _composed_program()
    errs = _errors(analysis.verify_program(pp))
    assert not errs, "\n".join(str(d) for d in errs)


def test_composed_axis_mismatch_caught():
    pp = _composed_program()
    block = pp.global_block()
    ar = next(op for op in block.ops if op.type == "tp_allreduce")
    ar.attrs["axis"] = "pp"
    assert _codes(analysis.verify_program(pp)) == \
        {"collective-axis-mismatch"}


def test_composed_tp_partial_leak_caught():
    pp = _composed_program()
    block = pp.global_block()
    ar = next(op for op in block.ops if op.type == "tp_allreduce")
    ar.type = "tp_ident"
    assert _codes(analysis.verify_program(pp)) == {"replica-divergence"}


def test_composed_stage_reorder_caught():
    pp = _composed_program()
    block = pp.global_block()
    region = next(op for op in block.ops
                  if op.type == "pp_pipeline_region")
    ridx = next(i for i, op in enumerate(block.ops)
                if op.type == "pp_recv")
    stages = [list(s) for s in region.attrs["stages"]]
    stages[1].remove(ridx)
    stages[0].append(ridx)           # recv moved onto the producing stage
    region.attrs["stages"] = stages
    assert "collective-order" in _codes(analysis.verify_program(pp))


def test_composed_optimizer_bypass_caught_by_divergence_too():
    """Rewiring an optimizer back to a raw gradient on the composed mesh:
    dp-comm-bypass (r10) still fires, and the taint detector now names
    the divergence — both layers see the same hazard."""
    pp = _composed_program()
    block = pp.global_block()
    comm = next(op for op in block.ops if op.type == "dp_grad_comm")
    raw = comm.inputs["X"][0]
    consumer = next(op for op in block.ops
                    if raw + "@COMM" in op.input_names())
    for slot, names in consumer.inputs.items():
        consumer.inputs[slot] = [raw if n == raw + "@COMM" else n
                                 for n in names]
    codes = _codes(analysis.verify_program(pp))
    assert "dp-comm-bypass" in codes
    if consumer.attrs.get("op_role") == "optimize":
        assert "replica-divergence" in codes


# ---------------------------------------------------------------------------
# zero false positives: every builder x every admissible config
# ---------------------------------------------------------------------------

import test_static_analysis as _tsa  # noqa: E402  (pytest puts tests/ on sys.path)


@pytest.mark.parametrize("name", sorted(_tsa.MODEL_BUILDERS))
def test_detectors_zero_false_positives(name):
    """The acceptance sweep: every model builder, under every parallelism
    rewrite its gates admit (plain / dp2 / pp2 / tp2), produces zero
    error-severity diagnostics. Gate rejections are skips, not failures —
    a pass refusing a config is the documented contract."""
    loss = _tsa.MODEL_BUILDERS[name]()
    if loss is not None:
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = pt.default_main_program()
    configs = {"plain": lambda p: p}
    if loss is not None:
        configs["dp2"] = lambda p: comm_optimize_pass(p, 2, dict(_DP_CFG))
        configs["pp2"] = get_pass("pipeline_partition_pass", num_stages=2,
                                  num_microbatches=4, schedule="1f1b")
        if _sharding.has_tp_annotations(prog):
            configs["tp2"] = get_pass("tp_shard_pass", tp=2)
    for cname, apply in configs.items():
        try:
            rewritten = apply(prog)
        except (EnforceError, analysis.ProgramAnalysisError):
            continue                 # gate-rejected: config does not apply
        errs = _errors(analysis.verify_program(rewritten))
        assert not errs, (name, cname,
                          "\n".join(str(d) for d in errs))


# ---------------------------------------------------------------------------
# peak_live_bytes beyond block 0 (satellite)
# ---------------------------------------------------------------------------


def test_peak_live_bytes_counts_backward_activations():
    """Two activations whose forward lifetimes are disjoint BOTH feed the
    backward recompute — the whole-program walk must count them live
    together at the region."""
    x = layers.data("x", shape=[256])
    label = layers.data("label", shape=[1], dtype="int64")
    a = layers.fc(x, size=4096, act="relu")
    b = layers.fc(a, size=4096, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(b, size=10), label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    mem = analysis.peak_live_bytes(pt.default_main_program(),
                                   nominal_batch=8)
    floor = 2 * (8 * 4096 * 4)       # a AND b live at the region
    assert mem["peak_transient_bytes"] >= floor, mem


def test_peak_live_bytes_walks_sub_blocks():
    """A While body's transient peak is attributed at its binder op."""
    x = layers.data("x", shape=[64])
    i = layers.fill_constant([1], "int64", 0)
    n = layers.fill_constant([1], "int64", 4)
    cond = layers.less_than(i, n)
    acc = layers.fc(x, size=64)
    w = layers.While(cond)
    with w.block():
        big = layers.fc(acc, size=2048, act="relu")   # sub-block transient
        layers.fc(big, size=64)
        layers.increment(i, value=1.0, in_place=True)
        layers.less_than(i, n, cond=cond)
    prog = pt.default_main_program()
    mem = analysis.peak_live_bytes(prog, nominal_batch=8)
    assert mem["sub_block_peaks"], mem
    sub_peak = sum(mem["sub_block_peaks"].values())
    assert sub_peak >= 8 * 2048 * 4
    # and the binder carries it: the whole-program peak covers the body
    assert mem["peak_transient_bytes"] >= sub_peak


def test_peak_live_bytes_on_pipelined_program():
    pp = get_pass("pipeline_partition_pass", num_stages=2,
                  num_microbatches=4,
                  schedule="1f1b")(_mlp_program()[0])
    mem = analysis.peak_live_bytes(pp, nominal_batch=8)
    assert mem["peak_transient_bytes"] > 0
    assert "op#" in mem["peak_at"]


# ---------------------------------------------------------------------------
# lint CLI --json + exit-code contract (satellite)
# ---------------------------------------------------------------------------


def _run_lint(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_program.py"),
         *args], capture_output=True, text=True, env=env, cwd=REPO,
        timeout=300)


def test_lint_json_contract_and_exit_codes():
    # clean model: exit 0, one JSON list on stdout, documented row keys
    r = _run_lint("--model", "mnist", "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    rows = json.loads(r.stdout)
    assert len(rows) == 1 and rows[0]["model"] == "mnist"
    row = rows[0]
    for key in ("config", "gate_rejected", "errors", "warnings",
                "diagnostics", "ops", "memory", "peak_at"):
        assert key in row, key
    assert row["errors"] == 0 and row["gate_rejected"] is None

    # gate-rejected config: exit 2 without the sweep flag...
    r2 = _run_lint("--model", "mnist", "--tp", "2", "--json")
    assert r2.returncode == 2, r2.stdout + r2.stderr
    assert json.loads(r2.stdout)[0]["gate_rejected"]

    # ...and exit 0 (a skip) with it
    r3 = _run_lint("--model", "mnist", "--tp", "2", "--json",
                   "--allow_gate_rejects")
    assert r3.returncode == 0, r3.stdout + r3.stderr
