"""Server-mode predictor (VERDICT r3 missing #3): long-lived serve loop,
clone-per-thread, concurrent + pipelined requests.

≙ reference inference/api/api_impl.cc:126 (NativePaddlePredictor::Run as a
long-lived request loop) and :170 (::Clone per serving thread).
"""

import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.serving import PredictorClient, PredictorServer


def _export_model(tmp_path):
    img = layers.data(name="img", shape=[16])
    logits = layers.fc(img, size=4, act="softmax", name="srv_fc")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "srv_model")
    pt.io.save_inference_model(d, ["img"], [logits], executor=exe)
    return d, logits


class TestPredictorServer:
    def test_roundtrip_matches_direct(self, tmp_path, rng):
        d, logits = _export_model(tmp_path)
        p = pt.Predictor(d)
        x = rng.rand(8, 16).astype("float32")
        direct, = p.run({"img": x})

        with PredictorServer(p) as srv:
            host, port = srv.address
            with PredictorClient(host, port) as c:
                got, = c.infer({"img": x})
        np.testing.assert_allclose(got, direct, rtol=1e-6)

    def test_concurrent_connections(self, tmp_path, rng):
        """Many client threads, each its own connection (server clones the
        predictor per connection); every response matches the direct run
        for that thread's distinct input."""
        d, _ = _export_model(tmp_path)
        p = pt.Predictor(d)
        xs = [rng.rand(4, 16).astype("float32") for _ in range(6)]
        refs = [p.run({"img": x})[0] for x in xs]

        errors = []
        with PredictorServer(p) as srv:
            host, port = srv.address

            def worker(i):
                try:
                    with PredictorClient(host, port) as c:
                        for _ in range(3):  # context reuse across requests
                            out, = c.infer({"img": xs[i]})
                            np.testing.assert_allclose(out, refs[i],
                                                       rtol=1e-6)
                except Exception as e:
                    errors.append((i, e))

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(xs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        assert not errors, errors

    def test_pipelined_requests_in_order(self, tmp_path, rng):
        """K requests in flight on one connection come back in order."""
        d, _ = _export_model(tmp_path)
        p = pt.Predictor(d)
        xs = [np.full((2, 16), i, np.float32) for i in range(5)]
        refs = [p.run({"img": x})[0] for x in xs]
        with PredictorServer(p) as srv:
            host, port = srv.address
            with PredictorClient(host, port) as c:
                for x in xs:
                    c.send({"img": x})
                for ref in refs:
                    out, = c.recv()
                    np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_bad_request_keeps_connection_alive(self, tmp_path, rng):
        d, _ = _export_model(tmp_path)
        p = pt.Predictor(d)
        x = rng.rand(2, 16).astype("float32")
        with PredictorServer(p) as srv:
            host, port = srv.address
            with PredictorClient(host, port) as c:
                with pytest.raises(RuntimeError, match="server error"):
                    c.infer({"wrong_name": x})
                out, = c.infer({"img": x})   # connection still serves
                assert out.shape == (2, 4)

    def test_exported_predictor_served(self, tmp_path, rng):
        """The cold-load StableHLO predictor serves through the same
        server (stateless call — no clone needed)."""
        img = layers.data(name="img2", shape=[16])
        logits = layers.fc(img, size=3, name="srv2_fc")
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        d = str(tmp_path / "srv2")
        pt.io.save_inference_model(d, ["img2"], [logits], executor=exe,
                                   export=True)
        ep = pt.Predictor.from_exported(d)
        x = rng.rand(4, 16).astype("float32")
        ref, = ep.run({"img2": x})
        with PredictorServer(ep) as srv:
            host, port = srv.address
            with PredictorClient(host, port) as c:
                out, = c.infer({"img2": x})
        np.testing.assert_allclose(out, ref, rtol=1e-6)


class TestServerRobustness:
    def test_worker_death_with_full_queue_recovers(self, tmp_path, rng):
        """Regression: a client that pipelines far past the 128-request
        queue bound and dies without reading must not wedge the server —
        the worker's exit (response send fails) has to unblock a reader
        stuck in put(), the connection must clean up, and the server must
        keep serving new connections."""
        import socket
        import struct
        import time

        class Slow:
            fetch_names = ["out"]

            def run(self, feed, fetch_names=None, return_numpy=True):
                time.sleep(0.02)  # keep the worker behind the reader
                return [np.asarray(feed["x"]).sum(keepdims=True)]

            def clone(self):
                return self

        x = np.ones((4,), "float32")
        with PredictorServer(Slow()) as srv:
            host, port = srv.address
            before = threading.active_count()
            raw = socket.create_connection((host, port))
            header = (b'{"feeds": [{"name": "x", "dtype": "float32", '
                      b'"shape": [4]}]}')
            msg = struct.pack("<I", len(header)) + header + x.tobytes()
            sent = 0
            try:
                raw.settimeout(10)
                for _ in range(300):   # > queue bound + worker backlog
                    raw.sendall(msg)
                    sent += 1
            except (OSError, socket.timeout):
                pass                  # TCP backpressure is fine too
            raw.close()               # die without reading a single reply
            assert sent > 150, sent

            # the pair must unwind: reader unblocked, worker drained
            deadline = time.time() + 30
            while time.time() < deadline:
                if threading.active_count() <= before:
                    break
                time.sleep(0.2)
            assert threading.active_count() <= before, \
                "connection threads leaked after client death"

            # and the server still answers a fresh connection
            with PredictorClient(host, port) as c:
                out, = c.infer({"x": x})
                assert float(out[0]) == 4.0
