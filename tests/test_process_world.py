"""Simulated multi-process world + chief-commits checkpoint barrier.

parallel/process_world.py (ranks, channels, per-rank/per-phase fault
injection) and the multi-writer half of parallel/elastic.py: every rank
stages + fsyncs its own shard files and acks a digest manifest; the
chief binds them into ONE COMMIT record whose atomic rename is the only
commit point. The crash-anywhere property test SIGKILLs a real writer
process at every (rank × phase) — chief and non-chief, randomized byte
offsets inside the stage phase — and asserts every surviving snapshot is
either bitwise-restorable or cleanly rejected.
docs/fault_tolerance.md documents the protocol these tests pin.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu.core.enforce import EnforceError
from paddle_tpu.parallel import elastic
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.process_world import (PHASES, ProcessWorld,
                                               RankDead, world_fault_plan)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECOVERY_SMOKE = os.path.join(REPO, "tools", "recovery_smoke.py")
sys.path.insert(0, os.path.join(REPO, "tools"))


# ---------------------------------------------------------------------------
# fault-directive parsing
# ---------------------------------------------------------------------------

class TestWorldFaultPlan:
    def test_parse_all_directives(self, monkeypatch):
        monkeypatch.setenv(
            "PTPU_FAULT_INJECT",
            "crash_rank:1@stage@137, drop_rank:2@ack, "
            "straggle_rank:0@barrier@1.5, slow_writer:0.2")
        plan = world_fault_plan()
        assert plan["crash"] == {1: ("stage", 137.0)}
        assert plan["drop"] == {2: ("ack", None)}
        assert plan["straggle"] == {0: ("barrier", 1.5)}
        # the classic directives pass through to the elastic parser,
        # which in turn ignores the world-aware ones
        cfg = elastic.fault_injection_config()
        assert cfg == {"slow_writer": 0.2}

    def test_crash_without_offset(self):
        plan = world_fault_plan("crash_rank:0@commit")
        assert plan["crash"] == {0: ("commit", None)}

    def test_bad_phase_rejected(self):
        with pytest.raises(EnforceError):
            world_fault_plan("crash_rank:1@flush")
        with pytest.raises(EnforceError):
            world_fault_plan("straggle_rank:1@stage")  # missing seconds

    def test_phase_set_is_the_documented_matrix(self):
        assert PHASES == ("stage", "ack", "barrier", "commit", "post")


# ---------------------------------------------------------------------------
# world runtime: channels, threads, simulated death
# ---------------------------------------------------------------------------

class TestProcessWorld:
    def test_send_recv_and_timeout(self):
        w = ProcessWorld(3)
        w.send(1, 0, "ack", rank=1, serial=7)
        msg = w.recv(0, timeout=1)
        assert msg["kind"] == "ack" and msg["src"] == 1 \
            and msg["serial"] == 7
        assert w.recv(0, timeout=0.05) is None   # deadline, not raise

    def test_drain_discards_stale_messages(self):
        w = ProcessWorld(2)
        w.send(1, 0, "ack", serial=1)
        w.drain(0)
        assert w.recv(0, timeout=0.05) is None

    def test_dead_rank_messages_dropped(self):
        w = ProcessWorld(2)
        w.dead.add(1)
        w.send(1, 0, "ack")          # from the dead: dropped
        w.send(0, 1, "committed")    # to the dead: dropped
        assert w.recv(0, timeout=0.05) is None
        assert w.live_ranks() == [0]

    def test_run_collects_results_and_rank_death(self):
        w = ProcessWorld(3)

        def fn(r):
            if r == 1:
                raise RankDead(1, "stage")
            return r * 10
        out = w.run(fn)
        assert out == [0, None, 20]
        assert w.dead == {1}
        # a later round proceeds without the dead rank
        out = w.run(fn)
        assert out == [0, None, 20]

    def test_run_reraises_protocol_bugs(self):
        w = ProcessWorld(2)

        def fn(r):
            if r == 1:
                raise ValueError("protocol bug")
            return r
        with pytest.raises(ValueError, match="protocol bug"):
            w.run(fn)
        assert 1 in w.failures


# ---------------------------------------------------------------------------
# barrier protocol (in-process: per-phase units, abort paths)
# ---------------------------------------------------------------------------

def _mesh_state(dp=4, generation=0):
    """Program+scope holding one dp-sharded and one replicated array on
    a dp-device mesh, plus a mesh-only executor stand-in — the minimal
    input save_train_state(world=...) needs (mirrors the recovery
    smoke's --world-atomic-child)."""
    from recovery_smoke import world_atomic_arrays

    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework.scope import Scope
    mesh = DeviceMesh(jax.devices()[:dp], {"dp": dp})

    class _MeshOnly:
        pass

    exe = _MeshOnly()
    exe.mesh = mesh
    arrays = world_atomic_arrays(generation)
    prog, startup = Program(), Program()
    scope = Scope()
    with program_guard(prog, startup):
        for name, val in arrays.items():
            prog.global_block().create_var(
                name=name, shape=list(val.shape), dtype="float32",
                persistable=True)
            sharding = (mesh.batch_sharding(val.ndim)
                        if name.startswith("sharded")
                        else mesh.replicated())
            scope.set_var(name, jax.device_put(np.asarray(val), sharding))
    return prog, scope, exe, arrays


def _world_save(root, world, generation=0, deadline=10.0, **kw):
    prog, scope, exe, arrays = _mesh_state(world.world_size, generation)
    path = elastic.save_train_state(str(root), program=prog, scope=scope,
                                    executor=exe, step=generation,
                                    world=world,
                                    barrier_deadline_s=deadline, **kw)
    return path, arrays


class TestBarrierCommit:
    def test_every_rank_writes_one_commit_binds_all(self, tmp_path):
        world = ProcessWorld(4)
        path, arrays = _world_save(tmp_path, world)
        assert path is not None and elastic.is_committed(path)
        elastic.validate_snapshot(path)          # sizes AND digests
        marker = json.load(open(os.path.join(path,
                                             elastic.COMMIT_MARKER)))
        assert marker["manifests"] == 4
        assert marker["world"] == {"world_size": 4, "axes": {"dp": 4}}
        names = set(marker["files"])
        for r in range(4):
            assert f"shard-{r}.pts" in names
            assert f"manifest-{r}.json" in names
        assert elastic.META_FILE in names
        # no staging leftovers after a clean commit
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith(elastic.STAGING_PREFIX)]
        # the ensemble restores: every chunk round-trips bit-exact
        from paddle_tpu.sharded_checkpoint import ShardedCheckpoint
        ckpt = ShardedCheckpoint(path)
        for name, want in arrays.items():
            np.testing.assert_array_equal(ckpt.read(name), want)
        # the dp-sharded var really was a multi-writer artifact: its
        # chunks spread across MORE than one rank's shard container
        files = {c["file"] for c in ckpt.vars["sharded_w"]["chunks"]}
        assert len(files) == 4

    def test_async_barrier_commits_in_background(self, tmp_path):
        world = ProcessWorld(2)
        prog, scope, exe, arrays = _mesh_state(2)
        handle = elastic.save_train_state(
            str(tmp_path), program=prog, scope=scope, executor=exe,
            step=0, world=world, block=False, barrier_deadline_s=10)
        assert isinstance(handle, elastic.AsyncSnapshot)
        path = handle.result(timeout=60)
        assert path is not None
        elastic.validate_snapshot(path)

    def test_meta_records_world_size_and_placements(self, tmp_path):
        world = ProcessWorld(2)
        path, arrays = _world_save(tmp_path, world)
        meta = elastic.read_meta(path)
        assert meta["world_size"] == 2
        assert meta["placements"]["sharded_w"] == [["dp"], None]
        # a replicated PartitionSpec renders as the empty entry list
        assert meta["placements"]["repl_w"] == []


class TestBarrierAborts:
    def _aborts(self):
        return elastic.metrics_registry().get(
            "ptpu_ckpt_barrier_aborts_total").value

    def test_straggler_past_deadline_aborts_then_recovers(
            self, tmp_path, monkeypatch):
        """The deadline branch: one rank sleeps through the barrier, the
        chief aborts (counted), NO snapshot becomes visible, and the
        next attempt — fault cleared — commits through the same world,
        sweeping the straggler's stale staging."""
        world = ProcessWorld(2)
        monkeypatch.setenv("PTPU_FAULT_INJECT",
                           "straggle_rank:1@stage@2.0")
        a0 = self._aborts()
        path, _ = _world_save(tmp_path, world, deadline=0.3)
        assert path is None
        assert self._aborts() == a0 + 1
        assert elastic.latest_snapshot(str(tmp_path)) is None
        monkeypatch.delenv("PTPU_FAULT_INJECT")
        path, _ = _world_save(tmp_path, world, generation=1)
        assert path is not None
        elastic.validate_snapshot(path)
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith(elastic.STAGING_PREFIX)]

    def test_rank_staged_but_ack_unsent_aborts(self, tmp_path,
                                               monkeypatch):
        """The satellite edge case: a rank's staged files are fsynced
        but its manifest/ack never arrives (simulated death at `ack`) —
        the chief must abort, and because a dead rank can never stage
        its shard of a FUTURE snapshot either, every subsequent attempt
        in this world aborts too (gang restart is the recovery, the
        Supervisor's job)."""
        world = ProcessWorld(2)
        monkeypatch.setenv("PTPU_FAULT_INJECT", "drop_rank:1@ack")
        a0 = self._aborts()
        path, _ = _world_save(tmp_path, world, deadline=0.5)
        assert path is None
        assert self._aborts() == a0 + 1
        assert world.dead == {1}
        # the dead rank's staged-but-unbound files must not have become
        # part of any visible snapshot
        assert elastic.latest_snapshot(str(tmp_path)) is None
        monkeypatch.delenv("PTPU_FAULT_INJECT")
        path, _ = _world_save(tmp_path, world, generation=1,
                              deadline=0.5)
        assert path is None
        assert self._aborts() == a0 + 2

    def test_chief_dying_before_acks_aborts_promptly(self, tmp_path,
                                                     monkeypatch):
        """A chief dropped at its OWN stage phase (before collecting a
        single ack) must still broadcast the abort and count it — the
        other ranks return promptly instead of blocking out the full
        verdict window."""
        import time
        world = ProcessWorld(2)
        monkeypatch.setenv("PTPU_FAULT_INJECT", "drop_rank:0@stage")
        a0 = self._aborts()
        t0 = time.monotonic()
        path, _ = _world_save(tmp_path, world, deadline=30.0)
        assert path is None
        assert time.monotonic() - t0 < 10.0
        assert self._aborts() == a0 + 1
        assert world.dead == {0}
        assert elastic.latest_snapshot(str(tmp_path)) is None

    def test_dead_chief_aborts_immediately(self, tmp_path, monkeypatch):
        world = ProcessWorld(2)
        monkeypatch.setenv("PTPU_FAULT_INJECT", "drop_rank:0@barrier")
        a0 = self._aborts()
        path, _ = _world_save(tmp_path, world, deadline=0.5)
        assert path is None
        assert world.dead == {0}
        monkeypatch.delenv("PTPU_FAULT_INJECT")
        # chief dead: fail fast, not a deadline wait
        import time
        t0 = time.monotonic()
        path, _ = _world_save(tmp_path, world, generation=1,
                              deadline=30.0)
        assert path is None
        assert time.monotonic() - t0 < 5.0
        assert self._aborts() == a0 + 2


# ---------------------------------------------------------------------------
# crash-anywhere property (real SIGKILL, every rank x phase)
# ---------------------------------------------------------------------------

def _child_env(fault=None):
    env = dict(os.environ)
    env.pop("PTPU_FAULT_INJECT", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    if fault:
        env["PTPU_FAULT_INJECT"] = fault
    return env


def _run_world_child(root, fault=None, timeout=180):
    return subprocess.run(
        [sys.executable, RECOVERY_SMOKE, "--world-atomic-child",
         "--world", "4", "--root", str(root)]
        + (["--fault", fault] if fault else []),
        env=_child_env(), timeout=timeout).returncode


class TestCrashAnywhereProperty:
    """The acceptance bar: for each barrier phase × each rank (chief and
    non-chief) × randomized byte offsets, a REAL SIGKILL of the writer
    world leaves every surviving snapshot either bitwise-restorable or
    cleanly rejected — zero torn restores across the sweep. The child
    commits generation 0 through the barrier, then saves generation 1
    under the fault; because all simulated ranks share the process, the
    SIGKILL freezes the WHOLE world at that instant — a strictly richer
    set of torn states than a single-rank death."""

    def _check_surviving_state(self, root, committed_steps):
        from recovery_smoke import world_atomic_arrays

        from paddle_tpu.sharded_checkpoint import ShardedCheckpoint
        seen = set()
        for _, path in elastic.list_snapshots(str(root),
                                              committed_only=False):
            if not elastic.is_committed(path):
                with pytest.raises(EnforceError):
                    elastic.validate_snapshot(path)  # cleanly rejected
                continue
            elastic.validate_snapshot(path)          # incl. digests
            meta = elastic.read_meta(path)
            want = world_atomic_arrays(meta["step"])
            ckpt = ShardedCheckpoint(path)
            for name, val in want.items():
                np.testing.assert_array_equal(
                    ckpt.read(name), val,
                    err_msg=f"{path}:{name} torn restore")
            seen.add(meta["step"])
        assert seen == committed_steps, \
            f"committed generations {seen}, expected {committed_steps}"

    def test_killed_at_every_rank_and_phase(self, tmp_path):
        # learn per-rank payload sizes from an unfaulted run
        ref_root = tmp_path / "ref"
        assert _run_world_child(ref_root) == 0
        snaps = elastic.list_snapshots(str(ref_root))
        assert len(snaps) == 2
        marker = json.load(open(os.path.join(snaps[-1][1],
                                             elastic.COMMIT_MARKER)))
        rank_total = {}
        for name, entry in marker["files"].items():
            for r in range(4):
                if name.endswith(f"-{r}.pts") or \
                        name.endswith(f"-{r}.json"):
                    rank_total[r] = rank_total.get(r, 0) + entry["size"]
        rng = np.random.RandomState(20260804)

        def _off(r):
            return int(rng.randint(0, max(rank_total[r], 2)))

        matrix = [
            # non-chief ranks: mid-write at a random offset, whole-file
            # boundary, and staged-but-ack-unsent
            ("crash_rank:1@stage@0", {0}),
            (f"crash_rank:1@stage@{_off(1)}", {0}),
            (f"crash_rank:3@stage@{_off(3)}", {0}),
            ("crash_rank:2@ack", {0}),
            # the chief: same stage/ack states, plus its exclusive
            # phases — between last rank-ack and the rename (barrier),
            # between rename and COMMIT marker (commit), after commit
            (f"crash_rank:0@stage@{_off(0)}", {0}),
            ("crash_rank:0@ack", {0}),
            ("crash_rank:0@barrier", {0}),
            ("crash_rank:0@commit", {0}),
            ("crash_rank:0@post", {0, 1}),
        ]
        from paddle_tpu.observability import flight_recorder
        for fault, committed in matrix:
            root = tmp_path / fault.replace(":", "_").replace("@", "_")
            rc = _run_world_child(root, fault=fault)
            assert rc == -9, f"{fault}: child exited {rc}, expected " \
                             f"SIGKILL"
            self._check_surviving_state(root, committed)
            # r16 acceptance: every surviving world carries a dossier
            # trail whose post-mortem names EXACTLY the dead rank and
            # barrier phase of the injected fault — the beacons are
            # written before the SIGKILL fires, so kill -9 cannot
            # outrun them
            spec = fault.split(":", 1)[1].split("@")
            want_rank, want_phase = int(spec[0]), spec[1]
            verdict = flight_recorder.analyze(str(root / "dossiers"))
            assert verdict["cause"] == "crash_rank SIGKILL", \
                (fault, verdict)
            assert verdict["dead_rank"] == want_rank, (fault, verdict)
            assert verdict["dead_phase"] == want_phase, (fault, verdict)
            assert verdict["serial"] is not None
            # the timeline covers every rank that got to beacon at all
            assert str(want_rank) in verdict["timeline"]
        # kill between rename and COMMIT must leave the generation-1 dir
        # VISIBLE but uncommitted (the dichotomy's interesting corner)
        root = tmp_path / "crash_rank_0_commit"
        uncommitted = [p for _, p in elastic.list_snapshots(
            str(root), committed_only=False)
            if not elastic.is_committed(p)]
        assert uncommitted, "chief@commit: renamed dir should be " \
                            "visible and uncommitted"


class TestSupervisorPostMortem:
    def test_gang_death_writes_post_mortem_naming_rank_and_phase(
            self, tmp_path):
        """The Supervisor side of the flight recorder: a supervised
        world-atomic child is SIGKILLed mid-barrier; the supervisor
        hands its children the dossier dir through the env, and after
        the incarnation dies it folds the beacons into
        post_mortem-1.json naming the dead rank and phase."""
        from paddle_tpu.trainer import Supervisor
        dossiers = str(tmp_path / "dossiers")
        sup = Supervisor(
            [sys.executable, RECOVERY_SMOKE, "--world-atomic-child",
             "--world", "4", "--root", str(tmp_path / "root")],
            max_restarts=0, backoff_s=0.0,
            env=_child_env(fault="crash_rank:3@stage"),
            dossier_dir=dossiers)
        rc = sup.run()
        assert rc == -9 and sup.exhausted
        assert len(sup.post_mortems) == 1
        doc = json.load(open(sup.post_mortems[0]))
        assert doc["dead_rank"] == 3
        assert doc["dead_phase"] == "stage"
        assert doc["cause"] == "crash_rank SIGKILL"
        assert doc["incarnation"] == 1 and doc["exit_code"] == -9
        # straggler timeline: every rank beaconed at least its stage
        assert set(doc["timeline"]) >= {"3"}
        # beacons/dossiers are ARCHIVED per incarnation after the
        # verdict: the next incarnation's fold starts clean, so a stale
        # crash marker can never win a later post-mortem
        top = os.listdir(dossiers)
        assert not any(n.startswith("flight-") for n in top), top
        archived = os.listdir(os.path.join(dossiers, "incarnation-1"))
        assert any(n.startswith("flight-") for n in archived)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-x", "-q"]))
