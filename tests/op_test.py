"""Per-op test harness: forward vs numpy reference, analytic vs numeric grads.

≙ reference python/paddle/fluid/tests/unittests/op_test.py (OpTest base with
get_numeric_gradient :29-120, check_output_with_place, check_grad_with_place).
TPU translation: ops lower to jax functions, so the analytic gradient comes
from jax.grad of the lowering and is compared against central finite
differences; the forward is compared against a numpy reference impl.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework.registry import LowerCtx, lookup_op


def run_op(op_type: str, inputs: Dict[str, Any], attrs=None, is_test=False,
           seed=0):
    """Run a single op's lowering eagerly. inputs values may be np arrays or
    lists of np arrays (multi-input slots)."""
    opdef = lookup_op(op_type)
    ins = {k: [jnp.asarray(x) for x in (v if isinstance(v, list) else [v])]
           for k, v in inputs.items()}
    ctx = LowerCtx(rng_key=jax.random.PRNGKey(seed), is_test=is_test)
    outs = opdef.lower(ctx, ins, dict(attrs or {}))
    return {k: [np.asarray(x) for x in v] for k, v in outs.items()}


def check_output(op_type: str, inputs: Dict[str, Any],
                 expected: Dict[str, Any], attrs=None, atol=1e-5, rtol=1e-5,
                 is_test=False):
    """Forward check against numpy reference (≙ check_output_with_place)."""
    got = run_op(op_type, inputs, attrs, is_test=is_test)
    for slot, exp in expected.items():
        exp_list = exp if isinstance(exp, list) else [exp]
        assert slot in got, f"{op_type}: missing output slot {slot}"
        for i, e in enumerate(exp_list):
            np.testing.assert_allclose(
                got[slot][i], e, atol=atol, rtol=rtol,
                err_msg=f"{op_type} output {slot}[{i}] mismatch")
    return got


def _numeric_grad(f: Callable[[np.ndarray], float], x: np.ndarray,
                  eps: float) -> np.ndarray:
    """Central finite differences (≙ get_numeric_gradient, op_test.py:29)."""
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        hi = f(x)
        flat[i] = orig - eps
        lo = f(x)
        flat[i] = orig
        gf[i] = (hi - lo) / (2 * eps)
    return g


def check_grad(op_type: str, inputs: Dict[str, Any],
               grad_slots: Sequence[str], out_slot: str = "Out",
               attrs=None, eps=1e-3, atol=5e-3, rtol=5e-3, seed=0,
               reduce_fn=None):
    """Compare jax.grad of the lowering against numeric finite differences
    (≙ check_grad_with_place). grad_slots name the input slots to check."""
    opdef = lookup_op(op_type)
    attrs = dict(attrs or {})
    base = {k: [np.asarray(x, dtype=np.float64 if
                           np.issubdtype(np.asarray(x).dtype, np.floating)
                           else None) for x in
                (v if isinstance(v, list) else [v])]
            for k, v in inputs.items()}
    if reduce_fn is None:
        reduce_fn = lambda o: jnp.sum(o)  # noqa: E731

    for slot in grad_slots:
        for idx in range(len(base[slot])):

            def f_jax(x):
                ins = {k: [jnp.asarray(np.asarray(v, dtype=np.float32)
                                       if np.issubdtype(
                                           np.asarray(v).dtype, np.floating)
                                       else v) for v in vs]
                       for k, vs in base.items()}
                ins[slot] = list(ins[slot])
                ins[slot][idx] = x
                ctx = LowerCtx(rng_key=jax.random.PRNGKey(seed))
                out = opdef.lower(ctx, ins, attrs)[out_slot][0]
                return reduce_fn(out)

            x0 = jnp.asarray(np.asarray(base[slot][idx], dtype=np.float32))
            analytic = np.asarray(jax.grad(f_jax)(x0), dtype=np.float64)

            def f_np(x):
                return float(f_jax(jnp.asarray(x.astype(np.float32))))

            numeric = _numeric_grad(
                f_np, np.asarray(base[slot][idx], dtype=np.float64), eps)
            np.testing.assert_allclose(
                analytic, numeric, atol=atol, rtol=rtol,
                err_msg=f"{op_type} grad wrt {slot}[{idx}] mismatch")
