"""Per-op checks: NN family (matmul, conv, pool, norms, losses, optimizers).

≙ reference tests/unittests/test_{mul,conv2d,pool2d,batch_norm,layer_norm,
softmax,cross_entropy,sgd,adam,...}_op.py.
"""

import numpy as np
import pytest

from op_test import check_grad, check_output, run_op


class TestMatmul:
    def test_mul(self, rng):
        x = rng.rand(4, 6).astype(np.float32)
        y = rng.rand(6, 3).astype(np.float32)
        check_output("mul", {"X": x, "Y": y}, {"Out": x @ y}, rtol=1e-5)
        check_grad("mul", {"X": x, "Y": y}, ["X", "Y"])

    def test_mul_flatten(self, rng):
        x = rng.rand(2, 3, 4).astype(np.float32)
        y = rng.rand(12, 5).astype(np.float32)
        out = run_op("mul", {"X": x, "Y": y}, {"x_num_col_dims": 1})
        np.testing.assert_allclose(out["Out"][0],
                                   x.reshape(2, 12) @ y, rtol=1e-5)

    def test_matmul_transpose(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        y = rng.rand(5, 4).astype(np.float32)
        check_output("matmul", {"X": x, "Y": y}, {"Out": x @ y.T},
                     attrs={"transpose_Y": True}, rtol=1e-5)

    def test_matmul_batched(self, rng):
        x = rng.rand(2, 3, 4).astype(np.float32)
        y = rng.rand(2, 4, 5).astype(np.float32)
        check_output("matmul", {"X": x, "Y": y}, {"Out": x @ y}, rtol=1e-5)


class TestConvPool:
    def test_conv2d_forward(self, rng):
        x = rng.rand(2, 3, 8, 8).astype(np.float32)
        w = rng.rand(4, 3, 3, 3).astype(np.float32)
        out = run_op("conv2d", {"Input": x, "Filter": w},
                     {"strides": [1, 1], "paddings": [1, 1]})
        assert out["Output"][0].shape == (2, 4, 8, 8)
        # compare against naive correlation at one output position
        ref00 = (x[0, :, 0:3, 0:3] * w[0]).sum()
        np.testing.assert_allclose(out["Output"][0][0, 0, 1, 1], ref00,
                                   rtol=1e-4)

    def test_conv2d_grad(self, rng):
        x = rng.rand(1, 2, 5, 5).astype(np.float32)
        w = rng.rand(3, 2, 3, 3).astype(np.float32)
        check_grad("conv2d", {"Input": x, "Filter": w},
                   ["Input", "Filter"], out_slot="Output",
                   attrs={"strides": [1, 1], "paddings": [0, 0]})

    def test_depthwise(self, rng):
        x = rng.rand(1, 4, 6, 6).astype(np.float32)
        w = rng.rand(4, 1, 3, 3).astype(np.float32)
        out = run_op("depthwise_conv2d", {"Input": x, "Filter": w},
                     {"strides": [1, 1], "paddings": [1, 1]})
        assert out["Output"][0].shape == (1, 4, 6, 6)

    def test_pool2d(self, rng):
        x = rng.rand(2, 3, 6, 6).astype(np.float32)
        out = run_op("pool2d", {"X": x}, {"pooling_type": "max",
                                          "ksize": [2, 2], "strides": [2, 2],
                                          "paddings": [0, 0]})
        ref = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
        np.testing.assert_allclose(out["Out"][0], ref, rtol=1e-6)
        out = run_op("pool2d", {"X": x}, {"pooling_type": "avg",
                                          "ksize": [2, 2], "strides": [2, 2],
                                          "paddings": [0, 0]})
        ref = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
        np.testing.assert_allclose(out["Out"][0], ref, rtol=1e-5)

    def test_global_pool(self, rng):
        x = rng.rand(2, 3, 5, 5).astype(np.float32)
        out = run_op("pool2d", {"X": x}, {"pooling_type": "avg",
                                          "global_pooling": True,
                                          "ksize": [1, 1]})
        np.testing.assert_allclose(out["Out"][0][..., 0, 0],
                                   x.mean(axis=(2, 3)), rtol=1e-5)


class TestNorms:
    def test_batch_norm_train(self, rng):
        x = rng.rand(4, 3, 5, 5).astype(np.float32)
        scale = np.ones(3, np.float32)
        bias = np.zeros(3, np.float32)
        mean = np.zeros(3, np.float32)
        var = np.ones(3, np.float32)
        out = run_op("batch_norm",
                     {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
                      "Variance": var}, {"momentum": 0.9, "epsilon": 1e-5})
        y = out["Y"][0]
        np.testing.assert_allclose(y.mean(axis=(0, 2, 3)), np.zeros(3),
                                   atol=1e-5)
        np.testing.assert_allclose(y.std(axis=(0, 2, 3)), np.ones(3),
                                   atol=1e-3)
        # moving stats updated toward batch stats
        np.testing.assert_allclose(
            out["MeanOut"][0], 0.9 * mean + 0.1 * x.mean(axis=(0, 2, 3)),
            rtol=1e-4)

    def test_batch_norm_infer(self, rng):
        x = rng.rand(4, 3, 5, 5).astype(np.float32)
        mean = rng.rand(3).astype(np.float32)
        var = rng.rand(3).astype(np.float32) + 0.5
        out = run_op("batch_norm",
                     {"X": x, "Scale": np.ones(3, np.float32),
                      "Bias": np.zeros(3, np.float32), "Mean": mean,
                      "Variance": var},
                     {"epsilon": 1e-5, "is_test": True})
        ref = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(
            var.reshape(1, 3, 1, 1) + 1e-5)
        np.testing.assert_allclose(out["Y"][0], ref, rtol=1e-4)

    def test_layer_norm(self, rng):
        x = rng.rand(4, 10).astype(np.float32)
        scale = rng.rand(10).astype(np.float32)
        bias = rng.rand(10).astype(np.float32)
        out = run_op("layer_norm", {"X": x, "Scale": scale, "Bias": bias},
                     {"begin_norm_axis": 1, "epsilon": 1e-5})
        mu = x.mean(axis=1, keepdims=True)
        sd = x.std(axis=1, keepdims=True)
        ref = (x - mu) / np.sqrt(sd ** 2 + 1e-5) * scale + bias
        np.testing.assert_allclose(out["Y"][0], ref, rtol=1e-4)


class TestLosses:
    def test_softmax(self, rng):
        x = rng.rand(4, 7).astype(np.float32)
        e = np.exp(x - x.max(axis=1, keepdims=True))
        check_output("softmax", {"X": x}, {"Out": e / e.sum(1, keepdims=True)},
                     rtol=1e-5)
        check_grad("softmax", {"X": x}, ["X"],
                   reduce_fn=lambda o: (o * o).sum())

    def test_softmax_with_cross_entropy(self, rng):
        logits = rng.rand(4, 5).astype(np.float32)
        label = np.array([[0], [2], [4], [1]], dtype=np.int32)
        out = run_op("softmax_with_cross_entropy",
                     {"Logits": logits, "Label": label}, {})
        lse = np.log(np.exp(logits).sum(axis=1, keepdims=True))
        ref = lse - np.take_along_axis(logits, label, axis=1)
        np.testing.assert_allclose(out["Loss"][0], ref, rtol=1e-4)

    def test_softmax_ce_soft_label(self, rng):
        logits = rng.rand(3, 4).astype(np.float32)
        soft = rng.rand(3, 4).astype(np.float32)
        soft /= soft.sum(1, keepdims=True)
        out = run_op("softmax_with_cross_entropy",
                     {"Logits": logits, "Label": soft}, {"soft_label": True})
        logp = logits - np.log(np.exp(logits).sum(1, keepdims=True))
        ref = -(soft * logp).sum(1, keepdims=True)
        np.testing.assert_allclose(out["Loss"][0], ref, rtol=1e-4)

    def test_cross_entropy(self, rng):
        probs = rng.rand(4, 5).astype(np.float32) + 0.1
        probs /= probs.sum(1, keepdims=True)
        label = np.array([[1], [0], [3], [2]], dtype=np.int32)
        out = run_op("cross_entropy", {"X": probs, "Label": label}, {})
        ref = -np.log(np.take_along_axis(probs, label, axis=1))
        np.testing.assert_allclose(out["Y"][0], ref, rtol=1e-4)

    def test_sigmoid_ce_and_mse(self, rng):
        x = rng.randn(4, 3).astype(np.float32)
        lbl = (rng.rand(4, 3) > 0.5).astype(np.float32)
        out = run_op("sigmoid_cross_entropy_with_logits",
                     {"X": x, "Label": lbl}, {})
        sig = 1 / (1 + np.exp(-x))
        ref = -(lbl * np.log(sig) + (1 - lbl) * np.log(1 - sig))
        np.testing.assert_allclose(out["Out"][0], ref, rtol=1e-4, atol=1e-5)


class TestOptimizers:
    def test_sgd(self, rng):
        p = rng.rand(4, 3).astype(np.float32)
        g = rng.rand(4, 3).astype(np.float32)
        lr = np.array([0.1], dtype=np.float32)
        out = run_op("sgd", {"Param": p, "Grad": g, "LearningRate": lr}, {})
        np.testing.assert_allclose(out["ParamOut"][0], p - 0.1 * g, rtol=1e-6)

    def test_momentum(self, rng):
        p = rng.rand(3).astype(np.float32)
        g = rng.rand(3).astype(np.float32)
        v = rng.rand(3).astype(np.float32)
        lr = np.array([0.1], dtype=np.float32)
        out = run_op("momentum", {"Param": p, "Grad": g, "Velocity": v,
                                  "LearningRate": lr}, {"mu": 0.9})
        v_new = 0.9 * v + g
        np.testing.assert_allclose(out["VelocityOut"][0], v_new, rtol=1e-6)
        np.testing.assert_allclose(out["ParamOut"][0], p - 0.1 * v_new,
                                   rtol=1e-6)

    def test_adam(self, rng):
        n = 6
        p, g, m, v = (rng.rand(n).astype(np.float32) for _ in range(4))
        lr = np.array([0.01], dtype=np.float32)
        b1p = np.array([0.9], dtype=np.float32)
        b2p = np.array([0.999], dtype=np.float32)
        out = run_op("adam", {"Param": p, "Grad": g, "Moment1": m,
                              "Moment2": v, "Beta1Pow": b1p, "Beta2Pow": b2p,
                              "LearningRate": lr},
                     {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
        m_new = 0.9 * m + 0.1 * g
        v_new = 0.999 * v + 0.001 * g * g
        lr_t = 0.01 * np.sqrt(1 - b2p) / (1 - b1p)
        ref = p - lr_t * m_new / (np.sqrt(v_new) + 1e-8)
        np.testing.assert_allclose(out["ParamOut"][0], ref, rtol=1e-5)

    @pytest.mark.parametrize("op,extra", [
        ("adagrad", {"Moment": None}),
        ("rmsprop", {"MeanSquare": None, "Moment": None}),
    ])
    def test_accumulator_updates_finite(self, rng, op, extra):
        n = 5
        feed = {"Param": rng.rand(n).astype(np.float32),
                "Grad": rng.rand(n).astype(np.float32),
                "LearningRate": np.array([0.1], np.float32)}
        for k in extra:
            feed[k] = rng.rand(n).astype(np.float32)
        out = run_op(op, feed, {})
        assert np.all(np.isfinite(out["ParamOut"][0]))


class TestMetrics:
    def test_accuracy(self, rng):
        indices = np.array([[0], [1], [2], [2]], dtype=np.int64)
        label = np.array([[0], [1], [0], [2]], dtype=np.int64)
        out = run_op("accuracy", {"Out": indices.astype(np.float32),
                                  "Indices": indices, "Label": label}, {})
        np.testing.assert_allclose(out["Accuracy"][0], 0.75, rtol=1e-6)


class TestDropout:
    def test_dropout_train_test(self, rng):
        x = np.ones((100, 100), dtype=np.float32)
        out = run_op("dropout", {"X": x}, {"dropout_prob": 0.3})
        keep = (np.asarray(out["Out"][0]) != 0).mean()
        assert 0.6 < keep < 0.8
        out = run_op("dropout", {"X": x}, {"dropout_prob": 0.3},
                     is_test=True)
        np.testing.assert_allclose(out["Out"][0], x * 0.7, rtol=1e-6)
        out = run_op("dropout", {"X": x},
                     {"dropout_prob": 0.3,
                      "dropout_implementation": "upscale_in_train"},
                     is_test=True)
        np.testing.assert_allclose(out["Out"][0], x, rtol=1e-6)


class TestReviewRegressions:
    def test_conv2d_transpose_channels(self, rng):
        """num_filters != C_in (regression: kernel layout was swapped)."""
        x = rng.rand(1, 3, 5, 5).astype(np.float32)
        w = rng.rand(3, 4, 3, 3).astype(np.float32)  # (C_in, C_out, kh, kw)
        out = run_op("conv2d_transpose", {"Input": x, "Filter": w},
                     {"strides": [1, 1], "paddings": [0, 0]})
        assert out["Output"][0].shape == (1, 4, 7, 7)
        # cross-check against autograd: conv_transpose is the VJP of conv
        import jax
        import jax.numpy as jnp

        def fwd(inp):
            return jax.lax.conv_general_dilated(
                inp, jnp.asarray(w).transpose(1, 0, 2, 3)[:, :, ::-1, ::-1],
                (1, 1), [(2, 2), (2, 2)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"))

        ref = fwd(jnp.asarray(x))
        np.testing.assert_allclose(out["Output"][0], ref, rtol=1e-4,
                                   atol=1e-5)

    def test_softmax_ce_ignore_index(self, rng):
        logits = rng.rand(4, 5).astype(np.float32)
        label = np.array([[0], [-100], [2], [-100]], dtype=np.int32)
        out = run_op("softmax_with_cross_entropy",
                     {"Logits": logits, "Label": label}, {})
        loss = out["Loss"][0]
        assert loss[1, 0] == 0.0 and loss[3, 0] == 0.0
        assert loss[0, 0] > 0.0 and loss[2, 0] > 0.0

    def test_pool2d_ceil_mode(self, rng):
        # 8x8, k=3, s=2: floor -> 3, ceil -> 4 (span 5 not divisible by 2)
        x = rng.rand(1, 1, 8, 8).astype(np.float32)
        out = run_op("pool2d", {"X": x},
                     {"pooling_type": "max", "ksize": [3, 3],
                      "strides": [2, 2], "paddings": [0, 0],
                      "ceil_mode": True})
        assert out["Out"][0].shape == (1, 1, 4, 4)
        np.testing.assert_allclose(out["Out"][0][0, 0, 3, 3],
                                   x[0, 0, 6:8, 6:8].max(), rtol=1e-6)
        out = run_op("pool2d", {"X": x},
                     {"pooling_type": "max", "ksize": [3, 3],
                      "strides": [2, 2], "paddings": [0, 0]})
        assert out["Out"][0].shape == (1, 1, 3, 3)

    def test_lookup_table_negative_padding_idx(self, rng):
        w = rng.rand(10, 4).astype(np.float32)
        ids = np.array([[1], [9], [3]], dtype=np.int32)
        out = run_op("lookup_table", {"W": w, "Ids": ids},
                     {"padding_idx": -1})  # means row 9
        np.testing.assert_allclose(out["Out"][0][1], 0.0, atol=1e-7)
        np.testing.assert_allclose(out["Out"][0][0], w[1], rtol=1e-6)


class TestUnitCellsAndMisc:
    def test_row_conv_matches_numpy(self, rng):
        x = rng.rand(2, 6, 3).astype("float32")
        w = rng.rand(3, 3).astype("float32")  # lookahead 2
        out = run_op("row_conv", {"X": x, "Filter": w})["Out"][0]
        exp = np.zeros_like(x)
        for t in range(6):
            for i in range(3):
                if t + i < 6:
                    exp[:, t] += x[:, t + i] * w[i]
        np.testing.assert_allclose(out, exp, rtol=1e-5)
        check_grad("row_conv", {"X": x, "Filter": w},
                   grad_slots=["X", "Filter"], atol=5e-3, rtol=5e-3)

    def test_lstm_unit(self, rng):
        B, H = 3, 4
        x = rng.randn(B, 4 * H).astype("float32")
        c = rng.randn(B, H).astype("float32")
        out = run_op("lstm_unit", {"X": x, "C_prev": c},
                     attrs={"forget_bias": 1.0})
        # REFERENCE slot order (lstm_unit_op.h:63-66): i, f, o, g
        i, f, o, g = np.split(x, 4, axis=1)
        sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
        exp_c = c * sig(f + 1.0) + sig(i) * np.tanh(g)
        exp_h = np.tanh(exp_c) * sig(o)
        np.testing.assert_allclose(out["C"][0], exp_c, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(out["H"][0], exp_h, rtol=1e-5, atol=1e-5)

    def test_gru_unit_consistency(self, rng):
        B, H = 2, 3
        x = rng.randn(B, 3 * H).astype("float32")
        h0 = rng.randn(B, H).astype("float32")
        w = rng.randn(H, 3 * H).astype("float32") * 0.5
        out = run_op("gru_unit", {"Input": x, "HiddenPrev": h0,
                                  "Weight": w})
        sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
        u = sig(x[:, :H] + h0 @ w[:, :H])
        r = sig(x[:, H:2*H] + h0 @ w[:, H:2*H])
        c = np.tanh(x[:, 2*H:] + (r * h0) @ w[:, 2*H:])
        # REFERENCE update semantics (gru_unit_op.h:116): toward candidate
        exp = u * c + (1 - u) * h0
        np.testing.assert_allclose(out["Hidden"][0], exp, rtol=1e-4,
                                   atol=1e-4)
        assert out["Gate"][0].shape == (B, 3 * H)

    def test_spp_pyramid(self, rng):
        x = rng.rand(2, 3, 8, 8).astype("float32")
        out = run_op("spp", {"X": x},
                     attrs={"pyramid_height": 2,
                            "pooling_type": "max"})["Out"][0]
        assert out.shape == (2, 3 * (1 + 4))
        np.testing.assert_allclose(out[:, :3], x.max(axis=(2, 3)), rtol=1e-6)
        # level-1 first bin = top-left quadrant max
        np.testing.assert_allclose(out[:, 3:6],
                                   x[:, :, :4, :4].max(axis=(2, 3)),
                                   rtol=1e-6)


class TestConv1x1MixedVjp:
    """The mixed-emitter 1x1 conv backward (dgrad as dot_general, wgrad on
    the conv emitter — ops/nn_ops.py _conv1x1_mixed, PROBE_DGRAD_r05) must
    be numerically invisible: training with the flag on and off produces
    identical trajectories."""

    def _train(self, flag, rng):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import flags as _flags

        pt.reset_default_programs()
        pt.reset_global_scope()
        old = _flags.get_flag("conv1x1_mixed_vjp")
        _flags._REGISTRY["conv1x1_mixed_vjp"].value = flag
        try:
            with pt.core.unique_name.guard():
                img = layers.data("img", shape=[8, 8, 16])
                y = layers.conv2d(img, num_filters=32, filter_size=1,
                                  data_format="NHWC", name="cm1")
                y = layers.conv2d(y, num_filters=16, filter_size=3,
                                  padding=1, data_format="NHWC", name="cm2")
                loss = layers.reduce_mean(layers.square(y))
                pt.optimizer.MomentumOptimizer(
                    learning_rate=0.1, momentum=0.9).minimize(loss)
            exe = pt.Executor()
            exe.run(pt.default_startup_program())
            feed = {"img": rng.rand(4, 8, 8, 16).astype("float32")}
            losses = [float(exe.run(feed=feed, fetch_list=[loss])[0])
                      for _ in range(4)]
            w = np.asarray(pt.global_scope().get("cm1.w_0")).copy()
            return losses, w
        finally:
            _flags._REGISTRY["conv1x1_mixed_vjp"].value = old

    def test_training_trajectory_identical(self):
        l1, w1 = self._train(True, np.random.RandomState(0))
        l2, w2 = self._train(False, np.random.RandomState(0))
        np.testing.assert_allclose(l1, l2, rtol=1e-6)
        np.testing.assert_allclose(w1, w2, rtol=1e-5, atol=1e-7)
        assert l1[-1] < l1[0]  # and it actually trains
