"""Uneven (non-dp-divisible) batch handling in ParallelExecutor.

≙ reference details/data_balance_op_handle.cc: the reference redistributes
uneven reader batches across devices so the last partial batch of an epoch
can run. The TPU translation pads the batch to the next dp multiple
(wrapping real rows) and zeroes those rows in the reserved batch-row mask
(layers.batch_row_mask), so a mask-weighted loss counts real rows exactly.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.framework.program import BATCH_ROW_MASK_NAME
from paddle_tpu.parallel import ParallelExecutor


def _build_masked_net():
    """Per-example CE weighted by the batch-row mask: padded rows contribute
    exactly nothing to loss or gradient."""
    img = layers.data(name="img", shape=[16], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    mask = layers.batch_row_mask()
    h = layers.fc(img, size=32, act="relu")
    logits = layers.fc(h, size=10)
    per_ex = layers.softmax_with_cross_entropy(logits, label)  # [B, 1]
    m = layers.reshape(mask, shape=[-1, 1])
    loss = layers.reduce_sum(per_ex * m) / layers.reduce_sum(m)
    return loss, logits


def _startup():
    pt.Executor().run(pt.default_startup_program())


class TestUnevenBatch:
    def test_partial_batch_loss_matches_single_device(self, rng):
        """PE loss on a padded 5-row batch == plain Executor loss on the
        same 5 rows (the mask must cancel the 3 wrapped pad rows)."""
        loss, _ = _build_masked_net()
        _startup()
        x = rng.rand(5, 16).astype("float32")
        y = rng.randint(0, 10, (5, 1)).astype("int64")

        exe = pt.Executor()
        ref, = exe.run(feed={"img": x, "label": y}, fetch_list=[loss])

        pe = ParallelExecutor(loss_name=loss.name)
        assert pe.device_count == 8
        got, = pe.run(fetch_list=[loss], feed={"img": x, "label": y})
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_partial_batch_per_row_fetch_sliced(self, rng):
        """Per-row fetches come back with the REAL batch size, pad rows
        stripped."""
        loss, logits = _build_masked_net()
        _startup()
        x = rng.rand(5, 16).astype("float32")
        y = rng.randint(0, 10, (5, 1)).astype("int64")
        pe = ParallelExecutor(loss_name=loss.name)
        lg, = pe.run(fetch_list=[logits], feed={"img": x, "label": y})
        assert np.asarray(lg).shape == (5, 10)

    def test_epoch_with_partial_last_batch_trains(self, rng):
        """A full epoch whose last batch is partial runs end-to-end and the
        gradient of the partial batch matches the unpadded single-device
        gradient (loss parity after the update step)."""
        loss, _ = _build_masked_net()
        opt = pt.optimizer.SGDOptimizer(learning_rate=1e-1)
        opt.minimize(loss)
        _startup()

        n, bs = 21, 8  # batches of 8, 8, 5
        xs = rng.rand(n, 16).astype("float32")
        ys = rng.randint(0, 10, (n, 1)).astype("int64")
        batches = [(xs[i:i + bs], ys[i:i + bs]) for i in range(0, n, bs)]
        assert batches[-1][0].shape[0] == 5

        # single-device reference epoch
        ref_losses = []
        exe = pt.Executor()
        for x, y in batches:
            out, = exe.run(feed={"img": x, "label": y}, fetch_list=[loss])
            ref_losses.append(float(np.asarray(out).ravel()[0]))

        # fresh params, same epoch through PE with dp=8
        pt.reset_global_scope()
        _startup()
        pe = ParallelExecutor(loss_name=loss.name)
        pe_losses = []
        for x, y in batches:
            out, = pe.run(fetch_list=[loss], feed={"img": x, "label": y})
            pe_losses.append(float(np.asarray(out).ravel()[0]))

        np.testing.assert_allclose(pe_losses, ref_losses, rtol=1e-4,
                                   atol=1e-5)

    def test_mask_autofeed_all_ones_on_plain_executor(self, rng):
        """Plain Executor synthesizes an all-ones mask: masked loss equals
        the unmasked mean."""
        img = layers.data(name="img", shape=[4], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        mask = layers.batch_row_mask()
        logits = layers.fc(img, size=3)
        per_ex = layers.softmax_with_cross_entropy(logits, label)
        m = layers.reshape(mask, shape=[-1, 1])
        wloss = layers.reduce_sum(per_ex * m) / layers.reduce_sum(m)
        uloss = layers.mean(per_ex)
        _startup()
        x = rng.rand(6, 4).astype("float32")
        y = rng.randint(0, 3, (6, 1)).astype("int64")
        exe = pt.Executor()
        a, b = exe.run(feed={"img": x, "label": y},
                       fetch_list=[wloss, uloss])
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_divisible_batch_untouched(self, rng):
        """dp-divisible feeds bypass padding entirely (no mask needed in
        the program either)."""
        img = layers.data(name="img", shape=[4], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        logits = layers.fc(img, size=3)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        _startup()
        pe = ParallelExecutor(loss_name=loss.name)
        x = rng.rand(16, 4).astype("float32")
        y = rng.randint(0, 3, (16, 1)).astype("int64")
        out, = pe.run(fetch_list=[loss], feed={"img": x, "label": y})
        assert np.isfinite(np.asarray(out)).all()

    def test_uneven_without_mask_raises_with_guidance(self, rng):
        """A program with a plain mean loss (no batch_row_mask) must NOT be
        silently padded — wrapped rows would bias the mean. It raises and
        names the fix."""
        img = layers.data(name="img", shape=[4], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        logits = layers.fc(img, size=3)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        _startup()
        pe = ParallelExecutor(loss_name=loss.name)
        x = rng.rand(5, 4).astype("float32")
        y = rng.randint(0, 3, (5, 1)).astype("int64")
        with pytest.raises(InvalidArgumentError, match="batch_row_mask"):
            pe.run(fetch_list=[loss], feed={"img": x, "label": y})

    def test_caller_fed_mask_respected_when_padding(self, rng):
        """A caller-fed per-row weight mask keeps its real-row weights when
        the batch is padded; only the pad rows are zeroed."""
        loss, _ = _build_masked_net()
        _startup()
        x = rng.rand(5, 16).astype("float32")
        y = rng.randint(0, 10, (5, 1)).astype("int64")
        w = np.array([1.0, 1.0, 0.0, 1.0, 1.0], np.float32)  # drop row 2

        exe = pt.Executor()
        ref, = exe.run(feed={"img": x, "label": y,
                             BATCH_ROW_MASK_NAME: w}, fetch_list=[loss])

        pe = ParallelExecutor(loss_name=loss.name)
        got, = pe.run(fetch_list=[loss],
                      feed={"img": x, "label": y, BATCH_ROW_MASK_NAME: w})
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_concrete_dim_fetch_not_sliced(self, rng):
        """A fetch whose concrete leading dim coincides with the padded
        size (a [16, k] parameter when 5 pads to 16... here 8) must come
        back whole — only declared batch-led ([-1,...]) fetches are
        sliced."""
        img = layers.data(name="img", shape=[16], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        layers.batch_row_mask()
        h = layers.fc(img, size=8, act="relu",
                      param_attr=pt.ParamAttr(name="fc_w16"))
        logits = layers.fc(h, size=10)
        per_ex = layers.softmax_with_cross_entropy(logits, label)
        m = layers.reshape(layers.batch_row_mask(), shape=[-1, 1])
        loss = layers.reduce_sum(per_ex * m) / layers.reduce_sum(m)
        _startup()
        pe = ParallelExecutor(loss_name=loss.name)
        x = rng.rand(5, 16).astype("float32")
        y = rng.randint(0, 10, (5, 1)).astype("int64")
        out = pe.run(fetch_list=[loss, "fc_w16"],
                     feed={"img": x, "label": y})
        # padded batch is 8; fc_w16 is [16, 8] — leading dim 16 != -1, so
        # it must come back [16, 8] even though 16 == 2*padded etc.
        assert np.asarray(out[1]).shape == (16, 8)

    def test_run_steps_pads_and_strips_stacked_fetches(self, rng):
        """run_steps pads each step's feed and strips pad rows from stacked
        per-row fetches ([K, batch, ...] -> [K, real, ...])."""
        loss, logits = _build_masked_net()
        opt = pt.optimizer.SGDOptimizer(learning_rate=1e-2)
        opt.minimize(loss)
        _startup()
        pe = ParallelExecutor(loss_name=loss.name)
        feeds = []
        for _ in range(3):
            feeds.append({"img": rng.rand(5, 16).astype("float32"),
                          "label": rng.randint(0, 10,
                                               (5, 1)).astype("int64")})
        out = pe.run_steps(feeds, fetch_list=[loss, logits])
        assert np.asarray(out[0]).shape == (3,)
        assert np.asarray(out[1]).shape == (3, 5, 10)

    def test_mismatched_batch_dims_still_raise(self, rng):
        img = layers.data(name="img", shape=[4], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        logits = layers.fc(img, size=3)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        _startup()
        pe = ParallelExecutor(loss_name=loss.name)
        x = rng.rand(5, 4).astype("float32")
        y = rng.randint(0, 3, (7, 1)).astype("int64")
        with pytest.raises(InvalidArgumentError):
            pe.run(fetch_list=[loss], feed={"img": x, "label": y})
