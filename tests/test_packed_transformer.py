"""Packed-batch transformer path: pack_sequences + segment-masked flash
attention + in-graph loss masking (VERDICT r2 #3: route the packed path
through the kernel).

Ground truth for the whole pipeline: per-token losses of sequences trained
PACKED (several per row, segment ids) must equal the same sequences trained
PADDED (one per row) — if any cross-segment attention or mis-masked loss
leaked in, these diverge immediately.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.data.packing import pack_lm_batch, pack_sequences


class TestPackSequences:
    def test_first_fit_packs_tightly(self):
        seqs = [np.arange(1, 9), np.arange(1, 5), np.arange(1, 4)]
        tokens, segments, _ = pack_sequences(seqs, max_len=16)
        assert tokens.shape == (1, 16)          # 8+4+3 = 15 <= 16: one row
        assert segments.max() == 3
        np.testing.assert_array_equal(segments[0, :8], 1)
        np.testing.assert_array_equal(segments[0, 8:12], 2)
        np.testing.assert_array_equal(segments[0, 12:15], 3)
        np.testing.assert_array_equal(segments[0, 15:], 0)

    def test_overflow_opens_new_row(self):
        seqs = [np.ones(10, np.int64), np.ones(10, np.int64)]
        tokens, segments, _ = pack_sequences(seqs, max_len=16)
        assert tokens.shape == (2, 16)
        assert segments[0].max() == 1 and segments[1].max() == 1

    def test_truncation(self):
        tokens, segments, _ = pack_sequences([np.arange(100)], max_len=8)
        assert tokens.shape == (1, 8)
        np.testing.assert_array_equal(tokens[0], np.arange(8))

    def test_lm_batch_targets_shifted(self):
        seqs = [np.array([5, 6, 7, 8], np.int64)]
        b = pack_lm_batch(seqs, max_len=8)
        np.testing.assert_array_equal(b["targets"][0, :3], [6, 7, 8])


class TestPackedTransformerLM:
    def _run_losses(self, feed, packed, vocab=31, max_len=24, steps=1):
        from paddle_tpu.models import transformer
        pt.reset_default_programs()
        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            loss, logits = transformer.transformer_lm(
                vocab=vocab, max_len=max_len, d_model=16, num_heads=2,
                num_layers=1, d_inner=32, dropout=0.0, packed=packed)
            exe = pt.Executor()
            exe.run(pt.default_startup_program())
            return float(exe.run(feed=feed, fetch_list=[loss])[0])

    def test_packed_loss_equals_padded_loss(self, rng):
        """Same sequences, same (seeded) init: mean per-token loss packed
        == mean per-token loss padded."""
        max_len = 24
        seqs = [rng.randint(1, 30, (L,)).astype(np.int64)
                for L in (10, 7, 6, 14, 9)]
        packed_feed = pack_lm_batch(seqs, max_len)

        # padded variant: one sequence per row
        B = len(seqs)
        toks = np.zeros((B, max_len), np.int64)
        tgts = np.zeros((B, max_len), np.int64)
        sl = np.zeros((B,), np.int32)
        for i, s in enumerate(seqs):
            toks[i, :len(s)] = s
            tgts[i, :len(s) - 1] = s[1:]
            # loss mask counts the first len-1 positions (next-token)
            sl[i] = len(s) - 1
        padded_feed = {"tokens": toks, "tokens@SEQLEN": sl,
                       "targets": tgts}

        # identical init: both builds create the same parameter set in the
        # same order from fresh (seed-0) programs, so the startup program
        # produces bit-identical weights
        l_packed = self._run_losses(packed_feed, packed=True,
                                    max_len=max_len)
        l_padded = self._run_losses(padded_feed, packed=False,
                                    max_len=max_len)
        np.testing.assert_allclose(l_packed, l_padded, rtol=1e-4)

    def test_packed_lm_trains(self, rng):
        from paddle_tpu.models import transformer
        max_len = 32
        loss, _ = transformer.transformer_lm(
            vocab=50, max_len=max_len, d_model=16, num_heads=2,
            num_layers=1, d_inner=32, dropout=0.0, packed=True)
        types = [op.type
                 for op in pt.default_main_program().global_block().ops]
        assert "fused_attention" in types
        pt.optimizer.AdamOptimizer(learning_rate=2e-3).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        seqs = [rng.randint(1, 50, (L,)).astype(np.int64)
                for L in (12, 9, 17, 6, 20, 8)]
        feed = pack_lm_batch(seqs, max_len)
        l0 = exe.run(feed=feed, fetch_list=[loss])[0]
        for _ in range(8):
            l1 = exe.run(feed=feed, fetch_list=[loss])[0]
        assert np.isfinite(l1).all() and l1 < l0

    def test_packed_rejects_attention_dropout(self):
        from paddle_tpu.models import transformer
        with pytest.raises(NotImplementedError):
            transformer.multi_head_attention(
                pt.layers.data(name="x", shape=[8, 16]),
                pt.layers.data(name="x", shape=[8, 16]),
                pt.layers.data(name="x", shape=[8, 16]),
                d_model=16, num_heads=2, dropout=0.5, causal=True,
                segment_ids=pt.layers.data(name="s", shape=[8],
                                           dtype="int32"))

    def test_packed_generate_skips_attention_downscale(self, rng):
        """A packed-trained LM applied NO attention-weight dropout
        (`0.0 if packed else dropout`), so its decode graph must not apply
        the (1-p) attention-context inference downscale either: generate
        with packed=True mirrors the train graph; packed=False (which
        downscales) must produce different scores on the same weights."""
        from paddle_tpu.core import unique_name
        from paddle_tpu.models import transformer

        V, D, T = 50, 16, 16
        loss, _ = transformer.transformer_lm(
            vocab=V, max_len=T, d_model=D, d_inner=32, num_heads=2,
            num_layers=1, dropout=0.3, packed=True)
        pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        seqs = [rng.randint(1, V, (L,)).astype(np.int64)
                for L in (10, 7, 12, 5)]
        feed = pack_lm_batch(seqs, T)
        for _ in range(3):
            exe.run(feed=feed, fetch_list=[loss])

        def decode(packed):
            prog = pt.Program()
            with pt.framework.program.program_guard(prog, pt.Program()), \
                    unique_name.guard():
                s, sc = transformer.transformer_lm_generate(
                    vocab=V, max_gen=6, d_model=D, d_inner=32,
                    num_heads=2, num_layers=1, dropout=0.3,
                    packed=packed)
                return exe.run(
                    program=prog,
                    feed={"prompt": np.array([[3], [9]], "int64")},
                    fetch_list=[s, sc])

        seq_p, score_p = decode(packed=True)
        seq_u, score_u = decode(packed=False)
        assert seq_p.shape == (2, 6, 1)
        assert np.isfinite(score_p).all() and np.isfinite(score_u).all()
        # the downscale shifts every attention context by (1-0.3); on the
        # same weights the two decode graphs cannot emit equal log-probs
        assert not np.allclose(score_p, score_u), (score_p, score_u)


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-x", "-q"]))
