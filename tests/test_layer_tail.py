"""Layer-level tests for the round-2 tail: 3-D conv family, RPN building
blocks, in-graph detection_map, dice_loss, image_resize, dynamic_lstmp,
sequence_reshape, positive_negative_pair.

≙ reference layers/detection.py (rpn_target_assign, generate_proposals,
detection_map), layers/nn.py (conv3d family, dice_loss, image_resize,
dynamic_lstmp, sequence_reshape), positive_negative_pair_op.cc.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.layers import detection


def test_conv3d_pool3d_train_step(rng):
    """A tiny 3-D conv net trains end to end (conv3d -> pool3d -> fc)."""
    vol = layers.data("vol", shape=[2, 6, 6, 6], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    c = layers.conv3d(vol, num_filters=3, filter_size=3, padding=1,
                      act="relu")
    p = layers.pool3d(c, pool_size=2, pool_stride=2, pool_type="avg")
    logits = layers.fc(p, size=4)
    loss = layers.reduce_mean(
        layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"vol": rng.rand(2, 2, 6, 6, 6).astype("float32"),
            "label": rng.randint(0, 4, (2, 1)).astype("int64")}
    l0 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    for _ in range(5):
        l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 < l0


def test_conv3d_transpose_upsamples(rng):
    x = layers.data("x", shape=[2, 3, 3, 3], dtype="float32")
    up = layers.conv3d_transpose(x, num_filters=4, filter_size=2, stride=2)
    assert list(up.shape) == [-1, 4, 6, 6, 6]
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    out, = exe.run(feed={"x": rng.rand(1, 2, 3, 3, 3).astype("float32")},
                   fetch_list=[up])
    assert out.shape == (1, 4, 6, 6, 6)


def test_dice_loss_perfect_prediction_near_zero(rng):
    pred = layers.data("pred", shape=[4], dtype="float32")
    lab = layers.data("lab", shape=[1], dtype="int64")
    loss = layers.dice_loss(pred, lab)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    labels = rng.randint(0, 4, (6, 1)).astype("int64")
    onehot = np.eye(4, dtype="float32")[labels.reshape(-1)]
    perfect, = exe.run(feed={"pred": onehot, "lab": labels},
                       fetch_list=[loss])
    assert float(perfect) < 1e-3
    uniform, = exe.run(feed={"pred": np.full((6, 4), 0.25, "float32"),
                             "lab": labels}, fetch_list=[loss])
    assert float(uniform) > 0.5


def test_image_resize_and_short(rng):
    img = layers.data("img", shape=[3, 8, 6], dtype="float32")
    up = layers.image_resize(img, out_shape=[16, 12])
    short = layers.image_resize_short(img, out_short_len=12)
    assert list(up.shape) == [-1, 3, 16, 12]
    assert list(short.shape) == [-1, 3, 16, 12]  # short side 6 -> 12
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x = rng.rand(2, 3, 8, 6).astype("float32")
    a, b = exe.run(feed={"img": x}, fetch_list=[up, short])
    assert a.shape == (2, 3, 16, 12) and b.shape == (2, 3, 16, 12)
    # constant image stays constant under bilinear resize
    const, = exe.run(feed={"img": np.ones((1, 3, 8, 6), "float32")},
                     fetch_list=[up])
    np.testing.assert_allclose(const, 1.0, rtol=1e-6)


def test_dynamic_lstmp_shapes_and_masking(rng):
    x = layers.data("x", shape=[5, 6], dtype="float32", lod_level=1)
    proj = layers.fc(x, size=16, num_flatten_dims=2, bias_attr=False)
    proj = layers.sequence.tag_sequence(proj, layers.sequence.get_seqlen(x))
    r, c = layers.sequence.dynamic_lstmp(proj, size=16, proj_size=3)
    assert list(r.shape) == [-1, 5, 3]
    assert list(c.shape) == [-1, 5, 4]
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"x": rng.rand(2, 5, 6).astype("float32"),
            "x@SEQLEN": np.array([5, 3], "int32")}
    rv, cv = exe.run(feed=feed, fetch_list=[r, c])
    assert rv.shape == (2, 5, 3) and cv.shape == (2, 5, 4)
    # finished timesteps freeze the projected state (masked scan)
    np.testing.assert_allclose(rv[1, 3], rv[1, 2], rtol=1e-6)
    np.testing.assert_allclose(rv[1, 4], rv[1, 2], rtol=1e-6)


def test_dynamic_lstmp_peephole_numerics(rng):
    """Ground truth for the peephole connections (ADVICE r2): run the op
    with a 7H bias and compare against a hand-rolled numpy recurrence with
    w_ic/w_fc on c_{t-1} and w_oc on c_t (≙ reference lstmp_op.h)."""
    from op_test import run_op

    B, T, H, P = 2, 3, 4, 3
    x = (rng.rand(B, T, 4 * H) - 0.5).astype("float32")
    w = ((rng.rand(P, 4 * H) - 0.5) * 0.5).astype("float32")
    w_proj = ((rng.rand(H, P) - 0.5) * 0.5).astype("float32")
    bias = ((rng.rand(7 * H) - 0.5) * 0.5).astype("float32")
    seqlen = np.array([T, T], "int32")

    out = run_op("dynamic_lstmp",
                 {"Input": x, "Weight": w, "ProjWeight": w_proj,
                  "Bias": bias, "SeqLen": seqlen},
                 attrs={"use_peepholes": True})
    got_r = np.asarray(out["Projection"][0])
    got_c = np.asarray(out["Cell"][0])

    def sigmoid(v):
        return 1.0 / (1.0 + np.exp(-v))

    b4, w_ic, w_fc, w_oc = (bias[:4 * H], bias[4 * H:5 * H],
                            bias[5 * H:6 * H], bias[6 * H:])
    r_prev = np.zeros((B, P), "float32")
    c_prev = np.zeros((B, H), "float32")
    ref_r = np.zeros((B, T, P), "float32")
    ref_c = np.zeros((B, T, H), "float32")
    for t in range(T):
        gates = x[:, t] + b4 + r_prev @ w
        i, f, ch, o = np.split(gates, 4, axis=-1)
        i = sigmoid(i + w_ic * c_prev)
        f = sigmoid(f + w_fc * c_prev)
        c_new = f * c_prev + i * np.tanh(ch)
        o = sigmoid(o + w_oc * c_new)
        r_prev = (o * np.tanh(c_new)) @ w_proj
        c_prev = c_new
        ref_r[:, t] = r_prev
        ref_c[:, t] = c_new
    np.testing.assert_allclose(got_r, ref_r, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(got_c, ref_c, atol=1e-5, rtol=1e-5)


def test_sequence_reshape_roundtrip(rng):
    x = layers.data("x", shape=[4, 6], dtype="float32", lod_level=1)
    out = layers.sequence.sequence_reshape(x, new_dim=3)
    assert list(out.shape) == [-1, 8, 3]
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = rng.rand(2, 4, 6).astype("float32")
    ov, = exe.run(feed={"x": xv, "x@SEQLEN": np.array([4, 2], "int32")},
                  fetch_list=[out])
    np.testing.assert_allclose(ov, xv.reshape(2, 8, 3), rtol=1e-6)


def test_rpn_target_assign_layer(rng):
    anchors = layers.data("anchors", shape=[4], dtype="float32")
    gt = layers.data("gt", shape=[4], dtype="float32")
    labels, deltas, inw = detection.rpn_target_assign(
        anchors, gt, rpn_batch_size_per_im=16)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    def boxes(n, scale=1.0):
        x1 = rng.uniform(0, 0.5, (n,))
        y1 = rng.uniform(0, 0.5, (n,))
        return np.stack([x1, y1, x1 + rng.uniform(0.1, 0.5, (n,)),
                         y1 + rng.uniform(0.1, 0.5, (n,))],
                        -1).astype("float32") * scale

    av, gv = boxes(32), boxes(4)
    lv, dv, wv = exe.run(feed={"anchors": av, "gt": gv},
                         fetch_list=[labels, deltas, inw])
    assert set(np.unique(lv)) <= {-1, 0, 1}
    assert (lv == 1).sum() >= 1
    # deltas are zeroed outside the fg set
    assert np.all(dv[lv != 1] == 0)


def test_generate_proposals_layer(rng):
    scores = layers.data("scores", shape=[24], dtype="float32")
    deltas = layers.data("deltas", shape=[24, 4], dtype="float32")
    iminfo = layers.data("iminfo", shape=[3], dtype="float32")
    anchors_in = layers.data("anch", shape=[4], dtype="float32")
    rois, probs, nums = detection.generate_proposals(
        scores, deltas, iminfo, anchors_in, pre_nms_top_n=16,
        post_nms_top_n=5, nms_thresh=0.7)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x1 = rng.uniform(0, 10, (24,))
    y1 = rng.uniform(0, 10, (24,))
    av = np.stack([x1, y1, x1 + rng.uniform(2, 8, (24,)),
                   y1 + rng.uniform(2, 8, (24,))], -1).astype("float32")
    rv, pv, nv = exe.run(
        feed={"scores": rng.rand(1, 24).astype("float32"),
              "deltas": (rng.randn(1, 24, 4) * 0.1).astype("float32"),
              "iminfo": np.array([[20, 20, 1.0]], "float32"),
              "anch": av},
        fetch_list=[rois, probs, nums])
    assert rv.shape == (1, 5, 4) and pv.shape == (1, 5, 1)
    assert 1 <= int(nv[0]) <= 5
    # all kept rois inside the image
    assert rv.min() >= 0 and rv.max() <= 19.0 + 1e-5


def test_detection_map_layer_degrades_with_bad_boxes(rng):
    det = layers.data("det", shape=[2, 6], dtype="float32")
    gt = layers.data("gt", shape=[2, 5], dtype="float32")
    m = detection.detection_map(det, gt, class_num=3)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    gt_v = np.array([[[1, .1, .1, .4, .4], [2, .5, .5, .9, .9]]], "float32")
    perfect = np.array(
        [[[1, .9, .1, .1, .4, .4], [2, .8, .5, .5, .9, .9]]], "float32")
    wrong = np.array(
        [[[1, .9, .6, .6, .8, .8], [2, .8, .05, .05, .2, .2]]], "float32")
    mp, = exe.run(feed={"det": perfect, "gt": gt_v}, fetch_list=[m])
    mw, = exe.run(feed={"det": wrong, "gt": gt_v}, fetch_list=[m])
    assert abs(float(mp) - 1.0) < 1e-6
    assert float(mw) < 0.5


def test_positive_negative_pair_layer():
    s = layers.data("s", shape=[1], dtype="float32")
    l = layers.data("l", shape=[1], dtype="float32")
    q = layers.data("q", shape=[1], dtype="int64")
    pos, neg, neu = layers.positive_negative_pair(s, l, q)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pv, nv, uv = exe.run(
        feed={"s": np.array([[.9], [.5], [.1]], "float32"),
              "l": np.array([[2], [1], [0]], "float32"),
              "q": np.array([[0], [0], [0]], "int64")},
        fetch_list=[pos, neg, neu])
    assert float(pv) == 3.0 and float(nv) == 0.0 and float(uv) == 0.0


def test_pool_exclusive_avg_with_ceil_mode_tail(rng):
    """ceil_mode's implicit high padding must not dilute exclusive avg:
    the partial tail window divides by its valid element count."""
    x = layers.data("x", shape=[1, 1, 5], dtype="float32")
    out = layers.pool2d(x, pool_size=[1, 2], pool_stride=[1, 2],
                        pool_type="avg", ceil_mode=True, exclusive=True)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.arange(5, dtype="float32").reshape(1, 1, 1, 5)
    ov, = exe.run(feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(ov.reshape(-1), [0.5, 2.5, 4.0], rtol=1e-6)


def test_generate_proposals_pads_when_fewer_anchors_than_post_n(rng):
    """post_nms_top_n larger than the anchor count must still emit the
    declared static [B, post_n, 4] shape (zero-padded tail)."""
    scores = layers.data("scores", shape=[6], dtype="float32")
    deltas = layers.data("deltas", shape=[6, 4], dtype="float32")
    iminfo = layers.data("iminfo", shape=[3], dtype="float32")
    anchors_in = layers.data("anch", shape=[4], dtype="float32")
    rois, probs, nums = detection.generate_proposals(
        scores, deltas, iminfo, anchors_in, post_nms_top_n=10)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x1 = rng.uniform(0, 10, (6,))
    av = np.stack([x1, x1, x1 + 5, x1 + 5], -1).astype("float32")
    rv, pv, nv = exe.run(
        feed={"scores": rng.rand(1, 6).astype("float32"),
              "deltas": np.zeros((1, 6, 4), "float32"),
              "iminfo": np.array([[20, 20, 1.0]], "float32"),
              "anch": av},
        fetch_list=[rois, probs, nums])
    assert rv.shape == (1, 10, 4) and pv.shape == (1, 10, 1)
    assert int(nv[0]) <= 6


def test_rpn_target_assign_no_gt_image_samples_negatives(rng):
    """An image whose gt list is all padding must still produce background
    samples (not all-ignore), or empty images silently drop out of the RPN
    classification loss."""
    anchors = layers.data("anchors", shape=[4], dtype="float32")
    gt = layers.data("gt", shape=[4], dtype="float32")
    labels, _, _ = detection.rpn_target_assign(
        anchors, gt, rpn_batch_size_per_im=8)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x1 = rng.uniform(0, 0.5, (16,))
    av = np.stack([x1, x1, x1 + 0.3, x1 + 0.3], -1).astype("float32")
    lv, = exe.run(feed={"anchors": av,
                        "gt": np.zeros((3, 4), "float32")},
                  fetch_list=[labels])
    assert (lv == 0).sum() == 8      # full negative batch
    assert (lv == 1).sum() == 0


def test_contrib_beam_search_decoder_greedy_equivalence(rng):
    """contrib.BeamSearchDecoder with beam_size=1 must reproduce the greedy
    argmax chain of a deterministic next-token model (≙ reference
    contrib/decoder/beam_search_decoder.py)."""
    from paddle_tpu.contrib.decoder import BeamSearchDecoder

    vocab, hidden, max_len = 7, 5, 6
    x = layers.data("x", shape=[hidden], dtype="float32")

    decoder = BeamSearchDecoder(beam_size=1, bos_id=0, eos_id=vocab - 1,
                                max_len=max_len)

    def step(states, ids_prev):
        # ids as [B, K, 1] — with K=1 a bare [B, 1] would be read as an
        # index COLUMN by the embedding convention and squeeze the beam dim
        emb = layers.embedding(layers.unsqueeze(ids_prev, axes=[2]),
                               size=[vocab, hidden],
                               param_attr=pt.ParamAttr(name="dec_emb"))
        h = layers.fc(layers.concat([states["h"], emb], axis=2),
                      size=hidden, num_flatten_dims=2, act="tanh",
                      name="dec_cell")
        logits = layers.fc(h, size=vocab, num_flatten_dims=2,
                           name="dec_out")
        return {"h": h}, layers.log_softmax(logits)

    seqs, scores = decoder.decode(
        x, {"h": decoder.expand_to_beams(layers.fc(x, size=hidden,
                                                   name="dec_init"))},
        step)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = rng.rand(3, hidden).astype("float32")
    sv, scv = exe.run(feed={"x": xv}, fetch_list=[seqs, scores])
    assert sv.shape == (3, max_len, 1) and scv.shape == (3, 1)

    # greedy reference in numpy using the trained params
    emb_w = np.asarray(pt.global_scope().get("dec_emb"))
    cw = np.asarray(pt.global_scope().get("dec_cell.w_0"))
    cb = np.asarray(pt.global_scope().get("dec_cell.w_1"))
    ow = np.asarray(pt.global_scope().get("dec_out.w_0"))
    ob = np.asarray(pt.global_scope().get("dec_out.w_1"))
    iw = np.asarray(pt.global_scope().get("dec_init.w_0"))
    ib = np.asarray(pt.global_scope().get("dec_init.w_1"))
    h = xv @ iw + ib
    ids = np.zeros(3, dtype=np.int64)
    done = np.zeros(3, dtype=bool)
    for t in range(max_len):
        z = np.concatenate([h, emb_w[ids]], axis=1)
        h_new = np.tanh(z @ cw + cb)
        logits = h_new @ ow + ob
        nxt = logits.argmax(axis=1)
        for b in range(3):
            if not done[b]:
                assert sv[b, t, 0] == nxt[b], (b, t, sv[b, :, 0], nxt)
        done |= nxt == vocab - 1
        h = np.where(done[:, None], h, h_new)
        ids = nxt
        if done.all():
            break
