"""Executor-level tests for the program-level pipeline-parallel mode:
`pipeline_partition_pass` (framework/passes.py) + the GPipe/1F1B schedule
engine (parallel/pipeline.py) behind `BuildStrategy.pipeline_stages`.

Discipline mirrors tests/test_zero_comm.py: fixed-seed loss parity against
the single-device baseline, structure asserted from the program (one
pp_send/pp_recv pair per boundary) and the compiled HLO (exactly one
boundary-activation + one boundary-gradient collective-permute per tick),
and the schedule census read from the SAME tick tables the device executes
— bubble fraction pinned to the analytic (K-1)/(M+K-1), 1F1B's peak
stashed-activation count strictly below GPipe's at M >= 2*stages.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.framework.passes import get_pass
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.pipeline import (build_schedule, pipeline_apply,
                                          schedule_census)
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from probe_common import collective_census  # noqa: E402


def _build_mlp(depth=4):
    x = layers.data("x", shape=[32])
    label = layers.data("label", shape=[1], dtype="int64")
    h = x
    for _ in range(depth):
        h = layers.fc(h, size=64, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return loss


def _build_conv():
    img = layers.data("img", shape=[8, 8, 3])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.conv2d(img, 8, 3, padding=1, act="relu", data_format="NHWC")
    h = layers.pool2d(h, 2, "max", 2, data_format="NHWC")
    h = layers.conv2d(h, 16, 3, padding=1, act="relu", data_format="NHWC")
    h = layers.pool2d(h, 2, "max", 2, data_format="NHWC")
    h = layers.fc(h, size=32, act="relu", num_flatten_dims=1)
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return loss


def _mlp_feed(i, bs=16):
    return {"x": np.random.RandomState(100 + i).rand(bs, 32).astype("f4"),
            "label": np.random.RandomState(200 + i)
            .randint(0, 10, (bs, 1)).astype("int64")}


def _conv_feed(i, bs=16):
    return {"img": np.random.RandomState(300 + i)
            .rand(bs, 8, 8, 3).astype("f4"),
            "label": np.random.RandomState(400 + i)
            .randint(0, 10, (bs, 1)).astype("int64")}


def _baseline(build, feeds, fetch_extra=()):
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = build()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]


def _pipeline_run(build, feeds, axes, stages, microbatches, schedule,
                  reduce_strategy=ReduceStrategy.AllReduce, quant=""):
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = build()
    bst = BuildStrategy(pipeline_stages=stages,
                        num_microbatches=microbatches,
                        pipeline_schedule=schedule)
    bst.reduce_strategy = reduce_strategy
    bst.quant_comm = quant
    n = 1
    for s in axes.values():
        n *= s
    mesh = DeviceMesh(jax.devices()[:n], axes)
    exe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                           build_strategy=bst)
    pt.Executor().run(pt.default_startup_program())
    losses = [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
    return losses, exe, loss


def _compiled_hlo(exe, feed):
    scope = pt.global_scope()
    cs = list(exe._cache.values())[-1]
    feed_vals = tuple(jnp.asarray(feed[n]) for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    return cs.fn.lower(feed_vals, ro, rw, np.uint32(0)).compile().as_text()


# ---------------------------------------------------------------------------
# schedule tables (fast: host-side simulation only, no compile)
# ---------------------------------------------------------------------------

@pytest.mark.quick
class TestScheduleTables:
    def test_bubble_census_pins_analytic_model(self):
        for name in ("gpipe", "1f1b"):
            for m, k in ((4, 2), (8, 2), (16, 2), (4, 4), (8, 4), (16, 4)):
                c = schedule_census(name, m, k)
                assert c["ticks"] == 2 * (m + k - 1), (name, m, k, c)
                assert c["bubble_fraction"] == pytest.approx(
                    (k - 1) / (m + k - 1), abs=1e-12), (name, m, k, c)
                # per-stage: every stage idles exactly the bubble slots
                for frac in c["bubble_fraction_per_stage"]:
                    assert frac == pytest.approx(c["bubble_fraction"],
                                                 abs=1e-12), (name, m, k, c)

    def test_1f1b_stash_strictly_below_gpipe_at_2k_microbatches(self):
        # the acceptance claim, asserted via the census (the same tables
        # the engine executes), not assumed
        for k in (2, 4):
            for m in (2 * k, 4 * k):
                g = schedule_census("gpipe", m, k)
                f = schedule_census("1f1b", m, k)
                assert f["peak_stash"] < g["peak_stash"], (m, k, f, g)
                assert g["peak_stash"] == m, (m, k, g)
                assert f["peak_stash"] <= k, (m, k, f)

    def test_tables_cover_every_microbatch_in_dependency_order(self):
        for name in ("gpipe", "1f1b"):
            s = build_schedule(name, 6, 3)
            m_count, k_count = s.num_microbatches, s.num_stages
            for tbl in (s.fwd_mb, s.bwd_mb):
                for k in range(k_count):
                    mbs = [int(v) for v in tbl[:, k] if v >= 0]
                    assert sorted(mbs) == list(range(m_count)), (name, k)
            fs = {(k, m): t for t in range(s.ticks)
                  for k in range(k_count)
                  if (m := int(s.fwd_mb[t, k])) >= 0}
            bs = {(k, m): t for t in range(s.ticks)
                  for k in range(k_count)
                  if (m := int(s.bwd_mb[t, k])) >= 0}
            for m in range(m_count):
                for k in range(k_count - 1):
                    assert fs[(k, m)] < fs[(k + 1, m)], (name, k, m)
                    assert bs[(k + 1, m)] < bs[(k, m)], (name, k, m)
                assert fs[(k_count - 1, m)] < bs[(k_count - 1, m)], (name, m)


# ---------------------------------------------------------------------------
# the partition pass (program-level structure, no compile)
# ---------------------------------------------------------------------------

@pytest.mark.quick
class TestPartitionPass:
    def _partitioned(self, stages=2):
        with pt.core.unique_name.guard():
            loss = _build_mlp()
        prog = pt.default_main_program()
        out = get_pass("pipeline_partition_pass", num_stages=stages,
                       num_microbatches=4, schedule="1f1b", dp_axis="",
                       reduce_dp=False)(prog)
        return loss, prog, out

    def test_one_send_recv_pair_per_boundary(self):
        for stages in (2, 4):
            pt.reset_default_programs()
            loss, prog, out = self._partitioned(stages)
            ops = out.global_block().ops
            sends = [op for op in ops if op.type == "pp_send"]
            recvs = [op for op in ops if op.type == "pp_recv"]
            regions = [op for op in ops if op.type == "pp_pipeline_region"]
            assert len(sends) == stages - 1, [op.type for op in ops]
            assert len(recvs) == stages - 1
            assert len(regions) == 1
            assert not any(op.type == "vjp_region" for op in ops)
            # each send/recv pair shares one buffer and one crossing set
            for s, r in zip(sends, recvs):
                assert s.outputs["Out"] == r.inputs["X"]
                assert s.inputs["X"] == r.outputs["Out"]
            # the caller's program is untouched
            assert any(op.type == "vjp_region"
                       for op in prog.global_block().ops)

    def test_stages_contiguous_and_cost_balanced(self):
        pt.reset_default_programs()
        loss, prog, out = self._partitioned(2)
        region = next(op for op in out.global_block().ops
                      if op.type == "pp_pipeline_region")
        stages = region.attrs["stages"]
        assert len(stages) == 2
        flat = [i for lst in stages for i in lst]
        assert flat == sorted(flat)          # contiguous program order
        costs = region.attrs["stage_costs"]
        assert len(costs) == 2 and all(c > 0 for c in costs)
        # a 5-fc stack splits so neither stage carries everything
        assert max(costs) / sum(costs) < 0.9, costs

    def test_downstream_metric_head_pruned_and_fetch_gated(self):
        """A pure sink chain reading a forward activation (a metric head)
        is pruned — its values only exist per-microbatch inside the
        schedule — and fetching its output raises the clear pipeline
        error instead of a confusing trace failure."""
        with pt.core.unique_name.guard():
            x = layers.data("x", shape=[8])
            label = layers.data("label", shape=[1], dtype="int64")
            h = layers.fc(x, size=16, act="relu")
            logits = layers.fc(h, size=4)
            loss = layers.mean(layers.softmax_with_cross_entropy(
                logits, label))
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
            # a metric op outside the region reading a forward activation
            metric = layers.mean(h)
        out = get_pass("pipeline_partition_pass", num_stages=2,
                       num_microbatches=2, schedule="1f1b", dp_axis="",
                       reduce_dp=False)(pt.default_main_program())
        kept = [op.type for op in out.global_block().ops]
        # the sink mean over h is gone; the loss path survives
        assert kept.count("mean") == 1, kept
        assert metric.name in out._pp_hidden
        assert loss.name not in out._pp_hidden


# ---------------------------------------------------------------------------
# gates + kill switch
# ---------------------------------------------------------------------------

class TestGatesAndKillSwitch:
    def _exe(self, loss, stages=2, m=4):
        bst = BuildStrategy(pipeline_stages=stages, num_microbatches=m)
        mesh = DeviceMesh(jax.devices()[:stages], {"pp": stages})
        return ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                build_strategy=bst)

    def test_batch_norm_rejected(self):
        with pt.core.unique_name.guard():
            x = layers.data("x", shape=[16])
            label = layers.data("label", shape=[1], dtype="int64")
            h = layers.batch_norm(layers.fc(x, size=16))
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.fc(h, size=4), label))
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = self._exe(loss)
        pt.Executor().run(pt.default_startup_program())
        with pytest.raises(InvalidArgumentError, match="batch_norm"):
            exe.run(feed={"x": np.zeros((8, 16), np.float32),
                          "label": np.zeros((8, 1), np.int64)},
                    fetch_list=[loss])

    def test_non_mean_loss_rejected(self):
        with pt.core.unique_name.guard():
            x = layers.data("x", shape=[16])
            label = layers.data("label", shape=[1], dtype="int64")
            per_row = layers.softmax_with_cross_entropy(
                layers.fc(x, size=4), label)
            loss = layers.reduce_sum(per_row)
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = self._exe(loss)
        pt.Executor().run(pt.default_startup_program())
        with pytest.raises(InvalidArgumentError, match="MEAN-reduced"):
            exe.run(feed={"x": np.zeros((8, 16), np.float32),
                          "label": np.zeros((8, 1), np.int64)},
                    fetch_list=[loss])

    def test_non_divisible_microbatches_rejected(self):
        with pt.core.unique_name.guard():
            loss = _build_mlp()
        exe = self._exe(loss, m=4)
        pt.Executor().run(pt.default_startup_program())
        with pytest.raises(InvalidArgumentError, match="num_microbatches"):
            exe.run(feed=_mlp_feed(0, bs=14), fetch_list=[loss])

    def test_hidden_activation_fetch_rejected(self):
        with pt.core.unique_name.guard():
            x = layers.data("x", shape=[8])
            label = layers.data("label", shape=[1], dtype="int64")
            h = layers.fc(x, size=16, act="relu")
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.fc(h, size=4), label))
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = self._exe(loss)
        pt.Executor().run(pt.default_startup_program())
        with pytest.raises(InvalidArgumentError,
                           match="forward activation"):
            exe.run(feed=_mlp_feed(0, bs=8) | {
                "x": np.zeros((8, 8), np.float32)},
                fetch_list=[loss, h])

    def test_mesh_without_pp_axis_rejected(self):
        with pt.core.unique_name.guard():
            loss = _build_mlp()
        bst = BuildStrategy(pipeline_stages=2, num_microbatches=4)
        mesh = DeviceMesh(jax.devices()[:2], {"dp": 2})
        exe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                               build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        with pytest.raises(InvalidArgumentError, match="pp"):
            exe.run(feed=_mlp_feed(0), fetch_list=[loss])


@pytest.mark.quick
class TestPipelineApplyBoundary:
    def test_divisibility_enforced_with_clear_message(self):
        """Satellite (r09): the bare `assert` at the pipeline_apply API
        boundary is now an enforce-style error."""
        mesh = DeviceMesh(jax.devices()[:2], {"pp": 2})
        w = {"w": jnp.zeros((2, 4), jnp.float32)}
        x = jnp.zeros((6, 4), jnp.float32)
        with pytest.raises(InvalidArgumentError,
                           match="not divisible by num_microbatches"):
            pipeline_apply(mesh, lambda p, h: h, w, x, num_microbatches=4)
        with pytest.raises(InvalidArgumentError, match=">= 1"):
            pipeline_apply(mesh, lambda p, h: h, w, x, num_microbatches=0)
