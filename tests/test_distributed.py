"""Distributed job layer tests.

≙ reference go/master tests (task dispatch/retry/snapshot semantics,
go/master/service.go) and test_dist_base.py's forked-local-subprocess
pattern (tests run master + workers on 127.0.0.1, no cluster).
"""

import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed import (ElasticTrainer, FailureDetector, Master,
                                    MasterClient, PreemptionGuard, parse_env)


class TestMasterQueue:
    def test_dispatch_and_finish_full_pass(self):
        m = Master(timeout_s=60)
        n = m.set_dataset([f"chunk{i}" for i in range(6)])
        assert n == 6
        seen = []
        while True:
            t = m.get_task("w0")
            if t is None:
                break
            seen.extend(t["chunks"])
            m.task_finished(t["task_id"])
        assert sorted(seen) == [f"chunk{i}" for i in range(6)]
        assert m.stats()["done"] == 6

    def test_new_pass_recycles_done(self):
        m = Master(num_passes=2)
        m.set_dataset(["a", "b"])
        for _ in range(2):
            t = m.get_task()
            m.task_finished(t["task_id"])
        # all done -> next get_task starts epoch 1 (the final pass)
        t = m.get_task()
        assert t is not None and t["epoch"] == 1
        m.task_finished(t["task_id"])
        t = m.get_task()
        assert t is not None and t["epoch"] == 1
        m.task_finished(t["task_id"])
        assert m.get_task() is None   # num_passes exhausted

    def test_timeout_requeues_with_failure_count(self):
        m = Master(timeout_s=0.05, max_retry=3)
        m.set_dataset(["a"])
        t1 = m.get_task("w0")
        assert t1 is not None
        time.sleep(0.1)
        t2 = m.get_task("w1")    # lease expired -> requeued -> re-leased
        assert t2 is not None and t2["task_id"] == t1["task_id"]

    def test_max_retry_discards(self):
        m = Master(timeout_s=60, max_retry=2)
        m.set_dataset(["a"])
        for _ in range(2):
            t = m.get_task()
            m.task_failed(t["task_id"])
        assert m.get_task() is None
        assert m.stats()["discarded"] == 1

    def test_finish_unknown_task_rejected(self):
        m = Master()
        m.set_dataset(["a"])
        assert m.task_finished(123) is False

    def test_snapshot_recover(self, tmp_path):
        snap = str(tmp_path / "master.snap")
        m = Master(snapshot_path=snap, timeout_s=60)
        m.set_dataset(["a", "b", "c"])
        t = m.get_task("w0")
        m.task_finished(t["task_id"])
        t2 = m.get_task("w0")          # leave one pending
        del m

        m2 = Master(snapshot_path=snap, timeout_s=60)
        s = m2.stats()
        # pending lease did not survive: it is back in todo
        assert s["done"] == 1 and s["pending"] == 0 and s["todo"] == 2
        remaining = set()
        while True:
            t = m2.get_task("w1")
            if t is None:
                break
            remaining.update(t["chunks"])
            m2.task_finished(t["task_id"])
        assert len(remaining) == 2

    def test_heartbeat_liveness(self):
        m = Master()
        m.heartbeat("w0")
        m.heartbeat("w1")
        assert m.live_workers(horizon_s=10) == ["w0", "w1"]
        assert m.live_workers(horizon_s=0) == []


# Worker subprocess: loads ONLY master.py by file path — importing the full
# paddle_tpu package in a bare child would pull in jax (and the TPU-tunnel
# plugin) without the conftest guards, which can hang CI.
_WORKER_SCRIPT = r"""
import importlib.util, json, sys
spec = importlib.util.spec_from_file_location("ptd_master", sys.argv[1])
mod = importlib.util.module_from_spec(spec)
sys.modules["ptd_master"] = mod   # dataclasses needs the module registered
spec.loader.exec_module(mod)
endpoint, worker_id, fail_first = sys.argv[2], sys.argv[3], sys.argv[4] == "1"
c = mod.MasterClient(endpoint, worker_id=worker_id)
done, failed_once = [], False
for task_id, chunks in c.tasks(poll_interval_s=0.05, max_polls=10):
    if fail_first and not failed_once:
        failed_once = True
        c.task_failed(task_id)
        continue
    done.extend(chunks)
    c.task_finished(task_id)
print(json.dumps({"worker": worker_id, "done": done}))
"""


class TestMasterMultiProcess:
    def test_two_workers_share_dataset_with_retry(self):
        import subprocess
        import sys
        import json
        from paddle_tpu.distributed import master as master_mod

        m = Master(timeout_s=30, max_retry=5)
        server, _ = m.serve_forever()
        host, port = server.server_address
        endpoint = f"{host}:{port}"
        m.set_dataset([f"c{i}" for i in range(8)])

        master_path = master_mod.__file__
        procs = [
            subprocess.Popen([sys.executable, "-c", _WORKER_SCRIPT,
                              master_path, endpoint, wid, fail],
                             stdout=subprocess.PIPE, text=True)
            for wid, fail in (("w0", "1"), ("w1", "0"))
        ]
        got = {}
        for p in procs:
            out, _ = p.communicate(timeout=60)
            rec = json.loads(out.strip().splitlines()[-1])
            got[rec["worker"]] = rec["done"]
        server.shutdown()

        all_chunks = sorted(got.get("w0", []) + got.get("w1", []))
        # every chunk processed exactly once per pass despite the failure
        assert all_chunks == sorted(f"c{i}" for i in range(8))


class TestEnv:
    def test_parse_env_roles(self):
        env = parse_env({"PADDLE_TRAINING_ROLE": "pserver",
                         "PADDLE_TRAINER_ID": "3",
                         "PADDLE_TRAINERS_NUM": "8",
                         "PADDLE_COORDINATOR_ENDPOINT": "10.0.0.1:1234",
                         "PADDLE_PSERVER_IPS": "a:1,b:2"})
        assert env.training_role == "PSERVER"
        assert env.trainer_id == 3 and env.num_trainers == 8
        assert env.coordinator == "10.0.0.1:1234"
        assert env.pserver_endpoints == ("a:1", "b:2")
        assert not env.is_chief

    def test_single_host_bootstrap_noop(self):
        from paddle_tpu.distributed import init_parallel_env
        env = init_parallel_env(parse_env({}))  # no coordinator -> no-op
        assert env.num_trainers == 1


class TestElasticTrainer:
    def _build(self):
        from paddle_tpu.core import unique_name
        with unique_name.guard():   # stable param names across rebuilds
            x = layers.data("x", shape=[4])
            loss = layers.mean(layers.fc(x, size=4, name="el_fc"))
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        return exe, loss

    def test_preemption_checkpoint_and_resume(self, rng, tmp_path):
        exe, loss = self._build()
        guard = PreemptionGuard(signals=())
        et = ElasticTrainer(exe, str(tmp_path / "ckpt"),
                            save_interval_steps=1000, guard=guard)
        feed = {"x": rng.rand(4, 4).astype("float32")}

        def step(i):
            if i == 4:
                guard.request()       # preemption mid-run
            return exe.run(feed=feed, fetch_list=[loss])[0]

        out = et.run(step, num_steps=100)
        assert out["preempted"] and out["last_step"] == 4

        # "restart": fresh scope, resume from checkpoint, continue to end
        pt.reset_global_scope()
        pt.reset_default_programs()
        exe2, loss2 = self._build()
        w_before = np.asarray(pt.global_scope().get("el_fc.w_0")).copy()
        et2 = ElasticTrainer(exe2, str(tmp_path / "ckpt"),
                             save_interval_steps=1000)
        assert et2.resume_step() == 4
        w_after = np.asarray(pt.global_scope().get("el_fc.w_0"))
        assert not np.allclose(w_before, w_after)  # restored trained weights

        out2 = et2.run(lambda i: exe2.run(feed=feed,
                                          fetch_list=[loss2])[0],
                       num_steps=10)
        assert out2["last_step"] == 9 and not out2["preempted"]

    def test_failure_detector_fires(self):
        m = Master()
        m.heartbeat("w0")
        fired = []
        det = FailureDetector(m, expected_workers={"w0", "w1"},
                              horizon_s=10, poll_s=0.01, grace_s=0)
        det.start(lambda dead: fired.append(dead))
        time.sleep(0.2)
        det.stop()
        assert fired and fired[0] == {"w1"}

    def test_failure_detector_grace_tolerates_slow_boot(self):
        # workers that have not yet joined must not count as dead during
        # the startup grace window; ones that joined and vanished do
        m = Master()
        fired = []
        det = FailureDetector(m, expected_workers={"w0", "w1"},
                              horizon_s=0.1, poll_s=0.01, grace_s=30)
        det.start(lambda dead: fired.append(dead))
        time.sleep(0.1)
        assert not fired          # nobody joined yet -> silence, not alarm
        m.heartbeat("w0")         # w0 boots...
        time.sleep(0.3)           # ...then misses the 0.1s horizon
        det.stop()
        assert fired and fired[0] == {"w0"}
