"""Fusion subsystem evidence suite (paddle_tpu/fusion/ + the fuse passes).

Three committed claims, mirroring tests/test_pass_verification.py's
discipline (every rewrite numerically verified on REAL model programs, not
toy blocks):

  (a) kernel parity: the fused LSTM/GRU whole-sequence cells and the fused
      decode-attention step match the unfused math — forward AND gradient —
      with the Pallas kernels additionally pinned through the interpreter
      (the same tiling logic the TPU runs);
  (b) pass correctness: `fuse_recurrent_cell_pass` /
      `fuse_decode_attention_pass` rewrite real programs (stacked-LSTM
      train graph, the KV-cached LM decode graph) into the fused ops and
      leave them numerically equivalent end to end, parameters-after-update
      included;
  (c) pass safety: non-default activations, multi-consumer intermediates
      and multi-position queries are NOT rewritten.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.core import flags


@pytest.fixture(autouse=True)
def _fusion_flags_restored():
    """Tests flip the fuse_* flags; leave the session defaults intact."""
    rnn = flags.get_flag("fuse_recurrent_cells")
    dec = flags.get_flag("fuse_decode_attention")
    yield
    flags.set_flag("fuse_recurrent_cells", rnn)
    flags.set_flag("fuse_decode_attention", dec)


# ---------------------------------------------------------------------------
# (a) kernel-level parity
# ---------------------------------------------------------------------------


class TestFusedRecurrentKernels:
    def _lstm_args(self, rng, b=4, t=6, h=128):
        import jax.numpy as jnp
        return (jnp.asarray(rng.randn(b, t, 4 * h).astype("float32") * .3),
                jnp.asarray(rng.randn(b, h).astype("float32") * .1),
                jnp.asarray(rng.randn(b, h).astype("float32") * .1),
                jnp.asarray(rng.randn(h, 4 * h).astype("float32") * .1),
                jnp.asarray(np.array([t, t - 2, 1, t], "int32")))

    @pytest.mark.parametrize("reverse", [False, True])
    def test_lstm_interpret_matches_xla(self, rng, reverse):
        from paddle_tpu.fusion import fused_lstm_sequence
        x, h0, c0, w, sl = self._lstm_args(rng)
        hx, cx = fused_lstm_sequence(x, h0, c0, w, sl, reverse=reverse,
                                     backend="xla")
        hp, cp = fused_lstm_sequence(x, h0, c0, w, sl, reverse=reverse,
                                     backend="pallas_interpret")
        np.testing.assert_allclose(hx, hp, atol=2e-6, rtol=2e-6)
        np.testing.assert_allclose(cx, cp, atol=2e-6, rtol=2e-6)

    def test_lstm_matches_unfused_op_and_grads(self, rng):
        """Fused vs the registered dynamic_lstm lowering, fwd + full vjp
        (the fused backward is a manual custom_vjp — pin it against what
        jax.vjp derives from the unfused scan)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.fusion import fused_lstm_sequence
        from paddle_tpu.ops.sequence_ops import _lstm_scan
        x, h0, c0, w, sl = self._lstm_args(rng)

        def ref(args):
            hs, cs = _lstm_scan(args[0], args[1], args[2], args[3], sl,
                                jax.nn.sigmoid, jnp.tanh, jnp.tanh)
            return hs, cs

        def fused(args):
            return fused_lstm_sequence(args[0], args[1], args[2], args[3],
                                       sl, backend="xla")

        rf, ff = ref((x, h0, c0, w)), fused((x, h0, c0, w))
        np.testing.assert_allclose(rf[0], ff[0], atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(rf[1], ff[1], atol=1e-6, rtol=1e-6)

        def loss(f):
            def inner(args):
                hs, cs = f(args)
                wgt = jnp.cos(jnp.arange(hs.size)).reshape(hs.shape)
                return jnp.sum(hs * wgt) + jnp.sum(cs ** 2)
            return inner

        gr = jax.grad(loss(ref))((x, h0, c0, w))
        gf = jax.grad(loss(fused))((x, h0, c0, w))
        for a, b, name in zip(gf, gr, ["x", "h0", "c0", "w"]):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4,
                                       err_msg=f"d{name}")

    @pytest.mark.parametrize("reverse", [False, True])
    def test_gru_interpret_matches_xla_and_grads(self, rng, reverse):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.fusion import fused_gru_sequence
        from paddle_tpu.ops.sequence_ops import _dynamic_gru
        b, t, h = 4, 5, 128
        x = jnp.asarray(rng.randn(b, t, 3 * h).astype("float32") * .3)
        h0 = jnp.asarray(rng.randn(b, h).astype("float32") * .1)
        w = jnp.asarray(rng.randn(h, 3 * h).astype("float32") * .1)
        sl = jnp.asarray(np.array([t, 2, t, 1], "int32"))
        ax = fused_gru_sequence(x, h0, w, sl, reverse=reverse,
                                backend="xla")
        ap = fused_gru_sequence(x, h0, w, sl, reverse=reverse,
                                backend="pallas_interpret")
        np.testing.assert_allclose(ax, ap, atol=2e-6, rtol=2e-6)
        # fwd + grad vs the registered unfused lowering
        ins = {"Input": [x], "Weight": [w], "SeqLen": [sl], "H0": [h0]}
        ref = _dynamic_gru(None, ins, {"is_reverse": reverse})["Hidden"][0]
        np.testing.assert_allclose(ax, ref, atol=1e-6, rtol=1e-6)

        def loss_f(args):
            return jnp.sum(fused_gru_sequence(
                args[0], args[1], args[2], sl, reverse=reverse,
                backend="xla") ** 2)

        def loss_r(args):
            out = _dynamic_gru(None, {"Input": [args[0]], "Weight": [args[2]],
                                      "SeqLen": [sl], "H0": [args[1]]},
                               {"is_reverse": reverse})["Hidden"][0]
            return jnp.sum(out ** 2)

        gf = jax.grad(loss_f)((x, h0, w))
        gr = jax.grad(loss_r)((x, h0, w))
        for a, b_, name in zip(gf, gr, ["x", "h0", "w"]):
            np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4,
                                       err_msg=f"d{name}")

    def test_misaligned_hidden_falls_back_to_xla(self, rng):
        """H not a lane multiple: the Pallas path must silently take the
        composite (identical results, no crash)."""
        import jax.numpy as jnp
        from paddle_tpu.fusion import fused_lstm_sequence
        b, t, h = 2, 3, 24
        x = jnp.asarray(rng.randn(b, t, 4 * h).astype("float32"))
        h0 = jnp.zeros((b, h), jnp.float32)
        c0 = jnp.zeros((b, h), jnp.float32)
        w = jnp.asarray(rng.randn(h, 4 * h).astype("float32") * .1)
        sl = jnp.full((b,), t, jnp.int32)
        a = fused_lstm_sequence(x, h0, c0, w, sl, backend="pallas_interpret")
        b_ = fused_lstm_sequence(x, h0, c0, w, sl, backend="xla")
        np.testing.assert_allclose(a[0], b_[0], atol=1e-6)


class TestFusedDecodeAttentionKernel:
    def _args(self, rng, b=3, k=4, nh=2, t=10, dh=16):
        import jax.numpy as jnp
        q = jnp.asarray(rng.randn(b, k, nh, 1, dh).astype("float32"))
        kc = jnp.asarray(rng.randn(b, k, nh, t, dh).astype("float32"))
        vc = jnp.asarray(rng.randn(b, k, nh, t, dh).astype("float32"))
        keep = (np.arange(t)[None] < np.array([3, 5, t][:b])[:, None])
        bias = jnp.asarray((keep.astype("float32") * 1e9 - 1e9)
                           .reshape(b, 1, 1, 1, t))
        return q, kc, vc, bias

    def test_matches_unfused_chain_all_backends(self, rng):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.fusion import fused_decode_attention
        q, k, v, bias = self._args(rng)
        scale = q.shape[-1] ** -0.5
        s = jnp.matmul(q, jnp.swapaxes(k, -1, -2),
                       preferred_element_type=jnp.float32) * scale + bias
        ref = jnp.matmul(jax.nn.softmax(s, -1), v,
                         preferred_element_type=jnp.float32)
        for backend in ("xla", "pallas_interpret"):
            out = fused_decode_attention(q, k, v, bias, scale=scale,
                                         backend=backend)
            np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-5,
                                       err_msg=backend)

    def test_gradients_match_unfused_chain(self, rng):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.fusion import fused_decode_attention
        q, k, v, bias = self._args(rng)
        scale = q.shape[-1] ** -0.5

        def f_fused(args):
            return jnp.sum(fused_decode_attention(
                *args, scale=scale, backend="xla") ** 2)

        def f_ref(args):
            q_, k_, v_, b_ = args
            s = jnp.matmul(q_, jnp.swapaxes(k_, -1, -2),
                           preferred_element_type=jnp.float32) * scale + b_
            return jnp.sum(jnp.matmul(jax.nn.softmax(s, -1), v_,
                           preferred_element_type=jnp.float32) ** 2)

        gf = jax.grad(f_fused)((q, k, v, bias))
        gr = jax.grad(f_ref)((q, k, v, bias))
        for a, b_, name in zip(gf, gr, ["q", "k", "v", "bias"]):
            np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4,
                                       err_msg=f"d{name}")


# ---------------------------------------------------------------------------
# (b) pass verification on real model programs
# ---------------------------------------------------------------------------


def _lstm_losses_and_params(fuse, rng):
    pt.reset_default_programs()
    pt.reset_global_scope()
    flags.set_flag("fuse_recurrent_cells", fuse)
    from paddle_tpu.core import unique_name
    with unique_name.guard():
        loss, acc, _ = models.stacked_lstm.stacked_lstm_net(
            dict_dim=300, emb_dim=16, hid_dim=16, max_len=10)
        pt.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    pt.default_startup_program().random_seed = 11
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    r = np.random.RandomState(7)
    feed = {"words": r.randint(0, 300, (4, 10)).astype("int64"),
            "words@SEQLEN": np.array([10, 6, 2, 10], "int32"),
            "label": r.randint(0, 2, (4, 1)).astype("int64")}
    losses = [float(exe.run(feed=feed, fetch_list=[loss])[0])
              for _ in range(3)]
    params = {p.name: np.asarray(pt.global_scope().get(p.name))
              for p in pt.default_main_program().all_parameters()}
    return losses, params


@pytest.mark.quick
def test_fuse_recurrent_cell_pass_preserves_stacked_lstm_training(rng):
    """stacked_lstm_net + Adam, 3 steps: losses AND updated parameters are
    identical with the fuse pass on vs off — forward and gradient of the
    fused cells are drop-in (the training path exercises the custom_vjp)."""
    base_l, base_p = _lstm_losses_and_params(False, rng)
    fuse_l, fuse_p = _lstm_losses_and_params(True, rng)
    np.testing.assert_allclose(fuse_l, base_l, atol=1e-6, rtol=1e-6)
    assert base_p.keys() == fuse_p.keys()
    for name in base_p:
        np.testing.assert_allclose(fuse_p[name], base_p[name], atol=1e-5,
                                   rtol=1e-4, err_msg=name)


def _decode(fuse, beam, seed=3):
    from paddle_tpu.core import unique_name
    from paddle_tpu.models import transformer
    pt.reset_default_programs()
    pt.reset_global_scope()
    flags.set_flag("fuse_decode_attention", fuse)
    with unique_name.guard():
        seqs, scores = transformer.transformer_lm_generate(
            vocab=60, max_gen=6, d_model=16, d_inner=32, num_heads=2,
            num_layers=2, bos_id=1, beam_size=beam)
    exe = pt.Executor()
    pt.default_startup_program().random_seed = seed
    exe.run(pt.default_startup_program())
    feed = {"prompt": np.full((3, 1), 1, "int64")}
    out, sc = exe.run(feed=feed, fetch_list=[seqs, scores])
    return np.asarray(out), np.asarray(sc)


@pytest.mark.quick
@pytest.mark.parametrize("beam", [1, 4])
def test_fuse_decode_attention_pass_preserves_lm_decode(beam):
    """KV-cached LM decode (greedy + beam-4): generated sequences are
    IDENTICAL and scores agree to a bf16 ulp (the rewrite changes XLA's
    f32 summation order upstream of the bf16 lm_head) with the pass on
    vs off."""
    o0, s0 = _decode(False, beam)
    o1, s1 = _decode(True, beam)
    assert np.array_equal(o0, o1)
    np.testing.assert_allclose(s1, s0, atol=2e-2, rtol=1e-3)


def test_fuse_decode_attention_pass_rewrites_the_decode_subgraph():
    """Structural evidence: the pass replaces every per-layer 4-op decode
    attention chain (matmul/add/softmax/matmul) in the StaticRNN sub-block
    with one fused_decode_attention op, and drops the glue vars."""
    from paddle_tpu.core import unique_name
    from paddle_tpu.framework.passes import apply_fusion_passes
    from paddle_tpu.models import transformer
    with unique_name.guard():
        seqs, _ = transformer.transformer_lm_generate(
            vocab=60, max_gen=6, d_model=16, d_inner=32, num_heads=2,
            num_layers=3, bos_id=1, beam_size=4)
    prog = pt.default_main_program()

    def count(p, t):
        return sum(op.type == t for b in p.blocks for op in b.ops)

    flags.set_flag("fuse_decode_attention", True)
    rewritten = apply_fusion_passes(prog, protected=[seqs.name])
    assert rewritten is not prog, "pass should clone, not mutate"
    assert count(prog, "fused_decode_attention") == 0
    assert count(rewritten, "fused_decode_attention") == 3  # one per layer
    assert count(rewritten, "softmax") == count(prog, "softmax") - 3
    assert count(rewritten, "matmul") == count(prog, "matmul") - 2 * 3
    assert count(rewritten, "cache_write") == count(prog, "cache_write")
    # the glue vars are gone; every remaining op input still resolves
    from paddle_tpu.framework.passes import get_pass
    get_pass("check_pass")(rewritten)


# ---------------------------------------------------------------------------
# (c) pass safety: what must NOT be rewritten
# ---------------------------------------------------------------------------


def test_recurrent_pass_skips_non_default_activations():
    from paddle_tpu.core import unique_name
    from paddle_tpu.framework.passes import apply_fusion_passes
    with unique_name.guard():
        data = layers.data("w2", shape=[8], dtype="int64", lod_level=1)
        seqlen = layers.sequence.get_seqlen(data)
        emb = layers.embedding(input=data, size=[50, 16])
        emb = layers.sequence.tag_sequence(emb, seqlen)
        proj = layers.fc(emb, size=64, num_flatten_dims=2)
        proj = layers.sequence.tag_sequence(proj, seqlen)
        layers.dynamic_lstm(input=proj, size=64, gate_activation="relu")
        layers.dynamic_lstm(input=proj, size=64)
    flags.set_flag("fuse_recurrent_cells", True)
    prog = apply_fusion_passes(pt.default_main_program())
    types = [op.type for op in prog.global_block().ops]
    assert types.count("dynamic_lstm") == 1   # the relu one stays
    assert types.count("fused_lstm") == 1


def test_decode_pass_skips_multi_position_queries():
    """A full-sequence attention chain (Tq > 1) is not a decode step."""
    from paddle_tpu.core import unique_name
    from paddle_tpu.framework.passes import apply_fusion_passes
    with unique_name.guard():
        q = layers.data("q", shape=[2, 8, 16], dtype="float32")
        k = layers.data("k", shape=[2, 8, 16], dtype="float32")
        v = layers.data("v", shape=[2, 8, 16], dtype="float32")
        bias = layers.data("b", shape=[2, 8, 8], dtype="float32")
        s = layers.matmul(q, k, transpose_y=True, alpha=0.25)
        s = layers.elementwise_add(s, bias)
        w = layers.softmax(s)
        layers.matmul(w, v)
    flags.set_flag("fuse_decode_attention", True)
    prog = apply_fusion_passes(pt.default_main_program())
    types = [op.type for op in prog.global_block().ops]
    assert "fused_decode_attention" not in types


def test_decode_pass_skips_multi_consumer_intermediates():
    """If the attention weights are read elsewhere (e.g. fetched for
    attention maps), the chain must survive unfused."""
    from paddle_tpu.core import unique_name
    from paddle_tpu.framework.passes import apply_fusion_passes
    with unique_name.guard():
        q = layers.data("q", shape=[2, 1, 16], dtype="float32")
        k = layers.data("k", shape=[2, 8, 16], dtype="float32")
        v = layers.data("v", shape=[2, 8, 16], dtype="float32")
        bias = layers.data("b", shape=[2, 1, 8], dtype="float32")
        s = layers.matmul(q, k, transpose_y=True, alpha=0.25)
        s = layers.elementwise_add(s, bias)
        w = layers.softmax(s)
        layers.matmul(w, v)
        layers.reduce_mean(w)          # second consumer of the weights
    flags.set_flag("fuse_decode_attention", True)
    prog = apply_fusion_passes(pt.default_main_program())
    types = [op.type for op in prog.global_block().ops]
    assert "fused_decode_attention" not in types


def test_kill_switch_disables_rewrite():
    from paddle_tpu.core import unique_name
    from paddle_tpu.framework.passes import apply_fusion_passes
    with unique_name.guard():
        data = layers.data("w3", shape=[8], dtype="int64", lod_level=1)
        seqlen = layers.sequence.get_seqlen(data)
        emb = layers.embedding(input=data, size=[50, 16])
        emb = layers.sequence.tag_sequence(emb, seqlen)
        proj = layers.fc(emb, size=64, num_flatten_dims=2)
        proj = layers.sequence.tag_sequence(proj, seqlen)
        layers.dynamic_lstm(input=proj, size=64)
    flags.set_flag("fuse_recurrent_cells", False)
    flags.set_flag("fuse_decode_attention", False)
    prog = pt.default_main_program()
    assert apply_fusion_passes(prog) is prog   # untouched, not even cloned


@pytest.mark.slow
def test_bench_fusion_ab_harness_end_to_end():
    """The A/B bench harness itself (tools/bench_fusion.py) runs both
    sides and reports a sane record — slow-marked (excluded from tier-1)
    because it compiles 6 programs."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from bench_fusion import _decode_small, ab, measure_stacked_lstm
    r = ab("lstm_smoke", measure_stacked_lstm, batch=2, seq=4, hid=16,
           iters=1)
    assert r["unfused_ms"] > 0 and r["fused_ms"] > 0
    r = ab("decode_smoke", _decode_small, batch=2, gen_len=3, beam=2,
           iters=1)
    assert r["unfused_ms"] > 0 and r["fused_ms"] > 0


# ---------------------------------------------------------------------------
# satellite: cache_write uniform-Pos contract (ADVICE r5 #3)
# ---------------------------------------------------------------------------


class TestCacheWriteUniformPos:
    def _build(self):
        from paddle_tpu.core import unique_name
        with unique_name.guard():
            cache = layers.data("cache", shape=[4, 8], dtype="float32")
            new = layers.data("new", shape=[1, 8], dtype="float32")
            pos = layers.data("pos", shape=[2], dtype="int32")
            out = layers.cache_write(cache, new, pos, axis=1)
        return out

    def test_uniform_pos_ok(self):
        out = self._build()
        exe = pt.Executor()
        got = exe.run(feed={
            "cache": np.zeros((2, 4, 8), "float32"),
            "new": np.ones((2, 1, 8), "float32"),
            "pos": np.full((2, 2), 2, "int32")}, fetch_list=[out])[0]
        assert got[:, 2].sum() == 2 * 8 and got.sum() == 2 * 8

    def test_non_uniform_pos_raises(self):
        out = self._build()
        exe = pt.Executor()
        with pytest.raises(Exception, match="uniform position"):
            exe.run(feed={
                "cache": np.zeros((2, 4, 8), "float32"),
                "new": np.ones((2, 1, 8), "float32"),
                "pos": np.array([[1, 3], [1, 1]], "int32")},
                fetch_list=[out])
