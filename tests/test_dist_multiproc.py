"""Multi-process distributed tests: real jax.distributed bootstrap + elastic
kill/reassign/resume.

≙ reference test_dist_base.py:27 (forked localhost pserver/trainer harness)
and go/master/service.go:313 (task lease timeout -> requeue). Two scenarios:

1. Two localhost processes join one jax.distributed world through
   paddle_tpu.distributed.init_parallel_env (the PADDLE_* env protocol), form
   a global device mesh spanning both processes, and run a cross-process
   collective — the capability the reference proves with its nccl2 tests.

2. Elastic training: a master leases dataset chunks to two trainer
   subprocesses which chain model state through a locked checkpoint
   directory. One trainer is hard-killed mid-lease; the master requeues the
   expired lease, the survivor trains the reassigned chunk, and the final
   loss matches a single-process sequential run within a small delta.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# jaxlib < 0.5 cannot run multi-process computations on the CPU backend at
# all ("Multiprocess computations aren't implemented on the CPU backend")
# — the cross-process CPU client landed later. Skip the whole module there:
# the capability under test does not exist in that runtime, and a red X
# would misread as a product regression.
def _cpu_multiproc_supported():
    import jax
    major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    return (major, minor) >= (0, 5)


pytestmark = pytest.mark.skipif(
    not _cpu_multiproc_supported(),
    reason="jaxlib < 0.5: no multi-process CPU backend")


# Preamble for every child: CPU-only jax with the tunnel plugin dropped
# (children do not inherit conftest's bootstrap).
_BOOT = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax._src.xla_bridge as _xb
_xb._backend_factories.pop("axon", None)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, __REPO__)
"""


def _script(body):
    """Template a child script (scripts contain literal {} so str.format is
    unusable)."""
    return body.replace("__REPO__", repr(REPO))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# 1. jax.distributed bootstrap through the framework env protocol
# ---------------------------------------------------------------------------

_JOIN_SCRIPT = _BOOT + r"""
import json
import jax.numpy as jnp
from paddle_tpu.distributed import init_parallel_env
from paddle_tpu.distributed.env import global_rank, world_size

env = init_parallel_env()          # reads the PADDLE_* vars from os.environ
assert world_size() == 2, world_size()
assert global_rank() == env.trainer_id

# global mesh across both processes; one cross-process collective
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import make_array_from_process_local_data
mesh = Mesh(jax.devices(), ("dp",))
local = jnp.ones((2, 4)) * (env.trainer_id + 1)   # rank0: 1s, rank1: 2s
garr = make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), local, (4, 4))
total = jax.jit(lambda a: a.sum(),
                out_shardings=NamedSharding(mesh, P()))(garr)
print(json.dumps({"rank": env.trainer_id,
                  "world": world_size(),
                  "global_devices": len(jax.devices()),
                  "sum": float(total)}), flush=True)
"""


def test_two_process_jax_distributed_bootstrap(tmp_path):
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_COORDINATOR_ENDPOINT": f"127.0.0.1:{port}",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _script(_JOIN_SCRIPT)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(tmp_path)))
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=150)
        assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
        rec = json.loads(out.strip().splitlines()[-1])
        results[rec["rank"]] = rec
    assert set(results) == {0, 1}
    for rec in results.values():
        assert rec["world"] == 2
        assert rec["global_devices"] == 4      # 2 virtual cpu devs/process
        # rows: two of 1s (rank 0) + two of 2s (rank 1), each of width 4
        assert rec["sum"] == 24.0


# ---------------------------------------------------------------------------
# 1b. multi-process ParallelExecutor: the framework's OWN PE program runs
#     across two processes (2 virtual devices each) on one global 4-device
#     mesh, and its loss trajectory matches the single-process 4-device run.
#     ≙ reference test_dist_base.py:27 proving the real trainer program
#     multi-process over an nccl2 world (nccl_helper.h:118).
# ---------------------------------------------------------------------------

_PE_MODEL = r"""
import numpy as np


def build_and_train(steps=6, reduce_strategy=False, fused=False):
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.parallel import (BuildStrategy, DeviceMesh,
                                     ParallelExecutor, ReduceStrategy)
    from paddle_tpu.core import unique_name
    import jax

    with unique_name.guard():
        x = layers.data("x", shape=[8])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, size=16, act="relu", name="pe_fc1")
        pred = layers.fc(h, size=1, name="pe_fc2")
        loss = layers.reduce_mean(layers.square(pred - y))
        pt.optimizer.MomentumOptimizer(learning_rate=0.05,
                                       momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    bs = BuildStrategy()
    if reduce_strategy:
        bs.reduce_strategy = ReduceStrategy.Reduce     # ZeRO-1 over dp
    pe = ParallelExecutor(loss_name=loss.name,
                          mesh=DeviceMesh(jax.devices()),
                          build_strategy=bs)

    r = np.random.RandomState(7)
    W = r.randn(8, 1).astype("float32")
    feeds = []
    for i in range(steps):
        rb = np.random.RandomState(100 + i)
        xb = rb.rand(16, 8).astype("float32")          # global batch
        feeds.append({"x": xb, "y": (xb @ W).astype("float32")})
    if fused:
        # scan-fused multi-step loop over the cross-process mesh
        return [float(v) for v in
                pe.run_steps(feeds, fetch_list=[loss.name])[0]]
    return [float(pe.run(feed=f, fetch_list=[loss.name])[0])
            for f in feeds]
"""

_PE_SINGLE = r"""
import json
from pe_model import build_and_train
out = {"plain": build_and_train(), }
import paddle_tpu as pt
pt.reset_default_programs(); pt.reset_global_scope()
out["zero1"] = build_and_train(reduce_strategy=True)
print(json.dumps(out), flush=True)
"""

_PE_MULTI = _BOOT + r"""
import json
import jax
from paddle_tpu.distributed import init_parallel_env

env = init_parallel_env()
assert jax.process_count() == 2
assert len(jax.devices()) == 4
from pe_model import build_and_train
out = {"rank": env.trainer_id, "plain": build_and_train()}
import paddle_tpu as pt
pt.reset_default_programs(); pt.reset_global_scope()
out["zero1"] = build_and_train(reduce_strategy=True)
pt.reset_default_programs(); pt.reset_global_scope()
out["fused"] = build_and_train(fused=True)
print(json.dumps(out), flush=True)
"""


def test_multiprocess_parallel_executor_loss_parity(tmp_path):
    with open(tmp_path / "pe_model.py", "w") as f:
        f.write(_PE_MODEL)

    # single-process reference: one child with 4 virtual devices
    boot4 = _BOOT.replace('host_platform_device_count=2',
                          'host_platform_device_count=4')
    ref = subprocess.run(
        [sys.executable, "-c", _script(boot4 + _PE_SINGLE)],
        capture_output=True, text=True, timeout=300, cwd=str(tmp_path))
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_losses = json.loads(ref.stdout.strip().splitlines()[-1])

    # two processes x 2 devices = the SAME 4-device global mesh
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_COORDINATOR_ENDPOINT": f"127.0.0.1:{port}",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _script(_PE_MULTI)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(tmp_path)))
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"child failed:\n{err[-2500:]}"
        rec = json.loads(out.strip().splitlines()[-1])
        results[rec["rank"]] = rec

    assert set(results) == {0, 1}
    # the scan-fused multi-process loop matches the per-step trajectory
    np.testing.assert_allclose(results[0]["fused"], results[0]["plain"],
                               rtol=2e-4)
    np.testing.assert_allclose(results[0]["fused"], results[1]["fused"],
                               rtol=1e-6)
    for variant in ("plain", "zero1"):
        # both ranks observe the identical (replicated-fetch) trajectory
        np.testing.assert_allclose(results[0][variant], results[1][variant],
                                   rtol=1e-6)
        # and it matches the single-process 4-device run: same global
        # batch, same seeded init, same SPMD program — only the process
        # split differs (collective reduction order -> tiny fp delta)
        np.testing.assert_allclose(results[0][variant],
                                   ref_losses[variant], rtol=2e-4)
        # real training happened
        assert results[0][variant][-1] < results[0][variant][0]


# ---------------------------------------------------------------------------
# 2. elastic: kill a trainer mid-lease, master requeues, survivor resumes
#    from the shared checkpoint chain
# ---------------------------------------------------------------------------

# Deterministic per-chunk regression data; the model is a single fc layer so
# the run is fast and the loss trajectory is smooth.
_TRAINER_SCRIPT = _BOOT + r"""
import fcntl, json
import numpy as np

endpoint, worker_id, ckpt_dir, lock_path, die_after, result_path = \
    sys.argv[1:7]
die_after = int(die_after)

import paddle_tpu as pt
from paddle_tpu.distributed import MasterClient
from chunk_common import train_chunk, build

exe, loss_var, step_fn = build()
client = MasterClient(endpoint, worker_id=worker_id)
done = []
losses = []
for task_id, chunks in client.tasks(poll_interval_s=0.1, max_polls=100):
    if die_after and len(done) >= die_after:
        os._exit(9)                    # hard crash while holding the lease
    with open(lock_path, "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        try:
            if os.path.exists(os.path.join(ckpt_dir, "params")):
                pt.io.load_persistables(exe, os.path.join(ckpt_dir, "params"))
            for chunk in chunks:
                losses.append(train_chunk(step_fn, chunk))
                done.append(chunk)
            os.makedirs(os.path.join(ckpt_dir, "params"), exist_ok=True)
            pt.io.save_persistables(exe, os.path.join(ckpt_dir, "params"))
        finally:
            fcntl.flock(lk, fcntl.LOCK_UN)
    client.task_finished(task_id)
with open(result_path, "w") as f:
    json.dump({"worker": worker_id, "done": done, "losses": losses}, f)
"""

_CHUNK_COMMON = r"""
import numpy as np

W_TRUE = np.arange(1, 5, dtype="float32").reshape(4, 1) / 4.0


def chunk_data(chunk):
    seed = int(chunk[1:])
    r = np.random.RandomState(seed)
    x = r.rand(16, 4).astype("float32")
    y = (x @ W_TRUE).astype("float32")
    return x, y


def build():
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import unique_name
    with unique_name.guard():
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1, name="el_fc", bias_attr=False)
        loss = layers.reduce_mean(layers.square(pred - y))
        pt.optimizer.SGDOptimizer(learning_rate=0.2).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    def step_fn(xb, yb):
        return float(exe.run(feed={"x": xb, "y": yb},
                             fetch_list=[loss])[0])
    return exe, loss, step_fn


def train_chunk(step_fn, chunk, steps=5):
    xb, yb = chunk_data(chunk)
    last = None
    for _ in range(steps):
        last = step_fn(xb, yb)
    return last
"""


def test_elastic_kill_reassign_resume(tmp_path):
    """Kill a trainer mid-lease; master requeues; survivor resumes from the
    checkpoint chain; final loss matches a single-process sequential run."""
    from paddle_tpu.distributed import Master

    with open(tmp_path / "chunk_common.py", "w") as f:
        f.write(_CHUNK_COMMON)

    chunks = [f"c{i}" for i in range(8)]

    base_script = (_BOOT + r"""
import json
from chunk_common import build, train_chunk
exe, loss, step_fn = build()
losses = [train_chunk(step_fn, c) for c in CHUNKS]
print(json.dumps(losses), flush=True)
""").replace("CHUNKS", repr(chunks))
    out = subprocess.run(
        [sys.executable, "-c", _script(base_script)],
        capture_output=True, text=True, timeout=150, cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    baseline_final = json.loads(out.stdout.strip().splitlines()[-1])[-1]

    m = Master(timeout_s=3.0, max_retry=5)
    server, _ = m.serve_forever()
    host, port = server.server_address
    endpoint = f"{host}:{port}"
    m.set_dataset(chunks)

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    lock = str(tmp_path / "ckpt.lock")

    def spawn(worker_id, die_after):
        return subprocess.Popen(
            [sys.executable, "-c", _script(_TRAINER_SCRIPT),
             endpoint, worker_id, str(ckpt), lock, str(die_after),
             str(tmp_path / f"{worker_id}.json")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(tmp_path))

    # victim runs alone first: finishes exactly 2 chunks, then hard-crashes
    # the moment it leases its 3rd — deterministic, no scheduling race
    victim = spawn("victim", die_after=2)
    v_out, v_err = victim.communicate(timeout=200)
    assert victim.returncode == 9, f"victim should crash:\n{v_err[-1500:]}"

    # survivor joins after the crash; the victim's expired lease requeues
    # (timeout_s=3) and the survivor trains the reassigned chunk too
    survivor = spawn("survivor", die_after=0)
    s_out, s_err = survivor.communicate(timeout=200)
    server.shutdown()
    assert survivor.returncode == 0, f"survivor failed:\n{s_err[-1500:]}"

    with open(tmp_path / "survivor.json") as f:
        surv = json.load(f)

    stats = m.stats()
    # every chunk finished despite the crash: the victim's expired lease was
    # requeued and trained by the survivor
    assert stats["done"] == len(chunks), stats
    trained = sorted(surv["done"])
    victim_trained = sorted(set(chunks) - set(surv["done"]))
    assert len(victim_trained) == 2          # the two the victim finished
    assert sorted(set(trained + victim_trained)) == chunks

    # loss parity vs the sequential single-process run: same chunk multiset
    # through the same checkpoint-chained model, only the order differs
    elastic_final = surv["losses"][-1]
    assert elastic_final < 0.05, elastic_final      # actually converged
    assert abs(elastic_final - baseline_final) < 0.05, (
        elastic_final, baseline_final)


# ---------------------------------------------------------------------------
# 3. elastic WORLD RESIZE: one of two jax.distributed processes is
#    hard-killed; the chief detects the failure through master heartbeats
#    (FailureDetector), re-execs itself into a 1-process world, restores
#    from the SHARDED checkpoint written by both processes, and training
#    continues with loss parity vs an uninterrupted run.
#    ≙ SURVEY §5 failure-detection row + hard part #3 (XLA worlds are
#    static -> checkpoint-restart elasticity); reference
#    go/master/service.go:313 task requeue + etcd liveness.
# ---------------------------------------------------------------------------

_RESIZE_MODEL = r"""
import numpy as np


def build():
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import unique_name
    with unique_name.guard():
        x = layers.data("x", shape=[8])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, size=16, act="relu", name="rz_fc1")
        pred = layers.fc(h, size=1, name="rz_fc2")
        loss = layers.reduce_mean(layers.square(pred - y))
        pt.optimizer.MomentumOptimizer(learning_rate=0.05,
                                       momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe, loss


def global_batch(i):
    r = np.random.RandomState(7)
    W = r.randn(8, 1).astype("float32")
    rb = np.random.RandomState(100 + i)
    xb = rb.rand(16, 8).astype("float32")
    return xb, (xb @ W).astype("float32")


def pe_step(pe, loss, i):
    xb, yb = global_batch(i)
    return float(pe.run(feed={"x": xb, "y": yb},
                        fetch_list=[loss.name])[0])
"""

_RESIZE_JOINT_STEPS = 4
_RESIZE_TOTAL_STEPS = 8

_RESIZE_CHIEF = _BOOT + r"""
import glob, json, threading, time
import numpy as np
import jax

import paddle_tpu as pt
from paddle_tpu.distributed import init_parallel_env, MasterClient
from paddle_tpu.distributed.elastic import FailureDetector
from paddle_tpu.parallel import DeviceMesh, ParallelExecutor
from resize_model import build, pe_step

WORK = os.environ["RESIZE_WORKDIR"]
PHASE = os.environ.get("RESIZE_PHASE", "joint")
MASTER = os.environ["RESIZE_MASTER"]

client = MasterClient(MASTER, worker_id="chief")
stop_hb = threading.Event()
def hb():
    while not stop_hb.is_set():
        try:
            client.heartbeat()
        except Exception:
            pass
        time.sleep(0.2)
threading.Thread(target=hb, daemon=True).start()

def latest_complete_ckpt():
    dirs = sorted(glob.glob(os.path.join(WORK, "ckpt", "step-*")))
    best = None
    for d in dirs:
        if len(glob.glob(os.path.join(d, "manifest-*.json"))) == 2:
            best = d
    return best

if PHASE == "joint":
    env = init_parallel_env()
    assert jax.process_count() == 2
    exe, loss = build()
    pe = ParallelExecutor(loss_name=loss.name,
                          mesh=DeviceMesh(jax.devices()))
    losses = []
    for i in range(JOINT):
        losses.append(pe_step(pe, loss, i))
        d = os.path.join(WORK, "ckpt", f"step-{i}")
        pt.io.save_persistables(dirname=d, sharded=True)
        # wait until BOTH processes finished writing this step's shards
        while len(glob.glob(os.path.join(d, "manifest-*.json"))) < 2:
            time.sleep(0.05)
    with open(os.path.join(WORK, "chief_joint.json"), "w") as f:
        json.dump(losses, f)

    # joint quota done: hold here, heartbeating, until the peer's death is
    # DETECTED (not assumed) through the master heartbeat horizon
    failed = threading.Event()
    # own client: xmlrpc ServerProxy is not thread-safe, and the heartbeat
    # thread is still using `client`
    det_client = MasterClient(MASTER, worker_id="chief-detector")
    det = FailureDetector(det_client, expected_workers={"peer"},
                          horizon_s=1.5, poll_s=0.2, grace_s=60.0)
    det.start(lambda dead: failed.set())
    assert failed.wait(timeout=120), "peer death was never detected"
    det.stop()

    # restart-based elasticity (XLA worlds are static): re-exec into a
    # 1-process world and resume from the sharded checkpoint
    env2 = dict(os.environ)
    env2.update({"RESIZE_PHASE": "solo", "PADDLE_TRAINERS_NUM": "1",
                 "PADDLE_TRAINER_ID": "0"})
    env2.pop("PADDLE_COORDINATOR_ENDPOINT", None)
    stop_hb.set()
    os.execve(sys.executable, [sys.executable, sys.argv[0]], env2)

else:  # solo: fresh 1-process world over the local 2-device mesh
    env = init_parallel_env()
    assert jax.process_count() == 1
    exe, loss = build()
    ck = latest_complete_ckpt()
    assert ck is not None
    pt.io.load_persistables(dirname=ck, sharded=True)
    resume_from = int(os.path.basename(ck).split("-")[1]) + 1
    pe = ParallelExecutor(loss_name=loss.name,
                          mesh=DeviceMesh(jax.devices()))
    losses = []
    for i in range(resume_from, TOTAL):
        losses.append(pe_step(pe, loss, i))
    with open(os.path.join(WORK, "chief_solo.json"), "w") as f:
        json.dump({"resume_from": resume_from, "losses": losses}, f)
""".replace("JOINT", str(_RESIZE_JOINT_STEPS)).replace(
    "TOTAL", str(_RESIZE_TOTAL_STEPS))

_RESIZE_PEER = _BOOT + r"""
import glob, json, threading, time
import jax

import paddle_tpu as pt
from paddle_tpu.distributed import init_parallel_env, MasterClient
from paddle_tpu.parallel import DeviceMesh, ParallelExecutor
from resize_model import build, pe_step

WORK = os.environ["RESIZE_WORKDIR"]
client = MasterClient(os.environ["RESIZE_MASTER"], worker_id="peer")
def hb():
    while True:
        try:
            client.heartbeat()
        except Exception:
            pass
        time.sleep(0.2)
threading.Thread(target=hb, daemon=True).start()

env = init_parallel_env()
exe, loss = build()
pe = ParallelExecutor(loss_name=loss.name, mesh=DeviceMesh(jax.devices()))
for i in range(JOINT):
    pe_step(pe, loss, i)
    d = os.path.join(WORK, "ckpt", f"step-{i}")
    pt.io.save_persistables(dirname=d, sharded=True)
    while len(glob.glob(os.path.join(d, "manifest-*.json"))) < 2:
        time.sleep(0.05)
with open(os.path.join(WORK, "peer_done"), "w") as f:
    f.write("ok")
time.sleep(600)   # idle (heartbeating) until the parent SIGKILLs us
""".replace("JOINT", str(_RESIZE_JOINT_STEPS))

_RESIZE_REF = _BOOT + r"""
import json
from resize_model import build, pe_step
import jax
from paddle_tpu.parallel import DeviceMesh, ParallelExecutor
exe, loss = build()
pe = ParallelExecutor(loss_name=loss.name, mesh=DeviceMesh(jax.devices()))
print(json.dumps([pe_step(pe, loss, i) for i in range(TOTAL)]), flush=True)
""".replace("TOTAL", str(_RESIZE_TOTAL_STEPS))


def test_elastic_world_resize(tmp_path):
    import signal as _signal

    from paddle_tpu.distributed import Master

    with open(tmp_path / "resize_model.py", "w") as f:
        f.write(_RESIZE_MODEL)
    (tmp_path / "ckpt").mkdir()

    m = Master(timeout_s=5.0)
    server, _ = m.serve_forever()
    host, port = server.server_address
    master_ep = f"{host}:{port}"

    # uninterrupted reference: single process, 4 virtual devices
    boot4 = _BOOT.replace('host_platform_device_count=2',
                          'host_platform_device_count=4')
    ref = subprocess.run(
        [sys.executable, "-c", _script(boot4 + _RESIZE_REF.split(_BOOT)[1])],
        capture_output=True, text=True, timeout=300, cwd=str(tmp_path))
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_losses = json.loads(ref.stdout.strip().splitlines()[-1])

    coord_port = _free_port()
    chief_path = tmp_path / "chief.py"
    with open(chief_path, "w") as f:
        f.write(_script(_RESIZE_CHIEF))

    def env_for(rank):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_COORDINATOR_ENDPOINT": f"127.0.0.1:{coord_port}",
            "RESIZE_WORKDIR": str(tmp_path),
            "RESIZE_MASTER": master_ep,
        })
        return env

    chief = subprocess.Popen(
        [sys.executable, str(chief_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env_for(0), cwd=str(tmp_path))
    peer = subprocess.Popen(
        [sys.executable, "-c", _script(_RESIZE_PEER)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env_for(1), cwd=str(tmp_path))

    # wait for the peer to finish its joint quota, then murder it
    deadline = time.time() + 240
    while not (tmp_path / "peer_done").exists():
        assert time.time() < deadline, "joint phase never completed"
        if peer.poll() is not None:
            _, perr = peer.communicate()
            raise AssertionError(f"peer died early:\n{perr[-2000:]}")
        if chief.poll() is not None:
            _, cerr = chief.communicate()
            raise AssertionError(f"chief died early:\n{cerr[-2000:]}")
        time.sleep(0.2)
    peer.send_signal(_signal.SIGKILL)
    peer.wait(timeout=30)

    out, err = chief.communicate(timeout=300)
    server.shutdown()
    assert chief.returncode == 0, f"chief failed:\n{err[-3000:]}"

    with open(tmp_path / "chief_joint.json") as f:
        joint = json.load(f)
    with open(tmp_path / "chief_solo.json") as f:
        solo = json.load(f)

    # detection -> resize really happened where expected
    assert solo["resume_from"] == _RESIZE_JOINT_STEPS
    full = joint + solo["losses"]
    assert len(full) == _RESIZE_TOTAL_STEPS
    # same global batches, same math, different world shape: parity with
    # the uninterrupted run within collective-reorder tolerance
    np.testing.assert_allclose(full, ref_losses, rtol=2e-4)
    assert full[-1] < full[0]


# ---------------------------------------------------------------------------
# 4. multi-process sharded save_checkpoint: the barrier-separated commit
#    protocol (chief cleans -> all write shards -> chief marks _SUCCESS)
#    produces exactly one complete serial dir that load_checkpoint restores.
# ---------------------------------------------------------------------------

_CKPT_SCRIPT = _BOOT + r"""
import json
import numpy as np
import jax

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed import init_parallel_env
from paddle_tpu.trainer import (get_latest_checkpoint_serial,
                                load_checkpoint, save_checkpoint)

env = init_parallel_env()
root = os.environ["CKPT_ROOT"]

x = layers.data("x", shape=[4])
w_out = layers.fc(x, size=2, name="mpfc")
exe = pt.Executor()
exe.run(pt.default_startup_program())

serial = save_checkpoint(exe, root, pt.default_main_program(),
                         trainer_args={"step": 5}, sharded=True)
# both processes agree on the serial and see a COMPLETE checkpoint
assert serial == 0, serial
assert get_latest_checkpoint_serial(root) == 0

w_before = np.asarray(pt.global_scope().get("mpfc.w_0"))
pt.reset_global_scope()
args = load_checkpoint(exe, root, pt.default_main_program(), sharded=True)
assert args == {"step": 5}, args
np.testing.assert_array_equal(
    np.asarray(pt.global_scope().get("mpfc.w_0")), w_before)

# a second save lands in serial 1 on every process (no split-brain dirs)
serial2 = save_checkpoint(exe, root, pt.default_main_program(),
                          trainer_args={"step": 9}, sharded=True)
assert serial2 == 1, serial2
print(json.dumps({"rank": env.trainer_id, "ok": True}), flush=True)
"""


def test_multiprocess_sharded_save_checkpoint(tmp_path):
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_COORDINATOR_ENDPOINT": f"127.0.0.1:{port}",
            "CKPT_ROOT": str(tmp_path / "ck"),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _script(_CKPT_SCRIPT)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(tmp_path)))
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"child failed:\n{err[-2500:]}"
        assert json.loads(out.strip().splitlines()[-1])["ok"]
    # one complete dir per serial, two manifests each (one per process)
    import glob
    for serial in (0, 1):
        d = tmp_path / "ck" / f"checkpoint_{serial}"
        assert (d / "_SUCCESS").exists()
        assert len(glob.glob(str(d / "manifest-*.json"))) == 2
