"""Multi-process distributed tests: real jax.distributed bootstrap + elastic
kill/reassign/resume.

≙ reference test_dist_base.py:27 (forked localhost pserver/trainer harness)
and go/master/service.go:313 (task lease timeout -> requeue). Two scenarios:

1. Two localhost processes join one jax.distributed world through
   paddle_tpu.distributed.init_parallel_env (the PADDLE_* env protocol), form
   a global device mesh spanning both processes, and run a cross-process
   collective — the capability the reference proves with its nccl2 tests.

2. Elastic training: a master leases dataset chunks to two trainer
   subprocesses which chain model state through a locked checkpoint
   directory. One trainer is hard-killed mid-lease; the master requeues the
   expired lease, the survivor trains the reassigned chunk, and the final
   loss matches a single-process sequential run within a small delta.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Preamble for every child: CPU-only jax with the tunnel plugin dropped
# (children do not inherit conftest's bootstrap).
_BOOT = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax._src.xla_bridge as _xb
_xb._backend_factories.pop("axon", None)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, __REPO__)
"""


def _script(body):
    """Template a child script (scripts contain literal {} so str.format is
    unusable)."""
    return body.replace("__REPO__", repr(REPO))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# 1. jax.distributed bootstrap through the framework env protocol
# ---------------------------------------------------------------------------

_JOIN_SCRIPT = _BOOT + r"""
import json
import jax.numpy as jnp
from paddle_tpu.distributed import init_parallel_env
from paddle_tpu.distributed.env import global_rank, world_size

env = init_parallel_env()          # reads the PADDLE_* vars from os.environ
assert world_size() == 2, world_size()
assert global_rank() == env.trainer_id

# global mesh across both processes; one cross-process collective
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import make_array_from_process_local_data
mesh = Mesh(jax.devices(), ("dp",))
local = jnp.ones((2, 4)) * (env.trainer_id + 1)   # rank0: 1s, rank1: 2s
garr = make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), local, (4, 4))
total = jax.jit(lambda a: a.sum(),
                out_shardings=NamedSharding(mesh, P()))(garr)
print(json.dumps({"rank": env.trainer_id,
                  "world": world_size(),
                  "global_devices": len(jax.devices()),
                  "sum": float(total)}), flush=True)
"""


def test_two_process_jax_distributed_bootstrap(tmp_path):
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_COORDINATOR_ENDPOINT": f"127.0.0.1:{port}",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _script(_JOIN_SCRIPT)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(tmp_path)))
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=150)
        assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
        rec = json.loads(out.strip().splitlines()[-1])
        results[rec["rank"]] = rec
    assert set(results) == {0, 1}
    for rec in results.values():
        assert rec["world"] == 2
        assert rec["global_devices"] == 4      # 2 virtual cpu devs/process
        # rows: two of 1s (rank 0) + two of 2s (rank 1), each of width 4
        assert rec["sum"] == 24.0


# ---------------------------------------------------------------------------
# 2. elastic: kill a trainer mid-lease, master requeues, survivor resumes
#    from the shared checkpoint chain
# ---------------------------------------------------------------------------

# Deterministic per-chunk regression data; the model is a single fc layer so
# the run is fast and the loss trajectory is smooth.
_TRAINER_SCRIPT = _BOOT + r"""
import fcntl, json
import numpy as np

endpoint, worker_id, ckpt_dir, lock_path, die_after, result_path = \
    sys.argv[1:7]
die_after = int(die_after)

import paddle_tpu as pt
from paddle_tpu.distributed import MasterClient
from chunk_common import train_chunk, build

exe, loss_var, step_fn = build()
client = MasterClient(endpoint, worker_id=worker_id)
done = []
losses = []
for task_id, chunks in client.tasks(poll_interval_s=0.1, max_polls=100):
    if die_after and len(done) >= die_after:
        os._exit(9)                    # hard crash while holding the lease
    with open(lock_path, "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        try:
            if os.path.exists(os.path.join(ckpt_dir, "params")):
                pt.io.load_persistables(exe, os.path.join(ckpt_dir, "params"))
            for chunk in chunks:
                losses.append(train_chunk(step_fn, chunk))
                done.append(chunk)
            os.makedirs(os.path.join(ckpt_dir, "params"), exist_ok=True)
            pt.io.save_persistables(exe, os.path.join(ckpt_dir, "params"))
        finally:
            fcntl.flock(lk, fcntl.LOCK_UN)
    client.task_finished(task_id)
with open(result_path, "w") as f:
    json.dump({"worker": worker_id, "done": done, "losses": losses}, f)
"""

_CHUNK_COMMON = r"""
import numpy as np

W_TRUE = np.arange(1, 5, dtype="float32").reshape(4, 1) / 4.0


def chunk_data(chunk):
    seed = int(chunk[1:])
    r = np.random.RandomState(seed)
    x = r.rand(16, 4).astype("float32")
    y = (x @ W_TRUE).astype("float32")
    return x, y


def build():
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import unique_name
    with unique_name.guard():
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1, name="el_fc", bias_attr=False)
        loss = layers.reduce_mean(layers.square(pred - y))
        pt.optimizer.SGDOptimizer(learning_rate=0.2).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    def step_fn(xb, yb):
        return float(exe.run(feed={"x": xb, "y": yb},
                             fetch_list=[loss])[0])
    return exe, loss, step_fn


def train_chunk(step_fn, chunk, steps=5):
    xb, yb = chunk_data(chunk)
    last = None
    for _ in range(steps):
        last = step_fn(xb, yb)
    return last
"""


def test_elastic_kill_reassign_resume(tmp_path):
    """Kill a trainer mid-lease; master requeues; survivor resumes from the
    checkpoint chain; final loss matches a single-process sequential run."""
    from paddle_tpu.distributed import Master

    with open(tmp_path / "chunk_common.py", "w") as f:
        f.write(_CHUNK_COMMON)

    chunks = [f"c{i}" for i in range(8)]

    base_script = (_BOOT + r"""
import json
from chunk_common import build, train_chunk
exe, loss, step_fn = build()
losses = [train_chunk(step_fn, c) for c in CHUNKS]
print(json.dumps(losses), flush=True)
""").replace("CHUNKS", repr(chunks))
    out = subprocess.run(
        [sys.executable, "-c", _script(base_script)],
        capture_output=True, text=True, timeout=150, cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    baseline_final = json.loads(out.stdout.strip().splitlines()[-1])[-1]

    m = Master(timeout_s=3.0, max_retry=5)
    server, _ = m.serve_forever()
    host, port = server.server_address
    endpoint = f"{host}:{port}"
    m.set_dataset(chunks)

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    lock = str(tmp_path / "ckpt.lock")

    def spawn(worker_id, die_after):
        return subprocess.Popen(
            [sys.executable, "-c", _script(_TRAINER_SCRIPT),
             endpoint, worker_id, str(ckpt), lock, str(die_after),
             str(tmp_path / f"{worker_id}.json")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(tmp_path))

    # victim runs alone first: finishes exactly 2 chunks, then hard-crashes
    # the moment it leases its 3rd — deterministic, no scheduling race
    victim = spawn("victim", die_after=2)
    v_out, v_err = victim.communicate(timeout=200)
    assert victim.returncode == 9, f"victim should crash:\n{v_err[-1500:]}"

    # survivor joins after the crash; the victim's expired lease requeues
    # (timeout_s=3) and the survivor trains the reassigned chunk too
    survivor = spawn("survivor", die_after=0)
    s_out, s_err = survivor.communicate(timeout=200)
    server.shutdown()
    assert survivor.returncode == 0, f"survivor failed:\n{s_err[-1500:]}"

    with open(tmp_path / "survivor.json") as f:
        surv = json.load(f)

    stats = m.stats()
    # every chunk finished despite the crash: the victim's expired lease was
    # requeued and trained by the survivor
    assert stats["done"] == len(chunks), stats
    trained = sorted(surv["done"])
    victim_trained = sorted(set(chunks) - set(surv["done"]))
    assert len(victim_trained) == 2          # the two the victim finished
    assert sorted(set(trained + victim_trained)) == chunks

    # loss parity vs the sequential single-process run: same chunk multiset
    # through the same checkpoint-chained model, only the order differs
    elastic_final = surv["losses"][-1]
    assert elastic_final < 0.05, elastic_final      # actually converged
    assert abs(elastic_final - baseline_final) < 0.05, (
        elastic_final, baseline_final)
