"""Tests: host-side metrics accumulators, profiler, debugger, Trainer +
checkpoint/resume (≙ reference test_metrics.py / test_profiler.py /
trainer checkpoint tests)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import metrics, profiler


class TestMetrics:
    def test_accuracy(self):
        m = metrics.Accuracy()
        m.update(0.5, 10)
        m.update(1.0, 10)
        assert abs(m.eval() - 0.75) < 1e-9
        m.reset()
        with pytest.raises(Exception):
            m.eval()

    def test_precision_recall(self):
        preds = np.array([1, 1, 0, 1, 0])
        labels = np.array([1, 0, 0, 1, 1])
        p = metrics.Precision()
        p.update(preds, labels)
        assert abs(p.eval() - 2 / 3) < 1e-9
        r = metrics.Recall()
        r.update(preds, labels)
        assert abs(r.eval() - 2 / 3) < 1e-9

    def test_composite(self):
        c = metrics.CompositeMetric()
        c.add_metric(metrics.Precision())
        c.add_metric(metrics.Recall())
        c.update(np.array([1, 0]), np.array([1, 1]))
        p, r = c.eval()
        assert p == 1.0 and r == 0.5

    def test_auc_perfect_and_random(self):
        auc = metrics.Auc(num_thresholds=1023)
        scores = np.concatenate([np.full(50, 0.9), np.full(50, 0.1)])
        labels = np.concatenate([np.ones(50), np.zeros(50)])
        auc.update(scores, labels)
        assert auc.eval() > 0.99
        auc2 = metrics.Auc(num_thresholds=1023)
        rng = np.random.RandomState(0)
        auc2.update(rng.rand(2000), rng.randint(0, 2, 2000))
        assert 0.45 < auc2.eval() < 0.55
        auc2.reset()
        auc2.update(scores, labels)
        assert auc2.eval() > 0.99  # reset really cleared the buckets

    def test_edit_distance(self):
        m = metrics.EditDistance()
        m.update(np.array([[0.0], [2.0], [1.0]]), 3)
        avg, err = m.eval()
        assert abs(avg - 1.0) < 1e-9 and abs(err - 2 / 3) < 1e-9

    def test_chunk_evaluator(self):
        m = metrics.ChunkEvaluator()
        m.update(10, 8, 4)
        p, r, f1 = m.eval()
        assert abs(p - 0.4) < 1e-9 and abs(r - 0.5) < 1e-9
        assert abs(f1 - 2 * 0.4 * 0.5 / 0.9) < 1e-9

    def test_detection_map_perfect(self):
        m = metrics.DetectionMAP()
        # one image, one class, one perfectly-matching detection
        dets = np.array([[0, 0.9, 0.1, 0.1, 0.5, 0.5]])
        gts = np.array([[0, 0.1, 0.1, 0.5, 0.5]])
        m.update(dets, [1], gts, [1])
        assert m.eval() == pytest.approx(1.0)

    def test_detection_map_miss(self):
        m = metrics.DetectionMAP()
        dets = np.array([[0, 0.9, 0.6, 0.6, 0.9, 0.9]])  # no overlap
        gts = np.array([[0, 0.1, 0.1, 0.5, 0.5]])
        m.update(dets, [1], gts, [1])
        assert m.eval() == pytest.approx(0.0)


class TestProfiler:
    def test_record_and_summary(self, tmp_path, capsys):
        trace = str(tmp_path / "t.json")
        with profiler.profiler("CPU", sorted_key="total",
                               profile_path=trace):
            with profiler.RecordEvent("outer"):
                with profiler.RecordEvent("inner"):
                    pass
        out = capsys.readouterr().out
        assert "outer" in out and "inner" in out
        with open(trace) as f:
            data = json.load(f)
        names = {e["name"] for e in data["traceEvents"]}
        assert {"outer", "inner"} <= names

    def test_merge_process_traces(self, tmp_path):
        """Per-process traces merge into one timeline with disjoint,
        labeled per-rank lanes (≙ reference tools/timeline.py multi-
        profile_path mode)."""
        paths = []
        for r in range(3):
            p = str(tmp_path / f"trace_rank{r}.json")
            with open(p, "w") as f:
                json.dump({"traceEvents": [
                    {"name": f"step_{r}", "cat": "host", "ph": "X",
                     "ts": 10.0 * r, "dur": 5.0, "pid": 0, "tid": 1},
                    {"name": "dev", "cat": "device", "ph": "X",
                     "ts": 11.0 * r, "dur": 2.0, "pid": 1, "tid": 0},
                ]}, f)
            paths.append(p)
        out = profiler.merge_process_traces(
            paths, str(tmp_path / "merged.json"))
        with open(out) as f:
            merged = json.load(f)
        evs = merged["traceEvents"]
        pids = {e["pid"] for e in evs if e.get("ph") != "M"}
        assert pids == {0, 1, 100, 101, 200, 201}, pids
        labels = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
        assert "rank0/host" in labels and "rank2/device0" in labels, labels
        # every rank's host events survive with their names
        names = {e["name"] for e in evs}
        assert {"step_0", "step_1", "step_2"} <= names

    def test_executor_events_recorded(self, capsys):
        x = pt.layers.data("x", shape=[4], dtype="float32")
        y = pt.layers.fc(x, size=2)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        with profiler.profiler("CPU"):
            exe.run(feed={"x": np.ones((2, 4), "float32")},
                    fetch_list=[y])
        out = capsys.readouterr().out
        assert "executor/run" in out


class TestDebugger:
    def _build(self):
        x = pt.layers.data("x", shape=[4], dtype="float32")
        y = pt.layers.fc(x, size=2, act="relu")
        return x, y

    def test_pprint(self):
        self._build()
        text = pt.debugger.pprint_program_codes(pt.default_main_program())
        assert "matmul" in text or "fc" in text or "mul" in text
        assert "block 0" in text

    def test_graphviz(self, tmp_path):
        self._build()
        path = pt.debugger.draw_block_graphviz(
            pt.default_main_program().global_block(),
            str(tmp_path / "g.dot"))
        content = open(path).read()
        assert content.startswith("digraph") and "->" in content

    def test_dump_hlo(self):
        x, y = self._build()
        text = pt.debugger.dump_hlo(pt.default_main_program(),
                                    {"x": ((2, 4), "float32")},
                                    fetch_list=[y])
        assert "stablehlo" in text or "mhlo" in text or "func" in text


def _reader(n=8, batch=4, seed=0):
    def r():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield [(rng.rand(4).astype("float32"),
                    np.array([rng.randint(2)], dtype="int64"))
                   for _ in range(batch)]
    return r


def _train_func():
    x = pt.layers.data("x", shape=[4], dtype="float32")
    label = pt.layers.data("label", shape=[1], dtype="int64")
    logits = pt.layers.fc(x, size=2)
    loss = pt.layers.mean(
        pt.layers.softmax_with_cross_entropy(logits, label))
    return loss


class TestTrainer:
    def test_train_events_and_test(self):
        events = []

        def handler(ev):
            events.append(type(ev).__name__)

        t = pt.Trainer(train_func=_train_func,
                       optimizer_func=lambda:
                       pt.optimizer.SGDOptimizer(learning_rate=0.1))
        t.train(num_epochs=2, event_handler=handler, reader=_reader(),
                feed_order=["x", "label"])
        assert events.count("BeginEpochEvent") == 2
        assert events.count("EndStepEvent") == 16
        w_name = [v.name for v in
                  t.train_program.global_block().vars.values()
                  if getattr(v, "trainable", False)][0]
        before = np.asarray(t.scope.get(w_name)).copy()
        vals = t.test(reader=_reader(), feed_order=["x", "label"])
        assert np.isfinite(vals[0])
        # evaluation must not touch parameters
        np.testing.assert_array_equal(before, np.asarray(t.scope.get(w_name)))

    def test_checkpoint_save_resume(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        cfg = pt.CheckpointConfig(checkpoint_dir=ckpt_dir,
                                  max_num_checkpoints=2, step_interval=3)
        t = pt.Trainer(train_func=_train_func,
                       optimizer_func=lambda:
                       pt.optimizer.SGDOptimizer(learning_rate=0.1),
                       checkpoint_config=cfg)
        t.train(num_epochs=1, event_handler=lambda ev: None,
                reader=_reader(), feed_order=["x", "label"])
        serials = [d for d in os.listdir(ckpt_dir)
                   if d.startswith("checkpoint_")]
        assert 1 <= len(serials) <= 2  # retention enforced
        for d in serials:
            assert os.path.exists(os.path.join(ckpt_dir, d, "_SUCCESS"))

        # resume: a fresh process rebuilds the same program (names restart);
        # emulate with a fresh unique-name scope
        from paddle_tpu.core import unique_name
        pt.reset_default_programs()
        pt.reset_global_scope()
        cfg2 = pt.CheckpointConfig(checkpoint_dir=ckpt_dir)
        with unique_name.guard():
            t2 = pt.Trainer(train_func=_train_func,
                            optimizer_func=lambda:
                            pt.optimizer.SGDOptimizer(learning_rate=0.1),
                            checkpoint_config=cfg2)
        assert cfg2.load_serial is not None and cfg2.load_serial >= 0
        w_name = [v.name for v in
                  t2.train_program.global_block().vars.values()
                  if getattr(v, "trainable", False)][0]
        np.testing.assert_allclose(
            np.asarray(t2.scope.get(w_name)),
            np.asarray(t.scope.get(w_name)))
        # the first run COMPLETED num_epochs=1, so resuming train(1) must be
        # a no-op (no re-training of finished epochs)
        steps = []
        t2.train(num_epochs=1,
                 event_handler=lambda ev: steps.append(ev)
                 if isinstance(ev, pt.EndStepEvent) else None,
                 reader=_reader(), feed_order=["x", "label"])
        assert steps == []

    def test_incomplete_checkpoint_ignored(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        os.makedirs(os.path.join(ckpt_dir, "checkpoint_7"))  # no _SUCCESS
        from paddle_tpu.trainer import get_latest_checkpoint_serial
        assert get_latest_checkpoint_serial(ckpt_dir) == -1

    def test_stop(self):
        def handler(ev):
            if isinstance(ev, pt.EndStepEvent) and ev.step == 1:
                t.stop()

        t = pt.Trainer(train_func=_train_func,
                       optimizer_func=lambda:
                       pt.optimizer.SGDOptimizer(learning_rate=0.1))
        t.train(num_epochs=5, event_handler=handler, reader=_reader(),
                feed_order=["x", "label"])


def test_memory_usage_estimate(rng):
    """≙ reference contrib/memory_usage_calc.py test coverage."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.contrib import memory_usage

    x = layers.data("x", shape=[256])
    h = layers.fc(x, size=512)
    layers.fc(h, size=10)
    m = memory_usage(batch_size=32)
    # fc params: 256*512 + 512 + 512*10 + 10 floats
    expected_params = (256 * 512 + 512 + 512 * 10 + 10) * 4
    assert m["parameters"] == expected_params
    # activations scale with batch size
    m2 = memory_usage(batch_size=64)
    assert m2["activations"] > m["activations"]
    assert "state" in m["summary"]


def test_weighted_average_and_evaluator_aliases():
    """≙ reference average.py + evaluator.py surfaces."""
    import pytest as _pytest
    from paddle_tpu.average import WeightedAverage
    from paddle_tpu import evaluator

    w = WeightedAverage()
    with _pytest.raises(Exception):
        w.eval()
    w.add(1.0, weight=1)
    w.add(3.0, weight=3)
    assert abs(w.eval() - 2.5) < 1e-9
    w.reset()
    w.add(5.0)
    assert w.eval() == 5.0
    assert evaluator.ChunkEvaluator is not None


def test_get_places_lists_devices():
    from paddle_tpu.layers import get_places
    places = get_places()
    assert len(places) == 8  # the virtual CPU mesh
    assert get_places(device_count=2) == places[:2]


def test_jit_cache_flag_wires_persistent_cache(tmp_path, rng):
    """PTPU_JIT_CACHE -> jax persistent compilation cache (compiled
    executables survive restarts; the 20-40s TPU first-compiles become
    cache loads)."""
    import glob
    import jax
    from paddle_tpu.core import flags
    from paddle_tpu.framework import executor as ex

    prev = flags.get_flag("jit_cache")
    prev_cfg = jax.config.jax_compilation_cache_dir
    cache = str(tmp_path / "xla_cache")
    from paddle_tpu import layers
    try:
        flags.set_flag("jit_cache", cache)
        ex._jit_cache_configured.clear()
        x = layers.data("jcx", shape=[32])
        loss = layers.mean(layers.fc(x, size=32))
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        exe.run(feed={"jcx": np.zeros((4, 32), "float32")},
                fetch_list=[loss])
        assert jax.config.jax_compilation_cache_dir == cache
    finally:
        flags.set_flag("jit_cache", prev)
        jax.config.update("jax_compilation_cache_dir", prev_cfg)
        ex._jit_cache_configured.clear()


class TestNanGuard:
    def test_in_graph_guard_fires_on_cpu(self, rng):
        """PTPU_CHECK_NAN_INF on CPU: the per-op in-graph guard localizes
        the producing op (≙ CheckTensorNANOrInf, operator.cc:726)."""
        from paddle_tpu.core import flags
        import paddle_tpu as pt
        from paddle_tpu import layers

        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.log(x)   # log of a negative -> nan
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        old = flags.get_flag("check_nan_inf")
        flags.set_flag("check_nan_inf", True)
        try:
            with pytest.raises(Exception, match="NaN/Inf"):
                exe.run(feed={"x": np.full((2, 4), -1.0, "float32")},
                        fetch_list=[y])
        finally:
            flags.set_flag("check_nan_inf", old)

    def test_fetch_time_sweep_fires_off_cpu(self, rng, monkeypatch):
        """Off-CPU the in-graph guard cannot host-callback; the executor's
        fetch-time isfinite sweep still fails loudly, naming the bad var."""
        import jax
        from paddle_tpu.core import flags
        import paddle_tpu as pt
        from paddle_tpu import layers
        import paddle_tpu.framework.executor as exec_mod
        import paddle_tpu.framework.lowering as low_mod

        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.log(x)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        # simulate a TPU backend: both the in-graph guard (which then
        # no-ops) and the executor sweep consult jax.default_backend()
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        old = flags.get_flag("check_nan_inf")
        flags.set_flag("check_nan_inf", True)
        try:
            with pytest.raises(FloatingPointError, match="fetch-time"):
                exe.run(feed={"x": np.full((2, 4), -1.0, "float32")},
                        fetch_list=[y])
        finally:
            flags.set_flag("check_nan_inf", old)


class TestDeviceTimeline:
    def test_device_trace_merges_into_chrome_export(self, rng, tmp_path):
        """profiler(state='All', trace_dir=...) captures a device (XPlane)
        trace; RecordEvent names ride onto the device timeline as
        TraceAnnotations, and export merges host + device events into ONE
        chrome trace file (≙ device_tracer.h:49 + tools/timeline.py)."""
        import json as _json
        import paddle_tpu as pt
        from paddle_tpu import layers, profiler

        x = layers.data("x", shape=[32], dtype="float32")
        y = layers.fc(x, size=16, act="relu")
        loss = layers.reduce_mean(y)
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"x": rng.rand(8, 32).astype("float32")}

        trace_dir = str(tmp_path / "xplane")
        out_path = str(tmp_path / "timeline.json")
        with profiler.profiler(state="All", profile_path=out_path,
                               trace_dir=trace_dir):
            for _ in range(3):
                with profiler.RecordEvent("train_step"):
                    exe.run(feed=feed, fetch_list=[loss])

        with open(out_path) as f:
            trace = _json.load(f)
        evs = trace["traceEvents"]
        host = [e for e in evs if e.get("pid") == 0]
        device = [e for e in evs if e.get("pid", 0) >= 1]
        assert any(e["name"] == "train_step" for e in host)
        assert device, "device timeline missing from merged chrome trace"
        # the RecordEvent annotation is correlated onto the device side
        names = " ".join(str(e.get("name", "")) + str(e.get("args", ""))
                         for e in device)
        assert "train_step" in names
