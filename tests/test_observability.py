"""Tests: r12 unified observability layer.

- span nesting / kind typing / attribution + the PTPU_TRACE kill switch
  and the tracing overhead budget (<= 3% of step time enabled, <= 0.5%
  disabled — the ISSUE 7 acceptance bar, also committed in
  BENCH_OBS_r12.json);
- metrics registry semantics (counter/gauge/histogram) + a Prometheus
  text-format golden + the EngineServer /metrics endpoint smoked through
  EngineClient traffic;
- framework.costs.predict(): the promoted analytic models, with the
  ledger's predicted wire bytes == the HLO census EXACTLY on a dp2
  reduce-scatter run (the r08 balance through the new API) and the
  bubble model inside the r09 band;
- profiler compat: RecordEvent as a span alias, reset() isolation.
"""

import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.observability import ledger as obs_ledger
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import tracing

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


class TestTracing:
    def test_nesting_parent_depth_attrs(self):
        with tracing.span("pass", "outer", tp=2):
            with tracing.span("dp_comm", "inner", dp=4):
                pass
            with tracing.span("user", "inner2"):
                pass
        ss = tracing.spans()
        by_name = {s.name: s for s in ss}
        assert by_name["outer"].parent == "" and by_name["outer"].depth == 0
        assert by_name["inner"].parent == "outer"
        assert by_name["inner"].depth == 1
        assert by_name["inner2"].parent == "outer"
        assert by_name["outer"].attrs == {"tp": 2}
        assert by_name["inner"].attrs == {"dp": 4}
        assert by_name["inner"].kind == "dp_comm"
        # record order: inner completes before outer
        assert by_name["inner"].seq < by_name["outer"].seq

    def test_unknown_kind_rejected(self):
        with pytest.raises(Exception, match="unknown span kind"):
            tracing.span("not_a_kind", "x")

    def test_kill_switch_records_nothing(self):
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            m = tracing.mark()
            with tracing.span("user", "ghost"):
                pass
            assert tracing.spans_since(m) == []
        finally:
            flags.set_flag("trace", old)

    def test_mark_filters_window(self):
        with tracing.span("user", "before"):
            pass
        m = tracing.mark()
        with tracing.span("user", "after"):
            pass
        names = [s.name for s in tracing.spans_since(m)]
        assert names == ["after"]

    def test_aggregate_table(self):
        for _ in range(3):
            with tracing.span("tick", "t"):
                pass
        agg = tracing.aggregate()
        assert agg["t"]["calls"] == 3
        assert agg["t"]["kind"] == "tick"
        assert agg["t"]["total_ms"] >= agg["t"]["max_ms"]
        assert agg["t"]["avg_ms"] == pytest.approx(
            agg["t"]["total_ms"] / 3)

    def test_chrome_export(self, tmp_path):
        with tracing.span("pass", "p1", note="x"):
            with tracing.span("user", "u1"):
                pass
        path = tracing.export_chrome_trace(str(tmp_path / "t.json"))
        with open(path) as f:
            trace = json.load(f)
        evs = {e["name"]: e for e in trace["traceEvents"]}
        assert evs["p1"]["cat"] == "pass" and evs["p1"]["ph"] == "X"
        assert evs["u1"]["args"]["parent"] == "p1"
        assert evs["p1"]["args"]["note"] == "x"

    def test_ring_overwrites_oldest(self):
        old = flags.get_flag("trace_ring")
        flags.set_flag("trace_ring", 8)
        tracing.clear()
        try:
            for i in range(20):
                with tracing.span("user", f"s{i}"):
                    pass
            names = [s.name for s in tracing.spans()]
            assert len(names) <= 8
            assert "s19" in names and "s0" not in names
        finally:
            flags.set_flag("trace_ring", old)
            tracing.clear()

    def test_executor_records_compile_and_step_spans(self, rng):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, size=2)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        m = tracing.mark()
        exe.run(feed={"x": rng.rand(2, 4).astype("float32")},
                fetch_list=[y])
        kinds = {(s.kind, s.name) for s in tracing.spans_since(m)}
        assert ("compile", "executor/trace_and_compile") in kinds
        assert ("step", "executor/run") in kinds
        assert ("feed_fetch", "executor/feed") in kinds
        assert ("feed_fetch", "executor/state_writeback") in kinds

    def test_pass_spans_carry_pass_name(self):
        from paddle_tpu.parallel.pipeline import build_schedule
        m = tracing.mark()
        build_schedule("1f1b", 4, 2)
        ss = tracing.spans_since(m)
        assert any(s.kind == "pp_tick"
                   and s.name == "pipeline/build_schedule"
                   and s.attrs["schedule"] == "1f1b"
                   and s.attrs["microbatches"] == 4 for s in ss)


class TestOverheadBudget:
    """ISSUE 7 acceptance: tracing overhead <= 3% of step time with
    PTPU_TRACE=1 and <= 0.5% with it off. Overhead = measured per-span
    enter/exit cost x spans recorded per step, against the measured step
    time of the mnist mlp — the same arithmetic BENCH_OBS_r12.json
    commits (a direct wall-clock A/B on a 2-core CI box is noise-bound;
    the per-span microbench is stable)."""

    def _step_time_and_spans(self, rng):
        import time
        from paddle_tpu.models import mnist
        loss, acc = mnist.mlp()[:2]
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"img": rng.rand(8, 784).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}
        exe.run(feed=feed, fetch_list=[loss])   # compile
        m = tracing.mark()
        t0 = time.perf_counter()
        for _ in range(5):
            exe.run(feed=feed, fetch_list=[loss])
        step_s = (time.perf_counter() - t0) / 5
        spans_per_step = len(tracing.spans_since(m)) / 5
        return step_s, spans_per_step

    def test_overhead_within_budget_enabled_and_disabled(self, rng):
        step_s, spans_per_step = self._step_time_and_spans(rng)
        assert spans_per_step >= 3          # instrumentation is live
        on_cost = tracing.span_overhead_s()
        frac_on = on_cost * spans_per_step / step_s
        assert frac_on <= 0.03, (frac_on, on_cost, spans_per_step, step_s)
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            off_cost = tracing.span_overhead_s()
        finally:
            flags.set_flag("trace", old)
        frac_off = off_cost * spans_per_step / step_s
        assert frac_off <= 0.005, (frac_off, off_cost, spans_per_step,
                                   step_s)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_semantics(self):
        r = obs_metrics.MetricsRegistry()
        c = r.counter("ptpu_t_total")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(Exception, match="cannot decrease"):
            c.inc(-1)

    def test_gauge_semantics_and_callback(self):
        r = obs_metrics.MetricsRegistry()
        g = r.gauge("ptpu_g")
        g.set(5)
        g.inc()
        g.dec(2)
        assert g.value == 4
        box = [7]
        g2 = r.gauge("ptpu_g2", fn=lambda: box[0])
        assert g2.value == 7
        box[0] = 9
        assert g2.value == 9

    def test_histogram_buckets_and_quantiles(self):
        r = obs_metrics.MetricsRegistry()
        h = r.histogram("ptpu_h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.6, 3.0, 100.0):
            h.observe(v)
        assert h.count == 5 and h.sum == pytest.approx(106.6)
        # cumulative: le=1 -> 1, le=2 -> 3, le=4 -> 4, +Inf -> 5
        lines = h.sample_lines()
        assert 'ptpu_h_bucket{le="1"} 1' in lines
        assert 'ptpu_h_bucket{le="2"} 3' in lines
        assert 'ptpu_h_bucket{le="4"} 4' in lines
        assert 'ptpu_h_bucket{le="+Inf"} 5' in lines
        q50 = h.quantile(0.5)
        assert 1.0 <= q50 <= 2.0
        assert h.quantile(0.0) is not None
        assert obs_metrics.Histogram("ptpu_e").quantile(0.5) is None

    def test_duplicate_registration_rejected(self):
        r = obs_metrics.MetricsRegistry()
        r.counter("ptpu_dup")
        with pytest.raises(Exception, match="already registered"):
            r.gauge("ptpu_dup")

    def test_invalid_name_rejected(self):
        r = obs_metrics.MetricsRegistry()
        with pytest.raises(Exception, match="invalid metric name"):
            r.counter("0bad-name")

    def test_prometheus_text_golden(self):
        """Exact exposition-format golden: HELP/TYPE headers, sorted
        label rendering, histogram _bucket/_sum/_count family."""
        r = obs_metrics.MetricsRegistry()
        c = r.counter("ptpu_req_total", "Requests served.",
                      labels={"policy": "continuous"})
        c.inc(3)
        g = r.gauge("ptpu_depth", "Queue depth.")
        g.set(2)
        h = r.histogram("ptpu_lat_seconds", "Latency.", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        assert r.expose() == (
            "# HELP ptpu_depth Queue depth.\n"
            "# TYPE ptpu_depth gauge\n"
            "ptpu_depth 2\n"
            "# HELP ptpu_lat_seconds Latency.\n"
            "# TYPE ptpu_lat_seconds histogram\n"
            'ptpu_lat_seconds_bucket{le="0.1"} 1\n'
            'ptpu_lat_seconds_bucket{le="1"} 2\n'
            'ptpu_lat_seconds_bucket{le="+Inf"} 2\n'
            "ptpu_lat_seconds_sum 0.55\n"
            "ptpu_lat_seconds_count 2\n"
            "# HELP ptpu_req_total Requests served.\n"
            "# TYPE ptpu_req_total counter\n"
            'ptpu_req_total{policy="continuous"} 3\n')


@pytest.mark.quick
class TestEngineMetricsEndpoint:
    def test_metrics_endpoint_smoke_via_engine_client(self):
        """Drive the engine through EngineClient, then scrape /metrics:
        the serving telemetry (tokens, ticks, occupancy, latency
        quantiles, KV bytes) must reflect the traffic."""
        from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                               EngineClient, EngineServer,
                                               scrape_metrics)
        eng = ContinuousBatchingEngine(n_slots=2, vocab=50, max_len=8,
                                       d_model=16, d_inner=32, num_heads=2,
                                       num_layers=1)
        with EngineServer(eng) as srv:
            host, port = srv.address
            mhost, mport = srv.metrics_address
            with EngineClient(host, port) as c:
                tag = c.send_gen([3], max_new=4)
                got_tag, tokens, _ = c.recv_done()
                assert got_tag == tag and len(tokens) == 4
            text = scrape_metrics(mhost, mport)
        samples = {}
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            k, v = line.rsplit(" ", 1)
            samples[k] = float(v)
        assert samples["ptpu_engine_tokens_total"] == 4
        assert samples["ptpu_engine_ticks_total"] >= 4
        assert samples["ptpu_engine_requests_completed_total"] == 1
        assert samples["ptpu_engine_queue_depth"] == 0
        assert samples["ptpu_engine_kv_cache_bytes"] > 0
        assert samples["ptpu_engine_tick_latency_seconds_count"] >= 4
        assert samples["ptpu_engine_tick_latency_p50_seconds"] >= 0
        assert 0 < samples["ptpu_engine_slot_occupancy"] <= 1
        # non-/metrics paths 404
        import urllib.error
        import urllib.request
        with EngineServer(eng) as srv2:
            mh2, mp2 = srv2.metrics_address
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://{mh2}:{mp2}/other",
                                       timeout=5)

    def test_engine_tick_and_admission_spans(self):
        from paddle_tpu.serving_engine import ContinuousBatchingEngine
        eng = ContinuousBatchingEngine(n_slots=2, vocab=50, max_len=8,
                                       d_model=16, d_inner=32, num_heads=2,
                                       num_layers=1)
        m = tracing.mark()
        eng.submit([1], max_new=2)
        eng.run_until_idle()
        kinds = {s.kind for s in tracing.spans_since(m)}
        assert "tick" in kinds and "admission" in kinds


# ---------------------------------------------------------------------------
# framework.costs + ledger
# ---------------------------------------------------------------------------


def _mlp_dp2_reduce_scatter(rng):
    """dp2 ReduceScatter mlp: returns (pexe, rewritten program, loss,
    feed) after one training run (so the compiled step exists)."""
    import jax
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    bst = BuildStrategy()
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    mesh = DeviceMesh(jax.devices()[:2], {"dp": 2})
    pexe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                            mesh=mesh)
    pt.Executor().run(pt.default_startup_program())
    feed = {"x": rng.rand(16, 64).astype("float32"),
            "label": rng.randint(0, 10, (16, 1)).astype("int64")}
    pexe.run(feed=feed, fetch_list=[loss])
    prog = pexe._prepare_program(pt.default_main_program(),
                                 pt.global_scope())
    return pexe, prog, loss, feed


def _compiled_hlo(exe, feed):
    import jax.numpy as jnp
    cs = list(exe._cache.values())[-1]
    scope = pt.global_scope()
    feed_vals = tuple(jnp.asarray(feed[n]) if n in feed else scope.get(n)
                      for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    return cs.fn.lower(feed_vals, ro, rw,
                       np.uint32(0)).compile().as_text()


class TestCosts:
    def test_probe_common_reexports_framework_costs(self):
        """The r08/r09/r11 census tests import collective_census &co from
        tools/probe_common — those names must BE the framework.costs
        objects now (one model, rewired imports)."""
        import probe_common
        from paddle_tpu.framework import costs
        assert probe_common.collective_census is costs.collective_census
        assert probe_common.census_wire_bytes is costs.census_wire_bytes
        assert probe_common.hlo_shape_bytes is costs.hlo_shape_bytes
        assert probe_common.op_cost_flops_bytes is costs.op_cost_flops_bytes
        assert probe_common.HLO_ITEM_BYTES is costs.HLO_ITEM_BYTES

    def test_program_flops_bytes_sums_ops(self):
        from paddle_tpu.framework import costs
        x = layers.data("x", shape=[64])
        layers.fc(x, size=32)
        rep = costs.program_flops_bytes(pt.default_main_program(),
                                        nominal_batch=4)
        # the fc matmul alone: 2 * (4*32) * 64 flops
        assert rep["flops"] >= 2 * 4 * 32 * 64
        assert rep["bytes"] > 0 and rep["roofline_s"] > 0
        assert rep["n_ops"] >= 2

    def test_predict_plain_program_sections(self):
        from paddle_tpu.framework import costs
        x = layers.data("x", shape=[8])
        loss = layers.mean(layers.fc(x, size=4))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        rep = costs.predict(pt.default_main_program(), nominal_batch=4)
        assert rep["dp_comm"] is None and rep["pipeline"] is None
        assert rep["tp_comm"] is None
        assert rep["compute"]["flops"] > 0
        assert rep["memory"]["peak_total_bytes"] > 0

    def test_predict_spmd_dp(self):
        from paddle_tpu.framework import costs
        x = layers.data("x", shape=[8])
        loss = layers.mean(layers.fc(x, size=4))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        rep = costs.predict(pt.default_main_program(), dp=2)
        # SPMD all-reduce ring: 2n(dp-1)/dp over (8*4 + 4) f32 params
        n = (8 * 4 + 4) * 4
        assert rep["dp_comm"]["grad_wire_bytes"] == int(2.0 * n * 1 / 2)
        assert rep["dp_comm"]["explicit"] is False

    def test_ledger_wire_bytes_exact_dp2_reduce_scatter(self, rng):
        """The r08 discipline through the NEW API: predicted wire bytes
        from costs.predict == the HLO census ring total EXACTLY."""
        from paddle_tpu.framework.costs import collective_census
        pexe, prog, loss, feed = _mlp_dp2_reduce_scatter(rng)
        report = pexe.cost_report(nominal_batch=16)
        assert report["dp_comm"]["explicit"] is True
        census = collective_census(_compiled_hlo(pexe, feed))
        led = obs_ledger.CostLedger("test")
        row = led.row("mnist_dp2_rs", dp=2)
        row.set_prediction(report)
        row.set_census(census, 2, min_bytes=8)
        chk = row.check_wire_bytes_exact()
        assert chk["ok"], chk
        assert row.ok and led.ok

    def test_predict_pipeline_bubble_in_r09_band(self, rng):
        """dp-less pp2 M=4: predict()'s pipeline section must carry the
        schedule-table bubble fraction, equal to the analytic
        (K-1)/(M+K-1) (the r09 census identity) — and the ledger's band
        check at the r09 2% tolerance passes."""
        import jax
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.mesh import DeviceMesh
        from paddle_tpu.parallel.strategy import BuildStrategy

        x = layers.data("x", shape=[32])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=64, act="relu")
        h = layers.fc(h, size=64, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=10), label))
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
        bst = BuildStrategy(pipeline_stages=2, num_microbatches=4,
                            pipeline_schedule="1f1b")
        mesh = DeviceMesh(jax.devices()[:2], {"pp": 2})
        pexe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        report = pexe.cost_report(nominal_batch=16)
        pipe = report["pipeline"]
        assert pipe is not None
        assert pipe["bubble_fraction"] == pytest.approx(
            pipe["analytic_bubble_fraction"])
        assert pipe["bubble_fraction"] == pytest.approx(1 / 5)
        assert pipe["boundary"]["pp_boundary_bytes"] > 0
        led = obs_ledger.CostLedger("test")
        row = led.row("pp2_m4").set_prediction(report)
        assert row.check_bubble_fraction(
            pipe["analytic_bubble_fraction"], band=0.02)["ok"]
        # out-of-band measurement fails the check
        assert not row.check_bubble_fraction(0.5, band=0.02)["ok"]

    def test_ledger_wire_bytes_exact_dp2xpp2(self, rng):
        """The BENCH_OBS dp2 x pp2 discipline in-suite: once-per-step
        wire bytes (dp reduce-scatter/all-gather + the region's pp grad
        psum) == census exactly, and the boundary permutes reconcile
        structurally (exactly 2 at the predicted buffer bytes)."""
        import jax
        from paddle_tpu.framework.costs import collective_census
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.mesh import DeviceMesh
        from paddle_tpu.parallel.strategy import (BuildStrategy,
                                                  ReduceStrategy)

        x = layers.data("x", shape=[32])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=64, act="relu")
        h = layers.fc(h, size=64, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=10), label))
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
        bst = BuildStrategy(pipeline_stages=2, num_microbatches=4,
                            pipeline_schedule="1f1b")
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
        mesh = DeviceMesh(jax.devices()[:4], {"dp": 2, "pp": 2})
        pexe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        feed = {"x": rng.rand(16, 32).astype("float32"),
                "label": rng.randint(0, 10, (16, 1)).astype("int64")}
        pexe.run(feed=feed, fetch_list=[loss])
        report = pexe.cost_report(nominal_batch=16)
        census = collective_census(_compiled_hlo(pexe, feed))
        row = obs_ledger.CostLedger("t").row("mlp_dp2xpp2")
        row.set_prediction(report)
        row.set_census(census, 2, min_bytes=8)
        assert row.check_wire_bytes_exact()["ok"]
        assert row.check_pp_boundary()["ok"]
        assert report["pipeline"]["grad_psum_wire_bytes"] > 0

    def test_ledger_artifact_roundtrip(self, tmp_path):
        led = obs_ledger.CostLedger("r_test", meta={"host": "ci"})
        row = led.row("m1", dp=2)
        row.set_measured(step_ms=1.5)
        row.check("x", 10, 10, rel=0.0)
        path = led.write(str(tmp_path / "obs.json"))
        with open(path) as f:
            data = json.load(f)
        assert data["run"] == "r_test" and data["ok"]
        assert data["rows"][0]["measured"]["step_ms"] == 1.5
        assert data["rows"][0]["checks"][0]["ok"]

    def test_ledger_requires_inputs_before_check(self):
        row = obs_ledger.CostLedger("t").row("r")
        with pytest.raises(Exception, match="need both"):
            row.check_wire_bytes_exact()


# ---------------------------------------------------------------------------
# profiler compat over the new recorder
# ---------------------------------------------------------------------------


class TestProfilerCompat:
    def test_record_event_is_user_span(self):
        from paddle_tpu import profiler
        assert issubclass(profiler.RecordEvent, tracing.span)
        m = tracing.mark()
        with profiler.RecordEvent("anno"):
            pass
        ss = tracing.spans_since(m)
        assert ss and ss[0].kind == "user" and ss[0].name == "anno"

    def test_record_event_records_while_profiling_despite_kill_switch(self):
        """The pre-r12 contract: a profiler() context records RecordEvent
        scopes even with PTPU_TRACE=0 (force-enable window)."""
        from paddle_tpu import profiler
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            profiler.start_profiler("CPU")
            with profiler.RecordEvent("windowed"):
                pass
            m_inside = [s.name for s in tracing.spans()]
            profiler.stop_profiler()
        finally:
            flags.set_flag("trace", old)
        assert "windowed" in m_inside

    def test_reset_isolates_state(self, capsys):
        from paddle_tpu import profiler
        profiler.start_profiler("CPU")
        with profiler.RecordEvent("leaky"):
            pass
        profiler.reset()
        assert not profiler.profiler_enabled()
        # the window restarted: a fresh summary sees nothing
        profiler.print_profiler_summary()
        out = capsys.readouterr().out
        assert "no events recorded" in out
        assert "leaky" not in out
