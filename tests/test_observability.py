"""Tests: r12 unified observability layer.

- span nesting / kind typing / attribution + the PTPU_TRACE kill switch
  and the tracing overhead budget (<= 3% of step time enabled, <= 0.5%
  disabled — the ISSUE 7 acceptance bar, also committed in
  BENCH_OBS_r12.json);
- metrics registry semantics (counter/gauge/histogram) + a Prometheus
  text-format golden + the EngineServer /metrics endpoint smoked through
  EngineClient traffic;
- framework.costs.predict(): the promoted analytic models, with the
  ledger's predicted wire bytes == the HLO census EXACTLY on a dp2
  reduce-scatter run (the r08 balance through the new API) and the
  bubble model inside the r09 band;
- profiler compat: RecordEvent as a span alias, reset() isolation.
"""

import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.observability import ledger as obs_ledger
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import tracing

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


class TestTracing:
    def test_nesting_parent_depth_attrs(self):
        with tracing.span("pass", "outer", tp=2):
            with tracing.span("dp_comm", "inner", dp=4):
                pass
            with tracing.span("user", "inner2"):
                pass
        ss = tracing.spans()
        by_name = {s.name: s for s in ss}
        assert by_name["outer"].parent == "" and by_name["outer"].depth == 0
        assert by_name["inner"].parent == "outer"
        assert by_name["inner"].depth == 1
        assert by_name["inner2"].parent == "outer"
        assert by_name["outer"].attrs == {"tp": 2}
        assert by_name["inner"].attrs == {"dp": 4}
        assert by_name["inner"].kind == "dp_comm"
        # record order: inner completes before outer
        assert by_name["inner"].seq < by_name["outer"].seq

    def test_unknown_kind_rejected(self):
        with pytest.raises(Exception, match="unknown span kind"):
            tracing.span("not_a_kind", "x")

    def test_kill_switch_records_nothing(self):
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            m = tracing.mark()
            with tracing.span("user", "ghost"):
                pass
            assert tracing.spans_since(m) == []
        finally:
            flags.set_flag("trace", old)

    def test_mark_filters_window(self):
        with tracing.span("user", "before"):
            pass
        m = tracing.mark()
        with tracing.span("user", "after"):
            pass
        names = [s.name for s in tracing.spans_since(m)]
        assert names == ["after"]

    def test_aggregate_table(self):
        for _ in range(3):
            with tracing.span("tick", "t"):
                pass
        agg = tracing.aggregate()
        assert agg["t"]["calls"] == 3
        assert agg["t"]["kind"] == "tick"
        assert agg["t"]["total_ms"] >= agg["t"]["max_ms"]
        assert agg["t"]["avg_ms"] == pytest.approx(
            agg["t"]["total_ms"] / 3)

    def test_chrome_export(self, tmp_path):
        with tracing.span("pass", "p1", note="x"):
            with tracing.span("user", "u1"):
                pass
        path = tracing.export_chrome_trace(str(tmp_path / "t.json"))
        with open(path) as f:
            trace = json.load(f)
        evs = {e["name"]: e for e in trace["traceEvents"]}
        assert evs["p1"]["cat"] == "pass" and evs["p1"]["ph"] == "X"
        assert evs["u1"]["args"]["parent"] == "p1"
        assert evs["p1"]["args"]["note"] == "x"

    def test_ring_overwrites_oldest(self):
        old = flags.get_flag("trace_ring")
        flags.set_flag("trace_ring", 8)
        tracing.clear()
        try:
            for i in range(20):
                with tracing.span("user", f"s{i}"):
                    pass
            names = [s.name for s in tracing.spans()]
            assert len(names) <= 8
            assert "s19" in names and "s0" not in names
        finally:
            flags.set_flag("trace_ring", old)
            tracing.clear()

    def test_executor_records_compile_and_step_spans(self, rng):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, size=2)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        m = tracing.mark()
        exe.run(feed={"x": rng.rand(2, 4).astype("float32")},
                fetch_list=[y])
        kinds = {(s.kind, s.name) for s in tracing.spans_since(m)}
        assert ("compile", "executor/trace_and_compile") in kinds
        assert ("step", "executor/run") in kinds
        assert ("feed_fetch", "executor/feed") in kinds
        assert ("feed_fetch", "executor/state_writeback") in kinds

    def test_pass_spans_carry_pass_name(self):
        from paddle_tpu.parallel.pipeline import build_schedule
        m = tracing.mark()
        build_schedule("1f1b", 4, 2)
        ss = tracing.spans_since(m)
        assert any(s.kind == "pp_tick"
                   and s.name == "pipeline/build_schedule"
                   and s.attrs["schedule"] == "1f1b"
                   and s.attrs["microbatches"] == 4 for s in ss)


class TestTraceRingEnv:
    """ISSUE 12 satellite: a bad PTPU_TRACE_RING value must surface as a
    clear enforce error naming the variable and the accepted range, not
    a bare ValueError deep in _ensure_ring — one test per branch."""

    def test_non_integer_rejected_with_clear_error(self):
        old = flags.get_flag("trace_ring")
        flags.set_flag("trace_ring", "not-a-number")
        try:
            with pytest.raises(Exception) as ei:
                tracing.mark()
            assert "PTPU_TRACE_RING" in str(ei.value)
            assert "positive integer" in str(ei.value)
        finally:
            flags.set_flag("trace_ring", old)

    def test_zero_rejected(self):
        old = flags.get_flag("trace_ring")
        flags.set_flag("trace_ring", 0)
        try:
            with pytest.raises(Exception) as ei:
                with tracing.span("user", "x"):
                    pass
            assert "PTPU_TRACE_RING" in str(ei.value)
            assert ">= 1" in str(ei.value)
        finally:
            flags.set_flag("trace_ring", old)

    def test_negative_rejected(self):
        old = flags.get_flag("trace_ring")
        flags.set_flag("trace_ring", -8)
        try:
            with pytest.raises(Exception, match="PTPU_TRACE_RING"):
                tracing.mark()
        finally:
            flags.set_flag("trace_ring", old)

    def test_valid_string_value_accepted(self):
        """set_flag with a numeric string (the env-var shape) works."""
        old = flags.get_flag("trace_ring")
        flags.set_flag("trace_ring", "16")
        tracing.clear()
        try:
            with tracing.span("user", "ok"):
                pass
            assert [s.name for s in tracing.spans()] == ["ok"]
        finally:
            flags.set_flag("trace_ring", old)
            tracing.clear()


class TestDistributedTracing:
    """r16 tentpole (a): rank-tagged span streams + the merged
    cross-rank timeline."""

    def test_rank_scope_tags_every_span(self):
        with tracing.rank_scope("w7", 3, 8):
            with tracing.span("user", "inner"):
                pass
        s = [x for x in tracing.spans() if x.name == "inner"][0]
        assert s.attrs == {"world": "w7", "rank": 3, "world_size": 8}

    def test_span_attrs_win_over_thread_tags_and_scopes_nest(self):
        with tracing.scoped_tags(rank=1, color="red"):
            with tracing.scoped_tags(rank=2):
                with tracing.span("user", "a", color="blue"):
                    pass
            with tracing.span("user", "b"):
                pass
        by = {s.name: s.attrs for s in tracing.spans()}
        assert by["a"] == {"rank": 2, "color": "blue"}
        assert by["b"] == {"rank": 1, "color": "red"}
        assert tracing.current_tags() == {}

    def test_record_span_retroactive(self):
        s = tracing.record_span("request", "retro", 10.0, 10.5, rid="r1")
        assert s.duration_ms == pytest.approx(500.0)
        got = [x for x in tracing.spans() if x.name == "retro"][0]
        assert got.attrs == {"rid": "r1"}
        with pytest.raises(Exception, match="unknown span kind"):
            tracing.record_span("nope", "x", 0.0, 1.0)

    def test_record_span_disabled_returns_none(self):
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            assert tracing.record_span("user", "ghost", 0.0, 1.0) is None
        finally:
            flags.set_flag("trace", old)

    def test_ring_wrap_under_concurrent_rank_writers(self):
        """ISSUE 12 satellite: N rank threads recording through a wrap
        must keep per-rank attribution intact — every surviving span's
        rank tag matches the identity encoded in its name."""
        import threading
        old = flags.get_flag("trace_ring")
        flags.set_flag("trace_ring", 32)
        tracing.clear()
        try:
            n_ranks, per_rank = 4, 50   # 200 spans >> 32 slots: wraps

            def writer(r):
                with tracing.rank_scope("wrap", r, n_ranks):
                    for i in range(per_rank):
                        with tracing.span("user", f"r{r}-i{i}"):
                            pass

            ts = [threading.Thread(target=writer, args=(r,))
                  for r in range(n_ranks)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            survivors = tracing.spans()
            assert 0 < len(survivors) <= 32
            for s in survivors:
                want_rank = int(s.name[1:s.name.index("-")])
                assert s.attrs["rank"] == want_rank, (s.name, s.attrs)
                assert s.attrs["world"] == "wrap"
                assert s.attrs["world_size"] == n_ranks
        finally:
            flags.set_flag("trace_ring", old)
            tracing.clear()

    def test_trace_merge_rank_lanes_and_alignment(self, tmp_path):
        """tools/trace_merge.py: rank-tagged spans land on rank pids
        with process_name metadata; phase-family spans get named tid
        lanes; per-input clocks align on the --align-span event."""
        import trace_merge

        for r in (0, 1):
            with tracing.rank_scope("wm", r, 2):
                tracing.record_span("checkpoint", "barrier/stage",
                                    1.0 + r, 1.2 + r, serial=5)
                tracing.record_span("checkpoint", "barrier/ack",
                                    1.2 + r, 1.3 + r, serial=5)
        with tracing.span("user", "host_side"):
            pass
        path = str(tmp_path / "t.json")
        tracing.export_chrome_trace(path)
        merged = trace_merge.merge([path])
        evs = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
        meta = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
        assert {e["pid"] for e in evs
                if str(e["name"]).startswith("barrier/")} == {0, 1}
        pnames = {e["pid"]: e["args"]["name"] for e in meta
                  if e["name"] == "process_name"}
        assert pnames[0].startswith("rank 0")
        assert pnames[1].startswith("rank 1")
        assert 999 in pnames            # untagged host lane
        tnames = {e["args"]["name"] for e in meta
                  if e["name"] == "thread_name"}
        assert "barrier/stage" in tnames and "barrier/ack" in tnames
        # alignment: each input shifts its first barrier/stage to t=0
        stage_ts = [e["ts"] for e in evs if e["name"] == "barrier/stage"]
        assert min(stage_ts) == pytest.approx(0.0)


class TestOverheadBudget:
    """ISSUE 7 acceptance: tracing overhead <= 3% of step time with
    PTPU_TRACE=1 and <= 0.5% with it off. Overhead = measured per-span
    enter/exit cost x spans recorded per step, against the measured step
    time of the mnist mlp — the same arithmetic BENCH_OBS_r12.json
    commits (a direct wall-clock A/B on a 2-core CI box is noise-bound;
    the per-span microbench is stable)."""

    def _step_time_and_spans(self, rng):
        import time
        from paddle_tpu.models import mnist
        loss, acc = mnist.mlp()[:2]
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"img": rng.rand(8, 784).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}
        exe.run(feed=feed, fetch_list=[loss])   # compile
        m = tracing.mark()
        t0 = time.perf_counter()
        for _ in range(5):
            exe.run(feed=feed, fetch_list=[loss])
        step_s = (time.perf_counter() - t0) / 5
        spans_per_step = len(tracing.spans_since(m)) / 5
        return step_s, spans_per_step

    def test_overhead_within_budget_enabled_and_disabled(self, rng):
        step_s, spans_per_step = self._step_time_and_spans(rng)
        assert spans_per_step >= 3          # instrumentation is live
        on_cost = tracing.span_overhead_s()
        frac_on = on_cost * spans_per_step / step_s
        assert frac_on <= 0.03, (frac_on, on_cost, spans_per_step, step_s)
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            off_cost = tracing.span_overhead_s()
        finally:
            flags.set_flag("trace", old)
        frac_off = off_cost * spans_per_step / step_s
        assert frac_off <= 0.005, (frac_off, off_cost, spans_per_step,
                                   step_s)

    def test_overhead_budget_holds_with_rank_tagging_on(self, rng):
        """r16 acceptance: the budget must still hold with the
        distributed-tracing tag merge on the record path — measure the
        per-span cost INSIDE a rank scope (every span pays the
        {world, rank, world_size} dict merge) against the same step."""
        step_s, spans_per_step = self._step_time_and_spans(rng)
        with tracing.rank_scope("budget", 0, 4):
            tagged_cost = tracing.span_overhead_s()
        frac_on = tagged_cost * spans_per_step / step_s
        assert frac_on <= 0.03, (frac_on, tagged_cost, spans_per_step,
                                 step_s)
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            with tracing.rank_scope("budget", 0, 4):
                off_cost = tracing.span_overhead_s()
        finally:
            flags.set_flag("trace", old)
        frac_off = off_cost * spans_per_step / step_s
        assert frac_off <= 0.005, (frac_off, off_cost, spans_per_step,
                                   step_s)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_semantics(self):
        r = obs_metrics.MetricsRegistry()
        c = r.counter("ptpu_t_total")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(Exception, match="cannot decrease"):
            c.inc(-1)

    def test_gauge_semantics_and_callback(self):
        r = obs_metrics.MetricsRegistry()
        g = r.gauge("ptpu_g")
        g.set(5)
        g.inc()
        g.dec(2)
        assert g.value == 4
        box = [7]
        g2 = r.gauge("ptpu_g2", fn=lambda: box[0])
        assert g2.value == 7
        box[0] = 9
        assert g2.value == 9

    def test_histogram_buckets_and_quantiles(self):
        r = obs_metrics.MetricsRegistry()
        h = r.histogram("ptpu_h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.6, 3.0, 100.0):
            h.observe(v)
        assert h.count == 5 and h.sum == pytest.approx(106.6)
        # cumulative: le=1 -> 1, le=2 -> 3, le=4 -> 4, +Inf -> 5
        lines = h.sample_lines()
        assert 'ptpu_h_bucket{le="1"} 1' in lines
        assert 'ptpu_h_bucket{le="2"} 3' in lines
        assert 'ptpu_h_bucket{le="4"} 4' in lines
        assert 'ptpu_h_bucket{le="+Inf"} 5' in lines
        q50 = h.quantile(0.5)
        assert 1.0 <= q50 <= 2.0
        assert h.quantile(0.0) is not None
        assert obs_metrics.Histogram("ptpu_e").quantile(0.5) is None

    def test_duplicate_registration_rejected(self):
        r = obs_metrics.MetricsRegistry()
        r.counter("ptpu_dup")
        with pytest.raises(Exception, match="already registered"):
            r.gauge("ptpu_dup")

    def test_invalid_name_rejected(self):
        r = obs_metrics.MetricsRegistry()
        with pytest.raises(Exception, match="invalid metric name"):
            r.counter("0bad-name")

    def test_prometheus_text_golden(self):
        """Exact exposition-format golden: HELP/TYPE headers, sorted
        label rendering, histogram _bucket/_sum/_count family."""
        r = obs_metrics.MetricsRegistry()
        c = r.counter("ptpu_req_total", "Requests served.",
                      labels={"policy": "continuous"})
        c.inc(3)
        g = r.gauge("ptpu_depth", "Queue depth.")
        g.set(2)
        h = r.histogram("ptpu_lat_seconds", "Latency.", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        assert r.expose() == (
            "# HELP ptpu_depth Queue depth.\n"
            "# TYPE ptpu_depth gauge\n"
            "ptpu_depth 2\n"
            "# HELP ptpu_lat_seconds Latency.\n"
            "# TYPE ptpu_lat_seconds histogram\n"
            'ptpu_lat_seconds_bucket{le="0.1"} 1\n'
            'ptpu_lat_seconds_bucket{le="1"} 2\n'
            'ptpu_lat_seconds_bucket{le="+Inf"} 2\n'
            "ptpu_lat_seconds_sum 0.55\n"
            "ptpu_lat_seconds_count 2\n"
            "# HELP ptpu_req_total Requests served.\n"
            "# TYPE ptpu_req_total counter\n"
            'ptpu_req_total{policy="continuous"} 3\n')


@pytest.mark.quick
class TestEngineMetricsEndpoint:
    def test_metrics_endpoint_smoke_via_engine_client(self):
        """Drive the engine through EngineClient, then scrape /metrics:
        the serving telemetry (tokens, ticks, occupancy, latency
        quantiles, KV bytes) must reflect the traffic."""
        from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                               EngineClient, EngineServer,
                                               scrape_metrics)
        eng = ContinuousBatchingEngine(n_slots=2, vocab=50, max_len=8,
                                       d_model=16, d_inner=32, num_heads=2,
                                       num_layers=1)
        with EngineServer(eng) as srv:
            host, port = srv.address
            mhost, mport = srv.metrics_address
            with EngineClient(host, port) as c:
                tag = c.send_gen([3], max_new=4)
                got_tag, tokens, _ = c.recv_done()
                assert got_tag == tag and len(tokens) == 4
            text = scrape_metrics(mhost, mport)
        samples = {}
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            k, v = line.rsplit(" ", 1)
            samples[k] = float(v)
        assert samples["ptpu_engine_tokens_total"] == 4
        assert samples["ptpu_engine_ticks_total"] >= 4
        assert samples["ptpu_engine_requests_completed_total"] == 1
        assert samples["ptpu_engine_queue_depth"] == 0
        assert samples["ptpu_engine_kv_cache_bytes"] > 0
        assert samples["ptpu_engine_tick_latency_seconds_count"] >= 4
        assert samples["ptpu_engine_tick_latency_p50_seconds"] >= 0
        assert 0 < samples["ptpu_engine_slot_occupancy"] <= 1
        # non-/metrics paths 404
        import urllib.error
        import urllib.request
        with EngineServer(eng) as srv2:
            mh2, mp2 = srv2.metrics_address
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://{mh2}:{mp2}/other",
                                       timeout=5)

    def test_engine_tick_and_admission_spans(self):
        from paddle_tpu.serving_engine import ContinuousBatchingEngine
        eng = ContinuousBatchingEngine(n_slots=2, vocab=50, max_len=8,
                                       d_model=16, d_inner=32, num_heads=2,
                                       num_layers=1)
        m = tracing.mark()
        eng.submit([1], max_new=2)
        eng.run_until_idle()
        kinds = {s.kind for s in tracing.spans_since(m)}
        assert "tick" in kinds and "admission" in kinds


@pytest.mark.quick
class TestRequestDecomposition:
    """r16 tentpole (c): a request_id threads submit → admission → every
    tick it rides → completion, with a queue/prefill/decode/transport
    decomposition that partitions the measured e2e latency exactly."""

    def _engine(self, n_slots=1):
        from paddle_tpu.serving_engine import ContinuousBatchingEngine
        return ContinuousBatchingEngine(
            n_slots=n_slots, vocab=50, max_len=8, d_model=16,
            d_inner=32, num_heads=2, num_layers=1)

    def test_request_id_threads_through_spans_and_ticks(self):
        eng = self._engine()
        m = tracing.mark()
        eng.submit([1, 2], max_new=2, request_id="rid-42")
        eng.run_until_idle()
        ss = tracing.spans_since(m)
        names = {s.name for s in ss
                 if s.attrs.get("request_id") == "rid-42"}
        assert {"request/queue_wait", "request/prefill",
                "request/decode"} <= names, names
        ticks = [s for s in ss if s.name == "engine/tick"]
        assert ticks and all("rid-42" in s.attrs["request_ids"]
                             for s in ticks)

    def test_phases_partition_e2e_direct_engine(self):
        """No server: transport is 0 and the three engine-side phases
        sum to done-submitted exactly (same clock, shared boundaries)."""
        eng = self._engine(n_slots=1)
        # second request MUST queue behind the first on the single slot
        r1 = eng.submit([1, 2, 3], max_new=3)
        r2 = eng.submit([4], max_new=2)
        eng.run_until_idle()
        for req in (r1, r2):
            ph = req.phases()
            assert set(ph) == {"queue_wait", "prefill", "decode",
                               "transport"}
            assert ph["transport"] == 0.0
            assert sum(ph.values()) == pytest.approx(req.e2e_s(),
                                                     rel=1e-9)
        assert r2.phases()["queue_wait"] > r1.phases()["queue_wait"]
        assert list(eng.completed_log)[-2:] == [r1, r2] or \
            list(eng.completed_log)[-2:] == [r2, r1]

    def test_latency_histograms_labeled_per_phase(self):
        eng = self._engine()
        done = []
        # a direct caller WITH on_done (no server): transport/e2e must
        # still close at completion — only a server that will report
        # the frame sent (defer_transport=True) defers them
        eng.submit([1], max_new=2, on_done=done.append)
        eng.run_until_idle()
        assert done
        r = eng.metrics_registry
        for phase in ("queue_wait", "prefill", "decode", "transport"):
            h = r.get("ptpu_request_latency_seconds", {"phase": phase})
            assert h is not None and h.count >= 1, phase
        e2e = r.get("ptpu_request_e2e_seconds")
        assert e2e.count >= 1
        # conservation at the histogram level too: sums of the phase
        # series equal the e2e series sum (transport included)
        total = sum(
            r.get("ptpu_request_latency_seconds", {"phase": p}).sum
            for p in ("queue_wait", "prefill", "decode", "transport"))
        assert total == pytest.approx(e2e.sum, rel=1e-6)

    def test_server_transport_closes_the_decomposition(self):
        """Through the RPC server the transport phase is real (writer
        on_sent) and the four phases still sum to e2e within the 5%
        acceptance band (exact up to callback scheduling)."""
        import time as _time
        from paddle_tpu.serving_engine import (EngineClient, EngineServer)
        eng = self._engine(n_slots=2)
        with EngineServer(eng) as srv:
            host, port = srv.address
            with EngineClient(host, port) as c:
                c.send_gen([3], max_new=3, request_id="srv-req")
                c.recv_done()
            deadline = _time.time() + 5
            while _time.time() < deadline and (
                    not eng.completed_log
                    or eng.completed_log[-1].sent_pc is None):
                _time.sleep(0.01)
        req = list(eng.completed_log)[-1]
        assert req.request_id == "srv-req" and req.sent_pc is not None
        ph, e2e = req.phases(), req.e2e_s()
        assert ph["transport"] > 0.0
        assert abs(sum(ph.values()) - e2e) / e2e <= 0.05, (ph, e2e)


@pytest.mark.quick
class TestHealthz:
    """r16 tentpole (d): the structured /healthz surface on the metrics
    listener — the autoscaling control loop's signal."""

    def test_healthz_document_and_drain_503(self, monkeypatch):
        import urllib.request
        from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                               EngineServer,
                                               scrape_healthz)
        monkeypatch.setenv("PTPU_SUPERVISOR_RESTARTS", "3")
        eng = ContinuousBatchingEngine(n_slots=2, vocab=50, max_len=8,
                                       d_model=16, d_inner=32,
                                       num_heads=2, num_layers=1)
        eng.submit([1], max_new=2)
        eng.run_until_idle()
        with EngineServer(eng) as srv:
            mh, mp = srv.metrics_address
            h = scrape_healthz(mh, mp)
            assert h["status"] == "serving"
            assert h["engine"]["n_slots"] == 2
            assert h["engine"]["ticks"] >= 2
            assert h["engine"]["last_tick_age_s"] >= 0
            assert h["checkpoints"]["pending_async"] == 0
            assert h["supervisor"]["restarts"] == 3
            # plain /metrics still served from the same listener
            with urllib.request.urlopen(
                    f"http://{mh}:{mp}/metrics", timeout=5) as resp:
                assert resp.status == 200
            # draining flips the status and the HTTP code to 503 (the
            # load balancer's stop-routing signal); scrape_healthz
            # still returns the body
            srv._draining.set()
            h2 = scrape_healthz(mh, mp)
            assert h2["status"] == "draining"
            import urllib.error
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://{mh}:{mp}/healthz",
                                       timeout=5)

    def test_single_scrape_sees_ckpt_train_and_engine_series(self):
        """ISSUE 12 satellite: ONE /metrics scrape carries checkpoint
        (ptpu_ckpt_*), training (ptpu_train_*), and serving
        (ptpu_engine_*) series — the per-module registries are joined
        through default_registry()."""
        from paddle_tpu import trainer as _trainer
        from paddle_tpu.parallel import elastic
        from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                               EngineServer,
                                               scrape_metrics)
        assert elastic.metrics_registry() is obs_metrics.default_registry()
        tm = _trainer.training_metrics()
        assert (obs_metrics.default_registry()
                .get("ptpu_train_steps_total") is tm["steps"])
        eng = ContinuousBatchingEngine(n_slots=2, vocab=50, max_len=8,
                                       d_model=16, d_inner=32,
                                       num_heads=2, num_layers=1)
        with EngineServer(eng) as srv:
            text = scrape_metrics(*srv.metrics_address)
        assert "ptpu_engine_ticks_total" in text
        assert "ptpu_ckpt_saves_total" in text
        assert "ptpu_ckpt_barrier_aborts_total" in text
        assert "ptpu_train_steps_total" in text

    def test_multiregistry_union_and_lookup(self):
        a, b = obs_metrics.MetricsRegistry(), obs_metrics.MetricsRegistry()
        a.counter("ptpu_a_total").inc(2)
        b.gauge("ptpu_b").set(5)
        multi = obs_metrics.MultiRegistry([a, b])
        assert multi.get("ptpu_a_total").value == 2
        assert multi.get("ptpu_b").value == 5
        text = multi.expose()
        assert "ptpu_a_total 2" in text and "ptpu_b 5" in text


class TestFlightRecorder:
    """r16 tentpole (b), unit level: beacons, dossiers, post-mortems.
    (The real-SIGKILL integration lives in tests/test_process_world.py.)"""

    def test_disabled_by_default_and_state_board(self):
        from paddle_tpu.observability import flight_recorder as fr
        assert not fr.enabled()
        assert fr.dump_dossier("nothing to write") is None
        fr.set_state("engine", draining=False, ticks=3)
        fr.set_state("engine", ticks=4)
        assert fr.state_board()["engine"] == {"draining": False,
                                              "ticks": 4}
        fr.clear_state("engine")
        assert "engine" not in fr.state_board()

    def test_dossier_carries_spans_metrics_and_state(self, tmp_path):
        from paddle_tpu.observability import flight_recorder as fr
        fr.configure(str(tmp_path), world_id="wd")
        with tracing.span("user", "before_death"):
            pass
        fr.set_state("barrier", serial=9, phase="stage")
        path = fr.dump_dossier("unit test", rank=1,
                               exc=ValueError("boom"))
        doc = json.load(open(path))
        assert doc["reason"] == "unit test" and doc["rank"] == 1
        assert doc["exception"] == "ValueError: boom"
        assert doc["state"]["barrier"]["serial"] == 9
        assert any(s["name"] == "before_death" for s in doc["spans"])
        assert "default" in doc["metrics"]
        assert fr.collect_dossiers(str(tmp_path))[0]["reason"] == \
            "unit test"

    def test_beacons_survive_and_name_the_crashing_rank(self, tmp_path):
        from paddle_tpu.observability import flight_recorder as fr
        fr.configure(str(tmp_path), world_id="wb")
        for r in range(3):
            fr.note_phase("barrier", "stage", rank=r, serial=4)
        fr.note_phase("barrier", "ack", rank=0, serial=4)
        fr.note_phase("barrier", "ack", rank=2, serial=4)
        fr.note_phase("barrier", "ack", rank=1, serial=4,
                      crashing=True)
        verdict = fr.analyze(str(tmp_path))
        assert verdict["dead_rank"] == 1
        assert verdict["dead_phase"] == "ack"
        assert verdict["serial"] == 4
        assert verdict["cause"] == "crash_rank SIGKILL"
        assert set(verdict["timeline"]) == {"0", "1", "2"}
        pm = fr.write_post_mortem(str(tmp_path), incarnation=2)
        doc = json.load(open(pm))
        assert doc["incarnation"] == 2 and doc["dead_rank"] == 1

    def test_least_advanced_heuristic_without_markers(self, tmp_path):
        """Unplanned death (no fault directive announced itself): the
        rank that stopped beaconing first is named, with the heuristic
        cause spelled out."""
        import time as _time
        from paddle_tpu.observability import flight_recorder as fr
        fr.configure(str(tmp_path))
        fr.note_phase("barrier", "stage", rank=0, serial=1)
        fr.note_phase("barrier", "stage", rank=1, serial=1)
        _time.sleep(0.01)
        fr.note_phase("barrier", "ack", rank=0, serial=1)
        verdict = fr.analyze(str(tmp_path))
        assert verdict["dead_rank"] == 1
        assert verdict["dead_phase"] == "stage"
        assert "heuristic" in verdict["cause"]
        assert verdict["straggler_order"][0] == 1

    def test_configure_none_pins_disabled_despite_env(
            self, tmp_path, monkeypatch):
        """configure(None) means OFF — no silent re-enable through a
        leaked PTPU_DOSSIER_DIR; only a never-configured process (a
        supervised child) inherits the env var."""
        from paddle_tpu.observability import flight_recorder as fr
        monkeypatch.setenv("PTPU_DOSSIER_DIR", str(tmp_path))
        fr.configure(None)
        assert not fr.enabled()
        fr.note_phase("barrier", "stage", rank=0)
        assert not any(n.startswith(fr.BEACON_PREFIX)
                       for n in os.listdir(tmp_path))
        # the pristine (never-configured) state DOES inherit the env
        fr._configured = False
        assert fr.dossier_dir() == str(tmp_path)

    def test_dead_writer_still_closes_transport(self, tmp_path):
        """A client that disconnects before reading its completion must
        not leave the transport/e2e series lagging: the writer fires
        pending on_sent callbacks on its death path."""
        import time as _time
        from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                               EngineClient, EngineServer)
        eng = ContinuousBatchingEngine(n_slots=1, vocab=50, max_len=8,
                                       d_model=16, d_inner=32,
                                       num_heads=2, num_layers=1)
        with EngineServer(eng) as srv:
            host, port = srv.address
            c = EngineClient(host, port)
            c.send_gen([3], max_new=2, request_id="goner")
            c.close()                       # gone before the done frame
            deadline = _time.time() + 10
            while _time.time() < deadline and not any(
                    r.request_id == "goner" and r.sent_pc is not None
                    for r in eng.completed_log):
                _time.sleep(0.02)
        req = [r for r in eng.completed_log
               if r.request_id == "goner"][0]
        assert req.sent_pc is not None      # closed: sent or died trying
        e2e = eng.metrics_registry.get("ptpu_request_e2e_seconds")
        tr = eng.metrics_registry.get("ptpu_request_latency_seconds",
                                      {"phase": "transport"})
        assert e2e.count == 1 and tr.count == 1

    def test_rank_drop_dumps_a_dossier(self, tmp_path, monkeypatch):
        """A simulated rank death (drop_rank) is a death the process CAN
        see: ProcessWorld.run dumps a dossier naming the rank+phase."""
        from paddle_tpu.observability import flight_recorder as fr
        from paddle_tpu.parallel.process_world import ProcessWorld
        fr.configure(str(tmp_path))
        monkeypatch.setenv("PTPU_FAULT_INJECT", "drop_rank:1@ack")
        world = ProcessWorld(2)

        def fn(rank):
            world.fault(rank, "ack")
            return rank

        out = world.run(fn)
        assert out == [0, None] and world.dead == {1}
        dossiers = fr.collect_dossiers(str(tmp_path))
        assert any("rank 1 dropped" in d["reason"] for d in dossiers)
        verdict = fr.analyze(str(tmp_path))
        assert verdict["dead_rank"] == 1 and verdict["dead_phase"] == "ack"
        assert verdict["cause"] == "drop_rank simulated death"


# ---------------------------------------------------------------------------
# framework.costs + ledger
# ---------------------------------------------------------------------------


def _mlp_dp2_reduce_scatter(rng):
    """dp2 ReduceScatter mlp: returns (pexe, rewritten program, loss,
    feed) after one training run (so the compiled step exists)."""
    import jax
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    bst = BuildStrategy()
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    mesh = DeviceMesh(jax.devices()[:2], {"dp": 2})
    pexe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                            mesh=mesh)
    pt.Executor().run(pt.default_startup_program())
    feed = {"x": rng.rand(16, 64).astype("float32"),
            "label": rng.randint(0, 10, (16, 1)).astype("int64")}
    pexe.run(feed=feed, fetch_list=[loss])
    prog = pexe._prepare_program(pt.default_main_program(),
                                 pt.global_scope())
    return pexe, prog, loss, feed


def _compiled_hlo(exe, feed):
    import jax.numpy as jnp
    cs = list(exe._cache.values())[-1]
    scope = pt.global_scope()
    feed_vals = tuple(jnp.asarray(feed[n]) if n in feed else scope.get(n)
                      for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    return cs.fn.lower(feed_vals, ro, rw,
                       np.uint32(0)).compile().as_text()


class TestCosts:
    def test_probe_common_reexports_framework_costs(self):
        """The r08/r09/r11 census tests import collective_census &co from
        tools/probe_common — those names must BE the framework.costs
        objects now (one model, rewired imports)."""
        import probe_common
        from paddle_tpu.framework import costs
        assert probe_common.collective_census is costs.collective_census
        assert probe_common.census_wire_bytes is costs.census_wire_bytes
        assert probe_common.hlo_shape_bytes is costs.hlo_shape_bytes
        assert probe_common.op_cost_flops_bytes is costs.op_cost_flops_bytes
        assert probe_common.HLO_ITEM_BYTES is costs.HLO_ITEM_BYTES

    def test_program_flops_bytes_sums_ops(self):
        from paddle_tpu.framework import costs
        x = layers.data("x", shape=[64])
        layers.fc(x, size=32)
        rep = costs.program_flops_bytes(pt.default_main_program(),
                                        nominal_batch=4)
        # the fc matmul alone: 2 * (4*32) * 64 flops
        assert rep["flops"] >= 2 * 4 * 32 * 64
        assert rep["bytes"] > 0 and rep["roofline_s"] > 0
        assert rep["n_ops"] >= 2

    def test_predict_plain_program_sections(self):
        from paddle_tpu.framework import costs
        x = layers.data("x", shape=[8])
        loss = layers.mean(layers.fc(x, size=4))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        rep = costs.predict(pt.default_main_program(), nominal_batch=4)
        assert rep["dp_comm"] is None and rep["pipeline"] is None
        assert rep["tp_comm"] is None
        assert rep["compute"]["flops"] > 0
        assert rep["memory"]["peak_total_bytes"] > 0

    def test_predict_spmd_dp(self):
        from paddle_tpu.framework import costs
        x = layers.data("x", shape=[8])
        loss = layers.mean(layers.fc(x, size=4))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        rep = costs.predict(pt.default_main_program(), dp=2)
        # SPMD all-reduce ring: 2n(dp-1)/dp over (8*4 + 4) f32 params
        n = (8 * 4 + 4) * 4
        assert rep["dp_comm"]["grad_wire_bytes"] == int(2.0 * n * 1 / 2)
        assert rep["dp_comm"]["explicit"] is False

    def test_ledger_wire_bytes_exact_dp2_reduce_scatter(self, rng):
        """The r08 discipline through the NEW API: predicted wire bytes
        from costs.predict == the HLO census ring total EXACTLY."""
        from paddle_tpu.framework.costs import collective_census
        pexe, prog, loss, feed = _mlp_dp2_reduce_scatter(rng)
        report = pexe.cost_report(nominal_batch=16)
        assert report["dp_comm"]["explicit"] is True
        census = collective_census(_compiled_hlo(pexe, feed))
        led = obs_ledger.CostLedger("test")
        row = led.row("mnist_dp2_rs", dp=2)
        row.set_prediction(report)
        row.set_census(census, 2, min_bytes=8)
        chk = row.check_wire_bytes_exact()
        assert chk["ok"], chk
        assert row.ok and led.ok

    def test_predict_pipeline_bubble_in_r09_band(self, rng):
        """dp-less pp2 M=4: predict()'s pipeline section must carry the
        schedule-table bubble fraction, equal to the analytic
        (K-1)/(M+K-1) (the r09 census identity) — and the ledger's band
        check at the r09 2% tolerance passes."""
        import jax
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.mesh import DeviceMesh
        from paddle_tpu.parallel.strategy import BuildStrategy

        x = layers.data("x", shape=[32])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=64, act="relu")
        h = layers.fc(h, size=64, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=10), label))
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
        bst = BuildStrategy(pipeline_stages=2, num_microbatches=4,
                            pipeline_schedule="1f1b")
        mesh = DeviceMesh(jax.devices()[:2], {"pp": 2})
        pexe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        report = pexe.cost_report(nominal_batch=16)
        pipe = report["pipeline"]
        assert pipe is not None
        assert pipe["bubble_fraction"] == pytest.approx(
            pipe["analytic_bubble_fraction"])
        assert pipe["bubble_fraction"] == pytest.approx(1 / 5)
        assert pipe["boundary"]["pp_boundary_bytes"] > 0
        led = obs_ledger.CostLedger("test")
        row = led.row("pp2_m4").set_prediction(report)
        assert row.check_bubble_fraction(
            pipe["analytic_bubble_fraction"], band=0.02)["ok"]
        # out-of-band measurement fails the check
        assert not row.check_bubble_fraction(0.5, band=0.02)["ok"]

    def test_ledger_wire_bytes_exact_dp2xpp2(self, rng):
        """The BENCH_OBS dp2 x pp2 discipline in-suite: once-per-step
        wire bytes (dp reduce-scatter/all-gather + the region's pp grad
        psum) == census exactly, and the boundary permutes reconcile
        structurally (exactly 2 at the predicted buffer bytes)."""
        import jax
        from paddle_tpu.framework.costs import collective_census
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.mesh import DeviceMesh
        from paddle_tpu.parallel.strategy import (BuildStrategy,
                                                  ReduceStrategy)

        x = layers.data("x", shape=[32])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=64, act="relu")
        h = layers.fc(h, size=64, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=10), label))
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
        bst = BuildStrategy(pipeline_stages=2, num_microbatches=4,
                            pipeline_schedule="1f1b")
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
        mesh = DeviceMesh(jax.devices()[:4], {"dp": 2, "pp": 2})
        pexe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        feed = {"x": rng.rand(16, 32).astype("float32"),
                "label": rng.randint(0, 10, (16, 1)).astype("int64")}
        pexe.run(feed=feed, fetch_list=[loss])
        report = pexe.cost_report(nominal_batch=16)
        census = collective_census(_compiled_hlo(pexe, feed))
        row = obs_ledger.CostLedger("t").row("mlp_dp2xpp2")
        row.set_prediction(report)
        row.set_census(census, 2, min_bytes=8)
        assert row.check_wire_bytes_exact()["ok"]
        assert row.check_pp_boundary()["ok"]
        assert report["pipeline"]["grad_psum_wire_bytes"] > 0

    def test_ledger_artifact_roundtrip(self, tmp_path):
        led = obs_ledger.CostLedger("r_test", meta={"host": "ci"})
        row = led.row("m1", dp=2)
        row.set_measured(step_ms=1.5)
        row.check("x", 10, 10, rel=0.0)
        path = led.write(str(tmp_path / "obs.json"))
        with open(path) as f:
            data = json.load(f)
        assert data["run"] == "r_test" and data["ok"]
        assert data["rows"][0]["measured"]["step_ms"] == 1.5
        assert data["rows"][0]["checks"][0]["ok"]

    def test_ledger_requires_inputs_before_check(self):
        row = obs_ledger.CostLedger("t").row("r")
        with pytest.raises(Exception, match="need both"):
            row.check_wire_bytes_exact()


# ---------------------------------------------------------------------------
# profiler compat over the new recorder
# ---------------------------------------------------------------------------


class TestProfilerCompat:
    def test_record_event_is_user_span(self):
        from paddle_tpu import profiler
        assert issubclass(profiler.RecordEvent, tracing.span)
        m = tracing.mark()
        with profiler.RecordEvent("anno"):
            pass
        ss = tracing.spans_since(m)
        assert ss and ss[0].kind == "user" and ss[0].name == "anno"

    def test_record_event_records_while_profiling_despite_kill_switch(self):
        """The pre-r12 contract: a profiler() context records RecordEvent
        scopes even with PTPU_TRACE=0 (force-enable window)."""
        from paddle_tpu import profiler
        old = flags.get_flag("trace")
        flags.set_flag("trace", False)
        try:
            profiler.start_profiler("CPU")
            with profiler.RecordEvent("windowed"):
                pass
            m_inside = [s.name for s in tracing.spans()]
            profiler.stop_profiler()
        finally:
            flags.set_flag("trace", old)
        assert "windowed" in m_inside

    def test_reset_isolates_state(self, capsys):
        from paddle_tpu import profiler
        profiler.start_profiler("CPU")
        with profiler.RecordEvent("leaky"):
            pass
        profiler.reset()
        assert not profiler.profiler_enabled()
        # the window restarted: a fresh summary sees nothing
        profiler.print_profiler_summary()
        out = capsys.readouterr().out
        assert "no events recorded" in out
        assert "leaky" not in out
