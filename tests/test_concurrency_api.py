"""CSP channels/select/go tests + API.spec golden test.

≙ reference framework/channel_test.cc (28K of CSP semantics),
tests covering fluid.concurrency Go/Select/make_channel, and the
API.spec + tools/diff_api.py CI check.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from paddle_tpu.concurrency import (Channel, ChannelClosedError, Go, Select,
                                    channel_close, channel_recv, channel_send,
                                    go, make_channel)


class TestBufferedChannel:
    def test_fifo_order(self):
        ch = make_channel(capacity=4)
        for i in range(4):
            assert channel_send(ch, i)
        assert [channel_recv(ch)[0] for _ in range(4)] == [0, 1, 2, 3]

    def test_send_blocks_when_full_until_recv(self):
        ch = Channel(capacity=1)
        ch.send("a")
        got = []

        def sender():
            ch.send("b")
            got.append("sent")

        g = go(sender)
        time.sleep(0.05)
        assert not got          # blocked: buffer full
        assert ch.recv() == ("a", True)
        g.join(timeout=5)
        assert got == ["sent"]
        assert ch.recv() == ("b", True)

    def test_recv_blocks_until_send(self):
        ch = Channel(capacity=1)
        out = []
        g = go(lambda: out.append(ch.recv()))
        time.sleep(0.05)
        assert not out
        ch.send(42)
        g.join(timeout=5)
        assert out == [(42, True)]

    def test_close_semantics(self):
        ch = Channel(capacity=2)
        ch.send(1)
        ch.close()
        # drained values still readable after close (Go semantics)
        assert ch.recv() == (1, True)
        assert ch.recv() == (None, False)
        with pytest.raises(ChannelClosedError):
            ch.send(2)

    def test_close_wakes_blocked_receivers(self):
        ch = Channel(capacity=1)
        results = []
        gs = [go(lambda: results.append(ch.recv())) for _ in range(3)]
        time.sleep(0.05)
        channel_close(ch)
        for g in gs:
            g.join(timeout=5)
        assert results == [(None, False)] * 3


class TestUnbufferedChannel:
    def test_rendezvous(self):
        ch = Channel(capacity=0)
        order = []

        def sender():
            order.append("send-start")
            ch.send("x")
            order.append("send-done")

        g = go(sender)
        time.sleep(0.05)
        assert "send-done" not in order   # no receiver yet
        v, ok = ch.recv()
        g.join(timeout=5)
        assert (v, ok) == ("x", True)
        assert order == ["send-start", "send-done"]

    def test_many_producers_one_consumer(self):
        ch = Channel(capacity=0)
        n = 8
        gs = [go(ch.send, i) for i in range(n)]
        got = sorted(ch.recv()[0] for _ in range(n))
        for g in gs:
            g.join(timeout=5)
        assert got == list(range(n))

    def test_close_raises_for_blocked_sender(self):
        ch = Channel(capacity=0)
        g = go(ch.send, "never")
        time.sleep(0.05)
        ch.close()
        with pytest.raises(ChannelClosedError):
            g.join(timeout=5)


class TestSelect:
    def test_picks_ready_recv(self):
        a, b = Channel(capacity=1), Channel(capacity=1)
        b.send("from-b")
        fired = []
        sel = (Select()
               .case_recv(a, lambda v, ok: fired.append(("a", v)))
               .case_recv(b, lambda v, ok: fired.append(("b", v))))
        which = sel.run(timeout=5)
        assert which == 1 and fired == [("b", "from-b")]

    def test_default_when_nothing_ready(self):
        a = Channel(capacity=1)   # empty: recv not ready
        fired = []
        which = (Select()
                 .case_recv(a, lambda v, ok: fired.append("recv"))
                 .default(lambda: fired.append("default"))).run()
        assert which == -1 and fired == ["default"]

    def test_send_case(self):
        a = Channel(capacity=1)
        fired = []
        which = (Select()
                 .case_send(a, 7, lambda: fired.append("sent"))).run(timeout=5)
        assert which == 0 and fired == ["sent"]
        assert a.recv() == (7, True)

    def test_timeout(self):
        a = Channel(capacity=1)
        with pytest.raises(TimeoutError):
            Select().case_recv(a, lambda v, ok: None).run(timeout=0.05)

    def test_producer_consumer_pipeline(self):
        # ≙ the reference's CSP fibonacci/pipeline examples
        nums, done = Channel(capacity=0), Channel(capacity=0)

        def producer():
            for i in range(10):
                nums.send(i)
            nums.close()

        total = []

        def consumer():
            while True:
                v, ok = nums.recv()
                if not ok:
                    break
                total.append(v)
            done.send(sum(total))

        go(producer)
        go(consumer)
        s, ok = done.recv(timeout=10)
        assert ok and s == 45


class TestSelectRendezvous:
    def test_two_selects_rendezvous_on_unbuffered(self):
        """Regression: a send-select and a recv-select on the same
        unbuffered channel must complete (Go semantics), even though
        neither side is 'ready' until the other commits."""
        from paddle_tpu.concurrency import Channel
        ch = Channel(capacity=0)
        got = []

        def recv_side():
            Select().case_recv(ch, lambda v, ok: got.append(v)).run(timeout=10)

        def send_side():
            Select().case_send(ch, 42, lambda: got.append("sent")).run(
                timeout=10)

        g1, g2 = go(recv_side), go(send_side)
        g1.join(timeout=15)
        g2.join(timeout=15)
        assert sorted(map(str, got)) == ["42", "sent"]

    def test_recv_timeout_distinct_from_close(self):
        from paddle_tpu.concurrency import Channel, ChannelTimeout
        ch = Channel(capacity=1)
        with pytest.raises(ChannelTimeout):
            ch.recv(timeout=0.05)      # slow producer != end-of-stream
        ch.close()
        assert ch.recv(timeout=0.05) == (None, False)  # real close

    def test_unbuffered_send_timeout_bounded(self):
        from paddle_tpu.concurrency import Channel
        ch = Channel(capacity=0)
        t0 = time.time()
        assert ch.send("x", timeout=0.2) is False
        assert time.time() - t0 < 0.8   # single deadline, not 2x


class TestGo:
    def test_decorator_and_result(self):
        @Go
        def work():
            return 21 * 2
        assert work.join(timeout=5) == 42

    def test_exception_propagates_on_join(self):
        def boom():
            raise ValueError("boom")
        g = go(boom)
        with pytest.raises(ValueError):
            g.join(timeout=5)


class TestAPISpec:
    """≙ reference API.spec + tools/diff_api.py golden-surface test."""

    def test_api_surface_matches_golden(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(repo, "tools"))
        import print_signatures
        current = sorted(set(print_signatures.iter_api()))
        with open(os.path.join(repo, "API.spec")) as f:
            golden = [l for l in f.read().splitlines() if l.strip()]
        added = set(current) - set(golden)
        removed = set(golden) - set(current)
        assert not added and not removed, (
            f"public API changed — review and run "
            f"`python tools/print_signatures.py --update`.\n"
            f"added: {sorted(added)[:10]}\nremoved: {sorted(removed)[:10]}")

    def test_spec_is_nontrivial(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "API.spec")) as f:
            lines = f.read().splitlines()
        assert len(lines) > 400   # the surface is broad; guard against wipes
