"""Beam search ops + seq2seq machine translation book test.

≙ reference tests/book/test_machine_translation.py (train attention seq2seq
briefly, save, reload, beam-search decode) and test_beam_search_op.py /
test_beam_search_decode_op.py.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import unique_name
from paddle_tpu.models import machine_translation as mt

from op_test import run_op


class TestBeamSearchOp:
    def test_selects_topk_across_beams(self):
        # B=1, K=2, V=4; beam 0 score 0, beam 1 score -0.5
        pre_ids = np.array([[2, 3]], dtype="int64")
        pre_scores = np.array([[0.0, -0.5]], dtype="float32")
        logp = np.log(np.array(
            [[[0.1, 0.2, 0.3, 0.4],
              [0.25, 0.25, 0.25, 0.25]]], dtype="float32"))
        out = run_op("beam_search",
                     {"PreIds": pre_ids, "PreScores": pre_scores,
                      "Scores": logp}, attrs={"end_id": 99})
        ids, scores, parent = (out["SelectedIds"][0],
                               out["SelectedScores"][0],
                               out["ParentIdx"][0])
        # best two continuations: beam0/token3 (log .4), beam0/token2 (log .3)
        assert ids[0, 0] == 3 and parent[0, 0] == 0
        assert ids[0, 1] == 2 and parent[0, 1] == 0
        np.testing.assert_allclose(scores[0, 0], np.log(0.4), rtol=1e-5)

    def test_finished_beam_frozen(self):
        end = 1
        pre_ids = np.array([[end, 5]], dtype="int64")
        pre_scores = np.array([[10.0, 0.0]], dtype="float32")
        logp = np.full((1, 2, 6), np.log(1.0 / 6), dtype="float32")
        out = run_op("beam_search",
                     {"PreIds": pre_ids, "PreScores": pre_scores,
                      "Scores": logp}, attrs={"end_id": end})
        # finished beam stays: emits end_id at unchanged score, ranked first
        assert out["SelectedIds"][0][0, 0] == end
        assert out["ParentIdx"][0][0, 0] == 0
        np.testing.assert_allclose(out["SelectedScores"][0][0, 0], 10.0)

    def test_gather_tree_backtracks(self):
        # T=3, K=2: final beam 0 came from path b1 -> b0 -> b0
        ids = np.array([[[5, 6], [7, 8], [9, 10]]], dtype="int64")  # [1,3,2]
        parents = np.array([[[0, 0], [1, 0], [0, 1]]], dtype="int64")
        out = run_op("gather_tree", {"Ids": ids, "Parents": parents})
        seq = out["Out"][0]
        # beam 0 at t=2: token 9, parent 0 -> t=1 token 7? parent chain:
        # t=2 beam0 parent=0 -> t=1 beam0 token 7, its parent=1 -> t=0 token 6
        np.testing.assert_array_equal(seq[0, :, 0], [6, 7, 9])
        # beam 1 at t=2: token 10, parent 1 -> t=1 beam1 token 8, parent 0
        np.testing.assert_array_equal(seq[0, :, 1], [5, 8, 10])


def _toy_batch(rng, B, Ts, Tt, V, bos, eos):
    """Copy-ish task: target = source tokens shifted, ending with eos."""
    src = rng.randint(4, V, (B, Ts)).astype("int64")
    tgt = np.concatenate([src[:, :Tt - 1], np.full((B, 1), eos)], 1)
    tgt_in = np.concatenate([np.full((B, 1), bos), tgt[:, :-1]], 1)
    mask = np.ones((B, Tt), dtype="float32")
    return (src, np.full((B,), Ts, dtype="int64"),
            tgt_in.astype("int64"), tgt.astype("int64"), mask)


class TestMachineTranslationBook:
    def test_train_save_load_beam_infer(self, rng, tmp_path):
        B, Ts, Tt, V, K = 8, 5, 5, 24, 3
        BOS, EOS = 0, 1

        with unique_name.guard():
            src = layers.data("src", shape=[Ts], dtype="int64")
            src_lens = layers.data("src_lens", shape=[], dtype="int64")
            tgt_in = layers.data("tgt_in", shape=[Tt], dtype="int64")
            tgt_out = layers.data("tgt_out", shape=[Tt], dtype="int64")
            tgt_mask = layers.data("tgt_mask", shape=[Tt], dtype="float32")
            loss, _ = mt.train_net(src, src_lens, tgt_in, tgt_out, tgt_mask,
                                   dict_size=V, embed_dim=16, hidden_dim=32)
            pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)

        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        s, sl, ti, to, m = _toy_batch(rng, B, Ts, Tt, V, BOS, EOS)
        feed = {"src": s, "src_lens": sl, "tgt_in": ti, "tgt_out": to,
                "tgt_mask": m}
        first = float(exe.run(feed=feed, fetch_list=[loss])[0])
        for _ in range(30):
            last = float(exe.run(feed=feed, fetch_list=[loss])[0])
        assert last < first  # attention seq2seq trains

        # save trained params (book flow: save -> fresh build -> load)
        pt.io.save_params(exe, str(tmp_path / "mt"))

        pt.reset_default_programs()
        pt.reset_global_scope()
        with unique_name.guard():
            src_i = layers.data("src", shape=[Ts], dtype="int64")
            lens_i = layers.data("src_lens", shape=[], dtype="int64")
            seqs, scores = mt.infer_net(src_i, lens_i, dict_size=V,
                                        embed_dim=16, hidden_dim=32,
                                        beam_size=K, max_len=Tt,
                                        bos_id=BOS, eos_id=EOS)
        exe2 = pt.Executor()
        exe2.run(pt.default_startup_program())
        pt.io.load_params(exe2, str(tmp_path / "mt"))

        got_seqs, got_scores = exe2.run(
            feed={"src": s, "src_lens": sl}, fetch_list=[seqs, scores])
        assert got_seqs.shape == (B, Tt, K)
        assert np.isfinite(got_scores).all()
        # beams sorted best-first
        assert (np.diff(got_scores, axis=1) <= 1e-5).all()
        # all decoded tokens are valid vocab ids
        assert ((got_seqs >= 0) & (got_seqs < V)).all()

    def test_variable_length_sources_train_and_decode(self, rng):
        # exercises attention masking of padded source positions
        B, Ts, Tt, V, K = 6, 6, 4, 16, 2
        with unique_name.guard():
            src = layers.data("src", shape=[Ts], dtype="int64")
            src_lens = layers.data("src_lens", shape=[], dtype="int64")
            tgt_in = layers.data("tgt_in", shape=[Tt], dtype="int64")
            tgt_out = layers.data("tgt_out", shape=[Tt], dtype="int64")
            tgt_mask = layers.data("tgt_mask", shape=[Tt], dtype="float32")
            loss, _ = mt.train_net(src, src_lens, tgt_in, tgt_out, tgt_mask,
                                   dict_size=V, embed_dim=8, hidden_dim=16)
            pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        s = rng.randint(2, V, (B, Ts)).astype("int64")
        sl = rng.randint(2, Ts + 1, (B,)).astype("int64")  # ragged lengths
        for b in range(B):
            s[b, sl[b]:] = 0  # pad
        to = rng.randint(2, V, (B, Tt)).astype("int64")
        ti = np.concatenate([np.zeros((B, 1), "int64"), to[:, :-1]], 1)
        feed = {"src": s, "src_lens": sl, "tgt_in": ti, "tgt_out": to,
                "tgt_mask": np.ones((B, Tt), "float32")}
        l0 = float(exe.run(feed=feed, fetch_list=[loss])[0])
        for _ in range(10):
            l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
        assert np.isfinite(l1) and l1 < l0

        pt.reset_default_programs()
        with unique_name.guard():
            src_i = layers.data("src", shape=[Ts], dtype="int64")
            lens_i = layers.data("src_lens", shape=[], dtype="int64")
            seqs, scores = mt.infer_net(src_i, lens_i, dict_size=V,
                                        embed_dim=8, hidden_dim=16,
                                        beam_size=K, max_len=Tt)
        got, sc = pt.Executor().run(feed={"src": s, "src_lens": sl},
                                    fetch_list=[seqs, scores])
        assert got.shape == (B, Tt, K) and np.isfinite(sc).all()

    def test_beam_decode_prefers_trained_tokens(self, rng):
        # after training on a constant-target task, beam 0 should decode
        # mostly that target token
        B, Ts, Tt, V, K = 4, 4, 4, 12, 2
        BOS, EOS = 0, 1
        CONST = 7
        with unique_name.guard():
            src = layers.data("src", shape=[Ts], dtype="int64")
            src_lens = layers.data("src_lens", shape=[], dtype="int64")
            tgt_in = layers.data("tgt_in", shape=[Tt], dtype="int64")
            tgt_out = layers.data("tgt_out", shape=[Tt], dtype="int64")
            tgt_mask = layers.data("tgt_mask", shape=[Tt], dtype="float32")
            loss, _ = mt.train_net(src, src_lens, tgt_in, tgt_out, tgt_mask,
                                   dict_size=V, embed_dim=8, hidden_dim=16)
            pt.optimizer.AdamOptimizer(learning_rate=1e-2).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        s = rng.randint(2, V, (B, Ts)).astype("int64")
        sl = np.full((B,), Ts, dtype="int64")
        to = np.full((B, Tt), CONST, dtype="int64")
        ti = np.concatenate([np.full((B, 1), BOS), to[:, :-1]], 1)
        feed = {"src": s, "src_lens": sl, "tgt_in": ti.astype("int64"),
                "tgt_out": to, "tgt_mask": np.ones((B, Tt), "float32")}
        for _ in range(150):
            exe.run(feed=feed, fetch_list=[loss])
        scope_vals = pt.global_scope()

        pt.reset_default_programs()
        with unique_name.guard():
            src_i = layers.data("src", shape=[Ts], dtype="int64")
            lens_i = layers.data("src_lens", shape=[], dtype="int64")
            seqs, scores = mt.infer_net(src_i, lens_i, dict_size=V,
                                        embed_dim=8, hidden_dim=16,
                                        beam_size=K, max_len=Tt,
                                        bos_id=BOS, eos_id=EOS)
        exe2 = pt.Executor()  # shares global scope: params already live
        got = exe2.run(feed={"src": s, "src_lens": sl},
                       fetch_list=[seqs])[0]
        # best beam overwhelmingly emits the constant token
        assert (got[:, :, 0] == CONST).mean() > 0.7
